"""ISSUE 16: the per-row decode-feature plane + streaming delivery.

Per-feature token-parity pins vs the dense request-mode twin
(translator/beam_search.py): lexical shortlist, fixed-seed sampling
determinism + replay, n-best, force-decode (incl. the prefix-cache
COW-fork case), plus the #stream: delivery path (engine partials,
scheduler fan-out + ttft, server e2e) and the decode-surface validation
table (an UNCLASSIFIED set flag must refuse loudly — no flag may
silently fall through to wrong output)."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.shortlist import LexicalShortlistGenerator
from marian_tpu.data.vocab import DefaultVocab, EOS_ID
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.promlint import lint_metrics_text
from marian_tpu.serving.scheduler import ContinuousScheduler
from marian_tpu.translator.beam_iteration import PagedBeamEngine
from marian_tpu.translator.beam_search import (BeamConfig, BeamSearch,
                                               beam_search_jit)
from marian_tpu.translator.decode_features import FeaturePlane
from marian_tpu.translator.iteration import PagedDecodeEngine
from marian_tpu.translator.prefix_cache import PrefixCache

from tests.test_beam_search import tiny_model


@pytest.fixture(autouse=True)
def _lockdep_witness(lockdep_witness):
    yield


@pytest.fixture(autouse=True)
def _ownership_witness(ownership_witness):
    """Feature rows ride the same claim/share/retable handoffs the
    ownership witness audits; the plane must not mint new pairings."""
    yield


VOCAB_WORDS = [" ".join(f"w{i}" for i in range(35))]
TEXTS = ["w3 w4 w5", "w6 w7", "w8 w9 w10 w11", "w2 w3"]
K = 2


@pytest.fixture(scope="module")
def tiny():
    vocab = DefaultVocab.build(VOCAB_WORDS)
    model, params, _ = tiny_model(vocab=len(vocab), seed=7,
                                  **{"dec-depth": 2, "enc-depth": 2})
    return model, params, vocab


@pytest.fixture(scope="module")
def sl_gen(tiny, tmp_path_factory):
    """A small REAL lexical table: every source word maps to 6 clustered
    target ids, so a sentence's union is a strict subset of the vocab
    and k_multiple=8 keeps the padded widths small."""
    _, _, vocab = tiny
    n = len(vocab)
    srcs, trgs, probs = [], [], []
    for s in range(2, n):
        for j in range(6):
            srcs.append(s)
            trgs.append(2 + (s * 5 + j * 3) % (n - 2))
            probs.append(1.0 / (j + 1))
    path = tmp_path_factory.mktemp("sl") / "lex.npz"
    np.savez(path, srcs=np.array(srcs, np.int32),
             trgs=np.array(trgs, np.int32),
             probs=np.array(probs, np.float32))
    return LexicalShortlistGenerator(str(path), vocab, vocab,
                                     first=4, best=6, k_multiple=8)


def make_greedy(tiny, registry=None, prefix=None, features=None, **kw):
    model, params, vocab = tiny
    args = dict(max_rows=4, page_len=4, src_len_cap=8,
                max_length_cap=12, registry=registry,
                prefix_cache=prefix, features=features)
    args.update(kw)
    return PagedDecodeEngine(model, params, vocab, vocab, **args)


def make_beam(tiny, registry=None, prefix=None, features=None, **kw):
    model, params, vocab = tiny
    args = dict(beam_size=K, normalize=0.6, max_rows=2 * K, page_len=4,
                src_len_cap=8, max_length_cap=12, registry=registry,
                prefix_cache=prefix, features=features)
    args.update(kw)
    return PagedBeamEngine(model, params, vocab, vocab, **args)


def drive(eng, texts, metas=None):
    """Decode texts through the slot machinery, retrying deferred and
    pool-evicted sentences; returns (texts-by-key, info-by-key)."""
    outs, infos = {}, {}
    pending = list(enumerate(texts))
    guard = 0
    while pending or not eng.idle():
        joins = []
        while pending and len(joins) < max(1, eng.free_slots()):
            key, text = pending.pop(0)
            if metas is not None:
                joins.append((key, text, metas[key]))
            else:
                joins.append((key, text))
        res = eng.admit_and_step(joins)
        for key, why in res.rejected:
            assert why in ("no_slot", "no_pages"), (key, why)
            pending.insert(0, (key, texts[key]))
        for key in res.pool_evicted:
            pending.insert(0, (key, texts[key]))
        outs.update(dict(res.finished))
        infos.update(res.finished_info)
        guard += 1
        assert guard < 1000, "decode failed to converge"
    assert eng.audit(context="test") == []
    return outs, infos


def run(coro):
    return asyncio.run(coro)


def _dense_nbest(tiny, text, beam=K, normalize=0.6, shortlist=None,
                 forced=None):
    """The dense request-mode twin: one sentence through beam_search_jit
    with the engine's own cap rule, returning ranked (tokens, score)
    with EOS cropped — what drive()'s infos should reproduce."""
    model, params, vocab = tiny
    ids = vocab.encode(text, add_eos=True, inference=True)
    L = int(min(12, max(8, round(3.0 * len(ids)))))
    pfx = None
    if forced:
        L = max(L, min(12, len(forced) + 8))
        pfx = np.full((1, L), -1, np.int32)
        pfx[0, :len(forced)] = forced
        pfx = jnp.asarray(pfx)
    cfg = BeamConfig(beam_size=beam, normalize=normalize, max_length=L)
    src = jnp.asarray(np.array([ids], np.int32))
    mask = jnp.ones((1, len(ids)), jnp.float32)
    sl_idx = jnp.asarray(shortlist.indices) if shortlist is not None \
        else None
    toks, scores, lengths, norm, _, _ = beam_search_jit(
        model, [params], [1.0], cfg, src, mask, sl_idx, prefix=pfx)
    toks, scores, lengths, norm = map(
        np.asarray, (toks, scores, lengths, norm))
    order = np.argsort(-norm[0], kind="stable")
    out = []
    for j in order:
        ln = int(lengths[0, j])
        tl = toks[0, j, :ln].tolist()
        if tl and tl[-1] == EOS_ID:
            tl = tl[:-1]
        out.append((tl, float(scores[0, j]), float(norm[0, j])))
    return out


def _crop_eos(tokens, length):
    tl = list(tokens[:length])
    if tl and tl[-1] == EOS_ID:
        tl = tl[:-1]
    return tl


# ---------------------------------------------------------------------------
# the plane itself
# ---------------------------------------------------------------------------

class TestFeaturePlane:
    def test_from_options_none_without_features(self, tiny):
        _, _, vocab = tiny
        assert FeaturePlane.from_options(
            Options({"beam-size": 2}), vocab, vocab) is None

    def test_from_options_parses_features_and_seed(self, tiny):
        _, _, vocab = tiny
        p = FeaturePlane.from_options(
            Options({"output-sampling": ["topk", "5", "0.7"],
                     "n-best": True, "beam-size": 2}), vocab, vocab)
        assert p.sampling == ("topk", 5, 0.7)
        assert p.n_best and p.printer is not None
        assert p.seed == 1234          # dense twin's default-seed rule
        assert not p.cacheable         # sampling forbids replay/fork

    def test_shortlist_refuses_force_decode(self, sl_gen):
        with pytest.raises(ValueError, match="force-decode"):
            FeaturePlane(shortlist_gen=sl_gen, force_decode=True)

    def test_split_forced_tab_convention(self, tiny):
        _, _, vocab = tiny
        p = FeaturePlane(force_decode=True)
        src, forced = p.split_forced("w3 w4\tw5 w6", vocab)
        assert src == "w3 w4"
        assert forced == [int(t) for t in
                          vocab.encode("w5 w6", add_eos=False)]
        assert p.split_forced("w3 w4", vocab) == ("w3 w4", [])
        assert p.split_forced("w3 w4\t ", vocab) == ("w3 w4", [])

    def test_cache_key_salted_by_forced_trunk(self):
        p = FeaturePlane(force_decode=True)
        base = (3, 4, 0)
        assert p.cache_key(base, []) == base
        assert p.cache_key(base, [5, 6]) != base
        assert p.cache_key(base, [5, 6]) == p.cache_key(base, [5, 6])
        assert p.cache_key(base, [5, 6]) != p.cache_key(base, [5, 7])


# ---------------------------------------------------------------------------
# shortlist: token parity vs the dense shortlisted beam search
# ---------------------------------------------------------------------------

class TestShortlistParity:
    def test_greedy_token_parity_vs_dense(self, tiny, sl_gen):
        """Greedy engine rows decode in shortlist coords and map back —
        tokens must equal the dense beam-1 search over the SAME
        per-sentence shortlist (beam-1 == greedy; normalization cannot
        reorder a single hypothesis). The same drive also pins
        containment: every emitted token is inside the row's shortlist
        (one engine build covers both — jit compiles dominate tier-1)."""
        _, _, vocab = tiny
        plane = FeaturePlane(shortlist_gen=sl_gen, k_static=24)
        outs, _ = drive(make_greedy(tiny, features=plane), TEXTS)
        for i, t in enumerate(TEXTS):
            ids = vocab.encode(t, add_eos=True, inference=True)
            sl = sl_gen.generate(np.unique(np.asarray(ids, np.int32)))
            tl, _, _ = _dense_nbest(tiny, t, beam=1, normalize=0.0,
                                    shortlist=sl)[0]
            assert outs[i] == vocab.decode(tl), (i, outs[i])
            allowed = set(sl.indices.tolist())
            got = set(int(x) for x in
                      vocab.encode(outs[i], add_eos=False)) \
                if outs[i] else set()
            assert got <= allowed, (i, got - allowed)

    def test_beam_token_parity_vs_dense(self, tiny, sl_gen):
        """COW beam engine with per-row shortlists vs the dense
        shortlisted beam search: identical winning tokens."""
        _, _, vocab = tiny
        plane = FeaturePlane(shortlist_gen=sl_gen, k_static=24)
        _, infos = drive(make_beam(tiny, features=plane), TEXTS[:2])
        for i, t in enumerate(TEXTS[:2]):
            ids = vocab.encode(t, add_eos=True, inference=True)
            sl = sl_gen.generate(np.unique(np.asarray(ids, np.int32)))
            tl, score, _ = _dense_nbest(tiny, t, shortlist=sl)[0]
            mine = infos[i]
            assert _crop_eos(mine["tokens"], mine["length"]) == tl, (i, t)
            assert abs(mine["score"] - score) < 1e-4

    def test_shortlist_metrics_census(self, tiny, sl_gen):
        reg = msm.Registry()
        plane = FeaturePlane(shortlist_gen=sl_gen, k_static=24)
        eng = make_greedy(tiny, registry=reg, features=plane)
        drive(eng, TEXTS[:2])
        text = reg.render()
        assert "marian_shortlist_rows_total" in text
        assert "marian_shortlist_width_tokens" in text
        assert reg.get("marian_shortlist_rows_total").value >= 2
        assert lint_metrics_text(text) == []


# ---------------------------------------------------------------------------
# sampling: fixed-seed determinism + replay, lanes, cache interaction
# ---------------------------------------------------------------------------

class TestSampling:
    def test_fixed_seed_replay_greedy(self, tiny):
        """Fixed seed + same join schedule ⇒ identical sampled output
        across FRESH engines (per-row lane + per-step counter keys,
        nothing hidden in engine lifetime)."""
        def one_run():
            plane = FeaturePlane(sampling=("full", 1.0), seed=77)
            return drive(make_greedy(tiny, features=plane), TEXTS[:2])[0]
        a, b = one_run(), one_run()
        assert a == b

    def test_fixed_seed_replay_beam_sampled(self, tiny):
        """Sampled beam: every hypothesis is an independent trajectory
        on its own lane (feat.lane + j); replay is exact."""
        def one_run():
            plane = FeaturePlane(sampling=("topk", 5, 0.8), seed=31)
            _, infos = drive(make_beam(tiny, features=plane), TEXTS[:2])
            return {k: (v["tokens"], np.float32(v["score"]))
                    for k, v in infos.items()}
        a, b = one_run(), one_run()
        assert a == b

    def test_duplicate_requests_get_distinct_lanes(self, tiny):
        """Two identical sentences in one engine must sample on
        different RNG lanes — exactly as two dense batches fold
        different call counters."""
        plane = FeaturePlane(sampling=("full", 1.0), seed=77)
        eng = make_greedy(tiny, features=plane)
        drive(eng, [TEXTS[0], TEXTS[0]])
        assert eng._lane_ctr == 2      # one lane per admitted row

    def test_sampling_disables_prefix_cache(self, tiny):
        plane = FeaturePlane(sampling=("full", 1.0), seed=77)
        eng = make_greedy(tiny, features=plane,
                          prefix=PrefixCache(max_entries=8, version="v"))
        assert eng.prefix is None      # a dice roll must not be replayed


# ---------------------------------------------------------------------------
# force-decode: parity, caps, prefix-cache composition
# ---------------------------------------------------------------------------

class TestForceDecode:
    def test_forced_prefix_respected_and_parity_vs_dense(self, tiny):
        _, _, vocab = tiny
        plane = FeaturePlane(force_decode=True)
        lines = ["w3 w4 w5\tw6 w7", "w6 w7\tw2"]
        _, infos = drive(make_beam(tiny, features=plane), lines)
        for i, line in enumerate(lines):
            src, pfx = line.split("\t")
            forced = [int(t) for t in vocab.encode(pfx, add_eos=False)]
            got = _crop_eos(infos[i]["tokens"], infos[i]["length"])
            assert got[:len(forced)] == forced, (i, got, forced)
            tl, score, _ = _dense_nbest(tiny, src, forced=forced)[0]
            assert got == tl, (i, got, tl)
            assert abs(infos[i]["score"] - score) < 1e-4

    def test_unconstrained_line_decodes_normally(self, tiny):
        """No TAB = no constraint: output matches a plane-less engine."""
        plane = FeaturePlane(force_decode=True)
        a, _ = drive(make_greedy(tiny, features=plane), [TEXTS[0]])
        b, _ = drive(make_greedy(tiny), [TEXTS[0]])
        assert a == b

    def test_oversized_forced_prefix_is_fatal(self, tiny):
        plane = FeaturePlane(force_decode=True)
        eng = make_greedy(tiny, features=plane)
        long_pfx = " ".join(["w4"] * 6)   # 6 + 8 > max_length_cap 12
        res = eng.admit_and_step([(0, f"w3\t{long_pfx}")])
        assert res.rejected == [(0, "too_large")]
        assert "forced target prefix" in res.reject_detail[0]

    def test_prefix_cache_replay_and_cow_fork_salted_by_trunk(self, tiny):
        """A constrained prefix IS a shareable trunk: (a) an exact
        repeat of a COMPLETED forced decode replays from the cache; (b)
        a repeat arriving while the first is LIVE forks it copy-on-
        write; (c) the same source under a DIFFERENT forced trunk must
        do neither (the trunk salts the key)."""
        plane = FeaturePlane(force_decode=True)
        eng = make_greedy(tiny, features=plane,
                          prefix=PrefixCache(max_entries=8, version="v"))
        line = "w3 w4 w5\tw6 w7"
        outs, _ = drive(eng, [line])
        # (a) completed-decode replay
        res = eng.admit_and_step([(1, line)])
        assert dict(res.finished)[1] == outs[0]
        assert any(ev == "prefix.hit" and d.get("kind") == "replay"
                   for _, ev, d in res.row_events)
        # (b) COW fork off a LIVE forced decode (a line not yet cached)
        line2 = "w6 w7\tw3 w4"
        eng.admit_and_step([(2, line2)])          # live row, mid-decode
        res = eng.admit_and_step([(3, line2)])
        assert any(ev == "prefix.fork" for _, ev, d in res.row_events), \
            res.row_events
        fork_outs, _ = drive(eng, [])             # drain both rows
        assert fork_outs[2] == fork_outs[3]
        # (c) different trunk, same source: a MISS, decoded fresh
        hits_before = eng._counters["prefix_hits"]
        other = "w3 w4 w5\tw2"
        other_outs, _ = drive(eng, [other])
        assert eng._counters["prefix_hits"] == hits_before
        assert other_outs[0] != outs[0]
        assert eng.audit(context="test") == []


# ---------------------------------------------------------------------------
# n-best: collected from beam bookkeeping, dense-printer parity
# ---------------------------------------------------------------------------

class TestNBest:
    def test_nbest_matches_dense_twin(self, tiny):
        """The engine's n-best block is formatted through the SAME
        OutputPrinter as the dense driver: same shape (`sid ||| text
        ||| Score= cum norm` per rank), same texts in the same rank
        order, scores within the paged-vs-dense ULP tolerance."""
        _, _, vocab = tiny
        opts = Options({"n-best": True, "beam-size": K,
                        "normalize": 0.6})
        plane = FeaturePlane.from_options(opts, vocab, vocab)
        outs, infos = drive(make_beam(tiny, features=plane), TEXTS[:2])
        for i, t in enumerate(TEXTS[:2]):
            dense = _dense_nbest(tiny, t)
            lines = outs[i].split("\n")
            assert len(lines) == K
            assert infos[i]["nbest"], "collect must carry the raw n-best"
            for rank, line in enumerate(lines):
                fields = line.split(" ||| ")
                assert fields[0] == "0"            # join-key sid
                d_toks, d_score, d_norm = dense[rank]
                assert fields[1] == vocab.decode(d_toks), (i, rank)
                assert fields[2].startswith("Score= ")
                assert abs(float(fields[2].split()[1]) - d_score) < 1e-4
                assert abs(float(fields[3]) - d_norm) < 1e-4

    def test_greedy_engine_refuses_nbest(self, tiny):
        _, _, vocab = tiny
        opts = Options({"n-best": True, "beam-size": 1})
        plane = FeaturePlane.from_options(opts, vocab, vocab)
        with pytest.raises(ValueError, match="n-best"):
            make_greedy(tiny, features=plane)


# ---------------------------------------------------------------------------
# streaming: engine partials -> scheduler fan-out -> metrics
# ---------------------------------------------------------------------------

class TestStreaming:
    def test_engine_partials_append_only(self, tiny):
        """A greedy streaming row reports its text-so-far each round
        (append-only prefixes of the final text); non-streaming rows
        never appear in res.partials."""
        eng = make_greedy(tiny)
        seen = {0: [], 1: []}
        res = eng.admit_and_step([(0, TEXTS[2], {"stream": True}),
                                  (1, TEXTS[0])])
        guard = 0
        while not eng.idle():
            for key, text, ntok in res.partials:
                seen[key].append((text, ntok))
            res = eng.admit_and_step([])
            guard += 1
            assert guard < 100
        assert not seen[1], "non-streaming row leaked partials"
        texts = [t for t, _ in seen[0]]
        assert texts, "streaming row produced no partials"
        for a, b in zip(texts, texts[1:]):
            assert b.startswith(a), (a, b)
        toks = [n for _, n in seen[0]]
        assert toks == sorted(toks)

    def test_scheduler_stream_partials_and_ttft(self, tiny):
        """submit(on_partial=...) fans engine partials out per round,
        stamps ttft once, and counts both new series; the final reply
        is unchanged by streaming."""
        reg = msm.Registry()
        eng = make_greedy(tiny, registry=reg)
        sched = ContinuousScheduler(None, registry=reg,
                                    batching_mode="iteration",
                                    engine=eng, window_s=0.0)
        got = []

        async def main():
            sched.start()
            f = sched.submit([TEXTS[2]],
                             on_partial=lambda idx, text, ntok:
                             got.append((idx, text, ntok)))
            plain = sched.submit([TEXTS[2]])
            r = await f
            p = await plain
            await sched.stop()
            return r, p

        r, p = run(main())
        assert r == p                        # streaming changes delivery,
        assert got, "no partials delivered"  # never the translation
        assert all(idx == 0 for idx, _, _ in got)
        assert r[0].startswith(got[-1][1]) or got[-1][1] == r[0]
        assert reg.get("marian_stream_partials_total").value == len(got)
        hist = reg.get("marian_stream_ttft_seconds")
        assert hist is not None and hist._count == 1
        assert lint_metrics_text(reg.render()) == []

    def test_server_e2e_stream_tcp(self, tmp_path, monkeypatch):
        """#stream:1 over the dependency-free TCP framing against the
        real iteration-mode server: partial frames then the final reply,
        final text identical to a non-streaming request."""
        from marian_tpu.server import server as srv
        from tests.test_server import (_drive_serve, _tcp_request,
                                       _tiny_server_options)
        monkeypatch.setattr(srv, "HAVE_WS", False)
        sopts = _tiny_server_options(tmp_path).with_(**{
            "batching-mode": "iteration", "beam-size": 1,
            "iteration-rows": 8, "kv-page-len": 4,
            "iteration-steps": 1})

        async def stream_request(port, text):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            payload = f"#stream:1\n{text}".encode("utf-8")
            writer.write(b"MTPU %d\n" % len(payload) + payload)
            await writer.drain()
            partials = []
            while True:
                header = await reader.readline()
                assert header.startswith(b"MTPU ")
                frame = (await reader.readexactly(
                    int(header.split()[1]))).decode("utf-8")
                if frame.startswith(srv.PARTIAL_PREFIX):
                    partials.append(frame)
                else:
                    writer.close()
                    return partials, frame

        async def clients(port):
            plain = await _tcp_request(port, "w3 w4 w5 w6 w7")
            streamed = await stream_request(port, "w3 w4 w5 w6 w7")
            return plain, streamed

        plain, (partials, final) = asyncio.run(
            _drive_serve(sopts, clients))
        assert final == plain
        assert partials, "streaming reply carried no #partial: frames"
        for f in partials:
            idx, _, text = f[len(srv.PARTIAL_PREFIX):].partition(" ")
            assert idx == "0"
        # greedy partials are append-only prefixes of the final reply
        last = partials[-1]
        assert final.startswith(
            last[len(srv.PARTIAL_PREFIX):].partition(" ")[2])


# ---------------------------------------------------------------------------
# decode-surface validation: lifted flags pass, the rest refuse LOUDLY
# ---------------------------------------------------------------------------

class TestDecodeSurfaceValidation:
    BASE = {"batching-mode": "iteration", "beam-size": 2,
            "iteration-rows": 8}

    def _validate(self, **extra):
        from marian_tpu.server.server import ServingApp
        ServingApp._validate_iteration_options(
            Options({**self.BASE, **extra}))

    def test_lifted_features_now_accepted(self):
        self._validate(**{"n-best": True})
        self._validate(**{"output-sampling": ["full", "0.8"]})
        self._validate(**{"force-decode": True})
        self._validate(**{"shortlist": ["lex.npz"]})
        self._validate(**{"n-best": True,
                          "output-sampling": ["topk", "10"]})

    def test_unsupported_flags_still_refused(self):
        for flag, val in (("alignment", "soft"),
                          ("word-scores", True),
                          ("output-approx-knn", [8, 128])):
            with pytest.raises(ValueError, match=flag):
                self._validate(**{flag: val})

    def test_shortlist_with_force_decode_refused_at_boot(self):
        with pytest.raises(ValueError, match="full-vocab"):
            self._validate(**{"shortlist": ["lex.npz"],
                              "force-decode": True})

    def test_unknown_decode_flag_refuses_loudly(self, monkeypatch):
        """THE regression pin: a decode-surface flag that exists but has
        no verdict in ITERATION_DECODE_SURFACE must refuse as
        UNCLASSIFIED, never fall through to silently-wrong output."""
        from marian_tpu.server.server import ServingApp
        monkeypatch.setattr(
            ServingApp, "DECODE_SURFACE_FLAGS",
            ServingApp.DECODE_SURFACE_FLAGS + ("frobnicate",))
        assert "frobnicate" not in ServingApp.ITERATION_DECODE_SURFACE
        with pytest.raises(ValueError, match="UNCLASSIFIED"):
            self._validate(frobnicate=True)
        # every classified flag has a verdict — the census that keeps
        # the UNCLASSIFIED branch from ever firing on shipped flags
        for flag in ServingApp.DECODE_SURFACE_FLAGS[:-1]:
            assert flag in ServingApp.ITERATION_DECODE_SURFACE, flag
