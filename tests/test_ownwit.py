"""Unit tests for the runtime ownership witness (common/ownwit.py) —
the dynamic half of mtlint's resource-ownership analysis (ISSUE 15).

conftest.py arms MARIAN_OWNWIT=1 for the whole test process, so every
KVPool constructed here records its acquire/release/transfer sites. The
witness state is process-global (it accumulates across a whole suite),
so every test runs inside a sandbox that snapshots and restores it —
the serving/iteration/beam/prefix suites' module-teardown cross-check
must still see exactly what their own engines did, not this file's
synthetic records.

Includes THE SEEDED-LEAK DRILL (ISSUE 15 acceptance): the
``pool.release_drop`` faultpoint suppresses one real ``KVPool.release``
inside a real engine's row exit, and the test asserts the suite-level
detectors actually fire — the engine's row-exit/round auditors raise
``PoolCorruption`` (the suite fails), and the witness's live-owner
table still names the leaked owner with its real acquire site.
"""

from __future__ import annotations

import os

import pytest

from marian_tpu.common import faultpoints as fp
from marian_tpu.common import ownwit
from marian_tpu.analysis.ownership import OwnershipGraph
from marian_tpu.ops.pallas.kv_pool import KVPool, PoolCorruption

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def sandbox():
    with ownwit._WITNESS_LOCK:
        saved = (dict(ownwit._PAIRS), dict(ownwit._ACQ_SITES),
                 dict(ownwit._REL_SITES), dict(ownwit._LIVE))
    ownwit.reset()
    yield
    with ownwit._WITNESS_LOCK:
        for store, snap in zip((ownwit._PAIRS, ownwit._ACQ_SITES,
                                ownwit._REL_SITES, ownwit._LIVE), saved):
            store.clear()
            store.update(snap)


def _graph(sites=None, pairs=None) -> OwnershipGraph:
    g = OwnershipGraph()
    g.sites["kv-pages"] = {s: set(kinds)
                           for s, kinds in (sites or {}).items()}
    g.pairs["kv-pages"] = set(pairs or [])
    return g


SA = "marian_tpu/translator/x.py::acq"
SR = "marian_tpu/translator/x.py::rel"


def _record(acq=SA, rel=SR):
    """Plant one observed pairing directly (the public note_* API
    resolves real stack frames, which for a test file is always
    <external> — by design)."""
    with ownwit._WITNESS_LOCK:
        ownwit._ACQ_SITES.setdefault("kv-pages", set()).add(acq)
        ownwit._REL_SITES.setdefault("kv-pages", set()).add(rel)
        ownwit._PAIRS.setdefault("kv-pages", {}).setdefault(
            (acq, rel), "main")


class TestRecording:
    def test_disabled_pool_records_nothing(self, sandbox, monkeypatch):
        monkeypatch.delenv(ownwit.ENV_VAR, raising=False)
        assert not ownwit.enabled()
        p = KVPool(5, page_len=4)
        p.claim("a", 1)
        p.release("a")
        assert ownwit.observed_sites("kv-pages") == (set(), set())
        assert ownwit.observed_pairs("kv-pages") == {}

    def test_direct_test_use_records_external_sites(self, sandbox):
        assert ownwit.enabled()          # conftest armed it
        p = KVPool(5, page_len=4)
        p.claim("a", 2)
        p.release("a")
        acq, rel = ownwit.observed_sites("kv-pages")
        assert acq == {ownwit.EXTERNAL_SITE}
        assert rel == {ownwit.EXTERNAL_SITE}
        # external pairings are exempt from the cross-check by design:
        # the static analysis does not model test code either
        assert ownwit.check(_graph()) == []

    def test_transfer_re_owns_at_the_transfer_site(self, sandbox):
        p = KVPool(5, page_len=4)
        p.claim("row", 1)
        p.transfer("row", ("prefix", "v", "k"))
        assert not any("row" in owner
                       for owner, _ in ownwit.live_owners("kv-pages"))
        assert any("prefix" in owner
                   for owner, _ in ownwit.live_owners("kv-pages"))

    def test_live_owner_reported_until_released(self, sandbox):
        p = KVPool(5, page_len=4)
        p.claim("held", 1)
        assert any("held" in owner
                   for owner, _ in ownwit.live_owners("kv-pages"))
        assert ownwit.check_balanced("kv-pages") != []
        p.release("held")
        assert ownwit.check_balanced("kv-pages") == []

    def test_two_pools_same_owner_value_do_not_collide(self, sandbox):
        p1, p2 = KVPool(5, page_len=4), KVPool(5, page_len=4)
        p1.claim("a", 1)
        p2.claim("a", 1)
        p1.release("a")
        # p2's owner is still live under its own container token
        assert any(owner == "'a'"
                   for owner, _ in ownwit.live_owners("kv-pages"))


class TestVerdict:
    def test_unknown_sites_flagged(self, sandbox):
        _record()
        violations = ownwit.check(_graph())
        assert any("ACQUIRE site" in v and SA in v for v in violations)
        assert any("RELEASE site" in v and SR in v for v in violations)

    def test_unmodeled_pairing_flagged(self, sandbox):
        _record()
        g = _graph(sites={SA: ("acquire",), SR: ("release",)}, pairs=[])
        violations = ownwit.check(g)
        assert any("pairing" in v and SA in v and SR in v
                   for v in violations)

    def test_clean_when_modeled(self, sandbox):
        _record()
        g = _graph(sites={SA: ("acquire",), SR: ("release",)},
                   pairs=[(SA, SR)])
        assert ownwit.check(g) == []

    def test_transfer_site_counts_both_ways(self, sandbox):
        # a transfer site is a valid release target AND acquire source
        st = "marian_tpu/translator/x.py::adopt"
        _record(rel=st)
        _record(acq=st)
        g = _graph(sites={SA: ("acquire",), SR: ("release",),
                          st: ("transfer",)},
                   pairs=[(SA, st), (st, SR)])
        assert ownwit.check(g) == []


class TestAgainstRealStaticGraph:
    def test_real_engine_traffic_is_modeled(self, sandbox, tiny):
        """End-to-end contract: a real engine decode's observed
        pairings are a subset of the graph analysis/ownership.py builds
        from the real tree — the exact mechanism the tier-1
        serving/iteration/beam/prefix witness fixtures assert on."""
        from tests.test_iteration import TEXTS, make_engine
        eng = make_engine(tiny)
        outs = eng.decode_texts(TEXTS[:3])
        assert len(outs) == 3
        acq, _rel = ownwit.observed_sites("kv-pages")
        assert "marian_tpu/translator/iteration.py::_claim_pages" in acq
        assert ownwit.check_against_static(ROOT) == []

    def test_fabricated_pairing_fails_against_real_graph(self, sandbox):
        # release at a site the real model knows, acquire at one it
        # does not: the cross-check must call it out
        _record(acq="marian_tpu/serving/scheduler.py::submit",
                rel="marian_tpu/translator/iteration.py::_evict")
        violations = ownwit.check_against_static(ROOT)
        assert any("scheduler.py::submit" in v for v in violations)


class TestSeededLeakDrill:
    def test_suppressed_release_fails_the_suite_and_names_the_owner(
            self, sandbox, tiny):
        """THE drill: arm `pool.release_drop=fail@1` so the first real
        release inside the engine's row exit silently does nothing —
        the suppressed-release leak bug class. The suite must FAIL
        (row-exit auditor + the armed per-round audit raise
        PoolCorruption), and the ownership witness must still hold the
        leaked owner with its real acquire site."""
        from tests.test_iteration import TEXTS, make_engine
        eng = make_engine(tiny)
        with fp.active("pool.release_drop=fail@1"):
            with pytest.raises(PoolCorruption, match="leaked"):
                eng.decode_texts([TEXTS[0]])
        leaks = ownwit.check_balanced("kv-pages")
        assert any("_claim_pages" in v for v in leaks), leaks
        live = ownwit.live_owners("kv-pages")
        assert any("marian_tpu/translator/iteration.py::_claim_pages"
                   in sites for _owner, sites in live)

    def test_unarmed_drill_point_is_free_and_balanced(self, sandbox,
                                                      tiny):
        from tests.test_iteration import TEXTS, make_engine
        eng = make_engine(tiny)
        eng.decode_texts([TEXTS[0]])
        # every engine-side acquire was released (no prefix cache) —
        # the live table holds nothing for a drained pool
        assert ownwit.check_balanced("kv-pages") == []


@pytest.fixture(scope="module")
def tiny():
    from marian_tpu.data.vocab import DefaultVocab
    from tests.test_beam_search import tiny_model
    from tests.test_iteration import VOCAB_WORDS
    vocab = DefaultVocab.build(VOCAB_WORDS)
    model, params, _ = tiny_model(vocab=len(vocab), seed=7,
                                  **{"dec-depth": 2, "enc-depth": 2})
    return model, params, vocab
