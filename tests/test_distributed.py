"""Distributed (ZeRO-1 data-parallel) tests on the 8-virtual-device CPU mesh —
the coverage upgrade over the reference's real-2-GPU-only CI (SURVEY.md §4).

Gate (SURVEY.md §7 stage 5): the 8-device sharded step must produce the SAME
loss trajectory and parameters as the 1-device step on identical total
batches — SyncGraphGroup's contract that device count is a throughput knob,
not a semantics knob."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.optimizers.optimizers import OptimizerConfig, init_state
from marian_tpu.optimizers.schedule import LRSchedule
from marian_tpu.parallel import mesh as M
from marian_tpu.parallel.zero import build_train_step, place


def opts():
    return Options({
        "type": "transformer",
        "dim-emb": 32, "transformer-heads": 4, "transformer-dim-ffn": 64,
        "enc-depth": 2, "dec-depth": 2, "tied-embeddings-all": True,
        "precision": ["float32", "float32"], "max-length": 64,
        "label-smoothing": 0.1, "cost-type": "ce-mean-words",
        "learn-rate": 0.001, "optimizer": "adam",
        "optimizer-params": [0.9, 0.98, 1e-9], "clip-norm": 1.0,
        "exponential-smoothing": 1e-4,
    })


def batch(vocab, b=16, ts=12, tt=14, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, vocab, (b, ts)), jnp.int32),
        "src_mask": jnp.ones((b, ts), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, vocab, (b, tt)), jnp.int32),
        "trg_mask": jnp.ones((b, tt), jnp.float32),
    }


_run_steps_memo = {}


def run_steps(n_devices, n_steps=4, vocab=19, force_gspmd=False):
    # memoized on the full argument tuple: the 8-device manual run is the
    # baseline of BOTH trajectory tests, and on this 1-core box the jit
    # compile dominates — pay it once per session. Training never mutates
    # its inputs (donate=False) and results are device_get'd copies.
    key = (n_devices, n_steps, vocab, force_gspmd)
    if key in _run_steps_memo:
        return _run_steps_memo[key]
    o = opts()
    devices = jax.devices()[:n_devices]
    mesh = M.make_mesh(None, devices)
    model = create_model(o, vocab, vocab)
    params = model.init(jax.random.key(7))
    opt_cfg = OptimizerConfig.from_options(o)
    opt_state = init_state(opt_cfg, params)
    params, opt_state = place(params, opt_state, mesh)
    schedule = LRSchedule.from_options(o)
    step = build_train_step(model, opt_cfg, schedule, "ce-mean-words", mesh,
                            params, opt_state, delay=1, donate=False,
                            force_gspmd=force_gspmd)
    losses = []
    for i in range(n_steps):
        b = M.shard_batch(batch(vocab, seed=i), mesh)
        params, opt_state, metrics = step(
            params, opt_state, b, jnp.asarray(i + 1, jnp.float32),
            jax.random.key(0))  # train rng fixed; dropout off anyway
        losses.append(float(metrics["ce_sum"]) / float(metrics["labels"]))
    out = losses, jax.device_get(params), jax.device_get(opt_state)
    _run_steps_memo[key] = out
    return out


@pytest.mark.slow
class TestZero1DataParallel:
    def test_8dev_matches_1dev_trajectory(self):
        assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
        l1, p1, s1 = run_steps(1)
        l8, p8, s8 = run_steps(8)
        np.testing.assert_allclose(l1, l8, rtol=2e-4)
        for k in p1:
            if k.endswith("_bk"):
                continue  # structurally zero grad → Adam amplifies float noise
            np.testing.assert_allclose(p1[k], p8[k], rtol=2e-3, atol=2e-5,
                                       err_msg=k)

    def test_manual_and_gspmd_paths_agree(self):
        """The explicit scatter-reduce shard_map path and the GSPMD
        annotation path are two renderings of the SAME SyncGraphGroup
        semantics — head-to-head on the same 8-device mesh and batches
        they must produce matching trajectories and parameters (isolates
        manual-path bugs from batch-scaling effects; dropout off, so the
        rng-stream difference between the paths is inert)."""
        assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
        lm, pm, _ = run_steps(8)
        lg, pg, _ = run_steps(8, force_gspmd=True)
        np.testing.assert_allclose(lm, lg, rtol=2e-4)
        for k in pm:
            if k.endswith("_bk"):
                continue
            np.testing.assert_allclose(pm[k], pg[k], rtol=2e-3,
                                       atol=2e-5, err_msg=k)

    def test_opt_state_is_sharded(self):
        o = opts()
        vocab = 19
        mesh = M.make_mesh(None, jax.devices()[:8])
        model = create_model(o, vocab, vocab)
        params = model.init(jax.random.key(0))
        opt_cfg = OptimizerConfig.from_options(o)
        opt_state = init_state(opt_cfg, params)
        params, opt_state = place(params, opt_state, mesh)
        # a [dim_ffn, dim] tensor (64, 32): dim0 divisible by 8 → sharded
        leaf = opt_state["m"]["encoder_l1_ffn_W1"]
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(4, 64)}  # 32/8 rows per device
        # params stay replicated
        pleaf = params["encoder_l1_ffn_W1"]
        assert {s.data.shape for s in pleaf.addressable_shards} == {(32, 64)}

    def test_ema_state_sharded_and_used(self):
        from marian_tpu.optimizers.optimizers import smoothed_params
        o = opts()
        vocab = 19
        mesh = M.make_mesh(None, jax.devices()[:8])
        model = create_model(o, vocab, vocab)
        params = model.init(jax.random.key(0))
        opt_cfg = OptimizerConfig.from_options(o)
        opt_state = init_state(opt_cfg, params)
        params, opt_state = place(params, opt_state, mesh)
        sm = smoothed_params(opt_cfg, opt_state, params)
        for k in params:
            np.testing.assert_allclose(np.asarray(sm[k]),
                                       np.asarray(params[k]), rtol=1e-6)


class TestMeshSpec:
    def test_default_mesh_all_data(self):
        m = M.make_mesh(None, jax.devices()[:8])
        assert m.shape == {"data": 8, "model": 1, "seq": 1,
                           "pipe": 1, "expert": 1}

    def test_mesh_option_spec(self):
        o = Options({"mesh": ["data:4", "model:2"]})
        m = M.make_mesh(o, jax.devices()[:8])
        assert m.shape == {"data": 4, "model": 2, "seq": 1,
                           "pipe": 1, "expert": 1}

    def test_mesh_mismatch_raises(self):
        o = Options({"mesh": ["data:3"]})
        with pytest.raises(ValueError):
            M.make_mesh(o, jax.devices()[:8])

    def test_zero1_leaf_spec(self):
        m = M.make_mesh(None, jax.devices()[:8])
        from jax.sharding import PartitionSpec as P
        assert M.zero1_leaf_spec((64, 32), m) == P("data")
        assert M.zero1_leaf_spec((30, 64), m) == P(None, "data")
        assert M.zero1_leaf_spec((7, 5), m) == P()
        assert M.zero1_leaf_spec((), m) == P()


class TestZero1CollectivePattern:
    """Pin the compiled communication pattern of the ZeRO-1 step (VERDICT
    r3 #2): gradients must reduce-scatter onto their shard axis and updated
    params must all-gather back — NCCLCommunicator::scatterReduceAndReset-
    Grads / allGatherParams — with NO param-sized all-reduce. A sharding
    regression that degrades to all-reduce + replicated Adam keeps numerics
    bit-identical (every other test stays green) while inflating collective
    bytes ~1.5× and optimizer FLOPs N×; only the HLO shows it."""

    def _compiled_text(self):
        o = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "max-length": 16, "label-smoothing": 0.1,
            "cost-type": "ce-mean-words", "learn-rate": 0.001,
            "optimizer": "adam", "optimizer-params": [0.9, 0.98, 1e-9],
            "clip-norm": 1.0, "exponential-smoothing": 1e-4,
        })
        vocab = 32
        mesh = M.make_mesh(None, jax.devices()[:8])
        model = create_model(o, vocab, vocab)
        params = model.init(jax.random.key(7))
        opt_cfg = OptimizerConfig.from_options(o)
        opt_state = init_state(opt_cfg, params)
        params, opt_state = place(params, opt_state, mesh)
        step = build_train_step(model, opt_cfg, LRSchedule.from_options(o),
                                "ce-mean-words", mesh, params, opt_state,
                                delay=1, donate=False)
        b = M.shard_batch(batch(vocab, b=16, ts=8, tt=8), mesh)
        txt = step.lower(params, opt_state, b,
                         jnp.asarray(1.0, jnp.float32),
                         jax.random.key(0)).compile().as_text()
        return txt, params

    @pytest.mark.slow
    def test_reduce_scatter_plus_all_gather_no_fat_all_reduce(self):
        from marian_tpu.parallel.collectives import collective_stats
        txt, params = self._compiled_text()
        stats = collective_stats(txt)
        n_leaves = len(params)
        param_bytes = sum(int(np.prod(v.shape)) * 4 for v in params.values())

        # every sharded gradient leaf reduce-scatters; every updated param
        # leaf all-gathers back to replicated
        rs = stats.get("reduce-scatter", {"count": 0, "bytes": 0})
        ag = stats.get("all-gather", {"count": 0, "bytes": 0})
        assert rs["count"] == n_leaves, (rs, n_leaves)
        assert ag["count"] == n_leaves, (ag, n_leaves)
        # reduce-scatter outputs are the 1/8 shards of what all-gather
        # reassembles — byte accounting ties the two ends of the cycle
        assert rs["bytes"] * 8 == ag["bytes"] == param_bytes

        # all-reduces may only carry scalar reductions (loss sums, global
        # grad norm) — never a parameter-sized gradient. The smallest param
        # leaf here is 16 elems; scalar tuples stay well under it.
        ar = stats.get("all-reduce", {"max_elems": 0, "bytes": 0})
        assert ar["max_elems"] < 16, f"param-sized all-reduce: {ar}"
        assert ar["bytes"] < 0.02 * param_bytes

    @pytest.mark.slow
    def test_collective_bytes_accounting(self):
        from marian_tpu.parallel.collectives import (collective_stats,
                                                     format_stats)
        hlo = """
          %rs = f32[4,16]{1,0} reduce-scatter(%a), channel_id=1
          %ag.1 = f32[32,16]{1,0} all-gather(%b), channel_id=2
          %ar = (f32[], f32[8]{0}) all-reduce(%c, %d), channel_id=3
          %ars = bf16[64]{0} all-reduce-start(%e), channel_id=4
          %ard = bf16[64]{0} all-reduce-done(%ars), channel_id=4
          %ags = (f32[4,16]{1,0}, f32[32,16]{1,0}) all-gather-start(%f), channel_id=5
          %agd = f32[32,16]{1,0} all-gather-done(%ags), channel_id=5
          %cps = (f32[8]{0}, f32[8]{0}, u32[], u32[]) collective-permute-start(%g), channel_id=6
        """
        s = collective_stats(hlo)
        assert s["reduce-scatter"] == {"count": 1, "bytes": 256,
                                       "max_elems": 64}
        # async -start tuples count only the transferred result buffer
        # (not the operand alias / u32 context members); -done skipped
        assert s["all-gather"] == {"count": 2, "bytes": 2048 * 2,
                                   "max_elems": 512}
        assert s["collective-permute"] == {"count": 1, "bytes": 32,
                                           "max_elems": 8}
        # sync tuple members (combiner-grouped results) DO sum
        assert s["all-reduce"]["count"] == 2
        assert s["all-reduce"]["bytes"] == (1 + 8) * 4 + 64 * 2
        assert "all-reduce" in format_stats(s)


class TestBufferDonation:
    def test_train_step_aliases_all_state_buffers(self):
        """Every param + optimizer-state leaf must be donated (aliased
        input→output) in the compiled train step — a lost alias doubles
        HBM for that buffer and adds a device copy per update (VERDICT r1
        asked for donation to be *verified*, not assumed)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from marian_tpu.common.options import Options
        from marian_tpu.models.encoder_decoder import create_model
        from marian_tpu.optimizers.optimizers import (OptimizerConfig,
                                                      init_state)
        from marian_tpu.optimizers.schedule import LRSchedule
        from marian_tpu.parallel import mesh as M
        from marian_tpu.parallel.zero import build_train_step, place

        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "learn-rate": 1e-3, "optimizer": "adam", "clip-norm": 0.0,
            "cost-type": "ce-mean-words", "max-length": 16,
        })
        mesh = M.make_mesh(None, jax.devices()[:1])
        model = create_model(opts, 31, 31)
        params = model.init(jax.random.key(0))
        cfg = OptimizerConfig.from_options(opts)
        st = init_state(cfg, params)
        params, st = place(params, st, mesh)
        step = build_train_step(model, cfg, LRSchedule.from_options(opts),
                                "ce-mean-words", mesh, params, st,
                                donate=True)
        r = np.random.RandomState(0)
        batch = M.shard_batch({
            "src_ids": jnp.asarray(r.randint(2, 31, (8, 8)), jnp.int32),
            "src_mask": jnp.ones((8, 8), jnp.float32),
            "trg_ids": jnp.asarray(r.randint(2, 31, (8, 8)), jnp.int32),
            "trg_mask": jnp.ones((8, 8), jnp.float32)}, mesh)
        txt = step.lower(params, st, batch, jnp.asarray(1.0, jnp.float32),
                         jax.random.key(1)).compile().as_text()
        head = txt.split("entry_computation_layout")[0]
        n_leaves = len(params) + sum(
            len(v) if isinstance(v, dict) else 1 for v in st.values())
        assert head.count("may-alias") >= n_leaves


@pytest.mark.slow
class TestGradientDtype:
    """--gradient-dtype bfloat16 (r5): gradients produced/reduce-scattered
    in bf16, optimizer math still f32. Marian's fp16 gradient-communication
    analogue — the trajectory must stay close to f32 grads, and the ZeRO-1
    reduce-scatter bytes must HALVE."""

    def _run(self, grad_dtype, n_steps=4, vocab=19):
        o = opts().with_(**{"precision": ["bfloat16", "float32"],
                            "gradient-dtype": grad_dtype})
        devices = jax.devices()[:8]
        mesh = M.make_mesh(None, devices)
        model = create_model(o, vocab, vocab)
        params = model.init(jax.random.key(7))
        opt_cfg = OptimizerConfig.from_options(o)
        opt_state = init_state(opt_cfg, params)
        params, opt_state = place(params, opt_state, mesh)
        step = build_train_step(model, opt_cfg, LRSchedule.from_options(o),
                                "ce-mean-words", mesh, params, opt_state,
                                delay=1, donate=False,
                                grad_dtype=grad_dtype)
        losses = []
        for i in range(n_steps):
            b = M.shard_batch(batch(vocab, seed=i), mesh)
            params, opt_state, metrics = step(
                params, opt_state, b, jnp.asarray(i + 1, jnp.float32),
                jax.random.key(0))
            losses.append(float(metrics["ce_sum"]) / float(metrics["labels"]))
        lowered = step.lower(params, opt_state,
                             M.shard_batch(batch(vocab, seed=0), mesh),
                             jnp.asarray(1.0, jnp.float32), jax.random.key(0))
        return losses, lowered.as_text()

    def test_bf16_grads_close_trajectory_and_bf16_reduce_scatter(self):
        import re
        l32, txt32 = self._run("float32")
        l16, txt16 = self._run("bfloat16")
        # same data, same init: trajectories agree to bf16 rounding of the
        # gradient signal (the compute path is bf16 in BOTH runs)
        np.testing.assert_allclose(l32, l16, rtol=3e-2)
        # the program-level collective dtype IS the wire dtype on TPU
        # (bf16 collectives are native; the CPU test backend legalizes
        # them back to f32 post-partitioning, so the COMPILED text can't
        # be pinned here — program-level stablehlo can)
        def rs_dtypes(txt):
            return set(re.findall(
                r"reduce_scatter.*?\(tensor<[^>]*?x(bf16|f32)>\)", txt,
                re.S))
        assert rs_dtypes(txt32) == {"f32"}
        assert rs_dtypes(txt16) == {"bf16"}

    def test_f32_precision_refuses_bf16_grads(self):
        # f32 compute + bf16 grads would silently change the compute dtype
        # (the pre-cast makes model.loss's cast an identity) — the
        # machinery must warn and fall back to f32 grads
        from marian_tpu.parallel.zero import _GradMachinery
        o = opts()  # f32 precision
        vocab = 19
        model = create_model(o, vocab, vocab)
        params = model.init(jax.random.key(7))
        mesh = M.make_mesh(None, jax.devices()[:1])
        m = _GradMachinery(model, mesh, params, grad_dtype="bfloat16")
        assert m.grad_dtype is None


class TestGradientDtypeFailClosed:
    """The compute-dtype safety check fails CLOSED: a model whose compute
    dtype cannot be determined (no model.cfg) must not silently get bf16
    grads applied — it could be an f32-precision model (ISSUE 1
    satellite)."""

    def test_undeterminable_compute_dtype_forces_f32_grads(self):
        from marian_tpu.parallel.zero import _GradMachinery

        class NoCfgModel:          # e.g. a custom/legacy model family
            pass

        params = {"w": jnp.zeros((4, 4), jnp.float32)}
        mesh = M.make_mesh(None, jax.devices()[:1])
        m = _GradMachinery(NoCfgModel(), mesh, params,
                           grad_dtype="bfloat16")
        assert m.grad_dtype is None
