"""Pallas streaming fused softmax-CE kernel (ops/pallas/fused_ce.py) —
the round-2 headline perf kernel, here pinned directly: kernel vs dense
cross_entropy equivalence (values AND gradients, interpret mode on CPU),
and the end-to-end --fused-ce on/off loss parity through the real model
path. Previously only exercised implicitly on a TPU backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.ops.ops import cross_entropy
from marian_tpu.ops.pallas.fused_ce import fused_available, fused_softmax_xent


@pytest.fixture
def rng():
    return np.random.RandomState(5)


class TestKernelEquivalence:
    def _setup(self, rng, n=12, e=24, v=70):
        x = jnp.asarray(rng.randn(n, e), jnp.float32)
        w = jnp.asarray(rng.randn(v, e) * 0.1, jnp.float32)
        b = jnp.asarray(rng.randn(v) * 0.1, jnp.float32)
        labels = jnp.asarray(rng.randint(0, v, n), jnp.int32)
        return x, w, b, labels

    def test_available_in_interpret_mode_any_dim(self):
        assert fused_available(24, interpret=True)

    @pytest.mark.parametrize("eps", [0.0, 0.1])
    def test_values_match_dense_ce(self, rng, eps):
        x, w, b, labels = self._setup(rng)
        logits = x @ w.T + b
        want = cross_entropy(logits, labels, eps)
        got = fused_softmax_xent(x, w, b, labels, eps, block_n=8,
                                 block_v=32, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_dense_ce(self, rng):
        """The custom VJP (two-pass blockwise backward) must produce the
        same dx/dw/db as autodiff through the dense logits."""
        x, w, b, labels = self._setup(rng)

        def dense(x, w, b):
            return cross_entropy(x @ w.T + b, labels, 0.1).sum()

        def fused(x, w, b):
            return fused_softmax_xent(x, w, b, labels, 0.1, block_n=8,
                                      block_v=32, interpret=True).sum()

        gd = jax.grad(dense, argnums=(0, 1, 2))(x, w, b)
        gf = jax.grad(fused, argnums=(0, 1, 2))(x, w, b)
        for d, f, name in zip(gd, gf, "x w b".split()):
            np.testing.assert_allclose(np.asarray(f), np.asarray(d),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"d{name}")


class TestEndToEnd:
    def test_model_loss_parity_on_off(self, rng):
        """--fused-ce on (interpret on CPU) vs off through the REAL
        model.loss path: same loss to float tolerance."""
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, 64, (4, 5)), jnp.int32),
            "src_mask": jnp.ones((4, 5), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, 64, (4, 6)), jnp.int32),
            "trg_mask": jnp.ones((4, 6), jnp.float32),
        }
        losses = {}
        for mode in ("on", "off"):
            opts = Options({"type": "transformer", "dim-emb": 16,
                            "transformer-heads": 2,
                            "transformer-dim-ffn": 32,
                            "enc-depth": 1, "dec-depth": 1,
                            "tied-embeddings-all": True,
                            "label-smoothing": 0.1,
                            "precision": ["float32", "float32"],
                            "max-length": 16, "fused-ce": mode})
            model = create_model(opts, 64, 64)
            params = model.init(jax.random.key(4))
            total, aux = model.loss(params, batch, None, train=False)
            losses[mode] = float(total)
        assert losses["on"] == pytest.approx(losses["off"], rel=1e-5)