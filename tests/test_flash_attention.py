"""Pallas flash attention vs the dense reference path.

Runs in interpreter mode on CPU (conftest forces JAX_PLATFORMS=cpu); the same
kernels compile through Mosaic on TPU. Mirrors the reference's operator-parity
test tier (src/tests/units/attention_tests.cpp): small-tensor agreement
between two independent implementations, plus autodiff agreement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.ops.attention import (attention, causal_mask, combine_masks,
                                      dense_attention)
from marian_tpu.ops.pallas.flash_attention import flash_attention


def _rand(rng, *shape):
    return jnp.asarray(rng.randn(*shape), jnp.float32)


def _kv_mask(rng, b, t):
    m = (rng.rand(b, t) > 0.25).astype(np.float32)
    m[:, 0] = 1.0  # never fully-masked rows
    return jnp.asarray(m)


@pytest.mark.parametrize("tq,tk", [(64, 64), (70, 90), (128, 256), (200, 130)])
def test_flash_matches_dense_padding_mask(rng, tq, tk):
    b, h, dh = 2, 4, 32
    q, k, v = _rand(rng, b, h, tq, dh), _rand(rng, b, h, tk, dh), _rand(rng, b, h, tk, dh)
    m = _kv_mask(rng, b, tk)
    out = flash_attention(q, k, v, kv_mask=m)
    ref = dense_attention(q, k, v, mask=m[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("t", [64, 100, 256])
def test_flash_matches_dense_causal(rng, t):
    b, h, dh = 2, 2, 32
    q, k, v = _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh)
    m = _kv_mask(rng, b, t)
    out = flash_attention(q, k, v, kv_mask=m, causal=True)
    ref = dense_attention(q, k, v,
                          mask=combine_masks(causal_mask(t),
                                             m[:, None, None, :]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_no_mask(rng):
    b, h, t, dh = 2, 2, 96, 16
    q, k, v = _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh)
    out = flash_attention(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(rng, causal):
    b, h, t, dh = 2, 2, 96, 16
    q, k, v = _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh)
    m = _kv_mask(rng, b, t)
    dense_mask = combine_masks(causal_mask(t) if causal else None,
                               m[:, None, None, :])

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, kv_mask=m, causal=causal) ** 2).sum()

    def f_dense(q, k, v):
        return (dense_attention(q, k, v, mask=dense_mask) ** 2).sum()

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_flash_under_jit_and_vmapless_batch(rng):
    b, h, t, dh = 2, 2, 128, 32
    q, k, v = _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh)
    m = _kv_mask(rng, b, t)
    fn = jax.jit(lambda q, k, v: flash_attention(q, k, v, kv_mask=m,
                                                 causal=True))
    out = fn(q, k, v)
    ref = dense_attention(q, k, v,
                          mask=combine_masks(causal_mask(t),
                                             m[:, None, None, :]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_dispatcher_selects_flash_and_dense(rng):
    b, h, t, dh = 1, 2, 64, 16
    q, k, v = _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh)
    m = _kv_mask(rng, b, t)
    # flash "on": weights slot must be None
    out_f, w = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                         flash="on")
    assert w is None
    # flash "off": dense path
    out_d, _ = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                         flash="off")
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-5, atol=2e-5)
    # return_weights forces dense even when flash requested
    _, w2 = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                      flash="on", return_weights=True)
    assert w2 is not None


def test_bf16_inputs(rng):
    b, h, t, dh = 2, 2, 128, 32
    q = jnp.asarray(rng.randn(b, h, t, dh), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, t, dh), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, t, dh), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, mask=causal_mask(t))
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               rtol=2e-2, atol=2e-2)


class TestBlockEnvOverrides:
    """MARIAN_FLASH_BLOCK_Q/K sweep overrides: malformed values fall back
    to the 512/2048 defaults with a warning instead of raising at trace
    time, and block_k is clamped (halved) for heads wider than the
    dh=64 the defaults were validated at (ISSUE 1 satellite)."""

    def test_env_block_parses_and_falls_back(self):
        from marian_tpu.ops.pallas.flash_attention import _env_block
        import os
        for bad in ("banana", "12.5", "-64", "0", " "):
            os.environ["MARIAN_FLASH_BLOCK_Q"] = bad
            try:
                assert _env_block("MARIAN_FLASH_BLOCK_Q", 512) == 512
            finally:
                del os.environ["MARIAN_FLASH_BLOCK_Q"]
        os.environ["MARIAN_FLASH_BLOCK_Q"] = "256"
        try:
            assert _env_block("MARIAN_FLASH_BLOCK_Q", 512) == 256
        finally:
            del os.environ["MARIAN_FLASH_BLOCK_Q"]
        assert _env_block("MARIAN_FLASH_BLOCK_Q", 512) == 512  # unset

    def test_malformed_env_does_not_break_trace(self, rng, monkeypatch):
        monkeypatch.setenv("MARIAN_FLASH_BLOCK_Q", "not-a-number")
        monkeypatch.setenv("MARIAN_FLASH_BLOCK_K", "")
        q = _rand(rng, 1, 2, 16, 8)
        k = _rand(rng, 1, 2, 16, 8)
        v = _rand(rng, 1, 2, 16, 8)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_wide_head_runs_with_halved_default_k_block(self, rng):
        # dh=128 > 64: the default k block is halved (VMEM headroom);
        # numerics must be unchanged
        q = _rand(rng, 1, 1, 16, 128)
        k = _rand(rng, 1, 1, 16, 128)
        v = _rand(rng, 1, 1, 16, 128)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
