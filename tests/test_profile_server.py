"""--profile-server: live jax.profiler endpoint on a running job
(common/profiling.py::maybe_start_profile_server — SURVEY §5 tracing
row's 'trace server' answer to attaching nvprof to a running trainer)."""

import pytest

from marian_tpu.common import Options
from marian_tpu.common.profiling import maybe_start_profile_server


def test_off_by_default_and_zero_is_off():
    assert maybe_start_profile_server(Options({})) is False
    assert maybe_start_profile_server(
        Options({"profile-server": 0})) is False


def test_starts_on_port(monkeypatch):
    import jax
    calls = []
    monkeypatch.setattr(jax.profiler, "start_server",
                        lambda port: calls.append(port))
    assert maybe_start_profile_server(
        Options({"profile-server": 19878})) is True
    assert calls == [19878]


def test_start_failure_degrades_to_warning(monkeypatch):
    import jax

    def boom(port):
        raise OSError("address in use")

    monkeypatch.setattr(jax.profiler, "start_server", boom)
    # diagnostics must never kill training: False, no raise
    assert maybe_start_profile_server(
        Options({"profile-server": 19879})) is False