"""ULR embeddings + pretrained embedding import (reference:
src/layers/embedding.cpp :: ULREmbedding / Embedding-with-embFile)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.layers.embedding_io import (load_word2vec, load_word2vec_raw,
                                            normalize_rows)
from marian_tpu.models.encoder_decoder import create_model

from test_model import fake_batch


@pytest.fixture
def rng():
    return np.random.RandomState(13)


@pytest.fixture
def vocab():
    return DefaultVocab.build(["aa bb cc dd ee ff gg hh"])


def _write_vec(path, words, dim, rng, header=True):
    with open(path, "w") as fh:
        if header:
            fh.write(f"{len(words)} {dim}\n")
        for w in words:
            fh.write(w + " " + " ".join(
                f"{v:.4f}" for v in rng.randn(dim)) + "\n")


class TestWord2Vec:
    def test_load_maps_by_vocab_id(self, tmp_path, vocab, rng):
        p = tmp_path / "v.vec"
        _write_vec(str(p), ["bb", "dd", "zz"], 8, rng)
        tab = load_word2vec(str(p), vocab, 8)
        assert tab.shape == (len(vocab), 8)
        assert np.abs(tab[vocab["bb"]]).sum() > 0
        assert np.abs(tab[vocab["aa"]]).sum() == 0     # not in file
        # unknown file word 'zz' must NOT clobber the UNK row
        assert np.abs(tab[1]).sum() == 0

    def test_raw_and_normalize(self, tmp_path, rng):
        p = tmp_path / "k.vec"
        _write_vec(str(p), ["u1", "u2", "u3"], 4, rng, header=False)
        words, mat = load_word2vec_raw(str(p))
        assert words == ["u1", "u2", "u3"] and mat.shape == (3, 4)
        n = normalize_rows(mat)
        np.testing.assert_allclose(np.linalg.norm(n, axis=1), 1.0,
                                   rtol=1e-5)


class TestULR:
    def _model(self, tmp_path, vocab, rng, **over):
        qf = tmp_path / "q.vec"
        kf = tmp_path / "k.vec"
        _write_vec(str(qf), ["aa", "bb", "cc", "dd"], 6, rng)
        _write_vec(str(kf), [f"u{i}" for i in range(5)], 6, rng)
        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "max-length": 32, "ulr": True,
            "ulr-query-vectors": str(qf), "ulr-keys-vectors": str(kf),
            "ulr-softmax-temperature": 0.5, **over,
        })
        model = create_model(opts, vocab, vocab)
        return model, model.init(jax.random.key(0))

    def test_params_and_forward(self, tmp_path, vocab, rng):
        model, params = self._model(tmp_path, vocab, rng)
        assert params["ulr_Q"].shape == (len(vocab), 6)
        assert params["ulr_K"].shape == (5, 6)
        assert params["ulr_A"].shape == (6, 6)
        assert params["ulr_Wu"].shape == (5, 16)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=len(vocab))
        total, aux = model.loss(params, batch, key=None, train=False)
        assert np.isfinite(float(total))

    def test_ulr_changes_embeddings(self, tmp_path, vocab, rng):
        model, params = self._model(tmp_path, vocab, rng)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=len(vocab))
        l1, _ = model.loss(params, batch, key=None, train=False)
        p2 = dict(params)
        p2["ulr_Wu"] = params["ulr_Wu"] + 1.0
        l2, _ = model.loss(p2, batch, key=None, train=False)
        assert float(l1) != float(l2)

    def test_fixed_tables_frozen_in_training(self, tmp_path, vocab, rng):
        from marian_tpu.training.graph_group import GraphGroup
        qf = tmp_path / "q.vec"; kf = tmp_path / "k.vec"
        _write_vec(str(qf), ["aa", "bb"], 6, rng)
        _write_vec(str(kf), [f"u{i}" for i in range(4)], 6, rng)
        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "learn-rate": 0.1, "optimizer": "adam", "clip-norm": 0.0,
            "cost-type": "ce-mean-words", "max-length": 32,
            "ulr": True, "ulr-query-vectors": str(qf),
            "ulr-keys-vectors": str(kf),
        })
        model = create_model(opts, vocab, vocab)
        gg = GraphGroup(model, opts)
        gg.initialize(jax.random.key(0))
        q0 = np.asarray(gg.params["ulr_Q"]).copy()
        a0 = np.asarray(gg.params["ulr_A"]).copy()
        wu0 = np.asarray(gg.params["ulr_Wu"]).copy()
        batch = fake_batch(rng, b=8, ts=5, tt=6, vocab=len(vocab))
        gg.update(dict(batch), 1, jax.random.key(1))
        np.testing.assert_array_equal(np.asarray(gg.params["ulr_Q"]), q0)
        np.testing.assert_array_equal(np.asarray(gg.params["ulr_A"]), a0)
        assert not np.allclose(np.asarray(gg.params["ulr_Wu"]), wu0)

    def test_missing_vectors_raise(self, vocab):
        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "max-length": 32, "ulr": True,
        })
        model = create_model(opts, vocab, vocab)
        with pytest.raises(ValueError, match="ulr-query-vectors"):
            model.init(jax.random.key(0))


class TestEmbeddingVectorsCLI:
    def test_train_with_pretrained_vectors(self, tmp_path, rng):
        from marian_tpu.cli import marian_train
        from marian_tpu.common import io as mio
        src_lines = ["aa bb cc", "bb cc dd", "cc dd aa", "dd aa bb"] * 3
        trg_lines = ["x y z", "y z w", "z w x", "w x y"] * 3
        (tmp_path / "t.src").write_text("\n".join(src_lines) + "\n")
        (tmp_path / "t.trg").write_text("\n".join(trg_lines) + "\n")
        vec = tmp_path / "src.vec"
        _write_vec(str(vec), ["aa", "bb", "cc", "dd"], 16, rng)
        model = str(tmp_path / "m.npz")
        marian_train.main([
            "--type", "transformer",
            "--train-sets", str(tmp_path / "t.src"), str(tmp_path / "t.trg"),
            "--vocabs", str(tmp_path / "v.s.yml"), str(tmp_path / "v.t.yml"),
            "--model", model, "--dim-emb", "16",
            "--transformer-heads", "2", "--transformer-dim-ffn", "32",
            "--enc-depth", "1", "--dec-depth", "1",
            "--precision", "float32", "float32",
            "--embedding-vectors", str(vec),
            "--embedding-fix-src", "--embedding-normalization",
            "--mini-batch", "8", "--learn-rate", "0.01",
            "--after-batches", "4", "--disp-freq", "2u",
            "--save-freq", "100u", "--seed", "1", "--max-length", "20",
            "--quiet", "--cost-type", "ce-mean-words", "--overwrite",
        ])
        params, _ = mio.load_model(model)
        emb = params["encoder_Wemb"] if "encoder_Wemb" in params \
            else params["Wemb"]
        from marian_tpu.data.vocab import DefaultVocab
        v = DefaultVocab.load(str(tmp_path / "v.s.yml"))
        # fixed + normalized pretrained row survived training unchanged
        row = np.asarray(emb[v["aa"]], np.float32)
        np.testing.assert_allclose(np.linalg.norm(row), 1.0, rtol=1e-4)
