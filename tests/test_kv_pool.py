"""Paged KV-cache pool (ops/pallas/kv_pool.py — ISSUE 10): allocator
semantics, the one-scatter insert, and BITWISE interpret-mode parity of
the paged attention read against the dense decode path it replaces —
including rows that joined mid-decode (younger positions) and a row
that freed its pages early (inactive slot)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from marian_tpu.ops import auto_tuner
from marian_tpu.ops.pallas.decode_attention import decode_attention
from marian_tpu.ops.pallas.decode_attention import _reference as dense_ref
from marian_tpu.ops.pallas.kv_pool import (DEFAULT_PAGE_LEN, KVPool,
                                           PoolExhausted, ROW_BUCKETS,
                                           bucket_rows, pages_for_tokens,
                                           paged_decode_attention,
                                           pool_insert)


# ---------------------------------------------------------------------------
# allocator + bucket tables
# ---------------------------------------------------------------------------

class TestKVPoolAllocator:
    def test_page_zero_reserved_and_counts(self):
        p = KVPool(9, page_len=4)
        assert p.usable_pages == 8
        assert p.free_pages() == 8
        got = p.claim("a", 3)
        assert 0 not in got and len(got) == 3
        assert p.free_pages() == 5 and p.used_pages() == 3

    def test_all_or_nothing_and_exhaustion(self):
        p = KVPool(5, page_len=4)          # 4 usable
        p.claim("a", 3)
        with pytest.raises(PoolExhausted):
            p.claim("b", 2)                # only 1 free: nothing granted
        assert p.free_pages() == 1
        assert p.release("a") == 3
        assert p.free_pages() == 4
        # releasing an unknown owner is LOUD (ISSUE 15): the caller's
        # bookkeeping has already diverged from the pool's
        with pytest.raises(ValueError, match="holds no pages"):
            p.release("ghost")

    def test_oversized_claim_names_the_table_bound(self):
        p = KVPool(64, page_len=4, max_pages_per_row=4)
        with pytest.raises(PoolExhausted):
            p.claim("a", 5)

    def test_double_claim_refused(self):
        p = KVPool(8, page_len=4)
        p.claim("a", 1)
        with pytest.raises(ValueError):
            p.claim("a", 1)

    def test_claim_release_reclaim_is_deterministic(self):
        """Replay determinism: the same claim/release schedule yields
        the same physical pages (the join/evict replay test upstream
        relies on it)."""
        def schedule():
            p = KVPool(9, page_len=4)
            seq = [tuple(p.claim("a", 2)), tuple(p.claim("b", 3))]
            p.release("a")
            seq.append(tuple(p.claim("c", 2)))
            return seq
        assert schedule() == schedule()

    def test_bucket_and_page_math(self):
        assert pages_for_tokens(1, 16) == 1
        assert pages_for_tokens(16, 16) == 1
        assert pages_for_tokens(17, 16) == 2
        assert bucket_rows(1) == 1
        assert bucket_rows(3) == 4
        assert bucket_rows(9, (2, 8, 32)) == 32
        # past the largest bucket, the largest caps it
        assert bucket_rows(10_000) == ROW_BUCKETS[-1]

    def test_auto_tuner_registry_entry(self):
        assert auto_tuner.kv_pool_max_tokens(64) == 2048
        # dh-halving convention shared with the other kernels
        assert auto_tuner.kv_pool_max_tokens(128) == 1024


class TestTransferEdgeCases:
    """ISSUE 15 satellite: the ``transfer`` edge cases the ownership
    witness exercises — the handoff verb must refuse every shape that
    would silently corrupt the claims table."""

    def test_transfer_to_owner_already_holding_refused(self):
        p = KVPool(9, page_len=4)
        p.claim("row", 2)
        p.claim("cache", 1)
        with pytest.raises(ValueError, match="already holds pages"):
            p.transfer("row", "cache")
        # refused atomically: the source still owns its pages
        assert len(p.pages_of("row")) == 2
        assert len(p.pages_of("cache")) == 1
        assert p.audit() == []

    def test_transfer_of_freed_then_reforked_owner_moves_nothing(self):
        """An owner released and its pages recycled to a NEW owner: a
        late transfer of the ORIGINAL owner must move nothing — the
        recycled pages belong to the new lineage now."""
        p = KVPool(9, page_len=4)
        a = p.claim("row", 2)
        p.release("row")
        b = p.claim("refork", 2)
        assert a == b                    # deterministic recycle
        assert p.transfer("row", ("prefix", "v", "k")) == []
        assert p.pages_of(("prefix", "v", "k")) == []
        assert p.pages_of("refork") == b
        assert p.audit() == []

    def test_release_after_transfer_is_loud(self):
        """The references changed hands: a late release of the source
        owner is a ValueError, never a silent no-op that would decref
        the cache's pages out from under it."""
        p = KVPool(9, page_len=4)
        p.claim("row", 2)
        p.transfer("row", ("prefix", "v", "k"))
        with pytest.raises(ValueError, match="transferred away"):
            p.release("row")
        assert len(p.pages_of(("prefix", "v", "k"))) == 2
        assert p.audit() == []

    def test_double_release_is_loud(self):
        p = KVPool(9, page_len=4)
        p.claim("a", 1)
        assert p.release("a") == 1
        with pytest.raises(ValueError, match="released twice"):
            p.release("a")

    def test_zero_page_share_owner_releases_normally(self):
        """An owner holding an EMPTY reference list (a zero-page share,
        the beam reorder's transient-hold shape at a page boundary) is
        a real owner and releases without error."""
        p = KVPool(9, page_len=4)
        p.share("tmp", [], row_cap=False)
        assert p.release("tmp") == 0
        assert p.audit() == []


# ---------------------------------------------------------------------------
# paged attention: bitwise parity vs the dense decode path
# ---------------------------------------------------------------------------

def _build_pool(rng, R, H, dh, PL, MP, pos):
    """A dense per-row cache and the equivalent paged pool holding the
    same history (row r has pos[r] written positions)."""
    L = PL * MP
    ck = np.zeros((R, H, L, dh), np.float32)
    cv = np.zeros((R, H, L, dh), np.float32)
    for r in range(R):
        n = max(0, pos[r])
        ck[r, :, :n] = rng.randn(H, n, dh)
        cv[r, :, :n] = rng.randn(H, n, dh)
    P = 1 + R * MP
    table = np.zeros((R, MP), np.int32)
    pk = np.zeros((P, H, PL, dh), np.float32)
    pv = np.zeros((P, H, PL, dh), np.float32)
    nxt = 1
    for r in range(R):
        for j in range(MP):
            table[r, j] = nxt
            pk[nxt] = ck[r, :, j * PL:(j + 1) * PL]
            pv[nxt] = cv[r, :, j * PL:(j + 1) * PL]
            nxt += 1
    return ck, cv, table, pk, pv


class TestPagedDecodeParity:
    R, H, dh, PL, MP = 5, 2, 8, 4, 4

    def _case(self, rng, pos):
        R, H, dh, PL, MP = self.R, self.H, self.dh, self.PL, self.MP
        q = jnp.asarray(rng.randn(R, H, 1, dh), jnp.float32)
        kn = jnp.asarray(rng.randn(R, H, 1, dh), jnp.float32)
        vn = jnp.asarray(rng.randn(R, H, 1, dh), jnp.float32)
        ck, cv, table, pk, pv = _build_pool(rng, R, H, dh, PL, MP, pos)
        return q, kn, vn, ck, cv, table, pk, pv

    # per-row positions: row 1 JOINED MID-DECODE (pos 0 while its
    # neighbors are deep in), row 4 near a page boundary
    POS = np.array([7, 0, 15, 3, 11], np.int32)

    def test_kernel_bitwise_vs_dense_reference(self, rng):
        q, kn, vn, ck, cv, table, pk, pv = self._case(rng, self.POS)
        pos = jnp.asarray(self.POS)
        out, nk, nv = paged_decode_attention(
            q, kn, vn, jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(table), pos, interpret=True)
        ro, rk, rv = dense_ref(q, kn, vn, jnp.asarray(ck),
                               jnp.asarray(cv), pos, None,
                               1.0 / self.dh ** 0.5)
        # BITWISE: the paged kernel assembles the row in VMEM scratch
        # and then runs the dense op order verbatim
        assert (np.asarray(out) == np.asarray(ro)).all()
        # every live cache position matches the dense cache bitwise
        # (including this step's inserted token)
        for r in range(self.R):
            for t in range(self.POS[r] + 1):
                j, off = t // self.PL, t % self.PL
                assert (np.asarray(nk)[table[r, j], :, off]
                        == np.asarray(rk)[r, :, t]).all()
                assert (np.asarray(nv)[table[r, j], :, off]
                        == np.asarray(rv)[r, :, t]).all()

    def test_reference_fallback_bitwise(self, rng):
        """Past the VMEM token cap the jnp gather fallback must be
        bitwise-identical to the kernel's output too. The registry
        floors at one 64-wide block, so the span must exceed 64."""
        R, H, dh, PL, MP = 3, 2, 8, 16, 8          # span 128 > floor 64
        pos = np.array([7, 40, 100], np.int32)
        q = jnp.asarray(rng.randn(R, H, 1, dh), jnp.float32)
        kn = jnp.asarray(rng.randn(R, H, 1, dh), jnp.float32)
        vn = jnp.asarray(rng.randn(R, H, 1, dh), jnp.float32)
        _, _, table, pk, pv = _build_pool(rng, R, H, dh, PL, MP, pos)
        args = (q, kn, vn, jnp.asarray(pk), jnp.asarray(pv),
                jnp.asarray(table), jnp.asarray(pos))
        out_k, _, _ = paged_decode_attention(*args, interpret=True)
        orig = dict(auto_tuner.KERNEL_BLOCKS["kv_pool"])
        try:
            auto_tuner.KERNEL_BLOCKS["kv_pool"]["max_tokens"] = 8
            assert auto_tuner.kv_pool_max_tokens(dh) < MP * PL
            out_f, _, _ = paged_decode_attention(*args, interpret=True)
        finally:
            auto_tuner.KERNEL_BLOCKS["kv_pool"].update(orig)
        assert (np.asarray(out_k) == np.asarray(out_f)).all()

    def test_vs_dense_kernel_vector_pos(self, rng):
        """Against the dense KERNEL with the same per-row positions:
        cache CONTENTS bitwise (both materialize the same next-step
        state); outputs allclose (the dense kernel's own output is
        1-2 ulp from its reference — repo precedent, see
        test_decode_attention)."""
        q, kn, vn, ck, cv, table, pk, pv = self._case(rng, self.POS)
        pos = jnp.asarray(self.POS)
        op, nk, nv = paged_decode_attention(
            q, kn, vn, jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(table), pos, interpret=True)
        od, dk, dv = decode_attention(q, kn, vn, jnp.asarray(ck),
                                      jnp.asarray(cv), pos,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(op), np.asarray(od),
                                   rtol=2e-5, atol=2e-5)
        for r in range(self.R):
            for t in range(self.POS[r] + 1):
                j, off = t // self.PL, t % self.PL
                assert (np.asarray(nk)[table[r, j], :, off]
                        == np.asarray(dk)[r, :, t]).all()

    def test_early_freed_row_is_inactive_and_deterministic(self, rng):
        """A row that freed its pages early (pos = -1, table -> trash
        page): no pool write outside the trash page, and the whole step
        is deterministic across replays (idle-row scatter collisions
        write identical zeros)."""
        pos = np.array([7, -1, 15, 3, 11], np.int32)
        q, kn, vn, ck, cv, table, pk, pv = self._case(rng, pos)
        table[1, :] = 0                        # freed: points at trash
        args = (q, kn, vn, jnp.asarray(pk), jnp.asarray(pv),
                jnp.asarray(table), jnp.asarray(pos))
        o1, k1, v1 = paged_decode_attention(*args, interpret=True)
        o2, k2, v2 = paged_decode_attention(*args, interpret=True)
        assert (np.asarray(o1) == np.asarray(o2)).all()
        assert (np.asarray(k1) == np.asarray(k2)).all()
        assert (np.asarray(v1) == np.asarray(v2)).all()
        # ACTIVE rows still bitwise vs dense, with the freed row gone
        ro, _, _ = dense_ref(q, kn, vn, jnp.asarray(ck),
                             jnp.asarray(cv), jnp.asarray(pos), None,
                             1.0 / self.dh ** 0.5)
        for r in (0, 2, 3, 4):
            assert (np.asarray(o1)[r] == np.asarray(ro)[r]).all()
        # only page 0 (trash) differs from the no-write expectation
        changed = np.nonzero((np.asarray(k1) != pk).any(axis=(1, 2, 3)))[0]
        live = {int(table[r, pos[r] // self.PL])
                for r in (0, 2, 3, 4)} | {0}
        assert set(changed.tolist()) <= live

    def test_pool_insert_places_the_new_token(self, rng):
        pos = np.array([0, 5, 12, 3, 15], np.int32)
        q, kn, vn, ck, cv, table, pk, pv = self._case(rng, pos)
        nk, nv = pool_insert(jnp.asarray(pk), jnp.asarray(pv), kn, vn,
                             jnp.asarray(table), jnp.asarray(pos))
        for r in range(self.R):
            j, off = pos[r] // self.PL, pos[r] % self.PL
            assert (np.asarray(nk)[table[r, j], :, off]
                    == np.asarray(kn)[r, :, 0]).all()
            assert (np.asarray(nv)[table[r, j], :, off]
                    == np.asarray(vn)[r, :, 0]).all()

    def test_default_page_len_sane(self):
        assert DEFAULT_PAGE_LEN >= 1
