"""Multi-tenant fleet serving (marian_tpu/serving/fleet/ — ISSUE 20):
the #model: protocol header, --fleet spec parsing, per-tenant KV-page
accounting + the tenant.page_leak detection drill, FleetManager
warm-on-demand / HBM-budget eviction / per-tenant SLO separation, and
the end-to-end ServingApp fleet contract with stub executors.

Everything runs under JAX_PLATFORMS=cpu with stub factories — no model,
no device; the CI leg scripts/fleet_smoke.py drills the same contract
against a real TCP server with a hot swap under open-loop load.
"""

import asyncio

import pytest

from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.ops.pallas.kv_pool import KVPool
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.admission import Overloaded
from marian_tpu.serving.fleet import accounting
from marian_tpu.serving.fleet.tenancy import (FleetManager, TenantSpec,
                                              UnknownTenant,
                                              parse_fleet_spec, valid_tag)
from marian_tpu.server.server import split_model_header
from marian_tpu.training import bundle as bdl


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """FleetManager._lock + tenant warm locks + the pool lock join the
    running lattice here; the shared conftest witness asserts
    observed ⊆ static at module teardown."""
    yield


def run(coro):
    return asyncio.run(coro)


def commit_bundle(model_path, tag="x", member="m.npz"):
    """One tiny committed bundle via the real commit protocol; the
    member CONTENT length is what the HBM residency estimate reads."""
    def write(p):
        with open(p, "w", encoding="utf-8") as fh:
            fh.write(tag)
    return bdl.write_bundle(str(model_path), {member: write})


def name_factory(calls=None):
    """Executor factory tagging replies ``<model stem>-b<seq>:<line>``
    so tests can prove WHICH tenant's WHICH bundle answered."""
    import os

    def factory(bundle_dir, manifest):
        if calls is not None:
            calls.append(bundle_dir)
        root = os.path.basename(os.path.dirname(os.path.abspath(
            bundle_dir)))
        name = root.split(".")[0]              # m_A.npz.bundles -> m_A
        seq = int(manifest["seq"]) if manifest else 0

        def translate(lines):
            return [f"{name}-b{seq}:{ln}" for ln in lines]
        return translate
    return factory


def make_fleet(tmp_path, tags="ABC", tag_bytes=4, registry=None, **kw):
    """A fleet of tiny committed tenants (one bundle each, member
    content ``tag_bytes`` long so est = tag_bytes * HBM_OVERHEAD)."""
    specs = []
    for t in tags:
        mp = str(tmp_path / f"m_{t}.npz")
        commit_bundle(mp, tag="x" * tag_bytes)
        specs.append(TenantSpec(t, mp))
    kw.setdefault("golden", ["hello"])
    return FleetManager(specs, name_factory(),
                        metrics_registry=registry or msm.Registry(),
                        **kw)


# ---------------------------------------------------------------------------
# #model: protocol header
# ---------------------------------------------------------------------------

class TestModelHeader:
    def test_tag_and_body(self):
        assert split_model_header("#model:en-de\nhello") \
            == ("en-de", "hello")

    def test_no_header_is_payload(self):
        assert split_model_header("hello world") == (None, "hello world")

    def test_domain_style_tags(self):
        assert split_model_header("#model:en-de.legal\nx")[0] \
            == "en-de.legal"

    def test_malformed_tag_is_payload_not_error(self):
        # the usual header discipline: a malformed header line is BODY
        for text in ("#model:\nx", "#model:has space\nx",
                     "#model:" + "a" * 65 + "\nx", "#model:bad/slash\nx"):
            tag, body = split_model_header(text)
            assert tag is None and body == text

    def test_header_without_body(self):
        assert split_model_header("#model:A") == ("A", "")

    def test_stacks_after_trace_before_priority(self):
        # server strips #trace first, then #model, then #priority — here
        # we only pin that #model yields the remaining headers as body
        tag, body = split_model_header("#model:A\n#priority:2\nhi")
        assert tag == "A" and body == "#priority:2\nhi"


# ---------------------------------------------------------------------------
# --fleet spec parsing
# ---------------------------------------------------------------------------

class TestFleetSpec:
    def test_parse(self):
        specs = parse_fleet_spec("A=/m/a.npz, B=/m/b.npz")
        assert [(s.tag, s.model_path) for s in specs] \
            == [("A", "/m/a.npz"), ("B", "/m/b.npz")]

    def test_duplicate_tag_is_hard_error(self):
        with pytest.raises(ValueError, match="duplicate"):
            parse_fleet_spec("A=/m/a.npz,A=/m/b.npz")

    def test_malformed_entry_is_hard_error(self):
        for spec in ("A", "A=", "=x", "bad tag=/m/a.npz"):
            with pytest.raises(ValueError):
                parse_fleet_spec(spec)

    def test_empty_spec_is_hard_error(self):
        with pytest.raises(ValueError, match="no tenants"):
            parse_fleet_spec(" , ")

    def test_valid_tag(self):
        assert valid_tag("en-de.legal_v2")
        assert not valid_tag("")
        assert not valid_tag("a" * 65)
        assert not valid_tag("a/b")


# ---------------------------------------------------------------------------
# per-tenant page accounting (fleet/accounting.py)
# ---------------------------------------------------------------------------

class _Unit:
    def __init__(self, tenant):
        self.tenant = tenant


class _Req:
    def __init__(self, tenant):
        self.req = _Unit(tenant)


class TestAccounting:
    def test_tenant_of_owner_conventions(self):
        assert accounting.tenant_of_owner(_Unit("A")) == "A"
        assert accounting.tenant_of_owner(_Req("B")) == "B"          # .req.tenant
        assert accounting.tenant_of_owner((_Unit("C"), 3, "k")) == "C"
        assert accounting.tenant_of_owner("D/slot-7") == "D"
        assert accounting.tenant_of_owner("untenanted") == ""
        assert accounting.tenant_of_owner(("plain", 1)) == ""

    def test_tenant_page_sums(self):
        sums = accounting.tenant_page_sums({
            "A/r1": [1, 2], "A/r2": [2], "B/r1": [3], "shared": [4]})
        assert sums["A"] == {"refs": 3, "owners": 2}
        assert sums["B"] == {"refs": 1, "owners": 1}
        assert sums[""] == {"refs": 1, "owners": 1}

    def test_cross_tenant_pages(self):
        # same-tenant sharing (beam COW) is legal; cross-tenant is not;
        # untenanted owners (prefix cache) are exempt
        assert accounting.cross_tenant_pages(
            {"A/r1": [1], "A/r2": [1], "shared": [1]}) == []
        bad = accounting.cross_tenant_pages({"A/r1": [1], "B/r1": [1]})
        assert len(bad) == 1 and "page 1" in bad[0]

    def test_audit_tenants_over_and_under(self):
        pool = KVPool(16, page_len=4)
        pool.claim("A/r1", 2)
        pool.claim("B/r1", 1)
        assert accounting.audit_tenants(pool, {"A": 2, "B": 1}) == []
        bad = accounting.audit_tenants(pool, {"A": 3, "B": 1})
        assert len(bad) == 1 and "'A'" in bad[0] and "under" in bad[0]
        bad = accounting.audit_tenants(pool, {"A": 2})
        assert any("'B'" in b and "over" in b for b in bad)

    def test_merge_expected(self):
        exp = accounting.merge_expected(
            [("A", 2), ("A", 3), ("B", 1), ("B", -1)])
        assert exp["A"] == 5 and exp["B"] == 0

    def test_tenant_sums_from_state(self):
        state = {"pages": {
            "1": {"refs": 2, "owners": ["A/r1", "A/r2"]},
            "2": {"refs": 1, "owners": ["B/r1"]},
        }}
        sums = accounting.tenant_sums_from_state(state)
        assert sums["A"] == {"refs": 2, "pages": 1}
        assert sums["B"] == {"refs": 1, "pages": 1}

    def test_check_tenant_isolation_document(self):
        clean = {
            "pages": {"1": {"refs": 1, "owners": ["A/r1"]},
                      "2": {"refs": 1, "owners": ["B/r1"]}},
            "tenants": {"A": {"refs": 1, "owners": 1},
                        "B": {"refs": 1, "owners": 1}},
            "rows": {"slots": [
                {"slot": 0, "owner": "A/r1", "pages": [1]}]},
        }
        assert accounting.check_tenant_isolation(clean) == []
        # (a) recorded tenants block diverges from the page map
        doc = dict(clean, tenants={"A": {"refs": 9, "owners": 1},
                                   "B": {"refs": 1, "owners": 1}})
        assert any("disagrees" in p
                   for p in accounting.check_tenant_isolation(doc))
        # (b) a page whose owner labels span two tenants
        doc = dict(clean, pages={
            "1": {"refs": 2, "owners": ["A/r1", "B/r9"]}})
        assert any("cross-tenant page" in p
                   for p in accounting.check_tenant_isolation(doc))
        # (c) a slot referencing a page owned by another tenant
        doc = dict(clean)
        doc["rows"] = {"slots": [
            {"slot": 0, "owner": "A/r1", "pages": [2]}]}
        assert any("slot 0" in p
                   for p in accounting.check_tenant_isolation(doc))


# ---------------------------------------------------------------------------
# the tenant.page_leak detection drill (ISSUE 20 satellite)
# ---------------------------------------------------------------------------

class TestTenantLeakDrill:
    def test_seeded_leak_caught_by_tenant_auditor_only(self):
        """The mischarged-page bug class: move one page reference from
        tenant A's claim list into tenant B's. No refcount changes, so
        the pool auditor stays green BY CONSTRUCTION — only the
        tenant-level auditor can catch it. The drill proves it does."""
        pool = KVPool(16, page_len=4)
        pool.claim("A/r1", 2)
        pool.claim("B/r1", 1)
        expected = {"A": 2, "B": 1}
        assert accounting.audit_tenants(pool, expected) == []
        with fp.active("tenant.page_leak=fail@*"):
            pool.chaos_tenant_leak()
        # the reference-level auditor CANNOT see the mischarge…
        assert pool.audit() == []
        # …the tenant-level auditor pins it: one tenant short EXACTLY
        # the references the other gained (the whole page reference
        # moved, so no page is cross-tenant — the sums are the tell)
        bad = accounting.audit_tenants(pool, expected)
        assert any("under by 1" in b for b in bad)
        assert any("over by 1" in b for b in bad)

    def test_unarmed_drill_is_a_noop(self):
        pool = KVPool(16, page_len=4)
        pool.claim("A/r1", 1)
        pool.claim("B/r1", 1)
        pool.chaos_tenant_leak()         # no faultpoint armed
        assert accounting.audit_tenants(pool, {"A": 1, "B": 1}) == []

    def test_single_tenant_pool_cannot_leak(self):
        pool = KVPool(16, page_len=4)
        pool.claim("A/r1", 2)
        with fp.active("tenant.page_leak=fail@*"):
            pool.chaos_tenant_leak()     # no second tenant: no-op
        assert accounting.audit_tenants(pool, {"A": 2}) == []


# ---------------------------------------------------------------------------
# FleetManager: warm-on-demand, HBM budget, eviction
# ---------------------------------------------------------------------------

class TestFleetManager:
    def test_warm_on_demand_and_routing(self, tmp_path):
        fleet = make_fleet(tmp_path)
        try:
            st = {r["tenant"]: r for r in fleet.status()["tenants"]}
            assert not any(r["resident"] for r in st.values())
            run_a = fleet.executor_for("A")
            assert run_a(["hi"]) == ["m_A-b1:hi"]
            run_b = fleet.executor_for("B")
            assert run_b(["yo"]) == ["m_B-b1:yo"]
            st = {r["tenant"]: r for r in fleet.status()["tenants"]}
            assert st["A"]["resident"] and st["B"]["resident"]
            assert not st["C"]["resident"] and st["C"]["live"] is None
            assert st["A"]["live"] == "bundle-00000001"
            assert st["A"]["cold_starts"] == 1
            assert fleet.m_cold_starts.labels("A").value == 1
            assert fleet.m_cold_start_s.labels("A").value > 0
            # a second request does NOT cold-start again
            fleet.executor_for("A")(["x"])
            assert fleet.m_cold_starts.labels("A").value == 1
        finally:
            fleet.stop()

    def test_unknown_tenant_raises(self, tmp_path):
        fleet = make_fleet(tmp_path, tags="A")
        try:
            with pytest.raises(UnknownTenant):
                fleet.executor_for("Z")
            assert fleet.live_version_name("Z") == "Z:unknown"
            assert fleet.live_version_name("A") == "A:cold"
        finally:
            fleet.stop()

    def test_evict_coldest_under_hbm_pressure(self, tmp_path):
        """The LRU contract: with room for two tenants, warming the
        third evicts the LEAST RECENTLY ROUTED one — and a shared KV
        pool releases ONLY the victim's page claims, leaving the hot
        tenant's live rows untouched."""
        clk = {"t": 0.0}
        pool = KVPool(16, page_len=4)
        # est per tenant = 4 bytes * HBM_OVERHEAD(2.0) = 8; budget fits 2
        fleet = make_fleet(tmp_path, tag_bytes=4,
                           hbm_budget_bytes=20, kv_pool=pool,
                           clock=lambda: clk["t"])
        try:
            clk["t"] = 1.0
            fleet.executor_for("A")(["a"])
            clk["t"] = 2.0
            fleet.executor_for("B")(["b"])
            pool.claim("A/row-1", 2)     # A's live decode rows
            pool.claim("B/row-1", 1)     # B's
            clk["t"] = 3.0
            fleet.executor_for("A")(["a"])   # A re-used: B is now coldest
            clk["t"] = 4.0
            fleet.executor_for("C")(["c"])   # needs room -> evict B
            st = {r["tenant"]: r for r in fleet.status()["tenants"]}
            assert st["A"]["resident"] and st["C"]["resident"]
            assert not st["B"]["resident"]
            assert fleet.m_evictions.labels("hbm_pressure").value == 1
            assert fleet.m_resident.labels("B").value == 0
            # ONLY B's pages were released; A's live rows are untouched
            assert pool.claims() == {"A/row-1": pool.claims()["A/row-1"]}
            assert len(pool.claims()["A/row-1"]) == 2
            assert accounting.audit_tenants(pool, {"A": 2}) == []
            assert fleet.status()["hbm_resident_bytes"] \
                <= fleet.hbm_budget_bytes
        finally:
            fleet.stop()

    def test_busy_tenant_never_evicted(self, tmp_path):
        """A tenant with an in-flight batch is never a victim: when
        every resident tenant is busy the fleet runs over budget
        LOUDLY instead of deadlocking the cold start."""
        fleet = make_fleet(tmp_path, tags="AB", tag_bytes=4,
                           hbm_budget_bytes=10)   # fits ONE tenant (8)
        try:
            run_a = fleet.executor_for("A")   # in-flight until called
            fleet.executor_for("B")(["b"])    # would need A's room
            st = {r["tenant"]: r for r in fleet.status()["tenants"]}
            assert st["A"]["resident"] and st["B"]["resident"]
            assert st["A"]["inflight_batches"] == 1
            assert fleet.m_evictions.labels("hbm_pressure").value == 0
            assert run_a(["a"]) == ["m_A-b1:a"]   # batch completes fine
        finally:
            fleet.stop()

    def test_status_shape(self, tmp_path):
        fleet = make_fleet(tmp_path, tags="A",
                           hbm_budget_bytes=1 << 20)
        try:
            doc = fleet.status()
            assert doc["hbm_budget_bytes"] == 1 << 20
            assert doc["hbm_overhead_factor"] > 1
            row = doc["tenants"][0]
            for field in ("tenant", "model_path", "resident", "live",
                          "est_bytes", "inflight_batches", "cold_starts",
                          "slo", "pages"):
                assert field in row
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# per-tenant SLOs: one tenant's burn never sheds another's traffic
# ---------------------------------------------------------------------------

class TestFleetSlo:
    def test_tenant_burn_sheds_only_its_own_low_priority(self, tmp_path):
        clk = {"t": 0.0}
        fleet = make_fleet(tmp_path, tags="AB", clock=lambda: clk["t"],
                           brownout_min_priority=1)
        try:
            assert fleet.build_slos(availability=0.999, window_s=10) == 2
            fleet.tick_slos(now=0.0)        # baseline sample
            # tenant A: 50% failures — torches a 99.9% objective;
            # tenant B: clean traffic on the SAME shared series
            for _ in range(50):
                fleet.note_outcome("A", "ok", 0.01)
                fleet.note_outcome("A", "failure", 0.01)
                fleet.note_outcome("B", "ok", 0.01)
            clk["t"] = 1.0
            fleet.tick_slos(now=1.0)
            a, b = fleet.slo_engine("A"), fleet.slo_engine("B")
            assert a.fast_burn() >= a.fast_factor
            assert b.fast_burn() < b.fast_factor
            # A's low-priority lane sheds; its high lane and ALL of B
            # keep serving — tenant A's incident never browns out B
            with pytest.raises(Overloaded):
                fleet.gate("A", priority=0)
            fleet.gate("A", priority=1)
            fleet.gate("B", priority=0)
            assert fleet.m_shed.labels("A", "tenant_brownout").value == 1
            assert fleet.m_shed.labels("B", "tenant_brownout").value == 0
        finally:
            fleet.stop()

    def test_no_engines_no_gate(self, tmp_path):
        fleet = make_fleet(tmp_path, tags="A")
        try:
            fleet.gate("A", priority=0)     # no SLOs built: no shedding
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# end-to-end: ServingApp in --fleet mode (stub executors)
# ---------------------------------------------------------------------------

def make_fleet_app(tmp_path, tags="ABC", registry=None, **opt):
    from marian_tpu.server.server import ServingApp
    models = {}
    for t in tags:
        mp = str(tmp_path / f"m_{t}.npz")
        commit_bundle(mp, tag=t)
        models[t] = mp
    base = {"batch-token-budget": 256, "max-queue": 512,
            "request-timeout": 0.0, "metrics-port": 0,
            "fleet": ",".join(f"{t}={mp}" for t, mp in models.items()),
            "fleet-default-tenant": tags[0]}
    base.update(opt)
    return ServingApp(Options(base), registry=registry or msm.Registry(),
                      executor_factory=name_factory())


class TestFleetServing:
    def test_routing_default_and_unknown(self, tmp_path):
        async def scenario():
            app = make_fleet_app(tmp_path)
            await app.start()
            try:
                # every tenant answers its own tagged traffic
                replies = await asyncio.gather(*[
                    app.handle_text(f"#model:{t}\nhello {i}")
                    for i, t in enumerate("ABCABC")])
                for i, t in enumerate("ABCABC"):
                    assert replies[i] == f"m_{t}-b1:hello {i}"
                # untagged traffic lands on --fleet-default-tenant
                assert await app.handle_text("plain") == "m_A-b1:plain"
                # a well-formed tag naming no tenant is an EXPLICIT
                # error — never a silent wrong-model translation
                r = await app.handle_text("#model:Z\nhello")
                assert r.startswith("!!SERVER-ERROR")
                assert "unknown model tag" in r
            finally:
                await app.shutdown(drain_timeout=5.0)
        run(scenario())

    def test_fleet_metric_census(self, tmp_path):
        """Every fleet series the runbooks page on must exist after
        real traffic — a rename breaks this test before it breaks the
        dashboards (the obs discipline)."""
        reg = msm.Registry()

        async def scenario():
            app = make_fleet_app(tmp_path, registry=reg)
            await app.start()
            try:
                await app.handle_text("#model:B\nhi")
                await app.handle_text("#model:Z\nnope")
            finally:
                await app.shutdown(drain_timeout=5.0)
        run(scenario())
        text = reg.render()
        for series in ("marian_fleet_tenants",
                       "marian_fleet_resident",
                       "marian_fleet_hbm_budget_bytes",
                       "marian_fleet_hbm_resident_bytes",
                       "marian_fleet_request_outcomes_total",
                       "marian_fleet_request_latency_seconds",
                       "marian_fleet_shed_total",
                       "marian_fleet_evictions_total",
                       "marian_fleet_cold_starts_total",
                       "marian_fleet_cold_start_seconds"):
            assert series in text, f"missing fleet series {series}"

    def test_fleetz_document(self, tmp_path):
        async def scenario():
            app = make_fleet_app(tmp_path, tags="AB")
            await app.start()
            try:
                await app.handle_text("#model:A\nhi")
                doc = app.fleet.status()
                rows = {r["tenant"]: r for r in doc["tenants"]}
                assert set(rows) == {"A", "B"}
                assert rows["A"]["resident"]
                assert rows["A"]["live"] == "bundle-00000001"
            finally:
                await app.shutdown(drain_timeout=5.0)
        run(scenario())
