"""scripts/record_bench.py — the harness's artifact recorder. A bug here
silently loses TPU numbers landed in a scarce tunnel-up window, so the
parsing/regeneration contract is pinned."""

import json
import os
import subprocess
import sys

import pytest

SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "record_bench.py")


@pytest.fixture
def repo(tmp_path, monkeypatch):
    # record_bench writes next to its own location's parent — run a COPY
    # in a scratch repo dir so tests never touch the real artifacts
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    with open(SCRIPT) as fh:
        (scripts / "record_bench.py").write_text(fh.read())
    return tmp_path


def _run_in(repo, stage, payload):
    p = repo / "out.json"
    p.write_text(payload)
    return subprocess.run(
        [sys.executable, str(repo / "scripts" / "record_bench.py"),
         stage, str(p)], capture_output=True, text=True)


def test_records_and_regenerates_latest_per_metric_stage(repo):
    r = _run_in(repo, "train",
                '{"metric": "m1", "value": 1.0, "unit": "u"}')
    assert r.returncode == 0, r.stderr
    r = _run_in(repo, "train",
                '{"metric": "m1", "value": 2.0, "unit": "u"}')
    assert r.returncode == 0
    r = _run_in(repo, "scan_off",
                '{"metric": "m1", "value": 3.0, "unit": "u"}')
    assert r.returncode == 0

    hist = (repo / "BENCH_HISTORY.jsonl").read_text().splitlines()
    assert len(hist) == 3
    latest = json.loads((repo / "BENCH_SELF.json").read_text())
    # latest per (metric, stage): train row shows 2.0, scan_off 3.0
    by_stage = {r["stage"]: r["value"] for r in latest}
    assert by_stage == {"train": 2.0, "scan_off": 3.0}
    assert all("ts" in r for r in latest)


def test_tolerates_stderr_noise_and_picks_last_json(repo):
    payload = ("WARNING: axon tunnel flaky\n"
               '{"metric": "old", "value": 0, "unit": "u"}\n'
               "garbage {not json}\n"
               '{"metric": "m", "value": 9.5, "unit": "u"}\n')
    r = _run_in(repo, "s", payload)
    assert r.returncode == 0
    latest = json.loads((repo / "BENCH_SELF.json").read_text())
    assert latest[-1]["metric"] == "m" and latest[-1]["value"] == 9.5


def test_empty_or_metricless_output_fails_loudly(repo):
    assert _run_in(repo, "s", "").returncode == 1
    assert _run_in(repo, "s", '{"no_metric": true}').returncode == 1
    # and neither wrote artifacts
    assert not (repo / "BENCH_SELF.json").exists()

def test_best_annotation_survives_degraded_rerun(repo):
    """A degraded late re-run (the r4 tunnel failure mode) stays the
    LATEST row but must not hide the healthy number: best_value/best_ts
    point back at it."""
    _run_in(repo, "train", '{"metric": "m", "value": 39000.0, "unit": "u"}')
    _run_in(repo, "train",
            '{"metric": "m", "value": 3000.0, "unit": "u", '
            '"final_sync_s": 48.5}')
    rows = json.loads((repo / "BENCH_SELF.json").read_text())
    (row,) = rows
    assert row["value"] == 3000.0             # honest latest
    assert row["best_value"] == 39000.0       # healthy number visible
    assert "best_ts" in row


def test_suspect_and_impossible_mfu_never_best(repo):
    """Rows marked suspect — or with mfu above physical peak, the rule
    applied retroactively to rows predating the marker — are excluded
    from best selection."""
    _run_in(repo, "t", '{"metric": "m", "value": 278000.0, "unit": "u", '
                       '"mfu": 1.79}')                    # pre-marker row
    _run_in(repo, "t", '{"metric": "m", "value": 500000.0, "unit": "u", '
                       '"suspect": "mfu>0.95"}')
    _run_in(repo, "t", '{"metric": "m", "value": 39000.0, "unit": "u", '
                       '"mfu": 0.25}')
    _run_in(repo, "t", '{"metric": "m", "value": 3000.0, "unit": "u", '
                       '"mfu": 0.02}')
    (row,) = json.loads((repo / "BENCH_SELF.json").read_text())
    assert row["value"] == 3000.0
    assert row["best_value"] == 39000.0       # not 278k, not 500k


def test_rebuild_regenerates_without_appending(repo):
    _run_in(repo, "train", '{"metric": "m", "value": 1.0, "unit": "u"}')
    hist = (repo / "BENCH_HISTORY.jsonl").read_text()
    (repo / "BENCH_SELF.json").unlink()
    r = subprocess.run(
        [sys.executable, str(repo / "scripts" / "record_bench.py"),
         "--rebuild"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert (repo / "BENCH_HISTORY.jsonl").read_text() == hist  # no append
    assert json.loads((repo / "BENCH_SELF.json").read_text())


def test_rebuild_without_history_fails_loudly(repo):
    r = subprocess.run(
        [sys.executable, str(repo / "scripts" / "record_bench.py"),
         "--rebuild"], capture_output=True, text=True)
    assert r.returncode == 1
    assert "nothing to rebuild" in r.stderr


def test_stale_fallback_row_refused(repo):
    """bench.py's outage fallback (emit_stale_row) must NOT enter the
    history: it is a re-print of an old measurement, and appending it
    would stamp a fresh ts + this stage's name onto the global-best row
    (corrupting per-stage latest/best). The nonzero rc also makes the
    ladder treat the stage as failed and back off."""
    r = _run_in(repo, "scan_on",
                '{"metric": "m", "value": 43377.3, "unit": "u", '
                '"stale": true, "stale_source_ts": "2026-07-31T05:13:57"}')
    assert r.returncode == 1
    assert "STALE" in r.stderr
    assert not (repo / "BENCH_HISTORY.jsonl").exists()


def test_lower_is_better_metrics_pin_min_as_best(repo):
    _run_in(repo, "t", '{"metric": "decode_latency_ms", "value": 12.0, '
                       '"unit": "ms/sentence"}')
    _run_in(repo, "t", '{"metric": "decode_latency_ms", "value": 8.0, '
                       '"unit": "ms/sentence"}')
    _run_in(repo, "t", '{"metric": "decode_latency_ms", "value": 20.0, '
                       '"unit": "ms/sentence"}')
    (row,) = json.loads((repo / "BENCH_SELF.json").read_text())
    assert row["value"] == 20.0               # latest
    assert row["best_value"] == 8.0           # min, not max
