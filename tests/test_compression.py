"""Train-time compression: model quantizer (--quantize-bits, reference
src/optimizers/quantizer.cpp) and DGC gradient dropping (reference
src/training/gradient_dropping/)."""

import jax
import jax.numpy as jnp
import numpy as np

from marian_tpu.common.options import Options
from marian_tpu.optimizers.compression import (drop_gradients, quantize_model,
                                               quantize_tensor,
                                               zeros_like_tree)
from marian_tpu.optimizers.optimizers import (OptimizerConfig, apply_update,
                                              init_state)


class TestQuantizeTensor:
    def test_levels(self, rng):
        v = jnp.asarray(rng.randn(32, 16), jnp.float32)
        q = np.asarray(quantize_tensor(v, bits=4))
        # at most 2^4-1 distinct magnitude levels (symmetric ±7 + 0)
        assert len(np.unique(np.round(np.abs(q), 7))) <= 8
        assert np.max(np.abs(q - np.asarray(v))) <= float(jnp.max(jnp.abs(v))) / 7 * 0.51 + 1e-6

    def test_log_based(self, rng):
        v = jnp.asarray(rng.randn(16, 16), jnp.float32)
        q = np.asarray(quantize_tensor(v, bits=4, log_based=True))
        s = float(jnp.max(jnp.abs(v)))
        nz = q[q != 0]
        ratios = np.log2(np.abs(nz) / s)
        np.testing.assert_allclose(ratios, np.round(ratios), atol=1e-5)

    def test_opt_steps_reduce_error(self, rng):
        v = jnp.asarray(rng.randn(64, 64), jnp.float32)
        e0 = float(jnp.sum((quantize_tensor(v, 3) - v) ** 2))
        e3 = float(jnp.sum((quantize_tensor(v, 3, opt_steps=3) - v) ** 2))
        assert e3 <= e0 * 1.001


class TestErrorFeedback:
    def test_quantize_model_error_carries(self, rng):
        params = {"W": jnp.asarray(rng.randn(8, 8), jnp.float32),
                  "b": jnp.asarray(rng.randn(1, 8), jnp.float32)}
        err = zeros_like_tree(params)
        q1, e1 = quantize_model(params, err, bits=4)
        # biases untouched by default
        np.testing.assert_array_equal(q1["b"], params["b"])
        np.testing.assert_allclose(np.asarray(q1["W"]) + np.asarray(e1["W"]),
                                   np.asarray(params["W"]), atol=1e-6)

    def test_drop_gradients(self, rng):
        g = {"W": jnp.asarray(rng.randn(100, 10), jnp.float32)}
        r = zeros_like_tree(g)
        g2, r2 = drop_gradients(g, r, drop_rate=0.9)
        kept = np.count_nonzero(np.asarray(g2["W"]))
        assert kept <= 200          # ~10% of 1000 kept (sampled threshold)
        assert kept >= 20
        np.testing.assert_allclose(np.asarray(g2["W"]) + np.asarray(r2["W"]),
                                   np.asarray(g["W"]), atol=1e-6)


class TestOptimizerIntegration:
    def _run_steps(self, opts_dict, n=5, seed=0):
        rs = np.random.RandomState(seed)
        params = {"W": jnp.asarray(rs.randn(16, 16), jnp.float32)}
        cfg = OptimizerConfig.from_options(Options(opts_dict))
        state = init_state(cfg, params)
        step = jax.jit(lambda s, p, g: apply_update(cfg, s, p, g, 0.01))
        for i in range(n):
            g = {"W": jnp.asarray(rs.randn(16, 16), jnp.float32)}
            state, params = step(state, params, g)
        return params, state

    def test_quantized_training_params_on_grid(self):
        params, state = self._run_steps(
            {"optimizer": "adam", "quantize-bits": 4})
        assert "qerr" in state
        w = np.asarray(params["W"])
        assert len(np.unique(np.round(np.abs(w), 7))) <= 8

    def test_gradient_dropping_state(self):
        params, state = self._run_steps(
            {"optimizer": "sgd", "gradient-dropping-rate": 0.99})
        assert "gerr" in state
        assert np.any(np.asarray(state["gerr"]["W"]) != 0)

    def test_off_by_default(self):
        params, state = self._run_steps({"optimizer": "adam"})
        assert "qerr" not in state and "gerr" not in state
