"""AutoTuner (reference: src/graph/auto_tuner.h) — per-key implementation
timing + binding, and the flash-attention crossover calibration."""

import time

import jax.numpy as jnp
import numpy as np

from marian_tpu.ops import auto_tuner as at


class TestAutoTuner:
    def test_picks_faster_candidate_and_caches(self):
        tuner = at.AutoTuner(warmup=0, iters=3)
        calls = {"fast": 0, "slow": 0}

        def fast(x):
            calls["fast"] += 1
            return x

        def slow(x):
            calls["slow"] += 1
            time.sleep(0.02)
            return x

        key = ("shape", 64)
        arg = jnp.ones((4,))
        assert tuner.pick(key, {"slow": (slow, (arg,)),
                                "fast": (fast, (arg,))}) == "fast"
        n_fast = calls["fast"]
        # cached: no re-timing on the second query
        assert tuner.pick(key, {"slow": (slow, (arg,)),
                                "fast": (fast, (arg,))}) == "fast"
        assert calls["fast"] == n_fast

    def test_run_calls_winner(self):
        tuner = at.AutoTuner(warmup=0, iters=1)
        out = tuner.run("k", {
            "a": (lambda: jnp.asarray(1.0), ()),
            "b": (lambda: jnp.asarray(2.0), ()),
        })
        assert float(out) in (1.0, 2.0)

    def test_flash_threshold_default_and_rebind(self):
        at._calibrated_threshold = None
        assert at.flash_threshold() == 1024
        assert at.flash_threshold(default=512) == 512
        at._calibrated_threshold = 256
        try:
            assert at.flash_threshold() == 256
        finally:
            at._calibrated_threshold = None

    def test_calibration_runs_and_binds(self):
        """On CPU the Pallas kernel runs interpreted (slow), so calibration
        should pick dense everywhere and bind a beyond-max threshold — the
        point here is that the machinery runs end-to-end."""
        at._calibrated_threshold = None
        try:
            thr = at.calibrate_flash_attention(heads=2, dim_head=8, batch=1,
                                               lengths=(32, 64))
            assert thr >= 32
            assert at.flash_threshold() == thr
        finally:
            at._calibrated_threshold = None
