"""ISSUE 18: the on-device fused beam merge + multi-step beam rounds.

Pins, against the per-step HOST merge (the pre-ISSUE-18 path, kept as
the A/B baseline):
- token AND raw-score parity on mixed-length traffic, single-step and
  multi-step rounds (different caps freeze sentences MID-round — the
  in-scan EOS masks carry frozen hypotheses through remaining steps);
- the flat top-k tie-break EXACTLY (value desc, flat index asc — a
  numpy reference over an engineered all-ties grid);
- shortlist and force-decode parity through the fused path;
- COW safety: the pool auditor runs every round (MARIAN_POOL_AUDIT=1,
  conftest) over state produced by DEVICE-computed retable diffs, and
  a seeded bad diff (beam.diff_corrupt) is proven to be CAUGHT;
- the closed shape set: a warm_grid-warmed fused engine serves mixed
  traffic with ZERO backend compiles in a strict jitwit window;
- the merge/steps option surface (engine clamps + boot validation).

Runs under JAX_PLATFORMS=cpu with the same tiny real transformer as
tests/test_beam_iteration.py."""

import numpy as np
import pytest

from marian_tpu.common import faultpoints as fp
from marian_tpu.common import jitwit
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.ops.pallas.kv_pool import PoolCorruption
from marian_tpu.translator.beam_iteration import (PagedBeamEngine,
                                                  fused_merge)
from marian_tpu.translator.beam_search import NEG_INF
from marian_tpu.translator.decode_features import FeaturePlane

from tests.test_beam_search import tiny_model
from tests.test_decode_features import sl_gen  # noqa: F401  (fixture)


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    yield


@pytest.fixture(scope="module", autouse=True)
def _ownership_witness(ownership_witness):
    """The fused round's roundfresh/cow hold owners ride the same
    claim/share/retable handoffs the witness audits."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _jitwit_witness(jitwit_witness):
    """The beam-scan jit (bstep) compiles here must map to sites the
    static jit model predicts, with no instrumented-key retrace."""
    yield


VOCAB_WORDS = [" ".join(f"w{i}" for i in range(35))]
# mixed lengths on purpose: sentences reach EOS/cap at different step
# counts, so multi-step rounds freeze some sentences mid-scan while
# others keep decoding — the masking the fused path must get right
TEXTS = ["w3 w4 w5", "w6 w7", "w8 w9 w10 w11", "w2 w3",
         "w4 w4 w4 w4 w4"]
K = 3


@pytest.fixture(scope="module")
def tiny():
    vocab = DefaultVocab.build(VOCAB_WORDS)
    model, params, _ = tiny_model(vocab=len(vocab), seed=7,
                                  **{"dec-depth": 2, "enc-depth": 2})
    return model, params, vocab


def make_engine(tiny, registry=None, prefix=None, features=None, **kw):
    model, params, vocab = tiny
    args = dict(beam_size=K, normalize=0.6, max_rows=2 * K, page_len=4,
                src_len_cap=8, max_length_cap=12, registry=registry,
                prefix_cache=prefix, features=features)
    args.update(kw)
    return PagedBeamEngine(model, params, vocab, vocab, **args)


def drive(eng, texts, metas=None):
    outs, infos = {}, {}
    pending = list(enumerate(texts))
    guard = 0
    while pending or not eng.idle():
        joins = []
        while pending and len(joins) < max(1, eng.free_slots()):
            key, text = pending.pop(0)
            if metas is not None:
                joins.append((key, text, metas[key]))
            else:
                joins.append((key, text))
        res = eng.admit_and_step(joins)
        for key, why in res.rejected:
            assert why in ("no_slot", "no_pages"), (key, why)
            pending.insert(0, (key, texts[key]))
        for key in res.pool_evicted:
            pending.insert(0, (key, texts[key]))
        outs.update(dict(res.finished))
        infos.update(res.finished_info)
        guard += 1
        assert guard < 1000, "beam decode failed to converge"
    assert eng.audit(context="test") == []
    return outs, infos


def assert_parity(a_infos, b_infos):
    """Token lists AND raw f32 path scores bitwise equal per sentence."""
    assert set(a_infos) == set(b_infos)
    for k in a_infos:
        assert a_infos[k]["tokens"] == b_infos[k]["tokens"], k
        assert np.float32(a_infos[k]["score"]) \
            == np.float32(b_infos[k]["score"]), k
        assert a_infos[k]["length"] == b_infos[k]["length"], k


# ---------------------------------------------------------------------------
# merge parity: fused vs host, plain / multi-step / shortlist / forced
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def host_baseline(tiny):
    """One host-merge drive of TEXTS — the baseline arm every parity
    test compares against. Module-scoped: the host engine is the A/B
    reference, identical for every test, so building it per test would
    just re-pay its jit warm cost on a 1-core CI box."""
    return drive(make_engine(tiny, merge="host"), TEXTS)


@pytest.fixture(scope="module")
def fused3_run(tiny):
    """One steps=3 fused engine driven over TEXTS once, shared by the
    multi-step parity test and the audit/drain test — same engine,
    same traffic: one asserts what came OUT, the other what the pool
    looks like AFTER."""
    eng = make_engine(tiny, merge="fused", steps_per_round=3)
    o, i = drive(eng, TEXTS)
    return eng, o, i


class TestMergeParity:
    def test_fused_matches_host_single_step(self, tiny, host_baseline):
        """THE merge-parity property: one fused round step produces the
        tokens and raw path scores of the per-sentence host merge, on
        mixed-length traffic (mid-stream joins, staggered finishes)."""
        host_o, host_i = host_baseline
        fused_o, fused_i = drive(make_engine(tiny, merge="fused"), TEXTS)
        assert host_o == fused_o
        assert_parity(host_i, fused_i)

    def test_fused_multistep_matches_host(self, host_baseline,
                                          fused3_run):
        """steps_per_round>1 (the tentpole's whole point — one host
        sync per N tokens): sentences hit EOS at different steps INSIDE
        a round, so the in-scan freeze masks carry frozen hypotheses as
        {EOS: score} candidates through the remaining steps. Output
        must not change by a bit vs the single-step host baseline.
        steps=3 does not divide the tiny cap, so rounds truncate AND
        freeze mid-scan; steps=2 adds no distinct regime (the
        shortlist + diff-safety tests drive it)."""
        host_o, host_i = host_baseline
        _, o, i = fused3_run
        assert host_o == o
        assert_parity(host_i, i)

    def test_fused_shortlist_matches_host(self, tiny, sl_gen):  # noqa: F811
        """Shortlisted rows merge in COORD space on device and map back
        through the block's shortlist in-graph (take_along_axis) — the
        host merge's coord->vocab mapping, fused. EOS sits at coord 0
        by shortlist construction, which the frozen-row candidate
        relies on."""
        plane = FeaturePlane(shortlist_gen=sl_gen, k_static=24)
        host_o, host_i = drive(
            make_engine(tiny, features=plane, merge="host"), TEXTS)
        plane2 = FeaturePlane(shortlist_gen=sl_gen, k_static=24)
        fused_o, fused_i = drive(
            make_engine(tiny, features=plane2, merge="fused",
                        steps_per_round=2), TEXTS)
        assert host_o == fused_o
        assert_parity(host_i, fused_i)

    def test_fused_force_decode_matches_host(self, tiny):
        """The forced-trunk gate is applied per scan step from the
        [rows, steps] forced array (host path reads one step at a
        time); forced scores must carry the TRUE logp either way."""
        _, _, vocab = tiny
        texts = ["w3 w4 w5\tw5 w5", "w6 w7\tw9", "w8 w9 w10 w11"]
        host_o, host_i = drive(
            make_engine(tiny, features=FeaturePlane(force_decode=True),
                        merge="host"), texts)
        fused_o, fused_i = drive(
            make_engine(tiny, features=FeaturePlane(force_decode=True),
                        merge="fused", steps_per_round=2), texts)
        assert host_o == fused_o
        assert_parity(host_i, fused_i)
        forced = vocab.encode("w5 w5", add_eos=False)
        assert fused_i[0]["tokens"][:2] == [int(t) for t in forced]


class TestFusedMergeTieBreak:
    def test_flat_topk_tiebreak_exact(self):
        """fused_merge vs the dense reference sort (-value, flat index
        asc) on a grid ENGINEERED to tie: NEG_INF saturates f32, and
        repeated finite values tie across rows and coords. The winner
        set AND its order must match the numpy reference exactly —
        this is the property that makes fused-vs-host parity hold
        through ties, not just in expectation."""
        import jax.numpy as jnp
        k, width, nb = 3, 7, 2
        rng = np.random.RandomState(5)
        lp = rng.choice([-1.0, -2.0, NEG_INF],
                        size=(nb * k, width)).astype(np.float32)
        score = rng.choice([0.0, -1.0], size=(nb * k,)).astype(np.float32)
        fin = np.zeros((nb * k,), bool)
        fin[1] = True               # one frozen row: {EOS: score} only
        eos_flat = 0
        vals, lanes, coords = fused_merge(
            jnp.asarray(lp), jnp.asarray(score), jnp.asarray(fin),
            k, eos_flat)
        vals, lanes, coords = (np.asarray(vals), np.asarray(lanes),
                               np.asarray(coords))
        for b in range(nb):
            cands = []
            for j in range(k):
                row = b * k + j
                if fin[row]:
                    for c in range(width):
                        cands.append((score[row] if c == eos_flat
                                      else NEG_INF, j * width + c))
                    continue
                for c in range(width):
                    cands.append((np.float32(score[row] + lp[row, c]),
                                  j * width + c))
            cands.sort(key=lambda t: (-t[0], t[1]))
            for i in range(k):
                want_val, want_flat = cands[i]
                assert np.float32(vals[b, i]) == np.float32(want_val), \
                    (b, i)
                assert lanes[b, i] * width + coords[b, i] == want_flat, \
                    (b, i, "tie-break order diverged from the dense "
                     "(-value, flat asc) rule")


# ---------------------------------------------------------------------------
# COW safety over device-computed diffs (satellite: audit + drill)
# ---------------------------------------------------------------------------

class TestDeviceDiffSafety:
    def test_audit_clean_and_pool_drains_after_fused_rounds(
            self, fused3_run):
        """Every round of the shared fused3_run drive already audited
        (conftest arms MARIAN_POOL_AUDIT=1): the device-computed
        retable diffs must keep refcounts, table mirrors and the
        write-target-refcount-1 COW invariant coherent. On exit the
        pool must drain to empty — no page leaked through a
        roundfresh/cow hold."""
        eng, _, _ = fused3_run
        assert eng.pool.free_pages() == eng.pool.usable_pages
        assert eng.pool.owners() == []

    def test_pressure_round_falls_back_to_host_merge(self, tiny):
        """A pool too tight for the WORST-CASE fused preclaim must not
        shed traffic the host path could serve: the round falls back to
        one single-step host-merge round (lazy claims at actual
        demand), and output stays bitwise the unpressured fused run's.
        max_rows=K over a minimal pool reproduces the squeeze: k rows
        at full divergence own the whole pool, so the boundary-round
        preclaim cannot fit. The pool is pinned by pool_bytes to
        max_rows full-cap rows with NO round-preclaim headroom (the
        unsized default adds it since ISSUE 18 — exactly to make this
        fallback rare — so the squeeze needs an explicit sizing, like
        a production --kv-pool-bytes brownout would)."""
        ref = make_engine(tiny, merge="fused", steps_per_round=2)
        tight = make_engine(
            tiny, merge="fused", steps_per_round=2, max_rows=K,
            pool_bytes=ref.page_bytes * K * ref.max_pages)
        o, i = drive(tight, [TEXTS[2]])
        assert tight._counters.get("fused_fallback_rounds", 0) > 0, \
            "the squeeze never hit the fallback — tighten the fixture"
        ref_o, ref_i = drive(ref, [TEXTS[2]])
        assert o == ref_o
        assert_parity(i, ref_i)

    def test_seeded_bad_diff_is_caught(self, tiny):
        """Detection drill (beam.diff_corrupt): one live slot's diff is
        applied TRUNCATED while the engine's table mirror keeps the
        full device row — the bad-device-diff bug class. The per-round
        auditor must catch the divergence in the SAME round, proving
        the table/claim cross-check guards real device-diff application
        (not a mocked report)."""
        eng = make_engine(tiny, merge="fused", steps_per_round=2)
        with fp.active("beam.diff_corrupt=fail@1"):
            with pytest.raises(PoolCorruption, match="pool audit"):
                # enough rounds that at least one sentence continues
                # past its first fused round (the drill site)
                eng.decode_texts(TEXTS[:2])


# ---------------------------------------------------------------------------
# closed shape set (satellite: jitwit strict window over the beam scan)
# ---------------------------------------------------------------------------

class TestClosedShapeSet:
    # steps=3 alone covers both key families: the fused s=3 round keys
    # AND the s=1 pressure-fallback keys the grid must also warm (the
    # steps=1 engine's window is a strict subset of that shape set).
    @pytest.mark.parametrize("steps", [3])
    def test_warmed_fused_engine_zero_postwarm_compiles(self, tiny,
                                                        steps):
        """The beam form of 'compile once, serve forever': warm_grid
        drives every block bucket x encode width, then mixed traffic —
        joins, forks, mid-round freezes, staggered finishes — must
        compile NOTHING (the fused path has no per-round fork jits at
        all: the COW forks live inside the scan)."""
        eng = make_engine(tiny, merge="fused", steps_per_round=steps)
        driven = eng.warm_grid()
        assert driven, "warm_grid drove nothing"
        assert {rb for rb, _, _, _ in driven} == set(eng.row_buckets)
        # fused round keys at the engine's steps, PLUS s=1 keys for the
        # pressure-fallback host rounds (warmed per width so even a
        # pool-squeezed steady-state round compiles nothing)
        assert {s for _, _, s, _ in driven} == {steps, 1}
        for rb in eng.row_buckets:
            assert any(r == rb and s == 1 for r, _, s, _ in driven)
        with jitwit.strict() as w:
            out = eng.decode_texts(TEXTS)
            out2 = eng.decode_texts(TEXTS[1:3])
        assert len(out) == len(TEXTS) and len(out2) == 2
        assert w.compiles == [], (
            "post-warm beam traffic recompiled — the block grid does "
            f"not close the fused engine's shape set: {w.compiles}")

    def test_cold_fused_engine_does_compile(self, tiny):
        """No vacuous pass: the same traffic on a cold fused engine
        does compile, attributed to the beam engine's scan-step site."""
        eng = make_engine(tiny, merge="fused", steps_per_round=2)
        with jitwit.strict() as w:
            eng.decode_texts(TEXTS[:2])
        assert any("translator/beam_iteration.py" in site
                   for site, _ in w.compiles)


# ---------------------------------------------------------------------------
# option surface (satellite: steps/merge validation + clamps)
# ---------------------------------------------------------------------------

class TestOptionSurface:
    def test_bad_merge_value_refused(self, tiny):
        with pytest.raises(ValueError, match="iteration-beam-merge"):
            make_engine(tiny, merge="gpu")

    def test_host_merge_pins_single_step(self, tiny):
        """merge='host' needs the host between steps: the engine clamps
        steps_per_round to 1 rather than silently mis-decoding."""
        eng = make_engine(tiny, merge="host", steps_per_round=4)
        assert eng.steps_per_round == 1 and eng.merge == "host"

    def test_cow_off_and_sampling_force_host_merge(self, tiny):
        """The replication baseline and sampled beams (independent
        trajectories — no k*k grid exists) stay on the host path."""
        eng = make_engine(tiny, cow=False, merge="fused",
                          steps_per_round=3)
        assert eng.merge == "host" and eng.steps_per_round == 1
        plane = FeaturePlane(sampling=("full", 1.0), seed=7)
        eng2 = make_engine(tiny, features=plane, steps_per_round=3)
        assert eng2.merge == "host" and eng2.steps_per_round == 1

    def test_row_buckets_are_block_multiples(self, tiny):
        """Fused mode needs k-aligned blocks: every compiled row bucket
        must be a whole number of sentences."""
        eng = make_engine(tiny)
        assert all(rb % K == 0 for rb in eng.row_buckets)
        assert max(eng.row_buckets) == eng.max_rows

    def test_boot_validator_rejects_host_multistep_beam(self):
        """--iteration-beam-merge host + --iteration-steps>1 + beam>1
        must refuse LOUDLY at boot (the engine would silently clamp;
        the operator asked for a combination that cannot run)."""
        from marian_tpu.server.server import ServingApp
        v = ServingApp._validate_iteration_options

        class Opts(dict):
            def get(self, k, d=None):
                return super().get(k, d)

        def opts(**kw):
            base = {"beam-size": 2, "iteration-steps": 1,
                    "iteration-beam-merge": "fused", "models": ["m"]}
            base.update(kw)
            return Opts(base)

        v(opts())                                      # default: fine
        v(opts(**{"iteration-steps": 4}))              # fused multi: fine
        v(opts(**{"iteration-beam-merge": "host"}))    # host single: fine
        with pytest.raises(ValueError, match="host merge needs"):
            v(opts(**{"iteration-beam-merge": "host",
                      "iteration-steps": 4}))
        with pytest.raises(ValueError, match="iteration-beam-merge"):
            v(opts(**{"iteration-beam-merge": "gpu"}))
        # 0 reads as unset (the codebase-wide `or default` idiom);
        # a NEGATIVE count is unambiguously wrong and must refuse
        with pytest.raises(ValueError, match="iteration-steps"):
            v(opts(**{"iteration-steps": -2}))
