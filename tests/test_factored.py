"""Factored vocabulary + factored softmax tests (config #4 family)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.factored_vocab import FactoredVocab
from marian_tpu.layers.logits import (FactorTables, factored_embed,
                                      factored_log_probs)
from marian_tpu.models.encoder_decoder import create_model

FSV = """\
</s>
<unk>
hello|ci
hello|cn
world|cn
world|ci|gl+
cat|cn
dog|cn
s|gl+
"""


@pytest.fixture
def fsv_path(tmp_path):
    p = tmp_path / "vocab.fsv"
    p.write_text(FSV)
    return str(p)


@pytest.fixture
def fvocab(fsv_path):
    return FactoredVocab.load(fsv_path)


class TestFactoredVocab:
    def test_specials_and_ids(self, fvocab):
        assert fvocab["</s>"] == 0 and fvocab["<unk>"] == 1
        assert len(fvocab) == 9

    def test_groups_and_slices(self, fvocab):
        # groups: c (ci/cn), gl (gl+)
        assert set(fvocab.groups) == {"c", "gl"}
        names = [s[0] for s in fvocab.group_slices]
        assert names[0] == "lemma"
        # slices partition the unit axis (minus PAD)
        total = sum(e - s for _, s, e in fvocab.group_slices)
        assert total == fvocab.n_units - 1

    def test_factor_indices_shape_and_pad(self, fvocab):
        tbl = fvocab.factor_indices
        assert tbl.shape == (len(fvocab), 1 + len(fvocab.groups))
        # '</s>' has no factors: all factor columns PAD
        assert all(tbl[0, 1:] == fvocab.pad_unit)
        # every word's lemma column is a valid lemma unit
        assert (tbl[:, 0] < fvocab.n_lemmas).all()

    def test_encode_capitalization_analysis(self, fvocab):
        ids = fvocab.encode("Hello world", add_eos=False)
        assert ids[0] == fvocab["hello|ci"]
        assert ids[1] == fvocab["world|cn"]

    def test_decode_realizes_caps_and_glue(self, fvocab):
        ids = [fvocab["hello|ci"], fvocab["world|ci|gl+"]]
        assert fvocab.decode(ids) == "HelloWorld"
        ids = [fvocab["cat|cn"], fvocab["s|gl+"]]
        assert fvocab.decode(ids) == "cats"

    def test_unknown_word_is_unk(self, fvocab):
        assert fvocab.encode("zebra", add_eos=False) == [1]


class TestFactoredMath:
    def test_log_probs_are_group_normalized(self, fvocab, rng):
        ft = FactorTables.from_vocab(fvocab)
        units = jnp.asarray(rng.randn(2, ft.n_units), jnp.float32)
        logp = factored_log_probs(units, ft)
        assert logp.shape == (2, len(fvocab))
        # each word's log-prob = sum of its units' group log-probs
        pieces = []
        for _n, s, e in ft.group_slices:
            pieces.append(jax.nn.log_softmax(units[..., s:e]))
        full = np.concatenate([np.asarray(x) for x in pieces] +
                              [np.zeros((2, 1), np.float32)], axis=-1)
        for wid in range(len(fvocab)):
            want = sum(full[:, u] for u in ft.factor_indices[wid]
                       if u != ft.pad_unit)
            np.testing.assert_allclose(np.asarray(logp[:, wid]), want,
                                       rtol=1e-5, atol=1e-5)

    def test_shortlist_slice_matches_full(self, fvocab, rng):
        ft = FactorTables.from_vocab(fvocab)
        units = jnp.asarray(rng.randn(3, ft.n_units), jnp.float32)
        sl = jnp.asarray([0, 2, 5], jnp.int32)
        full = factored_log_probs(units, ft)
        sliced = factored_log_probs(units, ft, shortlist=sl)
        np.testing.assert_allclose(np.asarray(sliced),
                                   np.asarray(full[:, sl]), rtol=1e-6)

    def test_factored_embed_sums_units(self, fvocab, rng):
        ft = FactorTables.from_vocab(fvocab)
        table = jnp.asarray(rng.randn(ft.n_units, 8), jnp.float32)
        wid = fvocab["world|ci|gl+"]
        emb = factored_embed(table, ft, jnp.asarray([[wid]]), jnp.float32)
        units = [u for u in ft.factor_indices[wid] if u != ft.pad_unit]
        want = sum(np.asarray(table[u]) for u in units)
        np.testing.assert_allclose(np.asarray(emb[0, 0]), want, rtol=1e-5)


class TestFactoredModel:
    def _model(self, fvocab, **over):
        base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
                "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
                "tied-embeddings-all": True, "label-smoothing": 0.0,
                "precision": ["float32", "float32"], "max-length": 32}
        base.update(over)
        model = create_model(Options(base), fvocab, fvocab)
        params = model.init(jax.random.key(0))
        return model, params

    def test_embedding_table_sized_by_units(self, fvocab):
        model, params = self._model(fvocab)
        assert params["Wemb"].shape[0] == fvocab.n_units
        assert params["decoder_ff_logit_out_b"].shape[1] == fvocab.n_units

    def test_loss_and_grads(self, fvocab, rng):
        model, params = self._model(fvocab)
        v = len(fvocab)
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, v, (2, 5)), jnp.int32),
            "src_mask": jnp.ones((2, 5), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, v, (2, 6)), jnp.int32),
            "trg_mask": jnp.ones((2, 6), jnp.float32),
        }
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        assert float(jnp.sum(jnp.abs(grads["Wemb"]))) > 0

    def test_teacher_forcing_matches_incremental(self, fvocab, rng):
        model, params = self._model(fvocab)
        v = len(fvocab)
        src = jnp.asarray(rng.randint(2, v, (2, 5)), jnp.int32)
        src_mask = jnp.ones((2, 5), jnp.float32)
        trg = jnp.asarray(rng.randint(2, v, (2, 4)), jnp.int32)
        from marian_tpu.models import transformer as T
        enc = model.encode_for_decode(params, src, src_mask)
        tf = T.decode_train(model.cfg, params, enc, src_mask, trg,
                            jnp.ones((2, 4), jnp.float32), train=False)
        state = model.start_state(params, enc, src_mask, max_len=4)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(4):
            logits, state = model.step(params, state, prev, src_mask)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(tf[:, t]),
                                       rtol=2e-4, atol=2e-4)
            prev = trg[:, t:t + 1]

    def test_beam_search_decodes_factored(self, fvocab, rng):
        from marian_tpu.translator.beam_search import BeamConfig, beam_search_jit
        model, params = self._model(fvocab)
        v = len(fvocab)
        src = jnp.asarray(rng.randint(2, v, (2, 5)), jnp.int32)
        mask = jnp.ones((2, 5), jnp.float32)
        cfg = BeamConfig(beam_size=2, max_length=6)
        tokens, scores, lengths, norm, _, _ws = beam_search_jit(
            model, [params], [1.0], cfg, src, mask)
        assert tokens.shape == (2, 2, 6)
        assert int(tokens.max()) < v
        assert np.all(np.isfinite(np.asarray(norm)))


class TestFactorWeight:
    def test_weight_scales_factor_groups_only(self, fvocab):
        import jax
        ft = FactorTables.from_vocab(fvocab)
        units = jnp.asarray(
            np.random.RandomState(5).randn(2, ft.n_units), jnp.float32)
        base = factored_log_probs(units, ft)
        half = factored_log_probs(units, ft, factor_weight=0.5)
        # lemma-only words (e.g. </s>: all factor cols PAD) are unaffected
        np.testing.assert_allclose(np.asarray(base[:, 0]),
                                   np.asarray(half[:, 0]), rtol=1e-6)
        # factored words shift by half their factor log-prob contribution
        diff = np.asarray(base - half)
        assert np.abs(diff[:, 2:]).max() > 0


class TestConcatFactors:
    """--factors-combine concat + --factors-dim-emb (embedding side)."""

    def _model(self, fvocab, **over):
        base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
                "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
                "tied-embeddings-all": False, "label-smoothing": 0.0,
                "factors-combine": "concat", "factors-dim-emb": 4,
                "precision": ["float32", "float32"], "max-length": 32}
        base.update(over)
        model = create_model(Options(base), fvocab, fvocab)
        params = model.init(jax.random.key(0))
        return model, params

    def test_table_shapes(self, fvocab):
        model, params = self._model(fvocab)
        groups = len(fvocab.groups)
        lemma_dim = 16 - groups * 4
        assert params["encoder_Wemb"].shape == (fvocab.n_lemmas, lemma_dim)
        assert params["encoder_Wemb_factors"].shape == \
            (fvocab.n_units - fvocab.n_lemmas, 4)
        # output stays the unit-axis matrix
        assert params["decoder_ff_logit_out_W"].shape[1] == fvocab.n_units

    def test_embedding_is_concatenation(self, fvocab, rng):
        from marian_tpu.layers.logits import factored_embed_concat
        ft = FactorTables.from_vocab(fvocab)
        groups = len(fvocab.groups)
        lemma_dim = 16 - groups * 4
        lt = jnp.asarray(rng.randn(ft.n_lemmas, lemma_dim), jnp.float32)
        ftb = jnp.asarray(rng.randn(ft.n_units - ft.n_lemmas, 4), jnp.float32)
        wid = fvocab["world|ci|gl+"]
        emb = factored_embed_concat(lt, ftb, ft, jnp.asarray([[wid]]),
                                    jnp.float32)
        assert emb.shape == (1, 1, 16)
        units = ft.factor_indices[wid]
        want = [np.asarray(lt[units[0]])]
        for u in units[1:]:
            want.append(np.zeros(4, np.float32) if u == ft.pad_unit
                        else np.asarray(ftb[u - ft.n_lemmas]))
        np.testing.assert_allclose(np.asarray(emb[0, 0]),
                                   np.concatenate(want), rtol=1e-6)

    def test_trains_and_decodes(self, fvocab, rng):
        model, params = self._model(fvocab)
        v = len(fvocab)
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, v, (2, 5)), jnp.int32),
            "src_mask": jnp.ones((2, 5), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, v, (2, 6)), jnp.int32),
            "trg_mask": jnp.ones((2, 6), jnp.float32),
        }
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        assert float(jnp.sum(jnp.abs(grads["encoder_Wemb_factors"]))) > 0
        from marian_tpu.translator.beam_search import (BeamConfig,
                                                       beam_search_jit)
        tokens, _, _, norm, _, _ws = beam_search_jit(
            model, [params], [1.0], BeamConfig(beam_size=2, max_length=5),
            batch["src_ids"], batch["src_mask"])
        assert np.all(np.isfinite(np.asarray(norm)))

    def test_concat_refuses_tied_and_bad_dims(self, fvocab):
        import pytest as _pt
        with _pt.raises(ValueError, match="tied"):
            self._model(fvocab, **{"tied-embeddings-all": True})
        with _pt.raises(ValueError, match="factors-dim-emb"):
            self._model(fvocab, **{"factors-dim-emb": 8})


class TestLemmaReembedding:
    """--lemma-dim-emb: lemma-conditioned factor prediction."""

    def _model(self, fvocab, lemma_dim=6, **over):
        base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
                "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
                "tied-embeddings-all": True, "label-smoothing": 0.0,
                "lemma-dim-emb": lemma_dim,
                "precision": ["float32", "float32"], "max-length": 32}
        base.update(over)
        model = create_model(Options(base), fvocab, fvocab)
        params = model.init(jax.random.key(0))
        return model, params

    def test_params_exist_and_train(self, fvocab, rng):
        model, params = self._model(fvocab)
        assert params["decoder_lemma_reembed_W"].shape == \
            (fvocab.n_lemmas, 6)
        assert params["decoder_lemma_reembed_Wp"].shape == (6, 16)
        v = len(fvocab)
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, v, (2, 5)), jnp.int32),
            "src_mask": jnp.ones((2, 5), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, v, (2, 6)), jnp.int32),
            "trg_mask": jnp.ones((2, 6), jnp.float32),
        }
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        # the re-embedding participates in the graph
        assert float(jnp.sum(jnp.abs(
            grads["decoder_lemma_reembed_W"]))) > 0

    def test_lemma_scores_unchanged_factors_conditioned(self, fvocab, rng):
        """Lemma log-probs must be identical with/without re-embedding for
        the SAME parameters (the lemma head sees the plain state); factor
        scores must differ (they see the lemma-conditioned state)."""
        from marian_tpu.models import transformer as T
        model, params = self._model(fvocab)
        x = jnp.asarray(rng.randn(2, 3, 16), jnp.float32)
        with_d = T.output_logits(model.cfg, params, x)
        cfg_off = dataclasses.replace(model.cfg, lemma_dim_emb=0)
        without = T.output_logits(cfg_off, params, x)
        ft = model.cfg.trg_factors
        # '</s>' is lemma-only → identical score either way
        np.testing.assert_allclose(np.asarray(with_d[..., 0]),
                                   np.asarray(without[..., 0]),
                                   rtol=1e-5, atol=1e-5)
        # factored words: conditioned factor logits shift the scores
        assert np.abs(np.asarray(with_d - without))[..., 2:].max() > 1e-6

    def test_minus_one_uses_dim_emb(self, fvocab):
        model, params = self._model(fvocab, lemma_dim=-1)
        assert params["decoder_lemma_reembed_W"].shape == \
            (fvocab.n_lemmas, 16)

    def test_requires_factored_target(self, tmp_path):
        import pytest as _pt
        from marian_tpu.data.vocab import DefaultVocab
        plain = DefaultVocab.build(["a b c"])
        with _pt.raises(ValueError, match="factored"):
            create_model(Options({"type": "transformer", "dim-emb": 16,
                                  "lemma-dim-emb": 4,
                                  "transformer-heads": 2}), plain, plain)
