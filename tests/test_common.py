"""Foundation-layer tests: Options, ConfigParser (YAML+CLI+aliases),
SchedulingParameter, npz/bin IO round-trips."""

import numpy as np
import pytest
import yaml

from marian_tpu.common import Options, ConfigParser, parse_options, SchedulingParameter
from marian_tpu.common.scheduling_parameter import SchedulingUnit
from marian_tpu.common import io as mio


class TestOptions:
    def test_get_set_has(self):
        o = Options({"dim-emb": 512})
        assert o.get("dim-emb") == 512
        assert o.get("dim_emb") == 512  # underscore alias
        assert o.has("dim-emb") and not o.has("missing")
        assert o.get("missing", 7) == 7
        with pytest.raises(KeyError):
            o.get("missing")

    def test_with_returns_copy(self):
        o = Options({"a": 1})
        o2 = o.with_(a=2, b=3)
        assert o.get("a") == 1 and o2.get("a") == 2 and o2.get("b") == 3

    def test_yaml_roundtrip(self):
        o = Options({"type": "transformer", "dim-emb": 256, "devices": [0, 1]})
        o2 = Options.from_yaml(o.as_yaml())
        assert o2.as_dict() == o.as_dict()


class TestConfigParser:
    def test_defaults(self):
        opts = ConfigParser("training").parse([])
        assert opts.get("dim-emb") == 512
        assert opts.get("mini-batch") == 64
        assert opts.get("type") == "amun"

    def test_cli_overrides(self):
        opts = ConfigParser("training").parse(
            ["--dim-emb", "1024", "--type", "transformer", "--tied-embeddings-all"])
        assert opts.get("dim-emb") == 1024
        assert opts.get("type") == "transformer"
        assert opts.get("tied-embeddings-all") is True

    def test_config_file_and_cli_precedence(self, tmp_path):
        cfg = tmp_path / "config.yml"
        cfg.write_text(yaml.safe_dump({"dim-emb": 300, "mini-batch": 17}))
        opts = ConfigParser("training").parse(
            ["--config", str(cfg), "--dim-emb", "400"])
        assert opts.get("dim-emb") == 400    # CLI wins
        assert opts.get("mini-batch") == 17  # file wins over default

    def test_task_alias_expansion(self):
        opts = ConfigParser("training").parse(["--task", "transformer-big"])
        assert opts.get("dim-emb") == 1024
        assert opts.get("transformer-dim-ffn") == 4096
        assert opts.get("transformer-heads") == 16
        assert opts.get("tied-embeddings-all") is True
        # CLI overrides alias
        opts = ConfigParser("training").parse(
            ["--task", "transformer-big", "--transformer-heads", "8"])
        assert opts.get("transformer-heads") == 8

    def test_unknown_flag_rejected(self):
        with pytest.raises(SystemExit):
            ConfigParser("training").parse(["--no-such-flag", "1"])

    def test_validation_catches_bad_config(self):
        with pytest.raises(ValueError):
            parse_options(["--type", "transformer", "--dim-emb", "100",
                           "--transformer-heads", "8", "--train-sets", "a", "b"],
                          mode="training")

    def test_dump_config_exits(self, capsys):
        with pytest.raises(SystemExit):
            ConfigParser("training").parse(["--dump-config", "minimal",
                                            "--dim-emb", "128"])
        out = capsys.readouterr().out
        data = yaml.safe_load(out)
        assert data["dim-emb"] == 128


class TestSchedulingParameter:
    def test_parse_units(self):
        assert SchedulingParameter.parse("100u") == SchedulingParameter(100, SchedulingUnit.UPDATES)
        assert SchedulingParameter.parse("10e").unit == SchedulingUnit.EPOCHS
        assert SchedulingParameter.parse("1Mt") == SchedulingParameter(10**6, SchedulingUnit.TRG_LABELS)
        assert SchedulingParameter.parse("16000").n == 16000
        assert SchedulingParameter.parse("500Ku").n == 500_000
        assert not SchedulingParameter.parse("0")
        assert SchedulingParameter.parse(300).n == 300

    def test_str_roundtrip(self):
        for s in ["100u", "10e", "1000000t"]:
            assert str(SchedulingParameter.parse(s)) == s


class TestIO:
    def _params(self):
        rs = np.random.RandomState(0)
        return {
            "encoder_l1_self_Wq": rs.randn(8, 8).astype(np.float32),
            "Wemb": rs.randn(31, 8).astype(np.float32),
            "decoder_ff_logit_out_b": rs.randn(1, 31).astype(np.float32),
        }

    @pytest.mark.parametrize("ext", ["npz", "bin"])
    def test_roundtrip(self, tmp_path, ext):
        path = str(tmp_path / f"model.{ext}")
        params = self._params()
        cfg = "type: transformer\ndim-emb: 8\n"
        mio.save_model(path, params, cfg)
        loaded, cfg2 = mio.load_model(path)
        assert cfg2 == cfg
        assert set(loaded) == set(params)
        for k in params:
            np.testing.assert_array_equal(loaded[k], params[k])

    def test_config_item_roundtrip(self):
        cfg = "type: s2s\n"
        item = mio.config_to_item(cfg)
        assert item.name == mio.SPECIAL_CONFIG_KEY
        assert item.array.dtype == np.int8
        assert mio.item_to_config(item) == cfg

    def test_atomic_save_overwrites(self, tmp_path):
        path = str(tmp_path / "model.npz")
        mio.save_model(path, self._params(), None)
        mio.save_model(path, {"x": np.zeros(3, np.float32)}, None)
        loaded, _ = mio.load_model(path)
        assert list(loaded) == ["x"]

    def test_yaml_io(self, tmp_path):
        p = str(tmp_path / "progress.yml")
        data = {"epochs": 2, "batches": 100, "stalled": 0}
        mio.save_yaml(p, data)
        assert mio.load_yaml(p) == data
