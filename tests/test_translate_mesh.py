"""Data-parallel decode over a device mesh: the reference translator
round-robins batches over --devices GPU workers, one model replica each
(src/translator/translator.h); the SPMD equivalent is ONE jitted beam
search with the batch dim sharded over a 'data' mesh. Outputs must be
identical to the single-device program — GSPMD only changes placement."""

import jax
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.translator.beam_search import BeamSearch

from tests.test_beam_search import tiny_model


def _batch(vocab, b=5, ts=7, seed=3):
    rs = np.random.RandomState(seed)
    lens = rs.randint(3, ts + 1, size=b)
    ids = np.zeros((b, ts), np.int32)
    mask = np.zeros((b, ts), np.float32)
    for i, n in enumerate(lens):
        ids[i, :n] = rs.randint(3, vocab, n)
        mask[i, :n] = 1.0
    return ids, mask


class TestMeshDecode:
    def test_mesh_equals_single_device(self):
        """8-device mesh decode == 1-device decode, bitwise on ids and
        allclose on scores. Batch of 5 rows exercises the pad-by-
        replication path (5 → 8 rows, extras dropped at collect)."""
        vocab = 19
        model, params, opts = tiny_model(vocab=vocab)
        ids, mask = _batch(vocab)
        res = {}
        for nd in (1, 8):
            bs = BeamSearch(model, [params], None,
                            opts.with_(**{"beam-size": 4, "normalize": 0.6,
                                          "num-devices": nd}), vocab)
            assert (bs.mesh is None) == (nd == 1)
            res[nd] = bs.search(ids, mask)
        assert len(res[8]) == 5            # padding rows dropped
        for h1, h8 in zip(res[1], res[8]):
            assert [h["tokens"] for h in h1] == [h["tokens"] for h in h8]
            np.testing.assert_allclose(
                [h["norm_score"] for h in h1],
                [h["norm_score"] for h in h8], rtol=1e-5)

    def test_sharded_params_disable_decode_mesh(self):
        """TP/pipe-sharded training params reaching a validation decode
        must NOT be re-replicated per device (a full model copy per chip
        mid-training): the decode mesh gates off and decodes them where
        they are."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        vocab = 19
        model, params, opts = tiny_model(vocab=vocab)
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("data", "model"))
        sharded = dict(params)
        k = next(k for k, v in params.items()
                 if getattr(v, "ndim", 0) == 2 and v.shape[-1] % 2 == 0)
        sharded[k] = jax.device_put(
            params[k], NamedSharding(mesh, P(None, "model")))
        bs = BeamSearch(model, [sharded], None,
                        opts.with_(**{"beam-size": 2}), vocab)
        assert bs.mesh is None
        # sharded params also veto the fused decode kernel (the pallas
        # call would make GSPMD all-gather the sharded caches per step)
        assert bs._sharded_params
        ids, mask = _batch(vocab, b=3)
        out = bs.search(ids, mask)       # still decodes correctly
        assert len(out) == 3

    def test_force_decode_on_mesh(self):
        """--force-decode prefixes ride the same 'data' sharding as the
        other batch inputs."""
        vocab = 19
        model, params, opts = tiny_model(vocab=vocab)
        ids, mask = _batch(vocab, b=5)
        prefix = np.full((5, 3), -1, np.int32)
        prefix[:, 0] = 7                 # force first target token
        res = {}
        for nd in (1, 8):
            bs = BeamSearch(model, [params], None,
                            opts.with_(**{"beam-size": 2,
                                          "num-devices": nd}), vocab)
            res[nd] = bs.search(ids, mask, prefix=prefix)
        for h1, h8 in zip(res[1], res[8]):
            assert h1[0]["tokens"] == h8[0]["tokens"]
            assert h1[0]["tokens"][0] == 7

    @pytest.mark.slow
    def test_sampling_topk_mesh_parity_and_collective_free(self):
        """--output-sampling topk under the mesh: same samples as
        single-device (counter-based PRNG → placement-independent) and
        no tensor-sized collectives from the [B,K,V] top-k filter."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from marian_tpu.parallel.collectives import collective_stats
        from marian_tpu.translator.beam_search import BeamConfig
        vocab = 19
        model, params, opts = tiny_model(vocab=vocab)
        ids, mask = _batch(vocab, b=8)
        res = {}
        for nd in (1, 8):
            bs = BeamSearch(
                model, [params], None,
                opts.with_(**{"beam-size": 2, "num-devices": nd, "seed": 11,
                              "output-sampling": ["topk", "5", "0.8"]}),
                vocab)
            res[nd] = bs.search(ids, mask)
            if nd == 8:
                cfg = BeamConfig.from_options(bs.options, 12)
                fn = bs._get_fn(cfg, has_shortlist=False)

                def _dev(x):
                    return jax.device_put(
                        jnp.asarray(x), NamedSharding(
                            bs.mesh,
                            P("data", *([None] * (np.ndim(x) - 1)))))
                txt = fn.lower(tuple(bs.params_list), _dev(ids), _dev(mask),
                               shortlist=None,
                               sample_key=jax.random.key(5),
                               prefix=None).compile().as_text()
                for k, v in collective_stats(txt).items():
                    if k in ("all-gather", "all-reduce", "reduce-scatter",
                             "all-to-all", "collective-permute"):
                        assert v["max_elems"] <= 64, (k, v)
        for h1, h8 in zip(res[1], res[8]):
            assert [h["tokens"] for h in h1] == [h["tokens"] for h in h8]

    @pytest.mark.slow
    def test_mesh_decode_is_collective_free(self):
        """Batch-dim-sharded beam search is embarrassingly parallel: the
        compiled 8-device program must contain NO cross-device data
        collectives (an accidental replicated intermediate or a sharding
        constraint regression would surface as all-gathers GSPMD inserts
        silently — the decode analogue of TestZero1CollectivePattern)."""
        import jax.numpy as jnp
        from marian_tpu.parallel.collectives import collective_stats
        from marian_tpu.translator.beam_search import BeamConfig, \
            beam_search_jit
        vocab = 19
        model, params, opts = tiny_model(vocab=vocab)
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 2, "num-devices": 8}),
                        vocab)
        assert bs.mesh is not None
        cfg = BeamConfig.from_options(bs.options.with_(**{"beam-size": 2}),
                                      12)
        fn = bs._get_fn(cfg, has_shortlist=False)
        ids, mask = _batch(vocab, b=8)

        def _dev(x):
            from jax.sharding import NamedSharding, PartitionSpec as P
            return jax.device_put(
                jnp.asarray(x),
                NamedSharding(bs.mesh, P("data",
                                         *([None] * (np.ndim(x) - 1)))))
        txt = fn.lower(tuple(bs.params_list), _dev(ids), _dev(mask),
                       shortlist=None, sample_key=None,
                       prefix=None).compile().as_text()
        stats = collective_stats(txt)
        data_moving = {k: v for k, v in stats.items()
                       if k in ("all-gather", "all-reduce",
                                "reduce-scatter", "all-to-all",
                                "collective-permute") and v["count"] > 0}
        # tolerate only scalar/tiny control traffic (e.g. an
        # all-finished early-exit reduction), never tensor-sized moves
        for k, v in data_moving.items():
            assert v["max_elems"] <= 64, (k, v)

    @pytest.mark.slow
    def test_fused_decode_parity_and_mesh_gate(self):
        """r6 fused decode kernel × the decode mesh (slow_core): the
        Pallas call is opaque to GSPMD, so under a 'data' mesh the gate
        must fall back to the shard_map'd flat-gather reorder — and the
        fused single-device program must still produce EXACTLY the mesh
        program's hypotheses (three-way parity: fused-on 1-dev ==
        unfused 1-dev == 8-dev mesh)."""
        vocab = 19
        ids, mask = _batch(vocab)
        res = {}
        for name, nd, fused in (("fused", 1, "on"), ("plain", 1, "off"),
                                ("mesh", 8, "on")):
            model, params, opts = tiny_model(
                vocab=vocab,
                **{"transformer-fused-decode-attention": fused,
                   "max-length": 12})
            bs = BeamSearch(model, [params], None,
                            opts.with_(**{"beam-size": 3, "normalize": 0.6,
                                          "num-devices": nd}), vocab)
            assert (bs.mesh is None) == (nd == 1)
            res[name] = bs.search(ids, mask)
        for a, b, c in zip(res["fused"], res["plain"], res["mesh"]):
            assert [h["tokens"] for h in a] == [h["tokens"] for h in b] \
                == [h["tokens"] for h in c]
            np.testing.assert_allclose([h["norm_score"] for h in a],
                                       [h["norm_score"] for h in c],
                                       rtol=1e-5)
        # and the gate must hold INSIDE the step too: with the config
        # gate forced on, the mesh program must still contain no
        # tensor-sized collectives (the step receives fused_decode=False
        # — a pallas call left in the sharded program would make GSPMD
        # re-replicate the caches around it)
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from marian_tpu.parallel.collectives import collective_stats
        from marian_tpu.translator.beam_search import BeamConfig
        model, params, opts = tiny_model(
            vocab=vocab, **{"transformer-fused-decode-attention": "on",
                            "max-length": 12})
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 3, "normalize": 0.6,
                                      "num-devices": 8}), vocab)
        cfg = BeamConfig.from_options(bs.options, 12)
        fn = bs._get_fn(cfg, has_shortlist=False)

        def _dev(x):
            return jax.device_put(
                jnp.asarray(x),
                NamedSharding(bs.mesh,
                              P("data", *([None] * (np.ndim(x) - 1)))))
        ids8, mask8 = _batch(vocab, b=8)
        txt = fn.lower(tuple(bs.params_list), _dev(ids8), _dev(mask8),
                       shortlist=None, sample_key=None,
                       prefix=None).compile().as_text()
        for kk, vv in collective_stats(txt).items():
            if kk in ("all-gather", "all-reduce", "reduce-scatter",
                      "all-to-all", "collective-permute") \
                    and vv["count"] > 0:
                assert vv["max_elems"] <= 64, (kk, vv)

    def test_mesh_divisible_batch_no_padding(self):
        vocab = 19
        model, params, opts = tiny_model(vocab=vocab)
        ids, mask = _batch(vocab, b=8)
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 2, "num-devices": 8}),
                        vocab)
        out = bs.search(ids, mask)
        assert len(out) == 8 and all(len(nb) >= 1 for nb in out)
