"""Fused beam-gather + attention decode kernel vs the unfused sequence
it replaces (tier-1, interpret mode on CPU).

Golden parity at three levels:
- kernel vs the explicit take_along_axis-style reorder + DUS + masked
  dense attention read (the exact op chain beam_search/_mha ran before);
- one _mha decode step with the fused gate on vs off;
- full beam search / greedy decode with the gate on vs off — the
  one-step-lagged backpointer contract in translator/beam_search.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.ops.pallas.decode_attention import decode_attention

from tests.test_beam_search import tiny_model


def _rand(rng, *shape, dtype=jnp.float32):
    return jnp.asarray(rng.randn(*shape), dtype)


def _unfused_reference(q, k_new, v_new, cache_k, cache_v, pos, src_rows,
                       scale):
    """The op chain the kernel replaces, written with take_along_axis —
    deliberately a DIFFERENT gather form than the kernel's index-map
    (and than the flat-gather fallback), so the parity check is against
    independent code."""
    if src_rows is not None:
        idx = src_rows.reshape(-1, 1, 1, 1)
        cache_k = jnp.take_along_axis(cache_k, idx, axis=0)
        cache_v = jnp.take_along_axis(cache_v, idx, axis=0)
    cache_k = jax.lax.dynamic_update_slice(
        cache_k, k_new.astype(cache_k.dtype), (0, 0, pos, 0))
    cache_v = jax.lax.dynamic_update_slice(
        cache_v, v_new.astype(cache_v.dtype), (0, 0, pos, 0))
    s = jnp.einsum("rhqd,rhkd->rhqk", q.astype(jnp.float32),
                   cache_k.astype(jnp.float32)) * scale
    steps = jnp.arange(cache_k.shape[2])[None, None, None, :]
    s = jnp.where(steps <= pos, s, -1e9)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("rhqk,rhkd->rhqd", p,
                     cache_v.astype(jnp.float32)).astype(q.dtype)
    return out, cache_k, cache_v


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_kernel_matches_take_along_axis_reference(rng, dtype):
    r, h, L, dh = 6, 2, 16, 8
    q = _rand(rng, r, h, 1, dh, dtype=dtype)
    kn = _rand(rng, r, h, 1, dh, dtype=dtype)
    vn = _rand(rng, r, h, 1, dh, dtype=dtype)
    ck = _rand(rng, r, h, L, dh, dtype=dtype)
    cv = _rand(rng, r, h, L, dh, dtype=dtype)
    src = jnp.asarray(rng.randint(0, r, r), jnp.int32)
    pos = jnp.asarray(5, jnp.int32)
    out, nk, nv = decode_attention(q, kn, vn, ck, cv, pos, src_rows=src)
    ro, rk, rv = _unfused_reference(q, kn, vn, ck, cv, 5, src,
                                    1.0 / dh ** 0.5)
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ro, np.float32),
                               rtol=tol, atol=tol)
    # the materialized caches must be BITWISE the reorder+DUS result —
    # they are the next step's input state
    assert (np.asarray(nk) == np.asarray(rk)).all()
    assert (np.asarray(nv) == np.asarray(rv)).all()


def test_identity_gather_and_traced_pos_under_jit(rng):
    """src_rows=None (greedy/scoring) = identity; pos traced (the decode
    loop's time index)."""
    r, h, L, dh = 4, 2, 12, 16
    q, kn, vn = (_rand(rng, r, h, 1, dh), _rand(rng, r, h, 1, dh),
                 _rand(rng, r, h, 1, dh))
    ck, cv = _rand(rng, r, h, L, dh), _rand(rng, r, h, L, dh)
    fn = jax.jit(lambda pos: decode_attention(q, kn, vn, ck, cv, pos))
    for pos in (0, 3, L - 1):
        out, nk, nv = fn(jnp.asarray(pos, jnp.int32))
        ro, rk, rv = _unfused_reference(q, kn, vn, ck, cv, pos, None,
                                        1.0 / dh ** 0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                                   rtol=2e-5, atol=2e-5)
        assert (np.asarray(nk) == np.asarray(rk)).all()


def test_oversized_cache_degrades_to_reference_path(rng):
    """Past the auto_tuner VMEM cap the kernel falls back to the jnp
    reference (degrade, don't OOM) with identical semantics."""
    from marian_tpu.ops import auto_tuner
    r, h, L, dh = 3, 2, 96, 8
    q, kn, vn = (_rand(rng, r, h, 1, dh), _rand(rng, r, h, 1, dh),
                 _rand(rng, r, h, 1, dh))
    ck, cv = _rand(rng, r, h, L, dh), _rand(rng, r, h, L, dh)
    src = jnp.asarray([2, 0, 1], jnp.int32)
    out_k, nk_k, _ = decode_attention(q, kn, vn, ck, cv, 4, src_rows=src)
    orig = dict(auto_tuner.KERNEL_BLOCKS["decode_attention"])
    try:
        # shrink the entry below L (the registry floors at one 64-wide
        # block, so L must exceed 64 to cross the cap)
        auto_tuner.KERNEL_BLOCKS["decode_attention"]["max_len"] = 8
        assert auto_tuner.decode_attention_max_len(dh) < L
        out_f, nk_f, _ = decode_attention(q, kn, vn, ck, cv, 4,
                                          src_rows=src)
    finally:
        auto_tuner.KERNEL_BLOCKS["decode_attention"].update(orig)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_f),
                               rtol=2e-5, atol=2e-5)
    assert (np.asarray(nk_k) == np.asarray(nk_f)).all()


def _toy_batch(vocab, b=3, ts=6, seed=3):
    rs = np.random.RandomState(seed)
    ids = np.zeros((b, ts), np.int32)
    mask = np.zeros((b, ts), np.float32)
    for i, n in enumerate(rs.randint(3, ts + 1, size=b)):
        ids[i, :n] = rs.randint(3, vocab, n)
        mask[i, :n] = 1.0
    return ids, mask


@pytest.mark.slow
def test_beam_search_fused_matches_unfused(rng):
    """The beam-reorder fold at full-beam-search level: fused on vs off
    must produce identical hypotheses — the pending-backpointer carry +
    in-kernel gather is exactly the take_along_axis/flat-gather reorder
    it replaces. (Tier-1 carries the kernel-level take_along_axis
    parity above; the slow_core mesh test adds the three-way
    fused/plain/mesh pin.)"""
    from marian_tpu.translator.beam_search import BeamSearch
    vocab = 19
    ids, mask = _toy_batch(vocab)
    res = {}
    for mode in ("off", "on"):
        model, params, opts = tiny_model(
            vocab=vocab,
            **{"transformer-fused-decode-attention": mode,
               "max-length": 12})
        assert model.fused_decode_reorder == (mode == "on")
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 3, "normalize": 0.6,
                                      "max-length": 12}), vocab)
        res[mode] = bs.search(ids, mask)
    for h0, h1 in zip(res["off"], res["on"]):
        assert [h["tokens"] for h in h0] == [h["tokens"] for h in h1]
        np.testing.assert_allclose([h["norm_score"] for h in h0],
                                   [h["norm_score"] for h in h1],
                                   rtol=1e-5)


@pytest.mark.slow
def test_greedy_fused_matches_unfused(rng):
    """Greedy decode (no beam reorder): the fused kernel runs with the
    identity gather and must not change a single token."""
    from marian_tpu.translator.greedy import greedy_decode
    vocab = 19
    ids, mask = _toy_batch(vocab, seed=5)
    outs = {}
    for mode in ("off", "on"):
        model, params, _ = tiny_model(
            vocab=vocab, seed=1,
            **{"transformer-fused-decode-attention": mode})
        outs[mode] = greedy_decode(model, params, jnp.asarray(ids),
                                   jnp.asarray(mask), 10)
    assert (outs["off"] == outs["on"]).all()


@pytest.mark.slow
def test_scanned_stack_fused_matches_unfused(rng):
    """The lax.scan decode stack slices per-layer caches from the
    [L, ...] stacked leaves; the kernel must compose with it."""
    from marian_tpu.translator.beam_search import BeamSearch
    vocab = 19
    ids, mask = _toy_batch(vocab, seed=7)
    res = {}
    for mode in ("off", "on"):
        model, params, opts = tiny_model(
            vocab=vocab,
            **{"transformer-fused-decode-attention": mode,
               "scan-layers": True, "enc-depth": 2, "dec-depth": 2,
               "max-length": 10})
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 2, "max-length": 10}),
                        vocab)
        res[mode] = bs.search(ids, mask)
    for h0, h1 in zip(res["off"], res["on"]):
        assert [h["tokens"] for h in h0] == [h["tokens"] for h in h1]


def test_fused_gate_resolution():
    """'auto' must stay off outside the TPU backend; 'on' forces; the
    non-self-attention autoreg modes never fuse (no KV cache to fold)."""
    from marian_tpu.models import transformer as T
    model, _, _ = tiny_model()
    assert T.fused_decode_active(model.cfg) is False          # auto on CPU
    model_on, _, _ = tiny_model(
        **{"transformer-fused-decode-attention": "on"})
    assert T.fused_decode_active(model_on.cfg) is True
    model_ssru, _, _ = tiny_model(
        **{"transformer-fused-decode-attention": "on",
           "transformer-decoder-autoreg": "rnn"})
    assert T.fused_decode_active(model_ssru.cfg) is False
    assert model_ssru.fused_decode_reorder is False


def test_while_body_op_count_parser():
    """bench_decode.while_body_op_count's HLO parse on a toy while
    program: the body computation's op count, not the entry's."""
    import sys
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from bench_decode import while_body_op_count

    def f(x):
        def body(c):
            i, v = c
            return i + 1, v * 2.0 + 1.0

        def cond(c):
            return c[0] < 10

        return jax.lax.while_loop(cond, body, (0, x))

    n = while_body_op_count(jax.jit(f), jnp.ones((4,), jnp.float32))
    assert n is not None and n >= 2
