"""--force-decode: constrained decoding of given target prefixes
(reference: translator force-decoding of the extra input stream)."""

import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.translator.beam_search import BeamSearch

from test_model import tiny_model, fake_batch


@pytest.fixture
def rng():
    return np.random.RandomState(19)


class TestForceDecode:
    def test_prefix_is_respected(self, rng):
        model, params = tiny_model(vocab=23)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=23)
        prefix = np.array([[5, 9, 2], [7, -1, -1]], np.int32)
        bs = BeamSearch(model, [params], None,
                        Options({"beam-size": 3, "max-length": 12}), None)
        out = bs.search(batch["src_ids"], batch["src_mask"], prefix=prefix)
        toks0 = out[0][0]["tokens"]
        toks1 = out[1][0]["tokens"]
        assert toks0[:3] == [5, 9, 2]
        assert toks1[:1] == [7]

    def test_scores_are_model_scores(self, rng):
        """The forced token keeps its true log-prob: forcing the tokens the
        model would pick anyway must not change the hypothesis score."""
        model, params = tiny_model(vocab=23)
        batch = fake_batch(rng, b=1, ts=5, tt=6, vocab=23)
        opts = Options({"beam-size": 1, "max-length": 12})
        free = BeamSearch(model, [params], None, opts, None).search(
            batch["src_ids"], batch["src_mask"])
        toks = free[0][0]["tokens"]
        if len(toks) < 2:
            pytest.skip("degenerate free decode")
        prefix = np.asarray([toks[:2]], np.int32)
        forced = BeamSearch(model, [params], None, opts, None).search(
            batch["src_ids"], batch["src_mask"], prefix=prefix)
        assert forced[0][0]["tokens"] == toks
        assert forced[0][0]["score"] == pytest.approx(
            free[0][0]["score"], rel=1e-4)

    def test_shortlist_combination_rejected(self, rng):
        model, params = tiny_model(vocab=23)
        batch = fake_batch(rng, b=1, ts=5, tt=6, vocab=23)
        bs = BeamSearch(model, [params], None,
                        Options({"beam-size": 1, "max-length": 8}), None)
        with pytest.raises(ValueError, match="shortlist"):
            bs.search(batch["src_ids"], batch["src_mask"],
                      shortlist=object(),
                      prefix=np.zeros((1, 2), np.int32))
