"""LSH approximate-kNN output search (--output-approx-knn; reference:
src/data/shortlist.h :: LSHShortlist + vendored faiss IndexLSH subset)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.ops.lsh import build_index, hamming_topk, lsh_logits

from test_model import tiny_model, fake_batch


@pytest.fixture
def rng():
    return np.random.RandomState(11)


class TestLSHCore:
    def test_recall_vs_exact_topk(self, rng):
        """Angular LSH with enough bits must recover most of the true
        inner-product top-k (the recall bar VERDICT r1 set vs the lexical
        shortlist, whose candidate sets routinely miss rare words)."""
        v, d, n = 512, 32, 16
        table = jnp.asarray(rng.randn(v, d), jnp.float32)
        x = jnp.asarray(rng.randn(n, d), jnp.float32)
        planes, sigs = build_index(table, nbits=1024)
        idx = np.asarray(hamming_topk(x, planes, sigs, k=64))
        exact = np.asarray(
            jax.lax.top_k(x @ table.T, 8)[1])            # true top-8
        hits = sum(len(set(exact[i]) & set(idx[i])) for i in range(n))
        recall = hits / (n * 8)
        assert recall >= 0.9, recall

    def test_logits_match_exact_on_candidates(self, rng):
        v, d, n = 128, 16, 4
        table = jnp.asarray(rng.randn(v, d), jnp.float32)
        bias = jnp.asarray(rng.randn(v), jnp.float32)
        x = jnp.asarray(rng.randn(n, d), jnp.float32)
        planes, sigs = build_index(table, nbits=256)
        out = np.asarray(lsh_logits(x, table, bias, planes, sigs, k=16))
        exact = np.asarray(x @ table.T + bias[None, :])
        cand = out > -1e8
        np.testing.assert_allclose(out[cand],
                                   exact[cand], rtol=1e-5, atol=1e-5)
        # EOS column always exact, candidates per row = k (+EOS)
        np.testing.assert_allclose(out[:, 0], exact[:, 0], rtol=1e-5,
                                   atol=1e-5)
        assert (cand.sum(1) >= 16).all()


class TestLSHDecode:
    def test_full_k_matches_dense_decode(self, rng):
        """k = V turns LSH into exact search — decode must equal the dense
        path token-for-token."""
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = tiny_model(vocab=23)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=23)
        dense = BeamSearch(model, [params], None,
                           Options({"beam-size": 4, "max-length": 12}),
                           None).search(batch["src_ids"], batch["src_mask"])
        m2, _ = tiny_model(vocab=23, **{"output-approx-knn": [23, 256]})
        approx = BeamSearch(m2, [params], None,
                            Options({"beam-size": 4, "max-length": 12}),
                            None).search(batch["src_ids"], batch["src_mask"])
        assert [h[0]["tokens"] for h in dense] == \
            [h[0]["tokens"] for h in approx]

    def test_small_k_decodes_and_terminates(self, rng):
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = tiny_model(vocab=64,
                                   **{"output-approx-knn": [16, 512]})
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=64)
        out = BeamSearch(model, [params], None,
                         Options({"beam-size": 2, "max-length": 10}),
                         None).search(batch["src_ids"], batch["src_mask"])
        assert len(out) == 2
        for nb in out:
            assert len(nb[0]["tokens"]) <= 10

    def test_factored_vocab_rejected(self):
        from marian_tpu.models import transformer as T
        model, params = tiny_model(vocab=23,
                                   **{"output-approx-knn": [8, 128]})
        import dataclasses
        cfg = dataclasses.replace(model.cfg, trg_factors=object())
        with pytest.raises(ValueError, match="plain-tensor"):
            T.init_decode_state(cfg, params,
                                jnp.zeros((1, 4, cfg.dim_emb)),
                                jnp.ones((1, 4)), max_len=8)
