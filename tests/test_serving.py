"""Serving subsystem (marian_tpu/serving/ — ISSUE 1): continuous
token-budget batching scheduler, admission control, metrics registry +
endpoints. Everything runs under JAX_PLATFORMS=cpu with stub translate
functions — no model, no websockets, no device."""

import asyncio
import threading
import urllib.request

import pytest

from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.common import lockdep
from marian_tpu.data.batch_generator import bucket_length
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.admission import AdmissionController, Overloaded
from marian_tpu.serving.scheduler import (ContinuousScheduler,
                                          DispatchStalled, RequestTimeout)



@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """After this suite has run the scheduler/admission/metrics thread
    mix, the shared conftest witness asserts observed ⊆ static."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _ownership_witness(ownership_witness):
    """Iteration-mode scheduler tests here claim/release pool pages;
    the shared conftest witness asserts observed pairings ⊆ the static
    ownership graph (ISSUE 15)."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _jitwit_witness(jitwit_witness):
    """Every backend compile this suite triggers must map to a site the
    static jit model predicts, with no instrumented-key retrace
    (ISSUE 17)."""
    yield


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# metrics registry + exposition
# ---------------------------------------------------------------------------

class TestMetricsRegistry:
    def test_counter_gauge_histogram_render(self):
        r = msm.Registry()
        c = r.counter("t_requests_total", "requests")
        c.inc()
        c.inc(2)
        g = r.gauge("t_depth", "queue depth")
        g.set(7)
        h = r.histogram("t_latency_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = r.render()
        assert "# TYPE t_requests_total counter" in text
        assert "t_requests_total 3" in text
        assert "t_depth 7" in text
        assert 't_latency_seconds_bucket{le="0.1"} 1' in text
        assert 't_latency_seconds_bucket{le="1"} 2' in text
        assert 't_latency_seconds_bucket{le="+Inf"} 3' in text
        assert "t_latency_seconds_count 3" in text

    def test_labels_and_get_or_create_idempotent(self):
        r = msm.Registry()
        c1 = r.counter("t_shed_total", "sheds", labels=("reason",))
        c1.labels("queue_full").inc()
        c1.labels("queue_full").inc()
        c1.labels("draining").inc()
        # same name returns the same metric (safe re-instantiation)
        c2 = r.counter("t_shed_total", "sheds", labels=("reason",))
        assert c2 is c1
        text = r.render()
        assert 't_shed_total{reason="queue_full"} 2' in text
        assert 't_shed_total{reason="draining"} 1' in text

    def test_type_conflict_raises(self):
        r = msm.Registry()
        r.counter("t_x", "")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("t_x", "")

    def test_gauge_function_sampled_at_scrape(self):
        r = msm.Registry()
        state = {"v": 3}
        g = r.gauge("t_live", "")
        g.set_function(lambda: state["v"])
        assert "t_live 3" in r.render()
        state["v"] = 9
        assert "t_live 9" in r.render()

    def test_counters_only_go_up(self):
        with pytest.raises(ValueError):
            msm.Registry().counter("t_c", "").inc(-1)


class TestMetricsEndpoint:
    def test_scrape_health_ready(self):
        r = msm.Registry()
        r.counter("t_up", "").inc()
        ready = {"ok": False}
        srv = msm.MetricsServer(0, registry=r,
                                ready_fn=lambda: ready["ok"]).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            body = urllib.request.urlopen(base + "/metrics").read().decode()
            assert "t_up 1" in body
            assert urllib.request.urlopen(base + "/healthz").status == 200
            # not ready -> 503; ready -> 200
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/readyz")
            assert ei.value.code == 503
            ready["ok"] = True
            assert urllib.request.urlopen(base + "/readyz").status == 200
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# continuous scheduler
# ---------------------------------------------------------------------------

class TestContinuousScheduler:
    def test_coalesces_concurrent_requests_one_device_batch(self):
        calls = []

        def fake(lines):
            calls.append(list(lines))
            return [f"T({l})" for l in lines]

        async def scenario():
            s = ContinuousScheduler(fake, token_budget=256,
                                    registry=msm.Registry())
            s.start()
            futs = [s.submit(["a b", "c"]), s.submit(["d"]),
                    s.submit(["e f g"])]
            out = await asyncio.gather(*futs)
            await s.stop()
            return out

        out = run(scenario())
        assert out == [["T(a b)", "T(c)"], ["T(d)"], ["T(e f g)"]]
        assert calls == [["a b", "c", "d", "e f g"]]

    def test_token_budget_splits_batches(self):
        calls = []

        def fake(lines):
            calls.append(list(lines))
            return list(lines)

        async def scenario():
            # each 3-word line buckets to width 8; budget 16 -> <=2 rows
            s = ContinuousScheduler(fake, token_budget=16,
                                    registry=msm.Registry())
            s.start()
            futs = [s.submit([f"w{i} x y"]) for i in range(6)]
            await asyncio.gather(*futs)
            await s.stop()

        run(scenario())
        assert len(calls) >= 3
        for call in calls:
            width = max(bucket_length(len(l.split()) + 1) for l in call)
            assert len(call) * width <= 16

    def test_fill_ratio_improves_over_single_request(self):
        """The acceptance-criterion property, at unit level: concurrent
        single-sentence requests coalesce into batches whose fill ratio
        beats the 1-request baseline."""
        def fake(lines):
            return list(lines)

        def mean_fill(n_concurrent):
            reg = msm.Registry()

            async def scenario():
                s = ContinuousScheduler(fake, token_budget=512,
                                        batch_multiple=8, registry=reg)
                s.start()
                futs = [s.submit(["a b c d e f g"])
                        for _ in range(n_concurrent)]
                await asyncio.gather(*futs)
                await s.stop()

            run(scenario())
            h = reg.get("marian_serving_batch_fill_ratio")
            return h.mean()

        assert mean_fill(16) > mean_fill(1)

    def test_deadline_expiry_while_queued(self):
        release = threading.Event()

        def blocking(lines):
            release.wait(5)
            return list(lines)

        async def scenario():
            reg = msm.Registry()
            s = ContinuousScheduler(blocking, token_budget=64,
                                    window_s=0.0, registry=reg)
            s.start()
            f1 = s.submit(["first"])                  # occupies the device
            await asyncio.sleep(0.05)
            f2 = s.submit(["second"], timeout=0.05)   # expires while queued
            with pytest.raises(RequestTimeout, match="deadline expired"):
                await f2
            release.set()
            await f1
            await s.stop()
            return reg.get("marian_serving_timeouts_total").value

        try:
            assert run(scenario()) == 1
        finally:
            release.set()

    def test_cancellation_mid_queue_drops_units(self):
        release = threading.Event()
        calls = []

        def blocking(lines):
            calls.append(list(lines))
            release.wait(5)
            return list(lines)

        async def scenario():
            reg = msm.Registry()
            s = ContinuousScheduler(blocking, token_budget=64,
                                    window_s=0.0, registry=reg)
            s.start()
            f1 = s.submit(["first"])
            await asyncio.sleep(0.05)                 # device now busy
            f2 = s.submit(["cancel me"])
            f2.cancel()
            release.set()
            await f1
            # another request proves the worker moved on past the
            # cancelled units
            f3 = s.submit(["third"])
            await f3
            await s.stop()
            return reg.get("marian_serving_cancelled_total").value

        try:
            cancelled = run(scenario())
        finally:
            release.set()
        assert cancelled == 1
        assert ["cancel me"] not in calls
        assert not any("cancel me" in c for c in calls)

    def test_bisection_isolates_poison_request(self):
        calls = []

        def poison_translate(lines):
            calls.append(list(lines))
            if any("POISON" in l for l in lines):
                raise ValueError("poison sentence")
            return [l.upper() for l in lines]

        async def scenario():
            reg = msm.Registry()
            s = ContinuousScheduler(poison_translate, token_budget=256,
                                    registry=reg)
            s.start()
            good1 = s.submit(["alpha"])
            bad = s.submit(["POISON"])
            good2 = s.submit(["beta"])
            r1 = await good1
            with pytest.raises(RuntimeError, match="poison"):
                await bad
            r2 = await good2
            await s.stop()
            return r1, r2, reg

        r1, r2, reg = run(scenario())
        assert r1 == ["ALPHA"] and r2 == ["BETA"]
        # the first batch coalesced all three and failed; bisection then
        # isolated the poison without failing the good requests
        assert len(calls[0]) == 3
        assert reg.get("marian_serving_retry_bisections_total").value >= 1
        assert reg.get("marian_serving_failures_total").value == 1

    def test_priority_lane_packs_first(self):
        release = threading.Event()
        calls = []

        def blocking(lines):
            calls.append(list(lines))
            if len(calls) == 1:
                release.wait(5)
            return list(lines)

        async def scenario():
            s = ContinuousScheduler(blocking, token_budget=256,
                                    window_s=0.0, registry=msm.Registry())
            s.start()
            f0 = s.submit(["warmup"])
            await asyncio.sleep(0.05)                 # device busy
            flow = s.submit(["low lane"], priority=0)
            fhigh = s.submit(["high lane"], priority=5)
            release.set()
            await asyncio.gather(f0, flow, fhigh)
            await s.stop()

        try:
            run(scenario())
        finally:
            release.set()
        assert calls[1][0] == "high lane"   # high priority packed first

    def test_worker_survives_translate_errors(self):
        state = {"fail": True}

        def flaky(lines):
            if state["fail"]:
                state["fail"] = False
                raise ValueError("boom")
            return [l.upper() for l in lines]

        async def scenario():
            s = ContinuousScheduler(flaky, token_budget=64,
                                    registry=msm.Registry())
            s.start()
            f1 = s.submit(["x"])
            with pytest.raises(RuntimeError, match="boom"):
                await f1
            f2 = s.submit(["ok"])
            out = await f2
            await s.stop()
            return out

        assert run(scenario()) == ["OK"]


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_queue_full_sheds_with_explicit_error(self):
        reg = msm.Registry()
        depth = {"v": 0}
        adm = AdmissionController(10, lambda: depth["v"], registry=reg)
        adm.admit(8)
        depth["v"] = 8
        with pytest.raises(Overloaded, match="queue full"):
            adm.admit(3)
        assert reg.get("marian_serving_shed_total") \
                  .labels("queue_full").value == 1
        adm.admit(2)          # exactly at the bound still admits

    def test_zero_limit_is_unbounded(self):
        adm = AdmissionController(0, lambda: 10**9,
                                  registry=msm.Registry())
        adm.admit(10**6)      # no shed

    def test_drain_stops_admission_and_finishes_queued(self):
        def fake(lines):
            return list(lines)

        async def scenario():
            reg = msm.Registry()
            s = ContinuousScheduler(fake, token_budget=64, registry=reg)
            adm = AdmissionController(100, s.queued_units, registry=reg)
            s.start()
            futs = [s.submit([f"s{i}"]) for i in range(5)]
            adm.begin_drain()
            with pytest.raises(Overloaded, match="draining") as ei:
                adm.admit(1)
            assert ei.value.retriable is False
            drained = await s.drain(timeout=5.0)
            out = await asyncio.gather(*futs)
            return drained, out

        drained, out = run(scenario())
        assert drained is True
        assert out == [[f"s{i}"] for i in range(5)]


# ---------------------------------------------------------------------------
# ServingApp over the dependency-free TCP framing (the real server wiring
# minus the model and minus websockets)
# ---------------------------------------------------------------------------

async def _tcp_request(port: int, text: str) -> str:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = text.encode("utf-8")
    writer.write(b"MTPU %d\n" % len(payload) + payload)
    await writer.drain()
    header = await reader.readline()
    assert header.startswith(b"MTPU ")
    reply = await reader.readexactly(int(header.split()[1]))
    writer.close()
    return reply.decode("utf-8")


def _make_app(translate, **opt):
    from marian_tpu.server.server import ServingApp
    base = {"batch-token-budget": 256, "max-queue": 64,
            "request-timeout": 0.0, "metrics-port": 0}
    base.update(opt)
    return ServingApp(Options(base), translate_lines=translate,
                      registry=msm.Registry())


def test_serving_smoke():
    """Fast tier-1 smoke: concurrent TCP clients -> admission ->
    continuous scheduler -> stub translate -> framed replies, plus the
    documented metric series present after traffic."""
    from marian_tpu.server.server import _make_tcp_handler

    calls = []

    def fake(lines):
        calls.append(list(lines))
        return [f"T({l})" for l in lines]

    async def scenario():
        app = _make_app(fake)
        await app.start()
        server = await asyncio.start_server(_make_tcp_handler(app),
                                            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            r1, r2, r3 = await asyncio.gather(
                _tcp_request(port, "a b\nc d"),
                _tcp_request(port, "e"),
                _tcp_request(port, "f g h"))
        finally:
            server.close()
            await server.wait_closed()
            await app.shutdown(drain_timeout=2.0)
        return r1, r2, r3, app

    r1, r2, r3, app = run(scenario())
    assert r1 == "T(a b)\nT(c d)"
    assert r2 == "T(e)"
    assert r3 == "T(f g h)"
    # concurrent requests coalesced (fewer device calls than requests)
    assert len(calls) < 3
    # every documented series is present in a scrape of the app registry
    text = app.registry.render()
    for series in ("marian_serving_requests_total",
                   "marian_serving_queue_depth_sentences",
                   "marian_serving_batches_total",
                   "marian_serving_batch_rows",
                   "marian_serving_batch_fill_ratio",
                   "marian_serving_padding_waste_ratio",
                   "marian_serving_time_to_first_batch_seconds",
                   "marian_serving_request_latency_seconds",
                   "marian_serving_timeouts_total",
                   "marian_serving_cancelled_total",
                   "marian_serving_failures_total",
                   "marian_serving_retry_bisections_total",
                   "marian_serving_watchdog_trips_total",
                   "marian_serving_shed_total",
                   "marian_serving_admitted_sentences_total",
                   "marian_serving_queue_limit_sentences"):
        assert series in text, f"missing metric series {series}"


def test_app_overload_reply_not_hang():
    release = threading.Event()

    def blocking(lines):
        release.wait(5)
        return list(lines)

    async def scenario():
        app = _make_app(blocking, **{"max-queue": 2})
        await app.start()
        # first request fills the queue bound while the device blocks
        t1 = asyncio.ensure_future(app.handle_text("s1\ns2"))
        await asyncio.sleep(0.05)
        # second request must be shed with an explicit error, instantly
        reply = await asyncio.wait_for(app.handle_text("s3\ns4\ns5"), 1.0)
        release.set()
        await t1
        await app.shutdown(drain_timeout=2.0)
        return reply

    try:
        reply = run(scenario())
    finally:
        release.set()
    assert reply.startswith("!!SERVER-OVERLOADED")
    assert "queue full" in reply


def test_app_timeout_reply():
    release = threading.Event()

    def blocking(lines):
        release.wait(5)
        return list(lines)

    async def scenario():
        app = _make_app(blocking, **{"request-timeout": 0.05})
        await app.start()
        t1 = asyncio.ensure_future(app.handle_text("hold"))
        await asyncio.sleep(0.05)          # device now busy with t1
        reply = await asyncio.wait_for(app.handle_text("late"), 1.0)
        release.set()
        await t1
        await app.shutdown(drain_timeout=2.0)
        return reply

    try:
        reply = run(scenario())
    finally:
        release.set()
    assert reply.startswith("!!SERVER-TIMEOUT")


def test_resolve_token_budget_defaults():
    from marian_tpu.server.server import resolve_token_budget
    # explicit flag wins
    assert resolve_token_budget(Options({"batch-token-budget": 999})) == 999
    # derived: mini-batch x bucketed (max-length + 1)
    got = resolve_token_budget(Options({"mini-batch": 8, "max-length": 50}))
    assert got == 8 * bucket_length(51)


def test_dead_queue_depth_not_counted_for_admission():
    """A timeout storm must not become a shed storm: expired requests'
    units still physically in the lanes (worker busy on a long device
    batch) are excluded from the admission-visible depth immediately."""
    release = threading.Event()

    def blocking(lines):
        release.wait(5)
        return list(lines)

    async def scenario():
        reg = msm.Registry()
        s = ContinuousScheduler(blocking, token_budget=64,
                                window_s=0.0, registry=reg)
        s.start()
        f1 = s.submit(["first"])               # occupies the device
        await asyncio.sleep(0.05)
        f2 = s.submit(["a", "b", "c"], timeout=0.05)
        assert s.queued_units() == 3
        with pytest.raises(RequestTimeout):
            await f2
        # expired units are still in the lanes (device busy) but the
        # live depth — what AdmissionController sheds against — is 0
        assert s.queued_units() == 0
        release.set()
        await f1
        await s.stop()

    try:
        run(scenario())
    finally:
        release.set()


def test_bisection_skips_dead_units():
    """Requests that die while a failed batch bisects must not be
    re-translated just to discard the result."""
    calls = []
    release = threading.Event()
    state = {"first": True}

    def translate(lines):
        calls.append(list(lines))
        if state["first"]:
            state["first"] = False
            release.wait(5)
            raise ValueError("first call fails")
        return [l.upper() for l in lines]

    async def scenario():
        s = ContinuousScheduler(translate, token_budget=256,
                                registry=msm.Registry())
        s.start()
        f1 = s.submit(["alpha"])
        f2 = s.submit(["omega"])
        await asyncio.sleep(0.05)   # batch [alpha, omega] now in flight
        f2.cancel()                 # dies while the batch is failing
        release.set()
        out = await f1
        await s.stop()
        return out

    try:
        out = run(scenario())
    finally:
        release.set()
    assert out == ["ALPHA"]
    assert calls[0] == ["alpha", "omega"]
    # bisection retried alpha but never re-dispatched the dead omega
    assert all("omega" not in c for c in calls[1:])


# ---------------------------------------------------------------------------
# dispatch watchdog + serving fault points (ISSUE 4)
# ---------------------------------------------------------------------------

class TestDispatchWatchdog:
    def test_stalled_batch_fails_retriable_and_scheduler_survives(self):
        """The acceptance-criterion property: a hung translate_lines call
        trips the watchdog — its requests fail with a RETRIABLE error —
        and the scheduler keeps serving subsequent batches on a fresh
        device worker instead of wedging forever."""
        release = threading.Event()

        def translate(lines):
            if lines == ["stall"]:
                release.wait(10)        # wedged device call
            return [l.upper() for l in lines]

        async def scenario():
            reg = msm.Registry()
            s = ContinuousScheduler(translate, window_s=0, registry=reg,
                                    stall_timeout=0.1)
            s.start()
            f1 = s.submit(["stall"])
            with pytest.raises(DispatchStalled, match="retry"):
                await asyncio.wait_for(f1, 5)
            assert DispatchStalled.retriable
            # the scheduler is alive: a new request completes while the
            # abandoned thread is still wedged
            out = await asyncio.wait_for(s.submit(["after"]), 5)
            trips = reg.get("marian_serving_watchdog_trips_total").value
            # the abandoned worker thread must be detached from
            # concurrent.futures' atexit join — a PERMANENTLY wedged
            # device call must not hang interpreter shutdown after an
            # otherwise graceful drain
            from concurrent.futures import thread as cf_thread
            wedged = [t for t in cf_thread._threads_queues
                      if t.name.startswith("serve-device")
                      and t.is_alive()]
            release.set()
            await s.stop()
            return out, trips, wedged

        try:
            out, trips, wedged = run(scenario())
        finally:
            release.set()
        assert out == ["AFTER"]
        assert trips == 1
        # only the replacement executor's (responsive) worker may remain
        # registered for the exit join; the wedged one was detached
        assert len(wedged) <= 1

    def test_injected_hang_trips_watchdog(self):
        """serving.translate=hang — the fault-injection route to the same
        stall (what scripts/chaos.py and operators use to drill it)."""
        async def scenario():
            reg = msm.Registry()
            s = ContinuousScheduler(lambda lines: list(lines), window_s=0,
                                    registry=reg, stall_timeout=0.05)
            s.start()
            with fp.active("serving.translate=hang:0.4"):
                with pytest.raises(DispatchStalled):
                    await asyncio.wait_for(s.submit(["x"]), 5)
            out = await asyncio.wait_for(s.submit(["ok"]), 5)
            await s.stop()
            return out, reg.get(
                "marian_serving_watchdog_trips_total").value

        out, trips = run(scenario())
        assert out == ["ok"] and trips == 1

    def test_injected_dispatch_failure_fails_loudly_not_silently(self):
        """serving.dispatch=fail: the batch's futures fail explicitly
        (never a dropped batch with hanging clients) and the worker
        survives."""
        async def scenario():
            s = ContinuousScheduler(lambda lines: list(lines), window_s=0,
                                    registry=msm.Registry())
            s.start()
            with fp.active("serving.dispatch=fail"):
                with pytest.raises(RuntimeError, match="injected fault"):
                    await asyncio.wait_for(s.submit(["x"]), 5)
            out = await asyncio.wait_for(s.submit(["ok"]), 5)
            await s.stop()
            return out

        assert run(scenario()) == ["ok"]

    def test_app_replies_server_retry_on_stall(self):
        """Transport level: a watchdog trip becomes an explicit
        !!SERVER-RETRY reply, not an empty string or a hang."""
        release = threading.Event()

        def blocking(lines):
            release.wait(10)
            return list(lines)

        async def scenario():
            app = _make_app(blocking, **{"dispatch-stall-timeout": 0.1})
            await app.start()
            reply = await asyncio.wait_for(app.handle_text("hold"), 5)
            release.set()
            await app.shutdown(drain_timeout=2.0)
            return reply

        try:
            reply = run(scenario())
        finally:
            release.set()
        assert reply.startswith("!!SERVER-RETRY")


def test_tcp_disconnect_cancels_request():
    """TCP cancellation parity with ws: a client that drops mid-request
    has its queued sentences cancelled before they cost device time."""
    from marian_tpu.server.server import _make_tcp_handler
    release = threading.Event()
    calls = []

    def blocking(lines):
        calls.append(list(lines))
        release.wait(5)
        return list(lines)

    async def scenario():
        app = _make_app(blocking)
        await app.start()
        server = await asyncio.start_server(_make_tcp_handler(app),
                                            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        # first request occupies the device
        hold = asyncio.ensure_future(_tcp_request(port, "hold"))
        await asyncio.sleep(0.05)
        # second client sends a frame and drops the connection
        _, w = await asyncio.open_connection("127.0.0.1", port)
        p = b"goner one\ngoner two"
        w.write(b"MTPU %d\n" % len(p) + p)
        await w.drain()
        await asyncio.sleep(0.05)
        w.close()
        await asyncio.sleep(0.1)               # EOF watch fires, cancels
        cancelled = app.registry.get(
            "marian_serving_cancelled_total").value
        release.set()
        await hold
        server.close()
        await server.wait_closed()
        await app.shutdown(drain_timeout=2.0)
        return cancelled

    try:
        cancelled = run(scenario())
    finally:
        release.set()
    assert cancelled == 1
    assert all("goner" not in l for c in calls for l in c)


def test_tcp_pipelined_disconnect_cancels_request():
    """The PR 8 review regression: once a PIPELINED byte arrived, the old
    handler stopped watching for EOF — a client that then disconnected
    while queued was only noticed at reply-write time, after its
    sentences had already cost device work. The watch must re-arm."""
    from marian_tpu.server.server import _make_tcp_handler
    release = threading.Event()
    calls = []

    def blocking(lines):
        calls.append(list(lines))
        release.wait(5)
        return list(lines)

    async def scenario():
        app = _make_app(blocking)
        await app.start()
        server = await asyncio.start_server(_make_tcp_handler(app),
                                            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        hold = asyncio.ensure_future(_tcp_request(port, "hold"))
        await asyncio.sleep(0.05)              # device busy on "hold"
        # second client: frame, then a PIPELINED next frame, then drops
        _, w = await asyncio.open_connection("127.0.0.1", port)
        p = b"goner one\ngoner two"
        w.write(b"MTPU %d\n" % len(p) + p)
        await w.drain()
        await asyncio.sleep(0.05)
        w.write(b"MTPU 4\n")                   # pipelined read-ahead bytes
        await w.drain()
        await asyncio.sleep(0.05)              # old code stops watching HERE
        w.close()
        await asyncio.sleep(0.1)               # re-armed watch sees EOF
        cancelled = app.registry.get(
            "marian_serving_cancelled_total").value
        release.set()
        await hold
        server.close()
        await server.wait_closed()
        await app.shutdown(drain_timeout=2.0)
        return cancelled

    try:
        cancelled = run(scenario())
    finally:
        release.set()
    assert cancelled == 1
    assert all("goner" not in l for c in calls for l in c)


@pytest.mark.parametrize("header", [b"MTPU -3\n", b"MTPU abc\n"])
def test_tcp_invalid_frame_length_rejected(header):
    """'MTPU -3' / 'MTPU abc' must be refused as a bad frame: the
    buffered _readexactly would python-slice the read-ahead buffer with
    a negative count and desync the protocol (the raw StreamReader used
    to raise ValueError for free), and a non-numeric length deserves the
    explicit reply, not a silent close."""
    from marian_tpu.server.server import _make_tcp_handler

    async def scenario():
        app = _make_app(lambda lines: list(lines))
        await app.start()
        server = await asyncio.start_server(_make_tcp_handler(app),
                                            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(header + b"ABCDEFGH")
            await w.drain()
            hdr = await asyncio.wait_for(r.readline(), 5)
            reply = await asyncio.wait_for(
                r.readexactly(int(hdr.split()[1])), 5)
            # server replies bad-frame and closes — no mis-sliced
            # 'payload' ever reaches the scheduler
            eof = await asyncio.wait_for(r.read(1), 5)
            w.close()
            return reply, eof
        finally:
            server.close()
            await server.wait_closed()
            await app.shutdown(drain_timeout=2.0)

    reply, eof = run(scenario())
    assert reply.startswith(b"!!SERVER-ERROR bad frame")
    assert eof == b""


def test_tcp_flooding_pipeliner_bounded_readahead():
    """A client flooding pipelined bytes while its reply is in flight
    must not grow the server's read-ahead buffer without bound — past
    MAX_READAHEAD the watch stops reading (TCP backpressure throttles
    the sender) and the framing still parses everything afterwards."""
    from marian_tpu.server import server as srv
    release = threading.Event()

    def blocking(lines):
        if lines == ["hold"]:
            release.wait(5)
        return [l.upper() for l in lines]

    async def scenario(monkey_cap):
        old_cap = srv.MAX_READAHEAD
        srv.MAX_READAHEAD = monkey_cap
        app = _make_app(blocking)
        await app.start()
        server = await asyncio.start_server(srv._make_tcp_handler(app),
                                            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        try:
            r, w = await asyncio.open_connection("127.0.0.1", port)
            p = b"hold"
            w.write(b"MTPU %d\n" % len(p) + p)
            await w.drain()
            await asyncio.sleep(0.05)
            # flood far past the cap while the reply is pending, ending
            # in a complete second frame
            flood = b"x" * (monkey_cap * 4)
            frame2 = b"MTPU 2\nok"
            w.write(b"MTPU %d\n" % (len(flood) + 2) + flood + b"ok")
            w.write(frame2)
            await w.drain()
            await asyncio.sleep(0.05)
            release.set()

            async def read_reply():
                hdr = await r.readline()
                return await r.readexactly(int(hdr.split()[1]))

            r1 = await asyncio.wait_for(read_reply(), 5)
            r2 = await asyncio.wait_for(read_reply(), 5)
            r3 = await asyncio.wait_for(read_reply(), 5)
            w.close()
            return r1, r2, r3
        finally:
            server.close()
            await server.wait_closed()
            await app.shutdown(drain_timeout=2.0)
            srv.MAX_READAHEAD = old_cap

    try:
        r1, r2, r3 = run(scenario(4096))
    finally:
        release.set()
    assert r1 == b"HOLD"
    assert r2.endswith(b"OK") and len(r2) == 4096 * 4 + 2
    assert r3 == b"OK"


# ---------------------------------------------------------------------------
# scheduler regressions (ISSUE 2 review pass)
# ---------------------------------------------------------------------------

class TestSchedulerRegressions:
    def test_dead_count_consistent_when_sweep_beats_done_callback(self):
        """future.done() flips at cancel/set_exception time, but the
        done-callback that adds leftover units to the dead count runs via
        call_soon — a forming pass in that gap must not drive the dead
        count negative (which would permanently inflate the
        admission-visible depth and shed live traffic)."""
        async def scenario():
            s = ContinuousScheduler(lambda lines: list(lines),
                                    registry=msm.Registry())
            fut = s.submit(["a", "b", "c"])
            fut.cancel()
            # sweep the lanes BEFORE the done-callback runs, like a worker
            # resuming ahead of it in the loop's ready queue
            assert s._form_batch(0.0) == []
            await asyncio.sleep(0)          # now let _on_request_done run
            assert s.queued_units() == 0
            with s._state_lock:
                assert s._dead == 0
            await s.stop()

        run(scenario())

    def test_stop_fails_inflight_requests_instead_of_hanging(self):
        """stop() mid-device-batch: the batch's units already left the
        lanes, so the lane sweep alone would leave those clients awaiting
        forever — in-flight futures must fail explicitly."""
        release = threading.Event()

        def blocking(lines):
            release.wait(5)
            return list(lines)

        async def scenario():
            s = ContinuousScheduler(blocking, window_s=0,
                                    registry=msm.Registry())
            s.start()
            fut = s.submit(["a"])
            while s._inflight == 0:
                await asyncio.sleep(0.005)
            await s.stop()
            release.set()
            assert fut.done()
            with pytest.raises(RuntimeError, match="shut down"):
                fut.result()

        try:
            run(scenario())
        finally:
            release.set()

    def test_submit_empty_resolves_immediately(self):
        """submit([]) must resolve NOW with [] — no unit would ever
        complete it, so it previously returned a future that hung
        forever without a timeout (deferred from the PR 8 review)."""
        async def scenario():
            s = ContinuousScheduler(lambda lines: list(lines),
                                    registry=msm.Registry())
            fut = s.submit([])
            assert fut.done() and fut.result() == []
            # and the counters saw nothing to queue
            assert s.queued_units() == 0
            out = await asyncio.wait_for(fut, 0.1)
            await s.stop()
            return out

        assert run(scenario()) == []

    def test_stop_leaves_no_stale_dead_count(self):
        """The set_exception done-callbacks from stop()'s sweep run AFTER
        stop returns; they must not re-inflate the zeroed counters, or a
        reused scheduler under-reports depth to admission forever."""
        async def scenario():
            s = ContinuousScheduler(lambda lines: list(lines),
                                    registry=msm.Registry())
            fut = s.submit(["a", "b", "c"])
            await s.stop()
            await asyncio.sleep(0)          # late done-callbacks fire now
            assert fut.done()
            with s._state_lock:
                assert s._dead == 0 and s._queued == 0
            s.submit(["x", "y"])
            assert s.queued_units() == 2
            await s.stop()

        run(scenario())
