"""CLI-level end-to-end tests: the Marian binary surface (train → decode →
score → serve) driven exactly as a Marian user would (reference: the
marian-regression-tests style, SURVEY.md §4)."""

import io
import os
import sys

import numpy as np
import pytest
import yaml

from marian_tpu.cli import marian_train, marian_decoder, marian_scorer
from marian_tpu.translator.metrics import corpus_bleu, corpus_chrf


@pytest.fixture(scope="module")
def trained_model(tmp_path_factory):
    """Train a toy model once for all CLI tests."""
    tmp = tmp_path_factory.mktemp("cli")
    src_lines = ["a b c", "b c d", "c d a", "d a b", "a c b", "b d c"] * 2
    tgt_lines = ["x y z", "y z w", "z w x", "w x y", "x z y", "y w z"] * 2
    src = tmp / "train.src"; src.write_text("\n".join(src_lines) + "\n")
    tgt = tmp / "train.tgt"; tgt.write_text("\n".join(tgt_lines) + "\n")
    model = tmp / "model.npz"
    argv = [
        "--type", "transformer",
        "--train-sets", str(src), str(tgt),
        "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
        "--model", str(model),
        "--dim-emb", "32", "--transformer-heads", "4",
        "--transformer-dim-ffn", "64", "--enc-depth", "1", "--dec-depth", "1",
        "--precision", "float32", "float32",
        "--mini-batch", "12", "--maxi-batch", "2",
        "--learn-rate", "0.01", "--after-batches", "30",
        "--disp-freq", "10u", "--save-freq", "1000u",
        "--seed", "1", "--max-length", "20", "--quiet",
        "--valid-sets", str(src), str(tgt),
        "--valid-metrics", "cross-entropy", "--valid-freq", "15u",
        "--beam-size", "2", "--cost-type", "ce-mean-words",
    ]
    marian_train.main(argv)
    return tmp, str(model), src_lines, tgt_lines


class TestTrainCLI:
    def test_artifacts_exist(self, trained_model):
        tmp, model, _, _ = trained_model
        assert os.path.exists(model)
        assert os.path.exists(model + ".progress.yml")
        assert os.path.exists(str(tmp / "v.src.yml"))

    def test_embedded_config_roundtrip(self, trained_model):
        from marian_tpu.common import io as mio
        _, model, _, _ = trained_model
        _, cfg = mio.load_model(model)
        data = yaml.safe_load(cfg)
        assert data["dim-emb"] == 32
        assert data["type"] == "transformer"


class TestDecoderCLI:
    def test_decode_file_to_file(self, trained_model):
        tmp, model, src_lines, _ = trained_model
        inp = tmp / "input.txt"; inp.write_text("a b c\nb c d\n")
        out = tmp / "output.txt"
        marian_decoder.main([
            "--models", model,
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--input", str(inp), "--output", str(out),
            "--beam-size", "4", "--normalize", "0.6",
            "--mini-batch", "8", "--maxi-batch", "1",
            "--max-length", "20", "--quiet",
        ])
        lines = out.read_text().splitlines()
        assert len(lines) == 2
        # overfit toy: source "a b c" should map toward "x y z"
        assert all(tok in "x y z w".split() for tok in lines[0].split())

    def test_nbest_output_format(self, trained_model):
        tmp, model, _, _ = trained_model
        inp = tmp / "in2.txt"; inp.write_text("a b c\n")
        out = tmp / "out2.txt"
        marian_decoder.main([
            "--models", model,
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--input", str(inp), "--output", str(out),
            "--beam-size", "3", "--n-best", "--max-length", "20", "--quiet",
        ])
        lines = out.read_text().splitlines()
        assert len(lines) == 3
        for line in lines:
            parts = line.split(" ||| ")
            assert parts[0] == "0"
            assert "Score=" in parts[2]


class TestScorerCLI:
    def test_scores_parallel_corpus(self, trained_model, capsys):
        tmp, model, _, _ = trained_model
        s = tmp / "sc.src"; s.write_text("a b c\nb c d\n")
        t = tmp / "sc.tgt"; t.write_text("x y z\ny z w\n")
        marian_scorer.main([
            "--models", model,
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--train-sets", str(s), str(t), "--quiet",
        ])
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 2
        scores = [float(x) for x in out]
        assert all(s <= 0 for s in scores)  # log-probs

    def test_nbest_rescoring(self, trained_model, capsys):
        """--n-best: the scorer re-emits the n-best list with the new
        feature appended to the features column (reference: rescorer.h
        n-best rescoring — the marian-scorer half of R2L reranking)."""
        tmp, model, _, _ = trained_model
        s = tmp / "nb.src"; s.write_text("a b c\nb c d\n")
        nb = tmp / "nb.lst"
        nb.write_text(
            "0 ||| x y z ||| F0= -0.1 ||| -0.1\n"
            "0 ||| x y w ||| F0= -0.9 ||| -0.9\n"
            "1 ||| y z w ||| F0= -0.2 ||| -0.2\n")
        marian_scorer.main([
            "--models", model,
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--train-sets", str(s), str(nb), "--n-best",
            "--n-best-feature", "Rescore", "--quiet",
        ])
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 3
        originals = nb.read_text().splitlines()
        rescores = []
        for i, line in enumerate(out):
            parts = line.split(" ||| ")
            assert parts[0] == ("0" if i < 2 else "1")
            assert parts[2].startswith("F0= ") and "Rescore= " in parts[2]
            # total column passes through untouched from the input list
            assert parts[3] == originals[i].split(" ||| ")[3]
            rescores.append(float(parts[2].split("Rescore= ")[1]))
        assert all(r <= 0 for r in rescores)   # log-probs
        # the overfit pair ("a b c" -> "x y z") must outscore the junk
        # hypothesis for the same sentence
        assert rescores[0] > rescores[1]

    def test_summary_perplexity(self, trained_model, capsys):
        tmp, model, _, _ = trained_model
        s = tmp / "sc.src"; s.write_text("a b c\n")
        t = tmp / "sc.tgt"; t.write_text("x y z\n")
        marian_scorer.main([
            "--models", model,
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--train-sets", str(s), str(t), "--summary", "perplexity",
            "--quiet",
        ])
        out = capsys.readouterr().out.strip()
        assert float(out) >= 1.0


class TestEmbedderCLI:
    def test_embeds_one_vector_per_line(self, trained_model, capsys):
        from marian_tpu.cli import marian_embedder
        tmp, model, _, _ = trained_model
        s = tmp / "emb.txt"; s.write_text("a b c\nb c d\nc d a\n")
        marian_embedder.main([
            "--models", model, "--vocabs", str(tmp / "v.src.yml"),
            "--train-sets", str(s), "--quiet",
        ])
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 3
        dims = {len(line.split()) for line in out}
        assert len(dims) == 1 and dims.pop() > 1

    def test_compute_similarity(self, trained_model, capsys):
        """--compute-similarity (reference: embedder similarity mode):
        cosine per line pair; identical lines score 1.0 and beat
        mismatched ones."""
        from marian_tpu.cli import marian_embedder
        tmp, model, _, _ = trained_model
        a = tmp / "sim.a"; a.write_text("a b c\na b c\n")
        b = tmp / "sim.b"; b.write_text("a b c\nd a b\n")
        marian_embedder.main([
            "--models", model, "--vocabs", str(tmp / "v.src.yml"),
            "--train-sets", str(a), str(b), "--compute-similarity",
            "--quiet",
        ])
        out = [float(x) for x in
               capsys.readouterr().out.strip().splitlines()]
        assert len(out) == 2
        assert out[0] == pytest.approx(1.0, abs=1e-4)
        assert -1.0 <= out[1] < out[0]


class TestMetrics:
    def test_bleu_perfect_and_zero(self):
        assert corpus_bleu(["a b c d"], ["a b c d"]) == pytest.approx(100.0)
        assert corpus_bleu(["x"], ["a b c d"]) < 5.0

    def test_bleu_known_value(self):
        # classic example: partial overlap
        hyp = ["the cat is on the mat"]
        ref = ["the cat sat on the mat"]
        b = corpus_bleu(hyp, ref)
        assert 30 < b < 80

    def test_chrf_monotone(self):
        assert corpus_chrf(["abcdef"], ["abcdef"]) == pytest.approx(100.0)
        a = corpus_chrf(["abcdxy"], ["abcdef"])
        b = corpus_chrf(["zzzzzz"], ["abcdef"])
        assert a > b

    def test_bleu_validator_integration(self, trained_model):
        from marian_tpu.common import Options
        from marian_tpu.common import io as mio
        from marian_tpu.data import DefaultVocab
        from marian_tpu.models.encoder_decoder import create_model
        from marian_tpu.translator.validators import TranslationMetricValidator
        import jax.numpy as jnp
        tmp, model, src_lines, tgt_lines = trained_model
        params, cfg = mio.load_model(model)
        opts = Options(yaml.safe_load(cfg)).with_(
            **{"valid-sets": [str(tmp / "train.src"), str(tmp / "train.tgt")],
               "valid-mini-batch": 8, "beam-size": 2, "quiet": True})
        vocabs = [DefaultVocab.load(str(tmp / "v.src.yml")),
                  DefaultVocab.load(str(tmp / "v.tgt.yml"))]
        mdl = create_model(opts, len(vocabs[0]), len(vocabs[1]))
        v = TranslationMetricValidator(opts, vocabs, mdl, "bleu")
        jparams = {k: jnp.asarray(x) for k, x in params.items()}
        score = v.validate(jparams)
        assert 0.0 <= score <= 100.0
        assert score > 10.0  # overfit toy should translate training data well


class TestEnsembleValidation:
    def test_mixed_architecture_models_fail_loudly(self, trained_model,
                                                   tmp_path):
        """--models with unlike architectures must name the offending
        file instead of dying in a traced shape error."""
        from marian_tpu.common import io as mio
        from marian_tpu.common.config_parser import ConfigParser
        from marian_tpu.translator.translator import Translate
        tmp, model, _, _ = trained_model
        other = tmp_path / "other.npz"
        params, cfg = mio.load_model(model)
        params = dict(params)
        params["encoder_l1_extra_W"] = np.zeros((2, 2), np.float32)
        mio.save_model(str(other), params, cfg)
        opts = ConfigParser("translation").parse([
            "--models", model, str(other),
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--beam-size", "2", "--quiet"])
        with pytest.raises(ValueError, match="share one architecture"):
            Translate(opts)

    def test_same_names_different_shapes_fail_loudly(self, trained_model,
                                                     tmp_path):
        """Same topology, different dimensions (the common accidental
        mix — e.g. dim-emb or vocab mismatch) must also be caught."""
        from marian_tpu.common import io as mio
        from marian_tpu.common.config_parser import ConfigParser
        from marian_tpu.translator.translator import Translate
        tmp, model, _, _ = trained_model
        other = tmp_path / "widened.npz"
        params, cfg = mio.load_model(model)
        params = {k: (np.zeros((v.shape[0] * 2,) + v.shape[1:],
                               np.float32) if k == "encoder_Wemb" else v)
                  for k, v in dict(params).items()}
        assert any(k == "encoder_Wemb" for k in params)
        mio.save_model(str(other), params, cfg)
        opts = ConfigParser("translation").parse([
            "--models", model, str(other),
            "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
            "--beam-size", "2", "--quiet"])
        with pytest.raises(ValueError, match="share one architecture"):
            Translate(opts)


class TestTranslationValidator:
    def test_templated_validation_output(self, tmp_path):
        """--valid-metrics translation + --valid-translation-output with
        {U}/{E} templates: each validation beam-decodes the dev set and
        writes its own file (reference: TranslationValidator path
        templates), so successive validations don't overwrite."""
        src = tmp_path / "t.src"; trg = tmp_path / "t.trg"
        src.write_text("a b c\nb c a\n" * 3)
        trg.write_text("x y z\ny z x\n" * 3)
        out_tpl = tmp_path / "dev.u{U}.e{E}.txt"
        marian_train.main([
            "--type", "transformer",
            "--train-sets", str(src), str(trg),
            "--vocabs", str(tmp_path / "v.s.yml"), str(tmp_path / "v.t.yml"),
            "--model", str(tmp_path / "m.npz"),
            "--dim-emb", "16", "--transformer-heads", "2",
            "--transformer-dim-ffn", "32", "--enc-depth", "1",
            "--dec-depth", "1", "--precision", "float32", "float32",
            "--mini-batch", "6", "--learn-rate", "0.01",
            "--after-batches", "8", "--disp-freq", "8",
            "--save-freq", "100", "--seed", "3", "--max-length", "16",
            "--valid-sets", str(src), str(trg),
            "--valid-metrics", "translation", "--valid-freq", "4",
            "--valid-translation-output", str(out_tpl),
            "--beam-size", "2", "--quiet",
        ])
        outs = sorted(p.name for p in tmp_path.glob("dev.u*.txt"))
        assert len(outs) >= 2, outs            # one file per validation
        assert "dev.u4.e" in outs[0] and "{U}" not in outs[0]
        first = (tmp_path / outs[0]).read_text().splitlines()
        assert len(first) == 6                 # one hyp per dev line
