"""Multi-host initialization (reference: src/training/communicator.cpp ::
initMPI / MPIWrapper; here jax.distributed over a localhost coordinator —
VERDICT r1 #7 'exercise multi-host init').

Two OS processes each expose 4 virtual CPU devices and form one 8-device
jax.distributed world; both run ONE identical data-parallel ZeRO-1 train
step through parallel/zero.py on a global mesh and must agree on the loss
to the last bit (the psum'd metrics are world-global)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow

WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    # drop any preloaded tpu/axon plugin state before jax init
    import jax
    import jax._src.xla_bridge as xb
    for plug in ("axon", "tpu"):
        xb._backend_factories.pop(plug, None)

    coord, pid = sys.argv[1], int(sys.argv[2])
    from marian_tpu.common.options import Options
    from marian_tpu.parallel.mesh import initialize_distributed
    initialize_distributed(Options({
        "multi-node": True, "coordinator-address": coord,
        "num-processes": 2, "process-id": pid}))
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import jax.numpy as jnp
    import numpy as np
    from marian_tpu.models.encoder_decoder import create_model
    from marian_tpu.optimizers.optimizers import OptimizerConfig, init_state
    from marian_tpu.optimizers.schedule import LRSchedule
    from marian_tpu.parallel import mesh as M
    from marian_tpu.parallel.zero import build_train_step, place

    opts = Options({
        "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
        "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
        "tied-embeddings-all": True, "precision": ["float32", "float32"],
        "learn-rate": 0.01, "optimizer": "adam", "clip-norm": 1.0,
        "cost-type": "ce-mean-words",
    })
    mesh = M.make_mesh(None, jax.devices())
    model = create_model(opts, 31, 31)
    params = model.init(jax.random.key(0))
    opt_cfg = OptimizerConfig.from_options(opts)
    opt_state = init_state(opt_cfg, params)
    params, opt_state = place(params, opt_state, mesh)
    step = build_train_step(model, opt_cfg, LRSchedule.from_options(opts),
                            "ce-mean-words", mesh, params, opt_state,
                            delay=1, donate=False)
    r = np.random.RandomState(5)
    host = {
        "src_ids": r.randint(2, 31, (8, 6)).astype("int32"),
        "src_mask": np.ones((8, 6), "float32"),
        "trg_ids": r.randint(2, 31, (8, 7)).astype("int32"),
        "trg_mask": np.ones((8, 7), "float32"),
    }
    # every process holds the full global batch; shard_batch lays it out
    # over the global mesh (jax.make_array_from_process-local data is
    # handled inside shard_batch via device_put on addressable shards)
    batch = M.shard_batch({k: jnp.asarray(v) for k, v in host.items()}, mesh)
    p2, o2, metrics = step(params, opt_state, batch,
                           jnp.asarray(1.0, jnp.float32), jax.random.key(1))
    jax.block_until_ready(p2)

    # data-parallel DECODE under multiprocess: the translator's mesh must
    # use only this process's ADDRESSABLE devices (4 of the 8 global) —
    # per-host independent decode, the reference's per-worker translator
    # decomposition. Both processes decode the same rows and must agree
    # exactly (placement-independent beam search).
    from marian_tpu.translator.beam_search import BeamSearch
    imodel = create_model(opts, 31, 31, inference=True)
    bs = BeamSearch(imodel, [params], None,
                    opts.with_(**{"beam-size": 2, "max-length": 12}), 31)
    assert bs.mesh is not None and bs.mesh.shape["data"] == 4, bs.mesh
    nb = bs.search(host["src_ids"][:5], host["src_mask"][:5])
    dec = [h[0]["tokens"] for h in nb]

    print("RESULT " + json.dumps({
        "pid": pid,
        "ce": float(metrics["ce_sum"]),
        "gnorm": float(metrics["gnorm"]),
        "decode": dec,
        "n_dev": len(jax.devices()),
        "n_proc": jax.process_count()}))
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_dp_step(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), coord, str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True)
        for i in range(2)]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][0]
        results.append(json.loads(line[len("RESULT "):]))
    assert all(r["n_proc"] == 2 and r["n_dev"] == 8 for r in results)
    # the loss/gnorm are global psums — both hosts must agree exactly
    assert results[0]["ce"] == results[1]["ce"]
    assert results[0]["gnorm"] == results[1]["gnorm"]
    # per-host decode (local 4-device mesh each) agrees bitwise
    assert results[0]["decode"] == results[1]["decode"]
    assert len(results[0]["decode"]) == 5
    import numpy as np
    assert np.isfinite(results[0]["ce"])
