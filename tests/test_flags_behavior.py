"""Behavior tests for round-2 flag implementations: LR-warmup variants,
early-stopping-on, embedding freezing, env-var interpolation, output
sampling, gradient checkpointing (reference: the corresponding Marian flags;
VERDICT r1 'stop silently ignoring flags')."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common.config_parser import ConfigParser
from marian_tpu.optimizers.schedule import LRSchedule
from marian_tpu.training.scheduler import Scheduler
from marian_tpu.training.training_state import TrainingState

from test_model import tiny_model, fake_batch


@pytest.fixture
def rng():
    return np.random.RandomState(3)


class TestLRScheduleVariants:
    def test_warmup_offset_restarts_ramp(self):
        s = LRSchedule(base_lr=1.0, warmup=10)
        assert float(s(5)) == pytest.approx(0.5)
        s.warmup_offset = 100
        assert float(s(105)) == pytest.approx(0.5)
        assert float(s(101)) == pytest.approx(0.1)

    def test_warmup_cycle_sawtooth(self):
        s = LRSchedule(base_lr=1.0, warmup=10, warmup_cycle=True)
        assert float(s(25)) == pytest.approx(0.5)
        assert float(s(10)) == pytest.approx(1.0)
        assert float(s(11)) == pytest.approx(0.1)

    def test_from_options_reads_cycle(self):
        s = LRSchedule.from_options(Options({
            "learn-rate": 1e-3, "lr-warmup": "16",
            "lr-warmup-cycle": True}))
        assert s.warmup_cycle


class TestEarlyStopping:
    def _sched(self, **over):
        opts = Options({"valid-metrics": ["cross-entropy", "bleu"],
                        "early-stopping": 2, **over})
        return Scheduler(opts, TrainingState())

    def test_epsilon_margin(self):
        sc = self._sched(**{"early-stopping-epsilon": [0.5]})
        assert sc.register_validation("cross-entropy", 10.0)
        # 9.8 improves by only 0.2 < eps 0.5 → stalled
        assert not sc.register_validation("cross-entropy", 9.8)
        assert sc.state.stalled == 1
        assert sc.register_validation("cross-entropy", 9.0)
        assert sc.state.stalled == 0

    def test_early_stopping_on_any_vs_all(self):
        for mode, expected in (("any", 1), ("all", 0), ("first", 0)):
            sc = self._sched(**{"early-stopping-on": mode})
            sc.register_validation("cross-entropy", 10.0)
            sc.register_validation("bleu", 20.0, lower_is_better=False)
            sc.register_validation("cross-entropy", 9.0)   # improves
            sc.register_validation("bleu", 19.0, lower_is_better=False)  # stalls
            assert sc.state.stalled == expected, mode


class TestEmbeddingFix:
    def test_frozen_embeddings_do_not_move(self, rng):
        from marian_tpu.training.graph_group import GraphGroup
        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "learn-rate": 0.1, "optimizer": "adam", "clip-norm": 0.0,
            "cost-type": "ce-mean-words", "embedding-fix-src": True,
        })
        from marian_tpu.models.encoder_decoder import create_model
        model = create_model(opts, 23, 23)
        gg = GraphGroup(model, opts)
        gg.initialize(jax.random.key(0))
        before = np.asarray(gg.params["Wemb"]).copy()
        other_before = np.asarray(
            gg.params["encoder_l1_self_Wq"]).copy()
        batch = fake_batch(rng, b=8, ts=6, tt=7, vocab=23)
        gg.update(dict(batch), 1, jax.random.key(1))
        np.testing.assert_array_equal(np.asarray(gg.params["Wemb"]), before)
        assert not np.allclose(np.asarray(gg.params["encoder_l1_self_Wq"]),
                               other_before)


class TestEnvInterpolation:
    def test_config_env_vars(self, tmp_path):
        os.environ["MTPU_TEST_DIR"] = str(tmp_path)
        cfg = tmp_path / "c.yml"
        cfg.write_text("interpolate-env-vars: true\n"
                       "model: ${MTPU_TEST_DIR}/m.npz\n")
        opts = ConfigParser("training").parse(
            ["--config", str(cfg), "--train-sets", "a", "b"])
        assert opts.get("model") == f"{tmp_path}/m.npz"

    def test_relative_paths(self, tmp_path):
        cfg = tmp_path / "c.yml"
        cfg.write_text("relative-paths: true\nmodel: sub/m.npz\n")
        opts = ConfigParser("training").parse(
            ["--config", str(cfg), "--train-sets", "a", "b"])
        assert opts.get("model") == str(tmp_path / "sub" / "m.npz")


class TestOutputSampling:
    def test_full_sampling_varies_and_topk_restricts(self, rng):
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = tiny_model(vocab=17)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=17)
        outs = []
        for seed in (1, 2):
            opts = Options({"beam-size": 1, "max-length": 12,
                            "output-sampling": ["full", "1.0"],
                            "seed": seed})
            bs = BeamSearch(model, [params], None, opts, None)
            res = bs.search(batch["src_ids"], batch["src_mask"])
            outs.append([h[0]["tokens"] for h in res])
        # two seeds rarely produce identical samples for every sentence
        # (untrained model ≈ uniform over 17 tokens × up to 12 positions)
        assert outs[0] != outs[1]

    def test_greedy_unchanged_without_sampling(self, rng):
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = tiny_model(vocab=17)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=17)
        opts = Options({"beam-size": 1, "max-length": 12})
        r1 = BeamSearch(model, [params], None, opts, None).search(
            batch["src_ids"], batch["src_mask"])
        r2 = BeamSearch(model, [params], None, opts, None).search(
            batch["src_ids"], batch["src_mask"])
        assert [h[0]["tokens"] for h in r1] == [h[0]["tokens"] for h in r2]


class TestGradientCheckpointing:
    def test_same_loss_and_grads(self, rng):
        m1, p1 = tiny_model(vocab=19)
        m2, p2 = tiny_model(vocab=19, **{"gradient-checkpointing": True})
        batch = fake_batch(rng, b=3, ts=6, tt=7, vocab=19)

        def loss(model, p):
            total, _ = model.loss(p, batch, key=None, train=True)
            return total

        l1, g1 = jax.value_and_grad(lambda p: loss(m1, p))(p1)
        l2, g2 = jax.value_and_grad(lambda p: loss(m2, p))(p2)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
        for k in g1:
            np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]),
                                       rtol=1e-5, atol=1e-6)


class TestMiniBatchWarmupTrackLR:
    def test_budget_scale_shrinks_early_batches(self, rng):
        from marian_tpu.data.batch_generator import BatchGenerator
        from marian_tpu.data.corpus import Corpus
        from marian_tpu.data.vocab import DefaultVocab
        import tempfile, os
        lines = ["a b c d e f g h"] * 64
        tmp = tempfile.mkdtemp()
        for name in ("w.src", "w.trg"):
            with open(os.path.join(tmp, name), "w") as fh:
                fh.write("\n".join(lines) + "\n")
        v = DefaultVocab.build(lines)
        opts = Options({"max-length": 20, "shuffle": "none",
                        "mini-batch": 32})
        corpus = Corpus([os.path.join(tmp, "w.src"),
                         os.path.join(tmp, "w.trg")], [v, v], opts)
        small = list(BatchGenerator(corpus, opts, prefetch=False,
                                    budget_scale=lambda: 0.25))
        corpus2 = Corpus([os.path.join(tmp, "w.src"),
                          os.path.join(tmp, "w.trg")], [v, v], opts)
        full = list(BatchGenerator(corpus2, opts, prefetch=False))
        assert max(b.size for b in small) <= 8
        assert max(b.size for b in full) == 32

    def test_track_lr_via_cli(self, tmp_path):
        """--mini-batch-track-lr anchors mini-batch-words-ref; the update
        then scales LR by actual/ref words (OptimizerConfig mechanism
        already covered by optimizer tests) — here: the wiring runs."""
        from marian_tpu.cli import marian_train
        lines_s = ["a b c", "b c d"] * 4
        lines_t = ["x y", "y z"] * 4
        (tmp_path / "t.src").write_text("\n".join(lines_s) + "\n")
        (tmp_path / "t.trg").write_text("\n".join(lines_t) + "\n")
        marian_train.main([
            "--type", "transformer",
            "--train-sets", str(tmp_path / "t.src"), str(tmp_path / "t.trg"),
            "--vocabs", str(tmp_path / "v.s.yml"), str(tmp_path / "v.t.yml"),
            "--model", str(tmp_path / "m.npz"),
            "--dim-emb", "16", "--transformer-heads", "2",
            "--transformer-dim-ffn", "32", "--enc-depth", "1",
            "--dec-depth", "1", "--precision", "float32", "float32",
            "--mini-batch", "8", "--mini-batch-words", "64",
            "--mini-batch-track-lr", "--mini-batch-warmup", "4u",
            "--learn-rate", "0.01", "--after-batches", "6",
            "--disp-freq", "3u", "--save-freq", "100u", "--seed", "1",
            "--max-length", "20", "--quiet", "--overwrite",
            "--cost-type", "ce-mean-words",
        ])
        assert (tmp_path / "m.npz").exists()
