"""Runtime jit retrace witness (common/jitwit.py, ISSUE 17): the
backend-compile listener, compile-key notes and retrace detection, the
domain cross-check against the static jit model (analysis/jitgraph.py),
engine integration over a real PagedDecodeEngine, and THE SEEDED DRILL:
with the `jit.closure_vary` fault point armed, the engine rebuilds a
step jit it already paid for — the witness must report the retrace AND
observe the real backend recompile, proving the detector against a real
compile-cache bug and never a mocked report."""

from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from marian_tpu.common import faultpoints as fp
from marian_tpu.common import jitwit
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.translator.iteration import PagedDecodeEngine

from tests.test_beam_search import tiny_model

ROOT = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module", autouse=True)
def _jitwit_witness(jitwit_witness):
    """Module teardown cross-check (the drill test resets the witness
    state it deliberately dirties, so the shared verdict stays green)."""
    yield


VOCAB_WORDS = [" ".join(f"w{i}" for i in range(35))]


@pytest.fixture(scope="module")
def tiny():
    vocab = DefaultVocab.build(VOCAB_WORDS)
    model, params, _ = tiny_model(vocab=len(vocab), seed=7,
                                  **{"dec-depth": 2, "enc-depth": 2})
    return model, params, vocab


def make_engine(tiny, **kw):
    model, params, vocab = tiny
    args = dict(max_rows=4, page_len=4, src_len_cap=8, max_length_cap=12)
    args.update(kw)
    return PagedDecodeEngine(model, params, vocab, vocab, **args)


class TestListener:
    def test_armed_and_installed(self):
        assert jitwit.enabled()        # conftest arms MARIAN_JITWIT=1
        assert jitwit.install()        # idempotent re-install

    def test_strict_window_captures_backend_compile(self):
        with jitwit.strict() as w:
            jax.jit(lambda x: x + 1)(jnp.ones((3,)))
        assert len(w.compiles) >= 1
        # test-driven compiles attribute to <external>: exempt from the
        # static cross-check by design (the model covers marian_tpu/)
        assert all(site == jitwit.EXTERNAL_SITE for site, _ in w.compiles)

    def test_strict_window_closes(self):
        with jitwit.strict() as w:
            pass
        jax.jit(lambda x: x * 2)(jnp.ones((3,)))
        assert w.compiles == []


class TestNotesAndRetraces:
    def test_duplicate_note_same_engine_is_a_retrace(self):
        jitwit.reset()
        tok = jitwit.new_token()
        jitwit.note_compile_key(tok, ("step", 4, 2),
                                domains=(("POW2", 4),))
        assert jitwit.retraces() == []
        other = jitwit.new_token()
        # a DIFFERENT engine noting the same key is legitimate
        jitwit.note_compile_key(other, ("step", 4, 2))
        assert jitwit.retraces() == []
        jitwit.note_compile_key(tok, ("step", 4, 2))
        assert len(jitwit.retraces()) == 1
        vs = jitwit.check_against_static(ROOT)
        assert any("RETRACE" in v for v in vs)
        jitwit.reset()

    def test_unknown_registry_fails_the_verdict(self):
        jitwit.reset()
        tok = jitwit.new_token()
        jitwit.note_compile_key(tok, ("k", 3),
                                domains=(("NO_SUCH_TABLE", 3),))
        vs = jitwit.check_against_static(ROOT)
        assert any("NO_SUCH_TABLE" in v for v in vs)
        jitwit.reset()


class TestDomainValidation:
    @pytest.fixture(scope="class")
    def model(self):
        from marian_tpu.analysis.jitgraph import static_jit_model
        return static_jit_model(ROOT)

    def test_registries_discovered(self, model):
        assert model.known_registry("ROW_BUCKETS")
        assert model.known_registry("JOIN_BUCKETS")
        assert model.known_registry("POW2")        # virtual
        assert model.known_registry("HALVING")     # virtual
        assert not model.known_registry("NO_SUCH_TABLE")

    def test_value_in_domain(self, model):
        assert jitwit._value_in_domain(model, "POW2", 8)
        assert not jitwit._value_in_domain(model, "POW2", 6)
        assert jitwit._value_in_domain(model, "HALVING", 1)
        assert not jitwit._value_in_domain(model, "HALVING", 0)
        vals = model.registry_values("ROW_BUCKETS")
        assert vals and jitwit._value_in_domain(
            model, "ROW_BUCKETS", max(vals))
        # cap-clamped draws (min(b, max_rows)) are in-domain
        assert jitwit._value_in_domain(model, "ROW_BUCKETS", 3)
        assert not jitwit._value_in_domain(
            model, "ROW_BUCKETS", max(vals) + 1)

    def test_engine_sites_are_compile_capable(self, model):
        assert any(
            s.startswith("marian_tpu/translator/iteration.py::")
            for s in model.compile_capable)


class TestEngineIntegration:
    def test_engine_notes_its_compile_keys(self, tiny):
        jitwit.reset()
        eng = make_engine(tiny)
        out = eng.decode_texts(["w3 w4"])
        assert len(out) == 1
        keys = {key[0] for (_s, _t, key) in jitwit.noted_keys()}
        assert "install" in keys and "step" in keys
        sites = {s for (s, _t, _k) in jitwit.noted_keys()}
        assert any("translator/iteration.py" in s for s in sites)
        # green path: real engine traffic satisfies the static model
        assert jitwit.check_against_static(ROOT) == []

    def test_closure_vary_drill_is_caught(self, tiny):
        """THE SEEDED DRILL: arm `jit.closure_vary` so the engine's
        next round varies a traced closure constant and rebuilds the
        step jit for a key it already compiled — the witness must
        record the duplicate note as a retrace, observe the REAL
        backend recompile it causes, and fail the verdict."""
        jitwit.reset()
        eng = make_engine(tiny)
        eng.decode_texts(["w3 w4"])            # warm the rb=1 step jit
        assert jitwit.retraces() == []
        with fp.active("jit.closure_vary=fail@1"):
            with jitwit.strict() as w:
                out = eng.decode_texts(["w3 w4"])
        assert len(out) == 1                   # traffic still served
        rts = jitwit.retraces()
        assert any(key[0] == "step" for (_site, key) in rts), \
            "drill varied the step closure but no retrace was recorded"
        # the rebuilt jit really recompiled, attributed to the engine
        assert any("translator/iteration.py" in site
                   for site, _ in w.compiles), \
            "drill retrace produced no observable backend compile"
        vs = jitwit.check_against_static(ROOT)
        assert any("RETRACE" in v for v in vs)
        jitwit.reset()   # leave the module-teardown verdict green
