"""Model-layer tests: transformer shapes, autodiff vs finite differences,
teacher-forcing vs incremental-decode consistency, label smoothing math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import transformer as T
from marian_tpu.models.encoder_decoder import create_model, batch_to_arrays
from marian_tpu.ops.ops import cross_entropy, layer_norm


def tiny_options(**over):
    base = {
        "type": "transformer",
        "dim-emb": 16, "transformer-heads": 2, "transformer-dim-ffn": 32,
        "enc-depth": 2, "dec-depth": 2,
        "transformer-ffn-activation": "relu",
        "tied-embeddings-all": True,
        "label-smoothing": 0.0,
        "precision": ["float32", "float32"],
        "max-length": 64,
    }
    base.update(over)
    return Options(base)


def tiny_model(vocab=23, **over):
    opts = tiny_options(**over)
    model = create_model(opts, vocab, vocab)
    params = model.init(jax.random.key(0))
    return model, params


def fake_batch(rng, b=4, ts=10, tt=12, vocab=23):
    src = rng.randint(2, vocab, size=(b, ts)).astype(np.int32)
    trg = rng.randint(2, vocab, size=(b, tt)).astype(np.int32)
    src_mask = np.ones((b, ts), np.float32)
    trg_mask = np.ones((b, tt), np.float32)
    # ragged lengths with EOS
    for i in range(b):
        ls = rng.randint(3, ts)
        lt = rng.randint(3, tt)
        src[i, ls:] = 0; src_mask[i, ls + 1:] = 0; src[i, ls] = 0
        trg[i, lt:] = 0; trg_mask[i, lt + 1:] = 0; trg[i, lt] = 0
    return {"src_ids": jnp.asarray(src), "src_mask": jnp.asarray(src_mask),
            "trg_ids": jnp.asarray(trg), "trg_mask": jnp.asarray(trg_mask)}


class TestTransformerStructure:
    def test_param_names_marian_style(self):
        model, params = tiny_model()
        names = set(params)
        assert "Wemb" in names  # tied-all
        assert "encoder_l1_self_Wq" in names
        assert "encoder_l2_ffn_W2" in names
        assert "decoder_l1_context_Wk" in names
        assert "decoder_ff_logit_out_b" in names
        assert "decoder_ff_logit_out_W" not in names  # tied
        assert "encoder_l1_self_Wo_ln_scale" in names  # postnorm "dan"

    def test_untied_has_output_matrix(self):
        model, params = tiny_model(**{"tied-embeddings-all": False})
        assert "encoder_Wemb" in params and "decoder_Wemb" in params
        assert "decoder_ff_logit_out_W" in params

    def test_forward_shapes_and_dtype(self, rng):
        model, params = tiny_model()
        batch = fake_batch(rng)
        enc = model.encode_for_decode(params, batch["src_ids"], batch["src_mask"])
        assert enc.shape == (4, 10, 16)
        logits = T.decode_train(model.cfg, params, enc, batch["src_mask"],
                                batch["trg_ids"], batch["trg_mask"], train=False)
        assert logits.shape == (4, 12, 23)
        assert logits.dtype == jnp.float32

    def test_prenorm_config(self):
        model, params = tiny_model(**{"transformer-preprocess": "n",
                                      "transformer-postprocess": "da",
                                      "transformer-postprocess-top": "n"})
        assert "encoder_top_ln_scale" in params
        assert "decoder_top_ln_scale" in params


class TestAutodiff:
    def test_grad_matches_finite_difference(self, rng):
        """jax.grad vs central finite difference on a few random weights
        (reference test model: src/tests/units/graph_tests.cpp)."""
        model, params = tiny_model(vocab=13)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=13)

        def loss_fn(p):
            total, _ = model.loss(p, batch, key=None, train=True)
            return total

        grads = jax.grad(loss_fn)(params)
        for name in ["encoder_l1_self_Wq", "decoder_l2_ffn_W1", "Wemb"]:
            g = np.asarray(grads[name])
            flat_idx = np.unravel_index(np.argmax(np.abs(g)), g.shape)
            eps = 1e-3
            p_plus = dict(params)
            arr = np.asarray(params[name]).copy()
            arr[flat_idx] += eps
            p_plus[name] = jnp.asarray(arr)
            p_minus = dict(params)
            arr2 = np.asarray(params[name]).copy()
            arr2[flat_idx] -= eps
            p_minus[name] = jnp.asarray(arr2)
            fd = (float(loss_fn(p_plus)) - float(loss_fn(p_minus))) / (2 * eps)
            assert abs(fd - g[flat_idx]) < 2e-2 * max(1.0, abs(fd)), \
                f"{name}: fd={fd} vs grad={g[flat_idx]}"


class TestDecodeConsistency:
    def test_step_matches_teacher_forcing(self, rng):
        """Incremental decode_step must reproduce decode_train logits when fed
        the gold prefix — validates KV caching, masks and the zero-embedding
        start (the reference checks this implicitly via regression decodes)."""
        model, params = tiny_model(vocab=17)
        batch = fake_batch(rng, b=3, ts=6, tt=7, vocab=17)
        enc = model.encode_for_decode(params, batch["src_ids"], batch["src_mask"])
        full = T.decode_train(model.cfg, params, enc, batch["src_mask"],
                              batch["trg_ids"], batch["trg_mask"], train=False)
        state = model.start_state(params, enc, batch["src_mask"], max_len=8)
        tt = batch["trg_ids"].shape[1]
        prev = jnp.zeros((3, 1), jnp.int32)
        for t in range(tt):
            logits, state = model.step(params, state, prev, batch["src_mask"])
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t, :]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]


class TestLossMath:
    def test_label_smoothing_formula(self):
        logits = jnp.asarray(np.random.RandomState(0).randn(2, 5).astype(np.float32))
        labels = jnp.asarray([1, 3])
        eps = 0.1
        ce = cross_entropy(logits, labels, eps)
        logp = np.asarray(jax.nn.log_softmax(logits))
        expected = (1 - eps) * -logp[np.arange(2), [1, 3]] + eps * -logp.mean(-1)
        np.testing.assert_allclose(np.asarray(ce), expected, rtol=1e-5)

    def test_layer_norm_oracle(self):
        x = np.random.RandomState(1).randn(3, 7).astype(np.float32)
        s = np.random.RandomState(2).rand(7).astype(np.float32)
        b = np.random.RandomState(3).randn(7).astype(np.float32)
        y = np.asarray(layer_norm(jnp.asarray(x), jnp.asarray(s), jnp.asarray(b)))
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-9) * s + b
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)

    def test_masked_positions_do_not_affect_loss(self, rng):
        model, params = tiny_model(vocab=13)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=13)
        total1, aux1 = model.loss(params, batch, train=False)
        # corrupt ids in masked positions — loss must not change
        trg = np.asarray(batch["trg_ids"]).copy()
        mask = np.asarray(batch["trg_mask"])
        trg[mask == 0] = 7
        batch2 = dict(batch, trg_ids=jnp.asarray(trg))
        total2, aux2 = model.loss(params, batch2, train=False)
        np.testing.assert_allclose(float(total1), float(total2), rtol=1e-5)
