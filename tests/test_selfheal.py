"""Self-healing training (ISSUE 19 tentpole): divergence policy ladder
(--on-divergence throw | warn | rollback), live NaN-skip surfacing
(marian_train_updates_skipped_total + bounded-lag consecutive-skip
detection), and the --train-stall-timeout step watchdog.

The subprocess drills inject the new train.* CATALOG fault points
(train.nan_grad / train.diverge_cost / train.hang) into the real
marian-train driver and assert on QUIET-PROOF evidence only: exit codes,
flight-dump files (named by their trip slug), the Prometheus metrics text
embedded in each dump, and the raw stderr lines the watchdog writes
below the logging layer.
"""

import glob
import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.training import bundle as bdl
from marian_tpu.training.scheduler import DivergenceError, Scheduler
from marian_tpu.training.train import STALL_EXIT_CODE
from marian_tpu.training.training_state import TrainingState


# ---------------------------------------------------------------------------
# in-process: policy resolution + skip accounting (scheduler.py)
# ---------------------------------------------------------------------------

def _sched(**over):
    base = {"disp-freq": 100, "cost-type": "ce-sum"}
    base.update(over)
    return Scheduler(Options(base), TrainingState())


def _skip_counter():
    from marian_tpu.serving import metrics as msm
    return msm.counter("marian_train_updates_skipped_total")


class _LazyFlag:
    """Stand-in for the optimizer's lazy device scalar: not fenced until
    someone forces it (float())."""

    def __init__(self, value):
        self.value = value
        self.forced = False

    def is_ready(self):
        return False

    def __float__(self):
        self.forced = True
        return float(self.value)


class TestDivergencePolicy:
    def test_mode_resolution(self):
        assert _sched().divergence_mode == "warn"
        assert _sched(**{"throw-on-divergence": True}) \
            .divergence_mode == "throw"
        assert _sched(**{"on-divergence": "rollback"}) \
            .divergence_mode == "rollback"
        # explicit flag wins over the legacy boolean
        assert _sched(**{"on-divergence": "warn",
                         "throw-on-divergence": True}) \
            .divergence_mode == "warn"

    def test_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="on-divergence"):
            _sched(**{"on-divergence": "explode"})

    def test_skips_counted_and_warned_immediately(self, monkeypatch):
        """A NaN-skipped update increments
        marian_train_updates_skipped_total and warns on the FIRST skip —
        no waiting for the display boundary."""
        from marian_tpu.training import scheduler as sched_mod
        warned = []
        monkeypatch.setattr(
            sched_mod.log, "warn",
            lambda fmt, *a: warned.append(str(fmt).format(*a)))
        s = _sched()
        c = _skip_counter()
        before = c.value
        s.update(1.0, 10.0, 2, skipped=np.float32(0.0))
        assert c.value == before
        s.update(0.0, 0.0, 2, skipped=np.float32(1.0))
        assert c.value == before + 1
        assert any("skipped" in w and "non-finite gradient" in w
                   for w in warned), warned

    def test_consecutive_skips_raise_within_window(self):
        s = _sched(**{"on-divergence": "throw",
                      "divergence-skip-window": 2,
                      "check-gradient-nan": True})
        s.update(1.0, 10.0, 2, skipped=np.float32(1.0))
        with pytest.raises(DivergenceError,
                           match="consecutive NaN-skipped"):
            s.update(0.0, 0.0, 2, skipped=np.float32(1.0))

    def test_good_update_resets_the_window(self):
        s = _sched(**{"on-divergence": "throw",
                      "divergence-skip-window": 2,
                      "check-gradient-nan": True})
        s.update(0.0, 0.0, 2, skipped=np.float32(1.0))
        s.update(1.0, 10.0, 2, skipped=np.float32(0.0))   # recovered
        s.update(0.0, 0.0, 2, skipped=np.float32(1.0))    # not consecutive
        assert s.state.batches == 3                       # no raise

    def test_lazy_flags_drain_with_bounded_lag(self):
        """An unfenced flag is left alone while young (never a hot-loop
        sync) but force-synced once it is _skip_lag updates old."""
        s = _sched(**{"on-divergence": "throw",
                      "divergence-skip-window": 1,
                      "check-gradient-nan": True})
        flag = _LazyFlag(1.0)
        s.update(0.0, 0.0, 2, skipped=flag)
        assert not flag.forced                 # young + not ready: deferred
        s.update(1.0, 10.0, 2)
        assert not flag.forced                 # age 1 < _skip_lag: still lazy
        with pytest.raises(DivergenceError):   # age 2: force-synced
            s.update(1.0, 10.0, 2)
        assert flag.forced

    def test_drain_skips_is_an_end_of_run_fence(self):
        """The train loop calls drain_skips() after its last update so a
        divergence inside the final lag window still raises."""
        s = _sched(**{"on-divergence": "throw",
                      "divergence-skip-window": 1,
                      "check-gradient-nan": True})
        s.update(0.0, 0.0, 2, skipped=_LazyFlag(1.0))
        with pytest.raises(DivergenceError):
            s.drain_skips()

    def test_warn_mode_names_armed_guards_and_rollback_plan(
            self, monkeypatch):
        """--on-divergence warn (the default) must say which guards were
        armed and what rollback WOULD have done — the old one-liner left
        the operator guessing (ISSUE 19 satellite fix)."""
        from marian_tpu.training import scheduler as sched_mod
        warned = []
        monkeypatch.setattr(
            sched_mod.log, "warn",
            lambda fmt, *a: warned.append(str(fmt).format(*a)))
        s = _sched(**{"disp-freq": 1, "check-gradient-nan": True,
                      "divergence-retries": 5})
        s.update(float("nan") * 10.0, 10.0, 2)    # display boundary syncs
        msg = "\n".join(warned)
        assert "armed guards" in msg
        assert "--check-gradient-nan on" in msg
        assert "rollback would restore the last good checkpoint" in msg
        assert "give up after 5 attempts" in msg

    def test_throw_mode_display_boundary_raises(self):
        s = _sched(**{"disp-freq": 1, "on-divergence": "throw"})
        with pytest.raises(DivergenceError, match="non-finite cost"):
            s.update(float("nan"), 10.0, 2)


# ---------------------------------------------------------------------------
# subprocess drills: the real driver under injected train.* faults
# ---------------------------------------------------------------------------

_TRAIN_SNIPPET = (
    "import json, sys\n"
    "from marian_tpu.common import Options\n"
    "from marian_tpu.training.train import train_main\n"
    "train_main(Options(json.load(open(sys.argv[1]))))\n")


def _selfheal_config(d, src, vocab, **over):
    cfg = {
        "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
        "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
        "tied-embeddings-all": True, "max-length": 16,
        "precision": ["float32", "float32"], "seed": 7,
        "train-sets": [src, src], "vocabs": [vocab, vocab],
        "model": os.path.join(d, "model.npz"),
        "mini-batch": 4, "maxi-batch": 1, "after-batches": 6,
        "save-freq": "2u", "disp-freq": 10, "learn-rate": 0.01,
        "shuffle": "none", "overwrite": True, "quiet": True,
        # the self-healing ladder under test
        "check-gradient-nan": True, "on-divergence": "rollback",
        "divergence-retries": 2, "divergence-skip-window": 1,
        "divergence-lr-backoff": 0.5,
        # arm the flight recorder: dumps are the quiet-proof evidence
        "trace-dump": os.path.join(d, "dumps"),
    }
    cfg.update(over)
    return cfg


def _run_train(cfg, d, faults):
    cfg_path = os.path.join(d, "cfg.json")
    with open(cfg_path, "w") as fh:
        json.dump(cfg, fh)
    env = dict(os.environ, JAX_PLATFORMS="cpu", MARIAN_FAULTS=faults)
    return subprocess.run(
        [sys.executable, "-c", _TRAIN_SNIPPET, cfg_path], env=env,
        timeout=300, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _dumps(d, slug):
    return sorted(glob.glob(os.path.join(d, "dumps", f"flight-*{slug}*.json")))


def _final_model_finite(mp):
    with np.load(mp) as z:
        for name in z.files:
            if name.startswith("special:"):
                continue
            assert np.isfinite(z[name]).all(), f"non-finite {name}"


def _progress_batches(mp):
    for line in open(mp + ".progress.yml"):
        if line.startswith("batches:"):
            return int(line.split(":")[1])
    raise AssertionError("no batches in progress.yml")


@pytest.fixture(scope="module")
def selfheal_env(tmp_path_factory):
    base = tmp_path_factory.mktemp("selfheal")
    lines = ["a b c d", "b c d e", "c d e f", "d e f g",
             "e f g a", "f g a b", "g a b c", "a c e g"] * 2
    src = str(base / "t.src")
    with open(src, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    from marian_tpu.data.vocab import DefaultVocab
    vocab = str(base / "v.yml")
    DefaultVocab.build(lines).save(vocab)
    return {"base": base, "src": src, "vocab": vocab}


class TestRollbackDrill:
    def test_nan_grad_rollback_recovers(self, selfheal_env):
        """One poisoned batch at update 3 ("train.nan_grad=fail@3"): the
        skip is detected within the bounded lag, the run rolls back to the
        update-2 bundle, backs off the LR, replays past the poison window
        (the exact-hit fault does not refire) and completes all 6 updates
        with exit 0 — self-healed, loudly."""
        d = str(selfheal_env["base"] / "rollback_recovers")
        os.mkdir(d)
        cfg = _selfheal_config(d, selfheal_env["src"], selfheal_env["vocab"])
        mp = cfg["model"]
        proc = _run_train(cfg, d, "train.nan_grad=fail@3")
        assert proc.returncode == 0, \
            proc.stderr.decode("utf-8", "replace")[-3000:]
        dumps = _dumps(d, "divergence-rollback")
        assert len(dumps) == 1, dumps
        dump = json.load(open(dumps[0]))
        assert "rollback 1/2" in dump["detail"]
        assert "NaN-skipped" in dump["detail"]
        # the dump's metrics snapshot carries the counters: skips seen,
        # one rollback taken
        assert "marian_train_divergence_rollbacks_total 1" in dump["metrics"]
        assert "marian_train_updates_skipped_total" in dump["metrics"]
        # rollback never tears checkpoints: every surviving bundle valid
        root = bdl.bundle_root(mp)
        for name in bdl.list_bundles(root):
            ok, why, _ = bdl.validate_bundle(os.path.join(root, name))
            assert ok, why
        assert _progress_batches(mp) == 6
        _final_model_finite(mp)
        # LR backoff left its mark in the final progress: decay factor 0.5
        assert "factor: 0.5" in open(mp + ".progress.yml").read()

    def test_retries_exhausted_gives_up_loudly(self, selfheal_env):
        """"train.nan_grad=fail@3+" poisons EVERY batch from hit 3 on —
        rollback cannot outrun it. After --divergence-retries attempts the
        driver must stop self-healing and abort with the full story, plus
        a divergence-giveup flight dump."""
        d = str(selfheal_env["base"] / "retries_exhausted")
        os.mkdir(d)
        cfg = _selfheal_config(d, selfheal_env["src"], selfheal_env["vocab"],
                               **{"divergence-retries": 1})
        proc = _run_train(cfg, d, "train.nan_grad=fail@3+")
        err = proc.stderr.decode("utf-8", "replace")
        assert proc.returncode not in (0, STALL_EXIT_CODE), err[-2000:]
        assert "divergence retries exhausted after 1 rollback" in err
        assert len(_dumps(d, "divergence-rollback")) == 1
        assert len(_dumps(d, "divergence-giveup")) == 1

    def test_diverge_cost_caught_at_display_boundary(self, selfheal_env):
        """train.diverge_cost poisons the APPLIED loss sum — params took a
        bad step, nothing for --check-gradient-nan to skip. The display
        boundary's cost sync must still route it into the same rollback
        ladder."""
        d = str(selfheal_env["base"] / "diverge_cost")
        os.mkdir(d)
        cfg = _selfheal_config(d, selfheal_env["src"], selfheal_env["vocab"],
                               **{"disp-freq": 1})
        mp = cfg["model"]
        proc = _run_train(cfg, d, "train.diverge_cost=fail@3")
        assert proc.returncode == 0, \
            proc.stderr.decode("utf-8", "replace")[-3000:]
        dumps = _dumps(d, "divergence-rollback")
        assert len(dumps) == 1, dumps
        assert "non-finite cost" in json.load(open(dumps[0]))["detail"]
        assert _progress_batches(mp) == 6
        _final_model_finite(mp)


class TestWatchdog:
    def test_hang_trips_watchdog(self, selfheal_env):
        """"train.hang=hang@2" wedges the loop before update 2 ever
        dispatches. The watchdog must notice within --train-stall-timeout,
        write a flight dump naming the stalled step, save a
        .stalled.progress.yml breadcrumb, and exit with the DISTINCT
        retriable code 75 (EX_TEMPFAIL) — not the generic fault code."""
        d = str(selfheal_env["base"] / "watchdog")
        os.mkdir(d)
        cfg = _selfheal_config(d, selfheal_env["src"], selfheal_env["vocab"],
                               **{"train-stall-timeout": 2.0})
        mp = cfg["model"]
        proc = _run_train(cfg, d, "train.hang=hang@2")
        err = proc.stderr.decode("utf-8", "replace")
        assert proc.returncode == STALL_EXIT_CODE, \
            (proc.returncode, err[-2000:])
        # raw stderr line survives --quiet (written below the log layer)
        assert "TRAIN WATCHDOG" in err
        dumps = _dumps(d, "train-watchdog")
        assert len(dumps) == 1, dumps
        dump = json.load(open(dumps[0]))
        assert "training step 2 never fenced" in dump["detail"]
        assert dump["extra"]["stalled_step"] == 2
        assert dump["extra"]["last_completed_update"] == 1
        assert "marian_train_watchdog_trips_total 1" in dump["metrics"]
        # checkpoint-what's-safe: the host-side progress breadcrumb
        assert os.path.exists(mp + ".stalled.progress.yml")

    def test_math_guard(self):
        # STALL_EXIT_CODE must stay distinct from the injected-fault code
        from marian_tpu.common.faultpoints import FAULT_EXIT_CODE
        assert STALL_EXIT_CODE == 75
        assert STALL_EXIT_CODE != FAULT_EXIT_CODE
        assert not math.isnan(STALL_EXIT_CODE)
