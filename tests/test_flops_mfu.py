"""MFU accounting (common/flops.py) + XLA-cache manifest hardening
(profiling.check_cache_manifest) — VERDICT r2 next-steps #3 and #6."""

import json
import os

from marian_tpu.common.flops import peak_bf16_flops, transformer_train_flops


class TestPeakTable:
    def test_known_generations(self):
        assert peak_bf16_flops("TPU v4") == 275e12
        assert peak_bf16_flops("TPU v5 lite") == 197e12
        assert peak_bf16_flops("TPU v5p") == 459e12
        assert peak_bf16_flops("TPU v6 lite") == 918e12
        # v2/v3: jax lists each of the chip's 2 TensorCores as a device,
        # so the table carries PER-DEVICE peaks (half the per-chip number)
        assert peak_bf16_flops("TPU v3") == 61.5e12
        assert peak_bf16_flops("TPU v2") == 22.5e12

    def test_v4_lite_not_confused_with_v4(self):
        assert peak_bf16_flops("TPU v4 lite") == 138e12

    def test_unknown_returns_none(self):
        assert peak_bf16_flops("cpu") is None
        assert peak_bf16_flops("TPU v99") is None
        assert peak_bf16_flops("") is None


class TestTrainFlops:
    dims = dict(emb=512, ffn=2048, enc_depth=6, dec_depth=6, vocab=32000)

    def _f(self, **kw):
        a = dict(self.dims, src_tokens=1000, trg_tokens=1000,
                 src_width=64, trg_width=64)
        a.update(kw)
        return transformer_train_flops(**a)

    def test_magnitude_vs_6n_rule(self):
        """The 6·N·tokens rule of thumb (N = matmul params incl. the tied
        output projection) should agree within ~25% at short widths where
        attention-score terms are small."""
        d, f, L, V = 512, 2048, 6, 32000
        n_enc = L * (4 * d * d + 2 * d * f)
        n_dec = L * (8 * d * d + 2 * d * f)
        n_out = d * V
        approx = 6 * (1000 * n_enc + 1000 * (n_dec + n_out))
        exact = self._f()
        assert 0.75 < exact / approx < 1.25

    def test_attention_term_scales_with_width(self):
        """Same token counts, wider padding → more score FLOPs (each real
        token attends over the padded row). At 32k vocab the logits term
        dominates, so 64→512 widths add ~13%, not 8× — the check is that
        the attention term exists and is the right order, not that it
        dominates."""
        assert self._f(src_width=512, trg_width=512) > 1.10 * self._f()
        # with a small vocab the width term is clearly visible
        small = dict(vocab=1000)
        assert self._f(src_width=512, trg_width=512, **small) \
            > 1.15 * self._f(**small)

    def test_linear_in_tokens(self):
        one = self._f()
        two = self._f(src_tokens=2000, trg_tokens=2000)
        assert abs(two / one - 2.0) < 1e-6

    def test_deeper_costs_more(self):
        assert self._f(enc_depth=12) > self._f() > self._f(enc_depth=3)


class TestCacheManifest:
    def test_write_then_check_roundtrip(self, tmp_path):
        from marian_tpu.common.profiling import check_cache_manifest
        p = str(tmp_path)
        assert check_cache_manifest(write=True, path=p) is True
        assert os.path.exists(os.path.join(p, "MANIFEST.json"))
        assert check_cache_manifest(path=p) is True

    def test_missing_manifest_is_cold(self, tmp_path):
        from marian_tpu.common.profiling import check_cache_manifest
        assert check_cache_manifest(path=str(tmp_path / "nope")) is False

    def test_drift_detected(self, tmp_path):
        from marian_tpu.common.profiling import check_cache_manifest
        p = str(tmp_path)
        check_cache_manifest(write=True, path=p)
        mp = os.path.join(p, "MANIFEST.json")
        with open(mp) as fh:
            fp = json.load(fh)
        fp["platform_version"] = "libtpu-from-another-era"
        with open(mp, "w") as fh:
            json.dump(fp, fh)
        assert check_cache_manifest(path=p) is False
