"""MFU accounting (common/flops.py) + XLA-cache manifest hardening
(profiling.check_cache_manifest) — VERDICT r2 next-steps #3 and #6."""

import json
import os

from marian_tpu.common.flops import peak_bf16_flops, transformer_train_flops


class TestPeakTable:
    def test_known_generations(self):
        assert peak_bf16_flops("TPU v4") == 275e12
        assert peak_bf16_flops("TPU v5 lite") == 197e12
        assert peak_bf16_flops("TPU v5p") == 459e12
        assert peak_bf16_flops("TPU v6 lite") == 918e12
        # v2/v3: jax lists each of the chip's 2 TensorCores as a device,
        # so the table carries PER-DEVICE peaks (half the per-chip number)
        assert peak_bf16_flops("TPU v3") == 61.5e12
        assert peak_bf16_flops("TPU v2") == 22.5e12

    def test_v4_lite_not_confused_with_v4(self):
        assert peak_bf16_flops("TPU v4 lite") == 138e12

    def test_unknown_returns_none(self):
        assert peak_bf16_flops("cpu") is None
        assert peak_bf16_flops("TPU v99") is None
        assert peak_bf16_flops("") is None


class TestTrainFlops:
    dims = dict(emb=512, ffn=2048, enc_depth=6, dec_depth=6, vocab=32000)

    def _f(self, **kw):
        a = dict(self.dims, src_tokens=1000, trg_tokens=1000,
                 src_width=64, trg_width=64)
        a.update(kw)
        return transformer_train_flops(**a)

    def test_magnitude_vs_6n_rule(self):
        """The 6·N·tokens rule of thumb (N = matmul params incl. the tied
        output projection) should agree within ~25% at short widths where
        attention-score terms are small."""
        d, f, L, V = 512, 2048, 6, 32000
        n_enc = L * (4 * d * d + 2 * d * f)
        n_dec = L * (8 * d * d + 2 * d * f)
        n_out = d * V
        approx = 6 * (1000 * n_enc + 1000 * (n_dec + n_out))
        exact = self._f()
        assert 0.75 < exact / approx < 1.25

    def test_attention_term_scales_with_width(self):
        """Same token counts, wider padding → more score FLOPs (each real
        token attends over the padded row). At 32k vocab the logits term
        dominates, so 64→512 widths add ~13%, not 8× — the check is that
        the attention term exists and is the right order, not that it
        dominates."""
        assert self._f(src_width=512, trg_width=512) > 1.10 * self._f()
        # with a small vocab the width term is clearly visible
        small = dict(vocab=1000)
        assert self._f(src_width=512, trg_width=512, **small) \
            > 1.15 * self._f(**small)

    def test_linear_in_tokens(self):
        one = self._f()
        two = self._f(src_tokens=2000, trg_tokens=2000)
        assert abs(two / one - 2.0) < 1e-6

    def test_deeper_costs_more(self):
        assert self._f(enc_depth=12) > self._f() > self._f(enc_depth=3)


class TestCacheManifest:
    def test_write_then_check_roundtrip(self, tmp_path):
        from marian_tpu.common.profiling import check_cache_manifest
        p = str(tmp_path)
        assert check_cache_manifest(write=True, path=p) is True
        assert os.path.exists(os.path.join(p, "MANIFEST.json"))
        assert check_cache_manifest(path=p) is True

    def test_missing_manifest_is_cold(self, tmp_path):
        from marian_tpu.common.profiling import check_cache_manifest
        assert check_cache_manifest(path=str(tmp_path / "nope")) is False

    def test_drift_detected(self, tmp_path):
        from marian_tpu.common.profiling import check_cache_manifest
        p = str(tmp_path)
        check_cache_manifest(write=True, path=p)
        mp = os.path.join(p, "MANIFEST.json")
        with open(mp) as fh:
            fp = json.load(fh)
        fp["platform_version"] = "libtpu-from-another-era"
        with open(mp, "w") as fh:
            json.dump(fp, fh)
        assert check_cache_manifest(path=p) is False


class TestDecodeRoofline:
    """VERDICT r3 #5: the decode levers (int8, shortlist) proven on the
    analytic roofline — docs/DECODE_ROOFLINE.md records the defaults
    decision these pins guard."""
    ARGS = dict(emb=1024, ffn=4096, dec_depth=6, vocab=32000,
                t_past=16, src_width=24)

    def _cost(self, rows, **kw):
        from marian_tpu.common.flops import decode_step_cost
        return decode_step_cost(rows=rows, **{**self.ARGS, **kw})

    def test_weight_bytes_do_not_scale_with_rows(self):
        assert self._cost(1)["weight_bytes"] == \
            self._cost(4096)["weight_bytes"]
        assert self._cost(4096)["flops"] > 1000 * self._cost(1)["flops"]

    def test_int8_halves_weight_bytes(self):
        assert self._cost(8, weight_bytes=1.0)["weight_bytes"] * 2 == \
            self._cost(8, weight_bytes=2.0)["weight_bytes"]

    def test_shortlist_cuts_logits_stream(self):
        full = self._cost(8)
        sl = self._cost(8, shortlist=256)
        # V=32k, d=1024 logits table is ~25% of the per-step bytes
        saved = full["weight_bytes"] - sl["weight_bytes"]
        assert saved == (32000 - 256) * 1024 * 2.0

    def test_levers_pay_when_weight_bound_and_fade_at_the_ridge(self):
        from marian_tpu.common.flops import decode_lever_report
        r = decode_lever_report(1024, 4096, 6, 32000, 16, 24, 256,
                                "TPU v4")
        small, big = r["rows"][8], r["rows"][4096]
        assert small["memory_bound"] and not big["memory_bound"]
        assert small["int8_speedup"] > 1.8
        assert small["int8_shortlist_speedup"] > 2.3
        assert abs(big["int8_speedup"] - 1.0) < 1e-6   # compute-bound
        assert big["shortlist_speedup"] > 1.2          # still cuts FLOPs

    def test_defaults_hint_fires_only_when_a_lever_pays(self):
        from marian_tpu.common.flops import decode_defaults_hint
        kw = dict(emb=1024, ffn=4096, dec_depth=6, vocab=32000, rows=64,
                  device_kind="TPU v4")
        hint = decode_defaults_hint(int8_on=False, shortlist_on=False, **kw)
        assert hint and "int8" in hint and "shortlist" in hint
        assert decode_defaults_hint(int8_on=True, shortlist_on=True,
                                    **kw) is None
        # unknown device / CPU: never advise
        assert decode_defaults_hint(int8_on=False, shortlist_on=False,
                                    **{**kw, "device_kind": "cpu"}) is None
        # compute-bound (huge rows): int8 off is fine; shortlist-only
        # advice may fire through its FLOPs cut, int8 must not be forced
        h = decode_defaults_hint(int8_on=True, shortlist_on=False,
                                 **{**kw, "rows": 8192})
        assert h is None or "int8" not in h
