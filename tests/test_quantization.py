"""Int8 quantized inference (config #5) — ops/quantization.py + marian-conv
(reference: intgemm8 CPU decode path, SURVEY.md §2.4/§2.9)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.ops.quantization import (QTensor, int8_affine, int8_gather,
                                         int8_logits, is_quantized, quantize,
                                         quantize_params, wrap_quantized)


class TestQuantizeOps:
    def test_roundtrip_error_bounded(self, rng):
        w = rng.randn(64, 32).astype(np.float32)
        q = quantize(w, axis=1)
        back = np.asarray(q.dequantize())
        # per-column symmetric int8: max error <= scale/2 per column
        scale = np.asarray(q.scale)
        assert np.all(np.abs(back - w) <= scale[None, :] * 0.5 + 1e-7)

    def test_int8_affine_close_to_float(self, rng):
        x = jnp.asarray(rng.randn(4, 64), jnp.float32)
        w = rng.randn(64, 32).astype(np.float32)
        b = rng.randn(1, 32).astype(np.float32)
        q = quantize(w, axis=1)
        y_int8 = np.asarray(int8_affine(x, q, jnp.asarray(b)))
        y_f32 = np.asarray(x) @ w + b
        # int8×int8 with dynamic act quant on unstructured gaussians:
        # worst element ~8-10% relative, mean ~1.5%
        denom = np.maximum(np.abs(y_f32), np.abs(y_f32).max() * 0.1)
        rel = np.abs(y_int8 - y_f32) / denom
        assert np.max(rel) < 0.15
        assert np.mean(rel) < 0.03

    def test_int8_logits_matches_transposed_affine(self, rng):
        x = jnp.asarray(rng.randn(3, 16), jnp.float32)
        table = rng.randn(40, 16).astype(np.float32)   # [V, d]
        q = quantize(table, axis=0)
        y = np.asarray(int8_logits(x, q))
        ref = np.asarray(x) @ table.T
        assert y.shape == (3, 40)
        denom = np.maximum(np.abs(ref), np.abs(ref).max() * 0.1)
        rel = np.abs(y - ref) / denom
        assert np.max(rel) < 0.15
        assert np.mean(rel) < 0.03
        # shortlist slicing
        sl = jnp.asarray([0, 5, 7], jnp.int32)
        y_sl = np.asarray(int8_logits(x, q, sl))
        np.testing.assert_allclose(y_sl, y[:, [0, 5, 7]], rtol=1e-6)

    def test_int8_gather(self, rng):
        table = rng.randn(20, 8).astype(np.float32)
        q = quantize(table, axis=0)
        ids = jnp.asarray([[1, 3], [0, 19]], jnp.int32)
        out = np.asarray(int8_gather(q, ids, jnp.float32))
        np.testing.assert_allclose(out, np.asarray(q.dequantize())[[[1, 3], [0, 19]]],
                                   rtol=1e-6)

    def test_qtensor_is_pytree(self, rng):
        q = quantize(rng.randn(8, 8).astype(np.float32))
        leaves, treedef = jax.tree_util.tree_flatten(q)
        assert len(leaves) == 2
        q2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert q2.axis == q.axis
        # usable inside jit as an argument
        out = jax.jit(lambda x, qq: int8_affine(x, qq))(
            jnp.ones((2, 8), jnp.float32), q)
        assert out.shape == (2, 8)


class TestQuantizeParams:
    def test_pairs_and_wrap(self, rng):
        params = {
            "Wemb": rng.randn(32, 16).astype(np.float32),
            "encoder_l1_self_Wq": rng.randn(16, 16).astype(np.float32),
            "encoder_l1_self_bq": np.zeros((1, 16), np.float32),
            "encoder_l1_self_Wo_ln_scale": np.ones((1, 16), np.float32),
        }
        qp = quantize_params(params)
        assert is_quantized(qp)
        assert qp["Wemb"].dtype == np.int8
        assert "Wemb:qscale" in qp and qp["Wemb:qscale"].shape == (32,)
        assert qp["encoder_l1_self_Wq:qscale"].shape == (16,)
        # biases / layer norms untouched
        assert qp["encoder_l1_self_bq"].dtype == np.float32
        assert "encoder_l1_self_bq:qscale" not in qp
        wrapped = wrap_quantized({k: jnp.asarray(v) for k, v in qp.items()})
        assert isinstance(wrapped["Wemb"], QTensor)
        assert wrapped["Wemb"].axis == 0
        assert isinstance(wrapped["encoder_l1_self_Wq"], QTensor)
        assert wrapped["encoder_l1_self_Wq"].axis == 1
        assert not isinstance(wrapped["encoder_l1_self_bq"], QTensor)


class TestConvCLI:
    def test_convert_and_decode(self, trained_model_q, capsys):
        """marian-conv int8tpu on a trained toy model; int8 beam decode
        reproduces the float decode on the training sentences."""
        from marian_tpu.cli import marian_conv, marian_decoder
        tmp, model, src_lines, _ = trained_model_q
        qmodel = str(tmp / "model.int8.npz")
        marian_conv.main(["--from", model, "--to", qmodel,
                          "--gemm-type", "int8tpu"])
        assert os.path.getsize(qmodel) < os.path.getsize(model)

        def decode(mpath, lines):
            from marian_tpu.translator.translator import Translate
            from marian_tpu.common.options import Options
            from marian_tpu.common.config_parser import parse_options
            opts = parse_options(
                ["--models", mpath,
                 "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
                 "--beam-size", "2", "--quiet"], mode="translation")
            import io as _io
            out = _io.StringIO()
            Translate(opts).run(lines, stream=out)
            return out.getvalue().strip().split("\n")

        f32 = decode(model, src_lines[:4])
        q8 = decode(qmodel, src_lines[:4])
        # int8 on an overfit toy model: decodes agree
        assert sum(a == b for a, b in zip(f32, q8)) >= 3

    def test_format_conversion_bin(self, trained_model_q):
        from marian_tpu.cli import marian_conv
        from marian_tpu.common import io as mio
        tmp, model, _, _ = trained_model_q
        bpath = str(tmp / "model.bin")
        marian_conv.main(["--from", model, "--to", bpath])
        p1, c1 = mio.load_model(model)
        p2, c2 = mio.load_model(bpath)
        assert set(p1) == set(p2)
        for k in p1:
            np.testing.assert_array_equal(p1[k], p2[k])


@pytest.fixture(scope="module")
def trained_model_q(tmp_path_factory):
    """Small trained model for conversion tests (separate from test_cli_e2e's
    fixture so the files can run independently)."""
    from marian_tpu.cli import marian_train
    tmp = tmp_path_factory.mktemp("conv")
    src_lines = ["a b c", "b c d", "c d a", "d a b", "a c b", "b d c"] * 2
    tgt_lines = ["x y z", "y z w", "z w x", "w x y", "x z y", "y w z"] * 2
    (tmp / "train.src").write_text("\n".join(src_lines) + "\n")
    (tmp / "train.tgt").write_text("\n".join(tgt_lines) + "\n")
    model = str(tmp / "model.npz")
    marian_train.main([
        "--type", "transformer",
        "--train-sets", str(tmp / "train.src"), str(tmp / "train.tgt"),
        "--vocabs", str(tmp / "v.src.yml"), str(tmp / "v.tgt.yml"),
        "--model", model,
        "--dim-emb", "32", "--transformer-heads", "4",
        "--transformer-dim-ffn", "64", "--enc-depth", "1", "--dec-depth", "1",
        "--precision", "float32", "float32",
        "--mini-batch", "12", "--maxi-batch", "2",
        "--learn-rate", "0.01", "--after-batches", "30",
        "--disp-freq", "10u", "--save-freq", "1000u",
        "--seed", "1", "--max-length", "20", "--quiet",
        "--cost-type", "ce-mean-words",
    ])
    return tmp, model, src_lines, tgt_lines
