"""Translator driver with the depth-1 dispatch/collect decode pipeline
(translator.py — the reference hides host n-best extraction behind a
worker thread pool, src/translator/translator.h; here XLA async dispatch
plays that role). Pins output order and equality with the direct
(unpipelined) BeamSearch path across multiple batches."""

import pathlib

import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.vocab import DefaultVocab


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    import jax
    from marian_tpu.models.encoder_decoder import create_model
    from marian_tpu.common import io as mio

    tmp = tmp_path_factory.mktemp("xlate")
    words = [f"w{i}" for i in range(30)]
    vocab = DefaultVocab.build([" ".join(words)])
    vpath = tmp / "v.yml"
    vocab.save(str(vpath))

    opts = Options({"type": "transformer", "dim-emb": 16,
                    "transformer-heads": 2, "transformer-dim-ffn": 32,
                    "enc-depth": 1, "dec-depth": 1,
                    "tied-embeddings-all": True, "max-length": 16,
                    "precision": ["float32", "float32"], "seed": 3})
    model = create_model(opts, len(vocab), len(vocab), inference=True)
    params = model.init(jax.random.key(3))
    mpath = tmp / "m.npz"
    mio.save_model(str(mpath), {k: np.asarray(v) for k, v in params.items()},
                   opts.as_yaml())

    rng = np.random.RandomState(3)
    lines = [" ".join(words[i] for i in rng.randint(2, 28, rng.randint(2, 7)))
             for _ in range(13)]           # 13 lines, mini-batch 4 → 4 batches
    src = tmp / "in.txt"
    src.write_text("\n".join(lines) + "\n")
    return tmp, str(mpath), str(vpath), str(src), lines


def _translate(setup, **extra):
    tmp, mpath, vpath, src, lines = setup
    from marian_tpu.translator.translator import Translate
    out = tmp / f"out{len(extra)}.txt"
    opts = Options({"models": [mpath], "vocabs": [vpath, vpath],
                    "input": [src], "output": str(out),
                    "beam-size": 3, "normalize": 0.6, "mini-batch": 4,
                    "maxi-batch": 2, "max-length": 16,
                    "max-length-crop": True, **extra})
    Translate(opts).run()
    return out.read_text().splitlines()


def test_pipeline_outputs_in_input_order_and_match_direct(setup,
                                                          monkeypatch):
    tmp, mpath, vpath, src, lines = setup
    got = _translate(setup)
    assert len(got) == len(lines)

    # metric census (mtlint MT-METRIC-UNTESTED): the decode-side series
    # are emitted by the run above into the process-wide registry
    from marian_tpu.serving import metrics as msm
    text = msm.REGISTRY.render()
    for name in ("marian_translate_batches_total",
                 "marian_translate_sentences_total",
                 "marian_translate_batch_fill_ratio"):
        assert name in text, name
    assert msm.REGISTRY.get(
        "marian_translate_sentences_total").value >= len(lines)

    # reference: IDENTICAL batch geometry (same padded shapes, same
    # compiled programs) but with the pipeline defeated — search_async
    # collects eagerly, so each batch finishes on-device before the next
    # is dispatched. Any difference is then attributable to pipelining
    # itself, not to pad-width-dependent float reduction order.
    from marian_tpu.translator.beam_search import BeamSearch

    orig = BeamSearch.search_async

    class _Done:
        def __init__(self, nbests):
            self._nbests = nbests

        def collect(self):
            return self._nbests

    def eager(self, *a, **kw):
        return _Done(orig(self, *a, **kw).collect())

    monkeypatch.setattr(BeamSearch, "search_async", eager)
    unpipelined = _translate(setup)
    assert got == unpipelined


def test_pipeline_nbest_format(setup):
    got = _translate(setup, **{"n-best": True})
    # n-best lines: 'idx ||| text ||| ... Score= x' covering every input
    idx = [int(line.split("|||")[0]) for line in got]
    assert set(idx) == set(range(13))
    assert all("|||" in line for line in got)