"""Test harness: run everything on CPU with 8 virtual XLA devices so
multi-device sharding logic (DP/ZeRO-1/TP/SP) is testable without TPU hardware
— the upgrade over the reference's "needs 2 real GPUs" CI gap (SURVEY.md §4).

Must set flags BEFORE jax initializes a backend, hence module-level here.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Tests must be hermetic CPU-only. If a TPU-tunnel PJRT plugin (axon) was
# registered by sitecustomize at interpreter start, jax is already imported
# and (a) the env-var JAX_PLATFORMS was read at import time, (b) backends()
# would initialize the tunnel client, whose health must not affect tests.
# Force the platform via jax.config and drop the plugin's backend factory
# BEFORE any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Pallas registers MLIR lowerings for the "tpu" platform at import time, which
# requires the tpu backend factory to still be registered — import it BEFORE
# dropping the factories (kernels then run in interpret mode on CPU).
try:
    import jax.experimental.pallas  # noqa: F401
    import jax.experimental.pallas.tpu  # noqa: F401
except Exception:
    pass

try:
    import jax._src.xla_bridge as _xb
    for _plugin in ("axon", "tpu"):
        _xb._backend_factories.pop(_plugin, None)
except Exception:
    pass

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


@pytest.fixture
def tmp_corpus(tmp_path):
    """A tiny parallel corpus on disk: (src_path, tgt_path, lines)."""
    src_lines = [
        "the cat sat on the mat",
        "a dog barks",
        "the quick brown fox jumps over the lazy dog",
        "hello world",
        "machine translation is fun",
        "the cat chased the dog",
        "a fox and a dog",
        "hello again world",
    ]
    tgt_lines = [
        "die katze sass auf der matte",
        "ein hund bellt",
        "der schnelle braune fuchs springt ueber den faulen hund",
        "hallo welt",
        "maschinelle uebersetzung macht spass",
        "die katze jagte den hund",
        "ein fuchs und ein hund",
        "hallo nochmal welt",
    ]
    src = tmp_path / "train.src"
    tgt = tmp_path / "train.tgt"
    src.write_text("\n".join(src_lines) + "\n")
    tgt.write_text("\n".join(tgt_lines) + "\n")
    return str(src), str(tgt), (src_lines, tgt_lines)
