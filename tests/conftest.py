"""Test harness: run everything on CPU with 8 virtual XLA devices so
multi-device sharding logic (DP/ZeRO-1/TP/SP) is testable without TPU hardware
— the upgrade over the reference's "needs 2 real GPUs" CI gap (SURVEY.md §4).

Must set flags BEFORE jax initializes a backend, hence module-level here.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Arm the runtime lockdep witness for the whole test process (ISSUE 6):
# every lock created through common/lockdep.py records its per-thread
# acquisition order, and the tier-1 serving + lifecycle suites assert at
# teardown that nothing was observed the STATIC lock-order graph does not
# model (tests/test_serving.py / test_lifecycle.py `lockdep_witness`).
# Must be set before any marian_tpu module constructs a lock — metrics.py
# builds the process-wide REGISTRY at import time — hence module-level
# here, before the first marian_tpu import below.
os.environ.setdefault("MARIAN_LOCKDEP", "1")

# Continuous KV-pool invariant auditing (ISSUE 11): every iteration-mode
# admit+step round in the suite ends with a full free-list / page-table /
# position audit — a pool bug fails tier-1 loudly at the round that
# introduced it, not at some later quiesce boundary. Read at engine
# construction time (translator/iteration.py), so module-level here.
os.environ.setdefault("MARIAN_POOL_AUDIT", "1")

# Arm the runtime OWNERSHIP witness (ISSUE 15): every KVPool
# acquire/release/transfer records its acting call site, and the tier-1
# serving/iteration/beam/prefix suites assert at teardown that every
# observed (acquire-site -> release-site) pairing is one the static
# ownership graph derived (tests use the shared `ownership_witness`
# fixture below). Read at pool-construction time, so module-level here.
os.environ.setdefault("MARIAN_OWNWIT", "1")

# Arm the runtime jit RETRACE witness (ISSUE 17): every backend compile
# the process performs (jax.monitoring's backend_compile_duration events)
# is attributed to the nearest marian_tpu frame, and the tier-1
# serving/iteration/beam suites assert at teardown that every observed
# compile maps to a site the static jit model (analysis/jitgraph.py)
# predicted — and that no instrumented compile key was ever traced twice
# (a silent retrace). Read lazily by common/jitwit.py, but set before the
# first marian_tpu import for symmetry with the other witnesses.
os.environ.setdefault("MARIAN_JITWIT", "1")

from marian_tpu.common.hermetic import force_cpu_devices  # noqa: E402

jax = force_cpu_devices(8)

# The compile listener must be registered before the first jit runs so
# the witness sees EVERY compile in the process, not just post-arming
# ones (idempotent; no-op when MARIAN_JITWIT is unset).
from marian_tpu.common import jitwit  # noqa: E402

jitwit.install()

import numpy as np
import pytest

# ---------------------------------------------------------------------------
# Test tiers. `pytest -m "not slow"` is the fast tier (CI-on-every-commit,
# ~7 min on one CPU core); `pytest` runs everything (the TP/SP sweeps and
# end-to-end training runs take several minutes more). Centralized here so
# the tier stays visible in one place; names are test functions (parametrized
# variants inherit).
# ---------------------------------------------------------------------------

SLOW_TESTS = {
    # multi-device sweeps (tests/test_parallel_tp_sp.py, test_distributed.py)
    "test_ring_is_differentiable",
    "test_dryrun_multichip_8",
    "test_tp_sp_matches_single_device_loss",
    "test_sp_training_step_matches_dense",
    "test_ring_grad_finite_with_empty_rows",
    "test_matches_dense",
    "test_8dev_matches_1dev_trajectory",
    "test_manual_and_gspmd_paths_agree",
    "test_compact_equivalent_on_composed_mesh",
    # end-to-end training runs (test_training.py)
    "test_exact_resume",
    "test_optimizer_delay_equivalent_to_big_batch",
    "test_loss_decreases_and_decodes",
    "test_ema_saved",
    "test_sigterm_like_save",
    "test_progress_state_counts",
    # heavier model/decoder correctness (several-second jit compiles each)
    "test_step_matches_teacher_forcing",
    "test_forward_shapes_and_dtype",
    "test_grad_matches_finite_difference",
    "test_loss_finite_and_grads_flow",
    "test_teacher_forcing_matches_incremental",
    "test_param_names",
    "test_learns_first_token_rule",
    "test_mlm_training_reduces_loss",
    "test_bert_pretraining_e2e",
    "test_loss_finite_and_masking_rate",
    "test_matches_reference_beam",
    "test_normalized_matches_reference",
    "test_beam1_equals_greedy",
    "test_ensemble_of_identical_models_is_identity",
    "test_loss_uses_both_sources",
    "test_translator_builds_all_encoders",
    "test_params_have_two_encoders_and_two_context_blocks",
    "test_second_source_changes_output",
    "test_loss_and_grads",
    "test_train_with_native_backend",
    "test_convert_and_decode",
    # crash-resume kill sweep over the full fault-point catalog (each
    # variant is one killed trainer subprocess + one in-process resume;
    # the two load-bearing points stay tier-1 in
    # test_kill_mid_save_resumes_bitexact)
    "test_kill_at_remaining_fault_points_resumes_bitexact",
}


# ---------------------------------------------------------------------------
# `-m slow_core`: the load-bearing slow tests, verifiable in ONE judging
# sitting (<8 min target; VERDICT r4 weak #6 — the full slow tier outgrew
# a review budget). Covers: two golden trajectory configs (plain + the
# composed pipe×expert mesh), the ZeRO-1 compiled-HLO collective pins and
# the rest of test_distributed, the collective-free mesh decode pins, and
# real 2-process multihost init.
# ---------------------------------------------------------------------------

SLOW_CORE_FILES = {"test_distributed.py", "test_translate_mesh.py",
                   "test_multihost.py"}
SLOW_CORE_IDS = {"test_golden[transformer-base]",
                 "test_golden[pipe-expert-moe]"}


# ---------------------------------------------------------------------------
# Time-budgeted tier ordering (ISSUE 19): harnesses run the fast tier
# under a wall-clock budget (CI step timeouts, the ROADMAP tier-1
# command's `timeout`), and the self-healing drill suites below spawn
# fresh interpreters that re-import jax and recompile the model — 5-20s
# per test, ~100x the suite median. They are scheduled after the rest of
# the suite so a truncated run sheds only these known-expensive drills
# instead of an equal wall-clock's worth of cheap unit coverage pushed
# past the deadline; an untruncated run (CI) executes the identical set.
# The in-process divergence-policy tests are sub-second and stay in their
# normal position. Everything else keeps plain collection order — per-test
# cost-sorting was tried and regressed: recorded per-test durations are
# warm-cache artifacts of the default order, so reordering silently moves
# compile costs onto formerly-cheap tests and rebuilds module fixtures.
# ---------------------------------------------------------------------------

TRAILING_DRILL_FILES = {"test_elastic_resume.py", "test_selfheal.py"}
TRAILING_EXEMPT_CLASSES = {"TestDivergencePolicy"}  # in-process, sub-second


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.name.split("[")[0]
        if base in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
        fname = os.path.basename(str(item.fspath))
        if fname in SLOW_CORE_FILES or item.name in SLOW_CORE_IDS:
            item.add_marker(pytest.mark.slow_core)

    def trailing(item):
        if os.path.basename(str(item.fspath)) not in TRAILING_DRILL_FILES:
            return False
        cls = getattr(item, "cls", None)
        return cls is None or cls.__name__ not in TRAILING_EXEMPT_CLASSES

    items[:] = sorted(items, key=trailing)


@pytest.fixture(scope="module")
def lockdep_witness():
    """Runtime lockdep witness cross-check (ISSUE 6), shared by the
    tier-1 serving + lifecycle suites (module-scoped autouse aliases
    there — NOT autouse here: the check rebuilds the static lock-order
    graph, too slow for every module): at module teardown, every lock
    acquisition order the witness OBSERVED must be an edge the static
    graph predicted. A violation is a blind spot in
    analysis/callgraph.py — extend the model, never baseline it."""
    yield
    from marian_tpu.common import lockdep
    if lockdep.enabled():
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        violations = lockdep.check_against_static(root)
        assert violations == [], (
            "runtime lockdep witness contradicts the static lock-order "
            "graph (docs/STATIC_ANALYSIS.md 'The lockdep witness'):\n"
            + "\n".join(violations))


@pytest.fixture(scope="module")
def ownership_witness():
    """Runtime ownership witness cross-check (ISSUE 15), shared by the
    tier-1 serving/iteration/beam/prefix suites (module-scoped autouse
    aliases there, mirroring `lockdep_witness`): at module teardown,
    every (acquire-site -> release-site) pairing the witness OBSERVED
    on the refcounted KV pool must be one the static ownership graph
    (analysis/ownership.py) derived. A violation is a blind spot in the
    verb registry or the pairing model — extend the analysis, never
    baseline it ("the auditor catches it at runtime, mtlint proves it
    can't happen")."""
    yield
    from marian_tpu.common import ownwit
    if ownwit.enabled():
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        violations = ownwit.check_against_static(root)
        assert violations == [], (
            "runtime ownership witness contradicts the static ownership "
            "graph (docs/STATIC_ANALYSIS.md 'The ownership witness'):\n"
            + "\n".join(violations))


@pytest.fixture(scope="module")
def jitwit_witness():
    """Runtime jit retrace witness cross-check (ISSUE 17), shared by the
    tier-1 serving/iteration/beam suites (module-scoped autouse aliases
    there, mirroring `lockdep_witness`/`ownership_witness`): at module
    teardown, every backend compile the witness OBSERVED must be
    attributed to a function the static jit model (analysis/jitgraph.py)
    knows can compile, every instrumented compile key's domain values
    must come from their declared bucket registries, and NO instrumented
    key may have been traced twice (a silent retrace — the compile-cache
    bug class MT-JIT-CLOSURE-VARYING exists to prevent). A violation is
    a blind spot in the jit model — extend the analysis, never baseline
    it."""
    yield
    from marian_tpu.common import jitwit as jw
    if jw.enabled():
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        violations = jw.check_against_static(root)
        assert violations == [], (
            "runtime jit retrace witness contradicts the static jit "
            "compile-cache model (docs/STATIC_ANALYSIS.md 'Compile-cache "
            "hygiene'):\n" + "\n".join(violations))


@pytest.fixture(autouse=True)
def _reset_perf_plane():
    """The perf/capacity plane (obs/perf.py — ISSUE 9) is process-wide
    and the CLI parser defaults --perf-accounting ON, so any test that
    drives a real CLI in-process (marian_train.main and friends)
    enables it globally. Left enabled it changes behavior tests rely
    on — e.g. lifecycle warmup becomes per-bucket (multiple golden
    calls), breaking call-counting stub executors. Disable it again
    after every test; suites that want it enable it explicitly."""
    yield
    from marian_tpu import obs
    if obs.PERF.enabled:
        obs.PERF.reset()


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


@pytest.fixture
def tmp_corpus(tmp_path):
    """A tiny parallel corpus on disk: (src_path, tgt_path, lines)."""
    src_lines = [
        "the cat sat on the mat",
        "a dog barks",
        "the quick brown fox jumps over the lazy dog",
        "hello world",
        "machine translation is fun",
        "the cat chased the dog",
        "a fox and a dog",
        "hello again world",
    ]
    tgt_lines = [
        "die katze sass auf der matte",
        "ein hund bellt",
        "der schnelle braune fuchs springt ueber den faulen hund",
        "hallo welt",
        "maschinelle uebersetzung macht spass",
        "die katze jagte den hund",
        "ein fuchs und ein hund",
        "hallo nochmal welt",
    ]
    src = tmp_path / "train.src"
    tgt = tmp_path / "train.tgt"
    src.write_text("\n".join(src_lines) + "\n")
    tgt.write_text("\n".join(tgt_lines) + "\n")
    return str(src), str(tgt), (src_lines, tgt_lines)
