"""Test harness: run everything on CPU with 8 virtual XLA devices so
multi-device sharding logic (DP/ZeRO-1/TP/SP) is testable without TPU hardware
— the upgrade over the reference's "needs 2 real GPUs" CI gap (SURVEY.md §4).

Must set flags BEFORE jax initializes a backend, hence module-level here.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from marian_tpu.common.hermetic import force_cpu_devices  # noqa: E402

jax = force_cpu_devices(8)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


@pytest.fixture
def tmp_corpus(tmp_path):
    """A tiny parallel corpus on disk: (src_path, tgt_path, lines)."""
    src_lines = [
        "the cat sat on the mat",
        "a dog barks",
        "the quick brown fox jumps over the lazy dog",
        "hello world",
        "machine translation is fun",
        "the cat chased the dog",
        "a fox and a dog",
        "hello again world",
    ]
    tgt_lines = [
        "die katze sass auf der matte",
        "ein hund bellt",
        "der schnelle braune fuchs springt ueber den faulen hund",
        "hallo welt",
        "maschinelle uebersetzung macht spass",
        "die katze jagte den hund",
        "ein fuchs und ein hund",
        "hallo nochmal welt",
    ]
    src = tmp_path / "train.src"
    tgt = tmp_path / "train.tgt"
    src.write_text("\n".join(src_lines) + "\n")
    tgt.write_text("\n".join(tgt_lines) + "\n")
    return str(src), str(tgt), (src_lines, tgt_lines)
