"""Elastic resume across device geometries (ISSUE 19 tentpole part 2,
plus ROADMAP item 3's ZeRO-1 no-re-replication regression).

Checkpoint bundles store the optimizer state LOGICALLY (gathered,
unsharded arrays in .optimizer.npz) and record the save-time mesh
geometry in the bundle manifest. Restoring on a different device count
must therefore (a) reassemble bit-identical logical optimizer state and
(b) re-shard it for the CURRENT mesh — per-device optimizer-sweep bytes
shrink ~N x instead of silently re-replicating.

The geometry sweep runs real subprocesses under
XLA_FLAGS=--xla_force_host_platform_device_count={8,4,1}: save at 8,
restore at 4 and at 1, compare sha256 digests of the gathered state.
"""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.parallel import zero
from marian_tpu.training import bundle as bdl
from marian_tpu.training.graph_group import GraphGroup


def _tiny_gg():
    opts = Options({"type": "transformer", "dim-emb": 16,
                    "transformer-heads": 2, "transformer-dim-ffn": 32,
                    "enc-depth": 1, "dec-depth": 1,
                    "tied-embeddings-all": True, "label-smoothing": 0.0,
                    "precision": ["float32", "float32"], "max-length": 16,
                    "learn-rate": 0.05, "optimizer": "adam",
                    "clip-norm": 0.0, "exponential-smoothing": 0.0})
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(21))
    return gg


def _batch(seed=0):
    import jax.numpy as jnp
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


class TestZero1NoReplication:
    """ROADMAP item 3: the regression that fails if per-device optimizer
    bytes quietly re-replicate. Runs on conftest's 8 forced CPU devices."""

    def test_sweep_bytes_shrink_per_device(self):
        gg = _tiny_gg()
        key = prng.stream(prng.root_key(21), prng.STREAM_DROPOUT)
        gg.update(_batch(0), 1, key)
        ndev = jax.device_count()
        assert ndev == 8, "conftest forces 8 host devices"
        sweep = zero.optimizer_sweep_bytes(gg.opt_state)
        logical = zero.optimizer_logical_bytes(gg.opt_state)
        assert logical > 0
        assert len(sweep) == ndev, "optimizer state absent from a device"
        # every tensor in the tiny model has a leading dim divisible by 8,
        # so a correctly sharded sweep is exactly logical/8 per device;
        # 1.5x slack tolerates a stray replicated scalar, while full
        # re-replication (= logical per device) fails by ~5x
        worst = max(sweep.values())
        assert worst * ndev <= logical * 1.5, (
            f"optimizer state re-replicated: {worst} bytes on one device "
            f"vs {logical} logical bytes across {ndev} devices "
            f"(sweep={sweep})")

    def test_logical_bytes_count_gathered_state(self):
        gg = _tiny_gg()
        flat = gg.optimizer_arrays()
        expect = sum(np.asarray(v).nbytes for k, v in flat.items()
                     if ":" in k)       # m:/v: groups; skip scalar 't'
        got = zero.optimizer_logical_bytes(gg.opt_state)
        # logical bytes reflect the gathered per-parameter arrays (the
        # scalar step count is noise either way)
        assert abs(got - expect) <= 64, (got, expect)


# ---------------------------------------------------------------------------
# geometry sweep: save at 8 devices, restore at 4 and at 1
# ---------------------------------------------------------------------------

_CHILD = r"""
import hashlib, json, os, sys
mode, d, ndev = sys.argv[1], sys.argv[2], int(sys.argv[3])
import jax
assert jax.device_count() == ndev, (jax.device_count(), ndev)
import numpy as np
import jax.numpy as jnp
from marian_tpu.common import Options, prng
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.parallel import zero
from marian_tpu.training.checkpoint import load_checkpoint, save_checkpoint
from marian_tpu.training.graph_group import GraphGroup
from marian_tpu.training.training_state import TrainingState

opts = Options({"type": "transformer", "dim-emb": 16,
                "transformer-heads": 2, "transformer-dim-ffn": 32,
                "enc-depth": 1, "dec-depth": 1,
                "tied-embeddings-all": True, "label-smoothing": 0.0,
                "precision": ["float32", "float32"], "max-length": 16,
                "learn-rate": 0.05, "optimizer": "adam", "clip-norm": 0.0,
                "exponential-smoothing": 0.0})
model = create_model(opts, 64, 64)
gg = GraphGroup(model, opts)
key = prng.root_key(21)
tk = prng.stream(key, prng.STREAM_DROPOUT)

def batch(seed):
    rs = np.random.RandomState(seed)
    return {"src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
            "src_mask": jnp.ones((8, 6), jnp.float32),
            "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
            "trg_mask": jnp.ones((8, 7), jnp.float32)}

def digest():
    flat = gg.optimizer_arrays()       # gathered LOGICAL state
    h = hashlib.sha256()
    for name in sorted(flat):
        a = np.asarray(flat[name])
        h.update(("%s|%s|%s" % (name, a.dtype, a.shape)).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()

mp = os.path.join(d, "model.npz")
out = {"devices": ndev}
if mode == "save":
    gg.initialize(key)
    for i in range(2):
        gg.update(batch(i), i + 1, tk)
    st = TrainingState(seed=21)
    st.batches = 2
    save_checkpoint(mp, gg.export_params(), opts.as_yaml(), gg, st)
    out["digest"] = digest()
else:
    host_p, _, st = load_checkpoint(mp, gg)
    assert st is not None and st.batches == 2, st
    gg.initialize(key, {k: jnp.asarray(v) for k, v in host_p.items()})
    out["digest"] = digest()
    sweep = zero.optimizer_sweep_bytes(gg.opt_state)
    out["n_dev_reported"] = len(sweep)
    out["max_dev_bytes"] = max(sweep.values())
    out["logical_bytes"] = zero.optimizer_logical_bytes(gg.opt_state)
    o = gg.update(batch(5), 3, tk)
    out["resumed_loss_finite"] = bool(
        np.isfinite(float(np.asarray(o.loss_sum))))
print("ELASTIC_JSON " + json.dumps(out))
"""


def _run_child(mode, d, ndev):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={ndev}")
    env.pop("MARIAN_FAULTS", None)
    p = subprocess.run(
        [sys.executable, "-c", _CHILD, mode, d, str(ndev)],
        env=env, timeout=600, capture_output=True, text=True)
    assert p.returncode == 0, p.stderr[-4000:]
    lines = [ln for ln in p.stdout.splitlines()
             if ln.startswith("ELASTIC_JSON ")]
    assert lines, p.stdout + "\n" + p.stderr[-2000:]
    return json.loads(lines[-1][len("ELASTIC_JSON "):]), p.stderr


@pytest.fixture(scope="module")
def saved_at_8(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("elastic"))
    out, _ = _run_child("save", d, 8)
    return d, out


class TestElasticGeometry:
    def test_manifest_records_save_geometry(self, saved_at_8):
        d, _ = saved_at_8
        root = bdl.bundle_root(os.path.join(d, "model.npz"))
        names = bdl.list_bundles(root)
        assert names
        manifest = json.load(
            open(os.path.join(root, names[-1], bdl.MANIFEST_NAME)))
        geo = manifest["meta"]["geometry"]
        assert geo["devices"] == 8
        assert geo["mesh"]["data"] == 8
        # manifest meta is the restore side's provenance record: the mesh
        # axes must all be present so a future geometry can log the delta
        assert set(geo["mesh"]) >= {"data", "model"}

    def test_restore_at_4_bitwise_equal_and_resharded(self, saved_at_8):
        d, saved = saved_at_8
        out, err = _run_child("restore", d, 4)
        assert out["digest"] == saved["digest"], (
            "logical optimizer state changed across 8->4 restore")
        assert out["resumed_loss_finite"]
        # re-sharded for the CURRENT mesh: 4 devices each hold ~1/4
        assert out["n_dev_reported"] == 4
        assert out["max_dev_bytes"] * 4 <= out["logical_bytes"] * 1.5, out
        # the elastic-resume breadcrumb names both geometries
        assert "elastic resume" in err
        assert "8 device" in err

    def test_restore_at_1_bitwise_equal(self, saved_at_8):
        d, saved = saved_at_8
        out, err = _run_child("restore", d, 1)
        assert out["digest"] == saved["digest"], (
            "logical optimizer state changed across 8->1 restore")
        assert out["resumed_loss_finite"]
        # single device: the whole logical state lives on it — the sweep
        # equals the logical bytes, nothing lost in the gather
        assert out["n_dev_reported"] == 1
        assert out["max_dev_bytes"] >= out["logical_bytes"]
        assert "elastic resume" in err
