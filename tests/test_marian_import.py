"""Upstream Marian checkpoint import (VERDICT r1 #10): the reference mount
is still empty, so no real upstream .npz exists to load — instead this
pins the exact upstream PARAMETER NAMING (reference: src/common/io.cpp ::
loadItems naming as catalogued in SURVEY.md §2.5) and proves that an
.npz written with those names + an embedded ``special:model.yml`` loads
through common/io → create_model → beam decode. When the mount is fixed,
pointing `_roundtrip` at a real upstream file is the only change needed."""

import numpy as np
import pytest

import jax

from marian_tpu.common import Options
from marian_tpu.common import io as mio
from marian_tpu.models.encoder_decoder import create_model


def _expected_transformer_names(enc_depth, dec_depth, tied_all=True,
                                ln=False):
    """The upstream marian transformer name set for --transformer-preprocess
    '' --transformer-postprocess 'dan' (post-norm)."""
    names = set()
    names.add("Wemb" if tied_all else "decoder_Wemb")
    if not tied_all:
        names.add("encoder_Wemb")
    names.add("decoder_ff_logit_out_b")
    if not tied_all:
        names.add("decoder_ff_logit_out_W")

    def attn(prefix):
        for s in ("Wq", "bq", "Wk", "bk", "Wv", "bv", "Wo", "bo"):
            names.add(f"{prefix}_{s}")
        names.add(f"{prefix}_Wo_ln_scale")
        names.add(f"{prefix}_Wo_ln_bias")

    def ffn(prefix):
        for s in ("W1", "b1", "W2", "b2"):
            names.add(f"{prefix}_{s}")
        names.add(f"{prefix}_ffn_ln_scale")
        names.add(f"{prefix}_ffn_ln_bias")

    for l in range(1, enc_depth + 1):
        attn(f"encoder_l{l}_self")
        ffn(f"encoder_l{l}_ffn")
    for l in range(1, dec_depth + 1):
        attn(f"decoder_l{l}_self")
        attn(f"decoder_l{l}_context")
        ffn(f"decoder_l{l}_ffn")
    return names


CONFIG = {
    "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
    "transformer-dim-ffn": 32, "enc-depth": 2, "dec-depth": 2,
    "tied-embeddings-all": True, "precision": ["float32", "float32"],
    "transformer-preprocess": "", "transformer-postprocess": "dan",
    "max-length": 32,
}


class TestUpstreamNaming:
    def test_init_params_match_upstream_name_set(self):
        model = create_model(Options(dict(CONFIG)), 23, 23)
        params = model.init(jax.random.key(0))
        expected = _expected_transformer_names(2, 2, tied_all=True)
        assert set(params) == expected, (
            f"missing={sorted(expected - set(params))} "
            f"extra={sorted(set(params) - expected)}")

    def test_untied_name_set(self):
        cfg = dict(CONFIG)
        cfg["tied-embeddings-all"] = False
        model = create_model(Options(cfg), 23, 23)
        params = model.init(jax.random.key(0))
        expected = _expected_transformer_names(2, 2, tied_all=False)
        assert set(params) == expected


class TestImportRoundTrip:
    def _roundtrip(self, tmp_path, path=None):
        """Write an upstream-named .npz (or take a real one via `path`),
        then load → build → decode."""
        if path is None:
            model = create_model(Options(dict(CONFIG)), 23, 23)
            params = {k: np.asarray(v) for k, v in
                      model.init(jax.random.key(1)).items()}
            path = str(tmp_path / "upstream.npz")
            import yaml
            cfg_yaml = yaml.safe_dump(dict(CONFIG))
            mio.save_model(path, params, cfg_yaml)
        host_params, cfg_yaml = mio.load_model(path)
        assert cfg_yaml is not None
        from marian_tpu.models.encoder_decoder import apply_embedded_config
        opts = apply_embedded_config(Options({"max-length": 32}), cfg_yaml)
        model = create_model(opts, 23, 23, inference=True)
        from marian_tpu.translator.beam_search import BeamSearch
        import jax.numpy as jnp
        bs = BeamSearch(model,
                        [{k: jnp.asarray(v) for k, v in host_params.items()}],
                        None, Options({"beam-size": 2, "max-length": 10}),
                        None)
        src = jnp.asarray(np.arange(2, 8)[None, :].repeat(2, 0))
        mask = jnp.ones_like(src, jnp.float32)
        out = bs.search(src, mask)
        assert len(out) == 2
        return out

    def test_constructed_upstream_npz_decodes(self, tmp_path):
        self._roundtrip(tmp_path)
