"""Data-pipeline tests: vocab build/encode/decode, corpus shuffle+resume,
batch generator token budgets + bucketed static shapes, shortlist."""

import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data import (
    DefaultVocab, create_vocab, Corpus, CorpusState, BatchGenerator,
    make_batch, bucket_length, bucket_batch_size, EOS_ID, UNK_ID,
    LexicalShortlistGenerator, WordAlignment, TextInput,
)


class TestVocab:
    def test_build_and_specials(self):
        v = DefaultVocab.build(["a b b c c c"])
        assert v["</s>"] == EOS_ID and v["<unk>"] == UNK_ID
        assert v["c"] == 2 and v["b"] == 3 and v["a"] == 4  # freq order
        assert v["zzz"] == UNK_ID
        assert len(v) == 5

    def test_encode_decode_roundtrip(self):
        v = DefaultVocab.build(["hello world foo"])
        ids = v.encode("hello foo")
        assert ids[-1] == EOS_ID
        assert v.decode(ids) == "hello foo"

    def test_save_load(self, tmp_path):
        v = DefaultVocab.build(["x y z z"])
        p = str(tmp_path / "vocab.yml")
        v.save(p)
        v2 = DefaultVocab.load(p)
        assert len(v2) == len(v)
        assert v2["z"] == v["z"]

    def test_create_builds_missing(self, tmp_corpus, tmp_path):
        src, tgt, _ = tmp_corpus
        p = str(tmp_path / "v.yml")
        v = create_vocab(p, train_paths=[src])
        assert (tmp_path / "v.yml").exists()
        assert v["the"] != UNK_ID


class TestCorpus:
    def _vocabs(self, tmp_corpus):
        src, tgt, (sl, tl) = tmp_corpus
        return DefaultVocab.build(sl), DefaultVocab.build(tl)

    def test_caps_augmentation_every_n(self, tmp_path):
        """--all-caps-every / --english-title-case-every (corpus.cpp
        augmentation): exactly every Nth sentence is upper/title-cased
        before encoding — the off sentences stay untouched."""
        (tmp_path / "c.src").write_text("ab cd\nab cd\nab cd\nab cd\n")
        (tmp_path / "c.trg").write_text("xy\nxy\nxy\nxy\n")
        v = DefaultVocab.build(["ab cd xy AB CD Ab Cd XY"])
        paths = [str(tmp_path / "c.src"), str(tmp_path / "c.trg")]
        opts = Options({"max-length": 20, "shuffle": "none",
                        "all-caps-every": 2})
        caps = [t.streams[0] for t in Corpus(paths, [v, v], opts)]
        assert caps[0] == caps[2] == v.encode("ab cd")   # odd: untouched
        assert caps[1] == caps[3] == v.encode("AB CD")   # every 2nd
        opts = Options({"max-length": 20, "shuffle": "none",
                        "english-title-case-every": 2})
        title = [t.streams[0] for t in Corpus(paths, [v, v], opts)]
        assert title[0] == title[2] == v.encode("ab cd")
        assert title[1] == title[3] == v.encode("Ab Cd")

    def test_iterates_epoch(self, tmp_corpus):
        src, tgt, (sl, _) = tmp_corpus
        vs, vt = self._vocabs(tmp_corpus)
        c = Corpus([src, tgt], [vs, vt], Options({"max-length": 100, "shuffle": "none", "seed": 1}))
        tuples = list(c)
        assert len(tuples) == len(sl)
        assert all(t.src[-1] == EOS_ID and t.trg[-1] == EOS_ID for t in tuples)

    def test_shuffle_deterministic_per_epoch(self, tmp_corpus):
        src, tgt, _ = tmp_corpus
        vs, vt = self._vocabs(tmp_corpus)
        c1 = Corpus([src, tgt], [vs, vt], Options({"max-length": 100, "shuffle": "data", "seed": 7}))
        c2 = Corpus([src, tgt], [vs, vt], Options({"max-length": 100, "shuffle": "data", "seed": 7}))
        assert [t.idx for t in c1] == [t.idx for t in c2]
        assert c1.state.epoch == 1
        # next epoch differs
        order1 = [t.idx for t in c1]
        order_e2 = [t.idx for t in c1]
        assert order1 != order_e2

    def test_resume_mid_epoch(self, tmp_corpus):
        src, tgt, _ = tmp_corpus
        vs, vt = self._vocabs(tmp_corpus)
        opts = Options({"max-length": 100, "shuffle": "data", "seed": 3})
        c = Corpus([src, tgt], [vs, vt], opts)
        it = iter(c)
        first_three = [next(it).idx for _ in range(3)]
        state = c.state.as_dict()
        # fresh corpus restored to that state continues identically
        c2 = Corpus([src, tgt], [vs, vt], opts)
        c2.restore(state)
        rest = [t.idx for t in c2]
        full = [t.idx for t in Corpus([src, tgt], [vs, vt], opts)]
        assert first_three + rest == full

    def test_max_length_skips_and_crops(self, tmp_corpus):
        src, tgt, (sl, _) = tmp_corpus
        vs, vt = self._vocabs(tmp_corpus)
        c = Corpus([src, tgt], [vs, vt], Options({"max-length": 4, "shuffle": "none"}))
        kept = list(c)
        assert len(kept) < len(sl)  # long ones skipped
        c2 = Corpus([src, tgt], [vs, vt],
                    Options({"max-length": 4, "max-length-crop": True, "shuffle": "none"}))
        cropped = list(c2)
        assert len(cropped) == len(sl)
        assert all(len(t.src) <= 5 for t in cropped)  # 4 + EOS


class TestBatchGenerator:
    def test_bucket_functions(self):
        assert bucket_length(1) == 8 and bucket_length(8) == 8
        assert bucket_length(9) == 16 and bucket_length(100) == 128
        assert bucket_batch_size(1) == 8 and bucket_batch_size(9) == 16

    def test_static_shapes(self, tmp_corpus):
        src, tgt, _ = tmp_corpus
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        c = Corpus([src, tgt], [vs, vt], Options({"max-length": 100, "shuffle": "none"}))
        bg = BatchGenerator(c, mini_batch=3, maxi_batch=10, prefetch=False)
        batches = list(bg)
        assert batches
        for b in batches:
            assert b.src.ids.shape[0] % 8 == 0
            assert b.src.ids.shape[1] in (8, 16, 24, 32)
            assert b.src.ids.shape == b.src.mask.shape
            # pad rows are fully masked
            pads = b.sentence_ids < 0
            assert b.src.mask[pads].sum() == 0
        total = sum(b.size for b in batches)
        assert total == 8

    def test_token_budget(self, tmp_corpus):
        src, tgt, _ = tmp_corpus
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        c = Corpus([src, tgt], [vs, vt], Options({"max-length": 100, "shuffle": "none"}))
        bg = BatchGenerator(c, mini_batch_words=24, maxi_batch=100, prefetch=False)
        batches = list(bg)
        for b in batches:
            real = b.size
            padded_trg = b.trg.ids.shape[1]
            assert real * padded_trg <= 24 or real == 1
        assert sum(b.size for b in batches) == 8

    def test_prefetch_thread_equivalent(self, tmp_corpus):
        src, tgt, _ = tmp_corpus
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        def make():
            c = Corpus([src, tgt], [vs, vt],
                       Options({"max-length": 100, "shuffle": "data", "seed": 5}))
            return c
        b1 = [b.src.ids.tolist() for b in BatchGenerator(make(), mini_batch=4, prefetch=False, seed=5)]
        b2 = [b.src.ids.tolist() for b in BatchGenerator(make(), mini_batch=4, prefetch=True, seed=5)]
        assert b1 == b2

    def test_length_sorting_reduces_padding(self, tmp_corpus):
        src, tgt, _ = tmp_corpus
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        c = Corpus([src, tgt], [vs, vt], Options({"max-length": 100, "shuffle": "none"}))
        bg = BatchGenerator(c, mini_batch=4, maxi_batch=2, maxi_batch_sort="trg",
                            prefetch=False, shuffle_batches=False)
        batches = list(bg)
        # with sorting, short sentences group together: first batch narrow
        widths = sorted(b.trg.ids.shape[1] for b in batches)
        assert widths[0] <= widths[-1]


class TestShortlist:
    def test_lexical_shortlist(self, tmp_path):
        vs = DefaultVocab.build(["katze hund fuchs"])
        vt = DefaultVocab.build(["cat dog fox"])
        lex = tmp_path / "lex.s2t"
        lex.write_text("katze cat 0.9\nkatze dog 0.05\nhund dog 0.95\nfuchs fox 0.8\n")
        gen = LexicalShortlistGenerator(str(lex), vs, vt, first=2, best=1, k_multiple=8)
        sl = gen.generate([vs["katze"], vs["hund"]])
        assert len(sl) % 8 == 0
        ids = set(sl.indices.tolist())
        assert vt["cat"] in ids and vt["dog"] in ids
        assert EOS_ID in ids
        # reverse map works
        pos = list(sl.indices).index(vt["cat"])
        assert sl.reverse_map(np.array([pos]))[0] == vt["cat"]

    def test_binary_roundtrip(self, tmp_path):
        vs = DefaultVocab.build(["a b"])
        vt = DefaultVocab.build(["x y"])
        lex = tmp_path / "lex.s2t"
        lex.write_text("a x 0.9\nb y 0.8\n")
        gen = LexicalShortlistGenerator(str(lex), vs, vt, first=1, best=2, k_multiple=8)
        binp = str(tmp_path / "lex.bin.npz")
        gen.save_binary(binp)
        gen2 = LexicalShortlistGenerator(binp, vs, vt, first=1, best=2, k_multiple=8)
        sl1 = gen.generate([vs["a"]]).indices.tolist()
        sl2 = gen2.generate([vs["a"]]).indices.tolist()
        assert sl1 == sl2


class TestAlignmentAndWeights:
    def test_alignment_parse_and_dense(self):
        a = WordAlignment.parse("0-0 1-2 2-1")
        m = np.zeros((3, 3), dtype=np.float32)
        a.fill_dense(m)
        assert m[0, 0] == 1.0 and m[2, 1] == 1.0 and m[1, 2] == 1.0

    def test_guided_alignment_batch(self, tmp_path):
        src = tmp_path / "s.txt"; src.write_text("a b\nc d\n")
        tgt = tmp_path / "t.txt"; tgt.write_text("x y\nz w\n")
        aln = tmp_path / "a.txt"; aln.write_text("0-0 1-1\n0-1 1-0\n")
        vs = DefaultVocab.build(["a b c d"])
        vt = DefaultVocab.build(["x y z w"])
        c = Corpus([str(src), str(tgt)], [vs, vt],
                   Options({"max-length": 10, "shuffle": "none",
                            "guided-alignment": str(aln)}))
        tuples = list(c)
        assert tuples[0].alignment is not None
        b = make_batch(tuples, 2)
        assert b.guided_alignment is not None
        assert b.guided_alignment.shape[0] == b.src.ids.shape[0]
        assert b.guided_alignment[0, 0, 0] == 1.0

    def test_text_input(self):
        vs = DefaultVocab.build(["hello world"])
        ti = TextInput([["hello world", "world hello"]], [vs])
        tuples = list(ti)
        assert len(tuples) == 2
        assert tuples[0].src[-1] == EOS_ID


class TestRightLeft:
    def test_target_reversed_eos_last(self, tmp_path):
        from marian_tpu.common import Options
        from marian_tpu.data.corpus import Corpus
        from marian_tpu.data.vocab import DefaultVocab
        (tmp_path / "r.src").write_text("a b c\n")
        (tmp_path / "r.trg").write_text("x y z\n")
        v = DefaultVocab.build(["a b c x y z"])
        opts = Options({"max-length": 20, "shuffle": "none",
                        "right-left": True})
        corpus = Corpus([str(tmp_path / "r.src"), str(tmp_path / "r.trg")],
                        [v, v], opts)
        st = next(iter(corpus))
        # source untouched, target tokens reversed, EOS still terminal
        assert st.streams[0] == v.encode("a b c")
        assert st.streams[1][:-1] == v.encode("x y z")[:-1][::-1]
        assert st.streams[1][-1] == v.eos_id

    def test_textinput_reverse_target_for_nbest_rescoring(self):
        """TextInput leaves targets alone at decode time, but the n-best
        rescorer must reverse hypotheses before scoring them against an
        R2L model (rescorer._run_nbest passes reverse_target=True)."""
        from marian_tpu.data.corpus import TextInput
        from marian_tpu.data.vocab import DefaultVocab
        v = DefaultVocab.build(["a b c x y z"])
        plain = next(iter(TextInput([["a b c"], ["x y z"]], [v, v])))
        rev = next(iter(TextInput([["a b c"], ["x y z"]], [v, v],
                                  reverse_target=True)))
        assert plain.streams[1] == v.encode("x y z")
        assert rev.streams[0] == plain.streams[0]       # source untouched
        assert rev.streams[1][:-1] == plain.streams[1][:-1][::-1]
        assert rev.streams[1][-1] == v.eos_id
