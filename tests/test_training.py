"""End-to-end training tests (the regression-suite analogue of the reference's
marian-regression-tests: tiny fixture data, fixed seeds, pinned behavior —
SURVEY.md §4)."""

import math
import os

import jax
import numpy as np
import pytest
import yaml

from marian_tpu.common import Options
from marian_tpu.common import io as mio
from marian_tpu.data import DefaultVocab, Corpus, BatchGenerator, EOS_ID
from marian_tpu.models.encoder_decoder import create_model, batch_to_arrays
from marian_tpu.optimizers.schedule import LRSchedule
from marian_tpu.optimizers.optimizers import OptimizerConfig, init_state, apply_update
from marian_tpu.training import Train, GraphGroup, TrainingState
from marian_tpu.translator.greedy import greedy_decode


def train_options(tmp_path, src, tgt, **over):
    base = {
        "type": "transformer",
        "dim-emb": 32, "transformer-heads": 4, "transformer-dim-ffn": 64,
        "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": False,
        "precision": ["float32", "float32"],
        "max-length": 64,
        "train-sets": [src, tgt],
        "vocabs": [src + ".v.yml", tgt + ".v.yml"],
        "model": str(tmp_path / "model.npz"),
        "mini-batch": 8, "maxi-batch": 2, "mini-batch-words": 0,
        "learn-rate": 0.01, "optimizer": "adam", "clip-norm": 1.0,
        "label-smoothing": 0.0,
        "cost-type": "ce-mean-words",
        "after-epochs": 0, "after-batches": 30, "after": "0e",
        "disp-freq": "10u", "save-freq": "100u", "valid-freq": "100u",
        "seed": 42, "shuffle": "data",
        "exponential-smoothing": 0.0,
        "optimizer-delay": 1.0,
        "quiet": True,
    }
    base.update(over)
    return Options(base)


class TestAdamOracle:
    def test_adam_matches_numpy_reference(self):
        """Marian Adam semantics vs a hand-written numpy implementation."""
        rs = np.random.RandomState(0)
        p0 = rs.randn(4, 3).astype(np.float32)
        cfg = OptimizerConfig(name="adam", beta1=0.9, beta2=0.98, eps=1e-9,
                              clip_norm=0.0, smoothing=0.0)
        import jax.numpy as jnp
        params = {"w": jnp.asarray(p0)}
        state = init_state(cfg, params)
        m = np.zeros_like(p0); v = np.zeros_like(p0); p = p0.copy()
        lr = 0.001
        for t in range(1, 6):
            g = rs.randn(4, 3).astype(np.float32)
            state, params = apply_update(cfg, state, params,
                                         {"w": jnp.asarray(g)}, lr)
            m = 0.9 * m + 0.1 * g
            v = 0.98 * v + 0.02 * g * g
            mhat = m / (1 - 0.9 ** t)
            vhat = v / (1 - 0.98 ** t)
            p = p - lr * mhat / (np.sqrt(vhat) + 1e-9)
            np.testing.assert_allclose(np.asarray(params["w"]), p, rtol=2e-5,
                                       atol=2e-6)

    def test_lr_schedule_warmup_invsqrt(self):
        opts = Options({"learn-rate": 0.0003, "lr-warmup": "100",
                        "lr-decay-inv-sqrt": ["100"]})
        sched = LRSchedule.from_options(opts)
        assert float(sched(50)) == pytest.approx(0.0003 * 0.5, rel=1e-5)
        assert float(sched(100)) == pytest.approx(0.0003, rel=1e-5)
        assert float(sched(400)) == pytest.approx(0.0003 * 0.5, rel=1e-5)


class TestTrainEndToEnd:
    def test_loss_decreases_and_decodes(self, tmp_corpus, tmp_path):
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt, **{"after-batches": 40})
        Train(opts).run()
        model_path = str(tmp_path / "model.npz")
        assert os.path.exists(model_path)
        assert os.path.exists(model_path + ".progress.yml")
        assert os.path.exists(model_path + ".optimizer.npz")

        # config embedded in checkpoint
        params, config = mio.load_model(model_path)
        assert config is not None
        assert yaml.safe_load(config)["type"] == "transformer"

        # overfit check: greedy decode of a training sentence should produce
        # mostly-gold tokens after 40 updates on 8 sentences
        vs = DefaultVocab.load(src + ".v.yml")
        vt = DefaultVocab.load(tgt + ".v.yml")
        model = create_model(opts, len(vs), len(vt), inference=True)
        import jax.numpy as jnp
        jparams = {k: jnp.asarray(v) for k, v in params.items()}
        ids = vs.encode("hello world")
        src_ids = jnp.asarray([ids], jnp.int32)
        src_mask = jnp.ones_like(src_ids, jnp.float32)
        out = greedy_decode(model, jparams, src_ids, src_mask, max_len=10)
        decoded = vt.decode([int(x) for x in out[0]])
        assert len(decoded) > 0  # produced something non-empty

    def test_progress_state_counts(self, tmp_corpus, tmp_path):
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt, **{"after-batches": 5})
        Train(opts).run()
        st = TrainingState.load(str(tmp_path / "model.npz.progress.yml"))
        assert st.batches == 5
        assert st.labels_total > 0
        assert st.corpus is not None

    def test_exact_resume(self, tmp_corpus, tmp_path):
        """Stop at update 6, resume to 12: parameters must be bitwise-close to
        an uninterrupted 12-update run (the reference's same-cost-trajectory
        regression gate)."""
        src, tgt, _ = tmp_corpus

        d1 = tmp_path / "run_full"; d1.mkdir()
        opts_full = train_options(d1, src, tgt, **{"after-batches": 12})
        Train(opts_full).run()
        p_full, _ = mio.load_model(str(d1 / "model.npz"))

        d2 = tmp_path / "run_split"; d2.mkdir()
        opts_a = train_options(d2, src, tgt, **{"after-batches": 6})
        Train(opts_a).run()
        opts_b = train_options(d2, src, tgt, **{"after-batches": 12})
        Train(opts_b).run()
        p_split, _ = mio.load_model(str(d2 / "model.npz"))

        assert set(p_full) == set(p_split)
        for k in p_full:
            np.testing.assert_allclose(p_full[k], p_split[k], rtol=1e-4,
                                       atol=1e-5, err_msg=k)

    def test_sigterm_like_save(self, tmp_corpus, tmp_path):
        """signal flag → finish update, save, exit 0 (reference:
        common/signal_handling.cpp contract)."""
        from marian_tpu.common import signal_handling
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt, **{"after-batches": 1000})
        import signal as _sig
        signal_handling._flags[_sig.SIGTERM] = True
        try:
            Train(opts).run()
        finally:
            signal_handling.clear_signal_flags()
        st = TrainingState.load(str(tmp_path / "model.npz.progress.yml"))
        assert st.batches < 1000  # stopped early but saved


class TestEMAAndDelay:
    def test_ema_saved(self, tmp_corpus, tmp_path):
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt,
                             **{"after-batches": 3,
                                "exponential-smoothing": 0.01})
        Train(opts).run()
        base = str(tmp_path / "model")
        assert os.path.exists(base + ".ema.npz")

    def test_optimizer_delay_equivalent_to_big_batch(self, tmp_corpus, tmp_path):
        """delay=2 with batch B must equal delay=1 with the two micro-batches
        concatenated (SyncGraphGroup accumulation semantics) for ce-mean-words."""
        import jax.numpy as jnp
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt)
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        model = create_model(opts, len(vs), len(vt))
        key = jax.random.key(0)

        def run(delayed):
            c = Corpus([src, tgt], [vs, vt],
                       Options({"max-length": 64, "shuffle": "none"}))
            bg = BatchGenerator(c, mini_batch=4, maxi_batch=1, prefetch=False,
                                shuffle_batches=False, pad_batch=True,
                                batch_multiple=8)
            batches = [batch_to_arrays(b) for b in list(bg)[:2]]
            o = opts.with_(**{"optimizer-delay": 2 if delayed else 1})
            gg = GraphGroup(model, o, donate=False)
            gg.initialize(key)
            if delayed:
                gg.update(batches, 1, jax.random.key(9))
            else:
                # concatenate along batch dim, padding time dims to match
                def cat_key(k):
                    a, b = batches[0][k], batches[1][k]
                    w = max(a.shape[1], b.shape[1])
                    a = jnp.pad(a, ((0, 0), (0, w - a.shape[1])))
                    b = jnp.pad(b, ((0, 0), (0, w - b.shape[1])))
                    return jnp.concatenate([a, b])
                cat = {k: cat_key(k) for k in batches[0]}
                gg.update([cat], 1, jax.random.key(9))
            return gg.params

        p_delay = run(True)
        p_cat = run(False)
        for k in p_delay:
            if k.endswith("_bk"):
                # attention key biases have structurally zero gradient
                # (softmax shift invariance); Adam's sign-like first step
                # amplifies pure float noise there — not a semantics issue
                continue
            np.testing.assert_allclose(np.asarray(p_delay[k]),
                                       np.asarray(p_cat[k]),
                                       rtol=5e-3, atol=5e-5, err_msg=k)


class TestCompactTransfer:
    def test_compact_batch_is_equivalent(self, tmp_corpus, tmp_path):
        """batch_to_arrays(compact=True) ships uint16 tokens + row
        lengths; the jitted step rebuilds ids/masks on device — the
        update must be numerically IDENTICAL to the full form."""
        import jax.numpy as jnp
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt)
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        model = create_model(opts, len(vs), len(vt))
        corpus = Corpus([src, tgt], [vs, vt], opts)
        batch = next(iter(BatchGenerator(corpus, opts, prefetch=False)))

        full = batch_to_arrays(batch, compact=False)
        comp = batch_to_arrays(batch, compact=True)
        assert "src_tok" in comp and comp["src_tok"].dtype == jnp.uint16
        assert "src_mask" not in comp
        # transfer bytes actually shrink (the point of the feature)
        assert sum(v.nbytes for v in comp.values()) < \
            0.5 * sum(v.nbytes for v in full.values())

        def run(arrays):
            gg = GraphGroup(model, opts, donate=False)
            gg.initialize(jax.random.key(0))
            out = gg.update(dict(arrays), 1, jax.random.key(3))
            return float(out.loss_sum), gg.params

        l_full, p_full = run(full)
        l_comp, p_comp = run(comp)
        assert l_full == l_comp
        for k in p_full:
            np.testing.assert_array_equal(np.asarray(p_full[k]),
                                          np.asarray(p_comp[k]), err_msg=k)

    def test_compact_equivalent_on_composed_mesh(self, tmp_corpus,
                                                 tmp_path):
        """Compact batches must also be exact through the GSPMD path on
        a composed dp×tp×sp mesh (the manual-DP path only runs on pure-
        data meshes; _tok/_len leaves carry their own sharding specs)."""
        import jax.numpy as jnp
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt).with_(
            **{"mesh": ["data:2", "model:2", "seq:2"]})
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        model = create_model(opts, len(vs), len(vt))
        corpus = Corpus([src, tgt], [vs, vt], opts)
        batch = next(iter(BatchGenerator(corpus, opts, prefetch=False)))

        def run(arrays):
            gg = GraphGroup(model, opts, donate=False)
            gg.initialize(jax.random.key(0))
            out = gg.update(dict(arrays), 1, jax.random.key(3))
            return float(out.loss_sum), gg.params

        l_full, p_full = run(batch_to_arrays(batch, compact=False))
        l_comp, p_comp = run(batch_to_arrays(batch, compact=True))
        # same ids/masks VALUES, but the partitioner schedules the
        # in-jit expansion differently than a transferred mask →
        # reduction orders differ at float-associativity level (the
        # pure-DP manual path above is bitwise; this one is merely
        # numerically tight)
        np.testing.assert_allclose(l_full, l_comp, rtol=1e-6)
        for k in p_full:
            np.testing.assert_allclose(np.asarray(p_full[k]),
                                       np.asarray(p_comp[k]),
                                       rtol=1e-5, atol=1e-7, err_msg=k)

    def test_ragged_mask_falls_back_to_full_form(self, tmp_corpus,
                                                 tmp_path):
        """A mask that is not a prefix run (hand-built hole) must ship
        in the classic ids+mask form rather than corrupt silently."""
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt)
        vs = DefaultVocab.build(open(src).read().splitlines())
        corpus = Corpus([src, tgt], [vs, vs], opts)
        batch = next(iter(BatchGenerator(corpus, opts, prefetch=False)))
        batch.src.mask[0, 0] = 0.0          # hole at position 0
        arrays = batch_to_arrays(batch, compact=True)
        assert "src_ids" in arrays and "src_mask" in arrays
        # the target stream is untouched and still compacts
        assert "trg_tok" in arrays


class TestFusedDelay:
    def test_fused_delay_matches_host_loop(self, tmp_corpus, tmp_path):
        """Shape-uniform micro-batches take the in-jit lax.scan
        accumulation; it must match the host-side loop bit-for-bit-ish,
        including per-micro dropout key folding."""
        import jax.numpy as jnp
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt).with_(
            **{"optimizer-delay": 2, "transformer-dropout": 0.1})
        vs = DefaultVocab.build(open(src).read().splitlines())
        vt = DefaultVocab.build(open(tgt).read().splitlines())
        model = create_model(opts, len(vs), len(vt))
        rs = np.random.RandomState(3)
        b = {
            "src_ids": jnp.asarray(rs.randint(2, len(vs), (8, 9)), jnp.int32),
            "src_mask": jnp.ones((8, 9), jnp.float32),
            "trg_ids": jnp.asarray(rs.randint(2, len(vt), (8, 9)), jnp.int32),
            "trg_mask": jnp.ones((8, 9), jnp.float32),
        }
        b2 = {k: jnp.roll(v, 1, axis=0) for k, v in b.items()}

        def run(force_host):
            gg = GraphGroup(model, opts, donate=False)
            gg.initialize(jax.random.key(0))
            if force_host:
                gg._fused_delay = None
            assert (gg._fused_delay is None) == force_host
            gg.update([dict(b), dict(b2)], 1, jax.random.key(5))
            return gg.params

        p_fused = run(False)
        p_host = run(True)
        for k in p_host:
            if k.endswith("_bk"):
                continue    # see delay-equivalence test above
            # both paths reduce in the SAME order — Σ_micro RS(g_i); the
            # fused scan scatters each micro inside the loop (zero.py
            # _scatter_reduce_body) — so elementwise they agree to
            # ~1e-4 rel EXCEPT isolated near-zero-gradient coordinates,
            # where Adam's step-1 m̂/(√v̂+ε) amplifies cross-program
            # fusion-reassociation noise unboundedly in relative terms.
            # Assert (a) almost all elements tight, (b) every element
            # within a fraction of one Adam step (lr=1e-3 here): a
            # dropout-key or scatter-axis bug perturbs MOST elements by
            # O(lr) and fails both.
            a, b = np.asarray(p_fused[k]), np.asarray(p_host[k])
            loose = ~np.isclose(a, b, rtol=1e-4, atol=2e-6)
            assert loose.mean() <= 2 / 1024, \
                f"{k}: {loose.sum()}/{loose.size} elements off"
            np.testing.assert_allclose(a, b, atol=5e-4, err_msg=k)
