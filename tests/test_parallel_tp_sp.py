"""Tensor-parallel + sequence-parallel correctness on the 8-device CPU mesh.

The strong invariant (SURVEY.md §4 "we can do better than the reference's
2-real-GPUs CI gap"): the SAME train step run (a) single-device, (b) pure-DP,
(c) dp×tp×sp sharded must produce the same loss/gradients up to fp tolerance,
because GSPMD partitioning and ring collectives are numerically equivalent
reorderings of the dense program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.optimizers.optimizers import OptimizerConfig, init_state
from marian_tpu.optimizers.schedule import LRSchedule
from marian_tpu.parallel import mesh as M
from marian_tpu.parallel import tensor as T
from marian_tpu.parallel.zero import build_train_step, place
from marian_tpu.parallel.sequence import ring_attention_sharded
from marian_tpu.ops.attention import dense_attention


VOCAB = 64


def _options(mesh=None, sp="none"):
    return Options({
        **({"mesh": mesh} if mesh else {}),
        "sequence-parallel": sp,
        "type": "transformer",
        "dim-emb": 32, "transformer-heads": 8, "transformer-dim-ffn": 64,
        "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "precision": ["float32", "float32"],
        "label-smoothing": 0.0,
        "cost-type": "ce-mean-words",
        "learn-rate": 1e-3, "optimizer": "adam",
        "clip-norm": 0.0,
        "max-length": 32,
    })


def _batch(b=8, t=16, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, VOCAB, (b, t)), jnp.int32),
        "src_mask": jnp.ones((b, t), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, VOCAB, (b, t)), jnp.int32),
        "trg_mask": jnp.ones((b, t), jnp.float32),
    }


def _run_step(mesh_spec, devices, optimizer="adam", sp="none"):
    opts = _options(mesh_spec, sp=sp)
    opts.set("optimizer", optimizer)
    opts.set("num-devices", len(devices))
    mesh = M.make_mesh(opts, devices)
    model = create_model(opts, VOCAB, VOCAB)
    params = model.init(jax.random.key(0))
    p0 = jax.device_get(params)
    opt_cfg = OptimizerConfig.from_options(opts)
    opt_state = init_state(opt_cfg, params)
    params, opt_state = place(params, opt_state, mesh)
    step = build_train_step(model, opt_cfg, LRSchedule.from_options(opts),
                            "ce-mean-words", mesh, params, opt_state,
                            delay=1, donate=False)
    batch = M.shard_batch(_batch(), mesh)
    p2, _, metrics = step(params, opt_state, batch,
                          jnp.asarray(1.0, jnp.float32), jax.random.key(1))
    p2 = jax.device_get(p2)
    deltas = {k: p2[k] - p0[k] for k in p0}
    return float(metrics["ce_sum"]), deltas


class TestTensorParallel:
    def test_specs_cover_transformer_params(self):
        opts = _options(["data:2", "model:2", "seq:2"])
        mesh = M.make_mesh(opts, jax.devices()[:8])
        model = create_model(opts, VOCAB, VOCAB)
        params = model.init(jax.random.key(0))
        specs = T.tp_param_specs(params, mesh)
        # every attention/ffn matmul weight must actually be model-sharded
        sharded = [k for k, s in specs.items() if "model" in jax.tree_util.tree_leaves(tuple(s))]
        for pat in ("_Wq", "_Wk", "_Wv", "_Wo", "_ffn_W1", "_ffn_W2", "Wemb"):
            assert any(pat in k for k in sharded), f"no model-sharding for {pat}"

    def test_zero1_composes_with_tp(self):
        opts = _options(["data:2", "model:2", "seq:2"])
        mesh = M.make_mesh(opts, jax.devices()[:8])
        spec = T.zero1_combined_spec(
            jax.sharding.PartitionSpec(None, "model"), (32, 32), mesh)
        assert tuple(spec) == ("data", "model")

    def test_tp_sp_matches_single_device_loss(self):
        # SGD so the param delta is LINEAR in the gradient (Adam's t=1 update
        # is sign(g), unstable for near-zero grads across reduction orders)
        devices = jax.devices()
        assert len(devices) >= 8
        loss_1, d_1 = _run_step(["data:1", "model:1", "seq:1"], devices[:1],
                                optimizer="sgd")
        loss_dp, d_dp = _run_step(["data:8"], devices[:8], optimizer="sgd")
        loss_tp, d_tp = _run_step(["data:2", "model:2", "seq:2"], devices[:8],
                                  optimizer="sgd")
        assert abs(loss_dp - loss_1) / abs(loss_1) < 1e-4
        assert abs(loss_tp - loss_1) / abs(loss_1) < 1e-4
        # gradient (= param delta / lr) identical across sharding layouts.
        # _bk is skipped: the q·bk score term is constant over keys, softmax
        # cancels it, so its analytic grad is 0 — computed values are pure
        # cancellation noise that differs across reduction orders.
        for k in d_1:
            if k.endswith("_bk"):
                continue
            scale = max(np.abs(d_1[k]).max(), 1e-8)
            np.testing.assert_allclose(d_tp[k] / scale, d_1[k] / scale,
                                       atol=1e-3, err_msg=k)
            np.testing.assert_allclose(d_dp[k] / scale, d_1[k] / scale,
                                       atol=1e-3, err_msg=k)


class TestSequenceParallel:
    @pytest.mark.parametrize("sp", ["ring", "ulysses"])
    def test_sp_training_step_matches_dense(self, sp):
        """Full train step with ring/ulysses attention INSIDE the model
        (shard_map within the GSPMD-jitted step) matches the dense program."""
        devices = jax.devices()
        loss_1, d_1 = _run_step(["data:1", "model:1", "seq:1"], devices[:1],
                                optimizer="sgd")
        loss_sp, d_sp = _run_step(["data:2", "model:2", "seq:2"], devices[:8],
                                  optimizer="sgd", sp=sp)
        assert abs(loss_sp - loss_1) / abs(loss_1) < 1e-4
        for k in d_1:
            if k.endswith("_bk"):
                continue  # analytic grad 0 (softmax shift-invariance), noise
            scale = max(np.abs(d_1[k]).max(), 1e-8)
            np.testing.assert_allclose(d_sp[k] / scale, d_1[k] / scale,
                                       atol=1e-3, err_msg=k)

    @pytest.mark.parametrize("mode", ["ring", "ulysses"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, mode, causal):
        opts = _options(["data:1", "model:1", "seq:8"])
        mesh = M.make_mesh(opts, jax.devices()[:8])
        rs = np.random.RandomState(7)
        b, h, t, dh = 2, 8, 32, 8
        q = jnp.asarray(rs.randn(b, h, t, dh), jnp.float32)
        k = jnp.asarray(rs.randn(b, h, t, dh), jnp.float32)
        v = jnp.asarray(rs.randn(b, h, t, dh), jnp.float32)
        kv_mask = jnp.asarray(rs.rand(b, t) > 0.2, jnp.float32)
        # keep at least position 0 unmasked per row
        kv_mask = kv_mask.at[:, 0].set(1.0)

        out = ring_attention_sharded(mesh, q, k, v, kv_mask=kv_mask,
                                     causal=causal, mode=mode)
        mask = kv_mask[:, None, None, :]
        if causal:
            mask = mask * jnp.tril(jnp.ones((t, t)))[None, None]
        ref = dense_attention(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_ring_grad_finite_with_empty_rows(self):
        """Batch-padding sentences have all-zero masks (bucket_batch_size
        pads B to a multiple of 8); the ring backward must stay finite
        (regression: o/l with l=0 produced inf*0=NaN in the VJP)."""
        opts = _options(["data:1", "model:1", "seq:2"])
        opts.set("num-devices", 2)
        mesh = M.make_mesh(opts, jax.devices()[:2])
        rs = np.random.RandomState(5)
        b, h, t, dh = 4, 2, 8, 4
        q = jnp.asarray(rs.randn(b, h, t, dh), jnp.float32)
        k = jnp.asarray(rs.randn(b, h, t, dh), jnp.float32)
        v = jnp.asarray(rs.randn(b, h, t, dh), jnp.float32)
        kv_mask = np.ones((b, t), np.float32)
        kv_mask[2:, :] = 0.0                     # empty padding rows
        kv_mask[0, 3:] = 0.0                     # plus a fully-masked chunk
        kv_mask = jnp.asarray(kv_mask)

        def f(q, k, v):
            out = ring_attention_sharded(mesh, q, k, v, kv_mask=kv_mask,
                                         causal=True)
            return jnp.sum(out ** 2)

        val = f(q, k, v)
        grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        assert np.isfinite(float(val))
        for g in grads:
            assert np.all(np.isfinite(np.asarray(g)))

    def test_ring_is_differentiable(self):
        opts = _options(["data:1", "model:1", "seq:8"])
        mesh = M.make_mesh(opts, jax.devices()[:8])
        rs = np.random.RandomState(3)
        q = jnp.asarray(rs.randn(1, 2, 16, 4), jnp.float32)
        k = jnp.asarray(rs.randn(1, 2, 16, 4), jnp.float32)
        v = jnp.asarray(rs.randn(1, 2, 16, 4), jnp.float32)

        def f_ring(q, k, v):
            return jnp.sum(ring_attention_sharded(mesh, q, k, v, causal=True))

        def f_dense(q, k, v):
            t = q.shape[2]
            m = jnp.tril(jnp.ones((t, t)))[None, None]
            return jnp.sum(dense_attention(q, k, v, m))

        g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-5, atol=2e-5)


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import importlib.util
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "__graft_entry__.py")
        spec = importlib.util.spec_from_file_location("__graft_entry__", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)


class TestFusedQKVGateOnTPMesh:
    """r4-advisor medium finding: a plain ``--mesh model:N`` run (no
    --sequence-parallel, so seq_mesh is None) must still see the 'model'
    axis and NOT fuse Q/K/V — the runtime concat crosses the Megatron
    column split and GSPMD would replicate the attention weights."""

    def test_plain_tp_mesh_sets_n_model_tp(self):
        from marian_tpu.models import transformer as TT
        cfg = TT.config_from_options(_options(["model:2"]), VOCAB, VOCAB)
        assert cfg.seq_mesh is None          # the advisor's exact case
        assert cfg.n_model_tp == 2

    def test_no_mesh_keeps_fusion_eligible(self):
        from marian_tpu.models import transformer as TT
        cfg = TT.config_from_options(_options(), VOCAB, VOCAB)
        assert cfg.n_model_tp == 1

    def test_data_only_mesh_keeps_fusion_eligible(self):
        from marian_tpu.models import transformer as TT
        cfg = TT.config_from_options(_options(["data:8"]), VOCAB, VOCAB)
        assert cfg.n_model_tp == 1
