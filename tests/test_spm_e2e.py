"""SentencePiece end-to-end train+decode (VERDICT r2 next-step #9).

The ``sentencepiece`` pip package is ABSENT from this image (verified
2026-07-30: ``pip install`` is disallowed and the wheel is not baked in),
so the real-data config-#1 path (SPM vocab → corpus → train → decode)
cannot be exercised here. This test is the explicit, driver-visible skip
marker the verdict asked for: it runs the full pipeline the moment the
package appears in the image, and until then reports exactly one SKIPPED
with the reason, instead of the gap being invisible.

Reference: src/data/sentencepiece_vocab.cpp :: SentencePieceVocab
(train-on-the-fly via --sentencepiece-options, encode/decode round trip).
"""

import os
import tempfile

import pytest

spm = pytest.importorskip(
    "sentencepiece",
    reason="sentencepiece package not in this image (pip install "
    "disallowed) — SPM e2e path gated off; marian_tpu/data/spm_vocab.py "
    "raises an actionable error at use. Unskips automatically when the "
    "image ships the wheel.")


def test_spm_train_encode_decode_roundtrip():
    """Train a tiny SPM model on-the-fly (the --sentencepiece-options
    path), then round-trip text through SentencePieceVocab."""
    from marian_tpu.common.options import Options
    from marian_tpu.data.spm_vocab import SentencePieceVocab

    lines = ["the quick brown fox jumps over the lazy dog",
             "pack my box with five dozen liquor jugs",
             "how vexingly quick daft zebras jump"] * 40
    with tempfile.TemporaryDirectory() as tmp:
        corpus = os.path.join(tmp, "train.txt")
        with open(corpus, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        model = os.path.join(tmp, "vocab.spm")
        opts = Options({"dim-vocabs": [64],
                        "sentencepiece-max-lines": 1000})
        # missing model path + train_paths → trains on the fly (the
        # reference's first-run marian-train behavior)
        vocab = SentencePieceVocab(model, opts, train_paths=[corpus])
        assert os.path.exists(model)
        ids = vocab.encode("the quick brown fox")
        assert len(ids) > 0
        assert vocab.decode(ids).replace(" ", "") == "thequickbrownfox"
