"""Head-packed Pallas attention vs the dense reference path (tier-1).

Runs in interpreter mode on CPU (conftest forces JAX_PLATFORMS=cpu); the
same kernel compiles through Mosaic on TPU. Golden parity against
ops/attention.py::dense_attention at the shapes the kernel exists for —
the dh=64 x T=48-64 MXU-tile-geometry regime — plus the pack-group
edges (g=1 wide heads, g=8 narrow heads), padding, bf16, and the custom
VJP in both backward orientations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.ops.attention import (attention, causal_mask, combine_masks,
                                      dense_attention)
from marian_tpu.ops.pallas.packed_attention import pack_group, packed_attention


def _rand(rng, *shape):
    return jnp.asarray(rng.randn(*shape), jnp.float32)


def _kv_mask(rng, b, t):
    m = (rng.rand(b, t) > 0.25).astype(np.float32)
    m[:, 0] = 1.0  # never fully-masked rows
    return jnp.asarray(m)


class TestPackGroup:
    def test_pack_group_geometry(self):
        assert pack_group(16, 64) == 2      # transformer-big: 2x64 = 128
        assert pack_group(8, 64) == 2
        assert pack_group(8, 32) == 4
        assert pack_group(8, 16) == 8
        assert pack_group(2, 128) == 1      # wide heads: nothing to pack
        assert pack_group(3, 64) == 1       # g must divide the head count
        assert pack_group(6, 64) == 2


@pytest.mark.parametrize("tq,tk", [
    (48, 48), (50, 70),
    # multi-bucket asymmetric Tk (200 pads to 256) — slow tier
    pytest.param(64, 200, marks=pytest.mark.slow)])
def test_packed_matches_dense_padding_mask(rng, tq, tk):
    b, h, dh = 2, 4, 64                     # the bench regime: g = 2
    q, k, v = (_rand(rng, b, h, tq, dh), _rand(rng, b, h, tk, dh),
               _rand(rng, b, h, tk, dh))
    m = _kv_mask(rng, b, tk)
    out = packed_attention(q, k, v, kv_mask=m)
    ref = dense_attention(q, k, v, mask=m[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("t", [
    100,
    # single-pad 48->64 causal geometry — slow tier
    pytest.param(48, marks=pytest.mark.slow)])
def test_packed_matches_dense_causal(rng, t):
    b, h, dh = 2, 4, 64
    q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
               _rand(rng, b, h, t, dh))
    m = _kv_mask(rng, b, t)
    out = packed_attention(q, k, v, kv_mask=m, causal=True)
    ref = dense_attention(q, k, v,
                          mask=combine_masks(causal_mask(t),
                                             m[:, None, None, :]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,dh", [(8, 16), (2, 128)])
def test_pack_group_edges_match_dense(rng, h, dh):
    """g=8 (narrow heads) and the g=1 wide-head degenerate pack must
    stay numerically exact (g=2/4 are covered by the other tests)."""
    b, t = 2, 48
    q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
               _rand(rng, b, h, t, dh))
    m = _kv_mask(rng, b, t)
    out = packed_attention(q, k, v, kv_mask=m, causal=True)
    ref = dense_attention(q, k, v,
                          mask=combine_masks(causal_mask(t),
                                             m[:, None, None, :]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_packed_no_mask(rng):
    b, h, t, dh = 2, 2, 96, 64
    q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
               _rand(rng, b, h, t, dh))
    out = packed_attention(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_packed_gradients_match_dense(rng, causal):
    """The custom VJP: both backward orientations (dq via the packed
    Tk contraction, dk/dv via the packed Tq contraction) against the
    dense path's autodiff."""
    b, h, t, dh = 2, 4, 48, 32
    q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
               _rand(rng, b, h, t, dh))
    m = _kv_mask(rng, b, t)
    dense_mask = combine_masks(causal_mask(t) if causal else None,
                               m[:, None, None, :])

    def f_packed(q, k, v):
        return (packed_attention(q, k, v, kv_mask=m, causal=causal) ** 2).sum()

    def f_dense(q, k, v):
        return (dense_attention(q, k, v, mask=dense_mask) ** 2).sum()

    gp = jax.grad(f_packed, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_packed_gradients_with_padding(rng):
    """Tq/Tk not multiples of the 64-pad: cotangents of padded rows are
    exact zeros (pad/slice transposes outside the custom VJP). Slow
    tier: tier-1 carries the unpadded fwd+bwd parity above and the
    padded FORWARD parity; this pins the padded backward specifically."""
    b, h, tq, tk, dh = 2, 2, 50, 70, 64
    q, k, v = (_rand(rng, b, h, tq, dh), _rand(rng, b, h, tk, dh),
               _rand(rng, b, h, tk, dh))
    m = _kv_mask(rng, b, tk)

    def f_packed(q, k, v):
        return (packed_attention(q, k, v, kv_mask=m) ** 2).sum()

    def f_dense(q, k, v):
        return (dense_attention(q, k, v, mask=m[:, None, None, :]) ** 2).sum()

    gp = jax.grad(f_packed, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_bf16_inputs(rng):
    b, h, t, dh = 2, 4, 64, 64
    q = jnp.asarray(rng.randn(b, h, t, dh), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, t, dh), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, t, dh), jnp.bfloat16)
    out = packed_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, mask=causal_mask(t))
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               rtol=2e-2, atol=2e-2)


def test_packed_under_jit(rng):
    b, h, t, dh = 2, 2, 64, 64
    q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
               _rand(rng, b, h, t, dh))
    m = _kv_mask(rng, b, t)
    fn = jax.jit(lambda q, k, v: packed_attention(q, k, v, kv_mask=m,
                                                  causal=True))
    out = fn(q, k, v)
    ref = dense_attention(q, k, v,
                          mask=combine_masks(causal_mask(t),
                                             m[:, None, None, :]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


class TestDispatcherGate:
    """ops/attention.py::attention routing for the packed gate."""

    def test_packed_on_selects_kernel_and_matches_dense(self, rng):
        b, h, t, dh = 1, 2, 48, 64
        q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
                   _rand(rng, b, h, t, dh))
        m = _kv_mask(rng, b, t)
        out_p, w = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                             flash="off", packed="on")
        assert w is None
        out_d, _ = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                             flash="off", packed="off")
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_d),
                                   rtol=2e-5, atol=2e-5)

    def test_auto_stays_dense_off_tpu(self, rng):
        """packed='auto' must NOT engage on the CPU backend (interpret
        mode is a debug path, not a fast one): weights stay available."""
        b, h, t, dh = 1, 2, 48, 64
        q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
                   _rand(rng, b, h, t, dh))
        m = _kv_mask(rng, b, t)
        _, w = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                         flash="off", packed="auto", return_weights=True)
        assert w is not None

    def test_return_weights_forces_dense(self, rng):
        b, h, t, dh = 1, 2, 48, 64
        q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
                   _rand(rng, b, h, t, dh))
        m = _kv_mask(rng, b, t)
        _, w = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                         flash="off", packed="on", return_weights=True)
        assert w is not None

    def test_over_cap_falls_back_to_dense(self, rng):
        """Sequences past the auto_tuner VMEM cap leave the shape to
        dense/flash even under packed='on'."""
        b, h, t, dh = 1, 2, 48, 64
        q, k, v = (_rand(rng, b, h, t, dh), _rand(rng, b, h, t, dh),
                   _rand(rng, b, h, t, dh))
        m = _kv_mask(rng, b, t)
        _, w = attention(q, k, v, mask=m[:, None, None, :], kv_mask=m,
                         flash="off", packed="on", packed_max_len=32,
                         return_weights=False)
        # dense path executed: weights slot is None either way, so pin
        # via numerics instead — the dense and packed paths agree, and
        # the call must not raise trying to pack past the cap
        assert w is None


class TestAutoTunerRegistry:
    """Block-size entries for both r6 kernels follow the dh-scaled VMEM
    convention (the r5 flash dh>64 halving; ISSUE 3 satellite)."""

    def test_dh_scaling_halves_past_64(self):
        from marian_tpu.ops.auto_tuner import (decode_attention_max_len,
                                               packed_attention_max_t)
        assert packed_attention_max_t(64) == 256
        assert packed_attention_max_t(128) == 128
        assert packed_attention_max_t(256) == 64
        assert decode_attention_max_len(64) == 2048
        assert decode_attention_max_len(128) == 1024
        # NARROW heads shrink too: the backward kernel's packed blocks
        # are [g*T, g*T] f32, so the cap bounds g*T (g = 128//dh) at
        # the validated 512 — not T alone
        assert packed_attention_max_t(32) == 128
        assert packed_attention_max_t(16) == 64
        assert packed_attention_max_t(8) == 64      # floor
        assert decode_attention_max_len(16) == 2048

    def test_registry_floor(self):
        from marian_tpu.ops.auto_tuner import kernel_block
        # absurd widths floor at one 64-wide block, never 0 (a 0 cap
        # would turn 'degrade' into 'never runs' silently)
        assert kernel_block("packed_attention", "max_t", 4096) == 64
