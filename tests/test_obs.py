"""Observability layer (marian_tpu/obs/ — ISSUE 8): span tracer, event
timeline, /tracez export, flight recorder, reply-metadata protocol,
histogram exemplars, StepTimer honesty. Everything runs under
JAX_PLATFORMS=cpu with stub translate functions.

The acceptance-critical properties covered tier-1:
- span-tree integrity through a REAL scheduler batch (parent/child
  edges + model_version tags);
- /tracez round-trips into a Perfetto-valid Chrome trace JSON document;
- an injected MARIAN_FAULTS watchdog trip and a canary auto-rollback
  each produce a flight-recorder dump holding the victim's full
  ingest→dispatch→failure span tree;
- tracer off ⇒ no ring allocation and no lock acquisition on the
  scheduler's per-batch hot path (the zero-overhead contract).
"""

import asyncio
import json
import os
import time
import urllib.request

import pytest

from marian_tpu import obs
from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.obs.trace import NOOP_SPAN, Tracer
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.lifecycle import SwapController
from marian_tpu.serving.scheduler import ContinuousScheduler, DispatchStalled
from marian_tpu.server.server import ServingApp, split_trace_header
from marian_tpu.training import bundle as bdl


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """The tracer adds Tracer._lock / FlightRecorder._lock (and the
    SwapController._lock -> Tracer._lock edge on the promote path) to
    the running lattice; the shared conftest witness asserts at teardown
    that the static graph models everything observed here."""
    yield


@pytest.fixture(autouse=True)
def _reset_obs():
    yield
    obs.TRACER.reset()
    obs.FLIGHT.disarm()
    obs.PERF.reset()
    fp.reset_for_tests()


class _RaisingLock:
    """Proof object for the zero-overhead contract: acquiring it fails
    the test, so any lock touch on a supposedly lock-free path is loud."""

    def __enter__(self):
        raise AssertionError("lock acquired on the disabled-tracer path")

    def __exit__(self, *exc):
        pass

    def acquire(self, *a, **kw):
        raise AssertionError("lock acquired on the disabled-tracer path")

    def release(self):
        pass


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

class TestTracerCore:
    def test_disabled_no_ring_no_lock_no_spans(self):
        t = Tracer()
        assert t._ring is None and t._events is None
        t._lock = _RaisingLock()     # any acquisition now fails the test
        sp = t.start_span("x", a=1)
        assert sp is NOOP_SPAN
        t.end(sp)
        t.event("e", k=1)
        t.record("r", 0.0, 1.0)
        with t.span("y") as sp2:
            assert sp2 is NOOP_SPAN
            t.set_attrs(z=1)         # no-op, no allocation
        assert t._ring is None and t._events is None

    def test_enable_records_parent_child_tree(self):
        t = Tracer()
        t.enable()
        with t.span("root", trace_id="t1") as root:
            with t.span("child") as child:
                assert child.trace_id == "t1"
                assert child.parent_id == root.span_id
            t.event("mark", k=3)
        spans, events = t.snapshot()
        assert [s.name for s in spans] == ["child", "root"]  # end order
        assert events[0]["name"] == "mark"
        assert events[0]["trace_id"] == "t1"   # inherited from context

    def test_explicit_parent_crosses_threads(self):
        t = Tracer()
        t.enable()
        root = t.start_span("root")
        child = t.start_span("c", parent=root)
        t.end(child)
        t.end(root)
        assert child.parent_id == root.span_id
        assert child.trace_id == root.trace_id

    def test_ring_bounded(self):
        t = Tracer(capacity=4)
        t.enable()
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        spans, _ = t.snapshot()
        assert len(spans) == 4
        assert spans[-1].name == "s9"        # newest kept

    def test_end_idempotent_and_error_attr(self):
        t = Tracer()
        t.enable()
        with pytest.raises(RuntimeError):
            with t.span("boom"):
                raise RuntimeError("x")
        spans, _ = t.snapshot()
        assert spans[0].attrs["error"] == "RuntimeError('x')"
        t.end(spans[0], late=True)           # second end: no-op
        assert "late" not in spans[0].attrs

    def test_chrome_trace_is_perfetto_valid(self):
        t = Tracer()
        t.enable()
        with t.span("a", k="v"):
            t.event("inst")
        doc = t.chrome_trace()
        # the Perfetto/chrome://tracing contract: JSON object with a
        # traceEvents list of {name, ph, ts, pid, tid}; "X" complete
        # events carry dur, "i" instants carry scope
        assert isinstance(doc["traceEvents"], list)
        text = json.dumps(doc)               # must serialize
        assert json.loads(text)["traceEvents"]
        phases = set()
        for ev in doc["traceEvents"]:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
            assert isinstance(ev["ts"], float)
            phases.add(ev["ph"])
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
            if ev["ph"] == "i":
                assert ev["s"] == "t"
        assert phases == {"X", "i"}

    def test_snapshot_last_n(self):
        t = Tracer()
        t.enable()
        for i in range(6):
            with t.span(f"s{i}"):
                pass
        spans, _ = t.snapshot(last=2)
        assert [s.name for s in spans] == ["s4", "s5"]


# ---------------------------------------------------------------------------
# span-tree integrity through a REAL scheduler batch
# ---------------------------------------------------------------------------

class TestSchedulerSpans:
    def test_span_tree_through_real_batch(self):
        obs.TRACER.enable()
        r = msm.Registry()

        async def main():
            sched = ContinuousScheduler(
                lambda lines: [ln.upper() for ln in lines],
                registry=r, version_fn=lambda: "bundle-7",
                window_s=0.005)
            sched.start()
            # two concurrent requests coalesce into one device batch
            f1 = sched.submit(["a b", "c d"], trace_id="req0001")
            f2 = sched.submit(["e f"], trace_id="req0002")
            assert await f1 == ["A B", "C D"]
            assert await f2 == ["E F"]
            await sched.stop()

        run(main())
        spans, _ = obs.TRACER.snapshot()
        by_name = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        # per-request tree: serve.request -> serve.queue + serve.dispatch
        roots = {s.trace_id: s for s in by_name["serve.request"]}
        assert set(roots) == {"req0001", "req0002"}
        for q in by_name["serve.queue"]:
            assert q.parent_id == roots[q.trace_id].span_id
        for d in by_name["serve.dispatch"]:
            assert d.parent_id == roots[d.trace_id].span_id
            assert d.attrs["model_version"] == "bundle-7"   # tagged
            assert d.attrs["outcome"] == "ok"
        assert all(r.attrs["model_version"] == "bundle-7"
                   for r in by_name["serve.request"])
        # batch level: one serve.batch holding both traces, with its
        # serve.translate child on the device worker thread
        batches = by_name["serve.batch"]
        assert len(batches) == 1
        assert set(batches[0].attrs["traces"]) == {"req0001", "req0002"}
        tr = by_name["serve.translate"][0]
        assert tr.parent_id == batches[0].span_id
        assert tr.thread != batches[0].thread      # executor thread
        # dispatch spans back-reference the batch span
        assert all(d.attrs["batch_span"] == batches[0].span_id
                   for d in by_name["serve.dispatch"])

    def test_reply_metadata_breakdown(self):
        r = msm.Registry()

        async def main():
            sched = ContinuousScheduler(
                lambda lines: list(lines), registry=r,
                version_fn=lambda: "vX")
            sched.start()
            meta = {}
            await sched.submit(["hello"], meta=meta, trace_id="m1")
            await sched.stop()
            return meta

        meta = run(main())
        assert meta["outcome"] == "ok"
        assert meta["model_version"] == "vX"
        assert meta["trace_id"] == "m1"
        assert meta["queue_s"] >= 0.0
        assert meta["service_s"] > 0.0

    def test_disabled_no_ring_no_lock_on_hot_path(self):
        """The acceptance overhead guard (extended for ISSUE 9): tracer
        off AND perf accounting off AND no --slo-* ⇒ the per-batch
        dispatch path allocates no ring and acquires neither the tracer
        lock nor the perf meter's lock (the SLO engine is not even
        constructed without an objective flag, so it has no lock to
        guard against)."""
        assert not obs.enabled()
        obs.PERF.reset()
        assert not obs.PERF.enabled
        saved = obs.TRACER._lock
        saved_perf = obs.PERF._lock
        obs.TRACER._lock = _RaisingLock()
        obs.PERF._lock = _RaisingLock()
        try:
            r = msm.Registry()

            async def main():
                sched = ContinuousScheduler(
                    lambda lines: list(lines), registry=r)
                sched.start()
                out = await sched.submit(["x y", "z"])
                await sched.stop()
                return out

            assert run(main()) == ["X Y".lower(), "z"]
        finally:
            obs.TRACER._lock = saved
            obs.PERF._lock = saved_perf
        assert obs.TRACER._ring is None
        assert obs.TRACER._events is None


# ---------------------------------------------------------------------------
# /tracez endpoint round-trip
# ---------------------------------------------------------------------------

class TestTracezEndpoint:
    def test_tracez_roundtrip_perfetto_valid(self):
        obs.TRACER.enable()
        with obs.span("served", who="test"):
            pass
        srv = msm.MetricsServer(0, registry=msm.Registry(),
                                routes=obs.trace_routes()).start()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/tracez?last=10").read()
            doc = json.loads(body)
            assert doc["otherData"]["tracer_enabled"] is True
            names = [e["name"] for e in doc["traceEvents"]]
            assert "served" in names
            ev = doc["traceEvents"][names.index("served")]
            assert ev["ph"] == "X" and ev["dur"] >= 0
            assert ev["args"]["who"] == "test"
        finally:
            srv.close()

    def test_tracez_disabled_still_valid_document(self):
        srv = msm.MetricsServer(0, registry=msm.Registry(),
                                routes=obs.trace_routes()).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/tracez").read())
            assert doc["traceEvents"] == []
            assert doc["otherData"]["tracer_enabled"] is False
        finally:
            srv.close()

    def test_tracez_last_bounds_spans(self):
        obs.TRACER.enable()
        for i in range(5):
            with obs.span(f"s{i}"):
                pass
        srv = msm.MetricsServer(0, registry=msm.Registry(),
                                routes=obs.trace_routes()).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/tracez?last=2").read())
            xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
            assert [e["name"] for e in xs] == ["s3", "s4"]
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _flight_dumps(d):
    return sorted(p for p in os.listdir(d) if p.startswith("flight-"))


class TestFlightRecorder:
    def test_watchdog_trip_dumps_victim_span_tree(self, tmp_path):
        """Acceptance: an injected MARIAN_FAULTS stall trips the dispatch
        watchdog and the dump holds the victim's full
        ingest→dispatch→failure tree."""
        obs.TRACER.enable()
        obs.FLIGHT.arm(str(tmp_path))

        async def main():
            # default (process-wide) registry: the dump snapshots it,
            # like production
            sched = ContinuousScheduler(
                lambda lines: list(lines),
                stall_timeout=0.15, version_fn=lambda: "vLive")
            sched.start()
            with fp.active("serving.translate=hang:1.2"):
                with pytest.raises(DispatchStalled):
                    await sched.submit(["victim sentence"],
                                       trace_id="victim01")
            await sched.stop()

        run(main())
        # the watchdog dump is written on a background thread (the trip
        # site is the event loop — a synchronous dump would freeze every
        # connection mid-incident): wait for it
        deadline = time.time() + 5.0
        while not _flight_dumps(str(tmp_path)) and time.time() < deadline:
            time.sleep(0.02)
        dumps = _flight_dumps(str(tmp_path))
        assert len(dumps) == 1 and "watchdog" in dumps[0]
        payload = json.loads((tmp_path / dumps[0]).read_text())
        assert payload["reason"] == "watchdog"
        assert payload["trace_id"] == "victim01"
        # the victim's complete tree: ingest (serve.request/serve.queue)
        # → dispatch → failure outcome, plus the watchdog event
        evs = payload["trace"]["traceEvents"]
        victim = [e for e in evs
                  if e.get("args", {}).get("trace_id") == "victim01"]
        names = {e["name"] for e in victim}
        assert {"serve.request", "serve.queue", "serve.dispatch"} <= names
        dispatch = next(e for e in victim
                        if e["name"] == "serve.dispatch")
        assert dispatch["args"]["outcome"] == "stalled"
        assert any(e["name"] == "serve.watchdog_trip" for e in evs)
        # timeline context + metrics snapshot ride along
        assert "marian_serving_watchdog_trips_total" in payload["metrics"]
        assert payload["faultpoints"]["hits"]["serving.translate"] >= 1

    def test_canary_rollback_dumps(self, tmp_path):
        """Acceptance: a canary auto-rollback produces a dump with the
        failing batches' span trees still in the ring."""
        obs.TRACER.enable()
        obs.FLIGHT.arm(str(tmp_path))
        mp = str(tmp_path / "m.npz")

        def bad_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"]:       # golden smoke passes, traffic dies
                    raise RuntimeError("canary decode explodes")
                calls["n"] += 1
                return list(lines)
            return translate

        ctrl = SwapController(bad_factory,
                              metrics_registry=msm.Registry(),
                              canary_fraction=1.0,
                              rollback_error_rate=0.5,
                              rollback_min_batches=2)
        ctrl.seed_live(0, "boot", lambda lines: [f"v1:{ln}"
                                                 for ln in lines])
        bdir = bdl.write_bundle(mp, {"m.npz": lambda p: open(p, "w").close()})
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == "canary"
        for i in range(6):
            assert ctrl.route([f"s{i}"])[0].startswith("v1:")
        assert v.state == "failed"
        dumps = _flight_dumps(str(tmp_path))
        assert len(dumps) == 1 and "canary-rollback" in dumps[0]
        payload = json.loads((tmp_path / dumps[0]).read_text())
        assert payload["reason"] == "canary-rollback"
        assert "failure rate" in payload["detail"]
        assert payload["extra"]["version"] == os.path.basename(bdir)
        # the event timeline shows the lifecycle history up to the trip
        ev_names = [e["name"] for e in payload["trace"]["traceEvents"]
                    if e["ph"] == "i"]
        assert "lifecycle.transition" in ev_names
        assert "lifecycle.rollback" in ev_names

    def test_fault_kill_hook_dumps_before_exit(self, tmp_path,
                                               monkeypatch):
        """MARIAN_FAULTS kill mode dumps the ring before os._exit."""
        obs.configure(None)    # no options: env-driven arming below
        monkeypatch.setenv(obs.ENV_TRACE, "1")
        monkeypatch.setenv(obs.ENV_DUMP, str(tmp_path))
        assert obs.configure(None) is True
        exits = []
        monkeypatch.setattr(fp.os, "_exit", lambda code:
                            exits.append(code))
        with obs.span("last-request", trace_id="dying01"):
            pass
        fp.activate("serving.dispatch=kill@1")
        fp.fault_point("serving.dispatch")
        assert exits == [fp.FAULT_EXIT_CODE]
        dumps = _flight_dumps(str(tmp_path))
        assert len(dumps) == 1 and "fault-kill" in dumps[0]
        payload = json.loads((tmp_path / dumps[0]).read_text())
        assert "serving.dispatch" in payload["detail"]
        names = [e["name"] for e in payload["trace"]["traceEvents"]]
        assert "last-request" in names     # the ring survived into disk
        assert "fault.fire" in names       # the firing itself on timeline

    def test_disarmed_trip_is_noop(self, tmp_path):
        assert obs.FLIGHT.trip("whatever") is None
        assert _flight_dumps(str(tmp_path)) == []

    def test_dump_counter_emitted(self, tmp_path):
        obs.TRACER.enable()
        obs.FLIGHT.arm(str(tmp_path))
        before = msm.REGISTRY.counter(
            "marian_flight_dumps_total", "", labels=("reason",)
        ).labels("manual-test").value
        assert obs.FLIGHT.trip("manual-test") is not None
        after = msm.REGISTRY.counter(
            "marian_flight_dumps_total", "", labels=("reason",)
        ).labels("manual-test").value
        assert after == before + 1


# ---------------------------------------------------------------------------
# server protocol: #trace header + reply metadata
# ---------------------------------------------------------------------------

def _stub_app(translate=None, **extra):
    opts = {"metrics-port": 0, "max-queue": 64, "port": 0}
    opts.update(extra)
    return ServingApp(Options(opts),
                      translate_lines=translate
                      or (lambda lines: [ln.upper() for ln in lines]))


class TestServerTraceProtocol:
    def test_split_trace_header(self):
        assert split_trace_header("#trace:abc123\nhello") \
            == ("abc123", "hello")
        assert split_trace_header("hello\nworld") == (None, "hello\nworld")
        # malformed ids are payload, never an error
        assert split_trace_header("#trace:\nx") == (None, "#trace:\nx")
        assert split_trace_header("#trace:has space\nx") \
            == (None, "#trace:has space\nx")
        assert split_trace_header("#trace:" + "a" * 65 + "\nx")[0] is None

    def test_reply_metadata_roundtrip(self):
        async def main():
            app = _stub_app()
            await app.start()
            try:
                reply = await app.handle_text("#trace:cafe01\nhello\nworld")
            finally:
                await app.shutdown(drain_timeout=2)
            return reply

        reply = run(main())
        meta_line, _, body = reply.partition("\n")
        assert meta_line.startswith("#trace:cafe01 ")
        assert "outcome=ok" in meta_line
        assert "queue_ms=" in meta_line and "service_ms=" in meta_line
        assert body == "HELLO\nWORLD"

    def test_plain_clients_see_old_protocol(self):
        async def main():
            app = _stub_app()
            await app.start()
            try:
                return await app.handle_text("hello")
            finally:
                await app.shutdown(drain_timeout=2)

        assert run(main()) == "HELLO"

    def test_shed_reply_still_carries_metadata(self):
        obs.TRACER.enable()

        async def main():
            app = _stub_app(**{"max-queue": 1})
            app.admission.begin_drain()
            return await app.handle_frame("#trace:x1\nhello")

        reply, done = run(main())
        done(len(reply))
        first, _, rest = reply.partition("\n")
        assert first.startswith("#trace:x1 outcome=shed")
        assert rest.startswith("!!SERVER-OVERLOADED")
        # the shed's timeline event is tied to the victim (admit runs
        # inside the request's span context)
        _, events = obs.TRACER.snapshot()
        shed = [e for e in events if e["name"] == "admission.shed"]
        assert shed and shed[-1]["trace_id"] == "x1"

    def test_request_span_covers_reply_write(self):
        obs.TRACER.enable()

        async def main():
            app = _stub_app()
            await app.start()
            try:
                reply, done = await app.handle_frame("#trace:w1\nhello")
                done(len(reply))
            finally:
                await app.shutdown(drain_timeout=2)

        run(main())
        spans, _ = obs.TRACER.snapshot()
        by_name = {s.name: s for s in spans if s.trace_id == "w1"}
        assert "request" in by_name and "reply.write" in by_name
        root = by_name["request"]
        assert by_name["reply.write"].parent_id == root.span_id
        assert root.attrs["outcome"] == "ok"
        assert by_name["reply.write"].attrs["nbytes"] > 0
        # scheduler children hang under the same root
        assert by_name["serve.queue"].parent_id == root.span_id


# ---------------------------------------------------------------------------
# histogram exemplars
# ---------------------------------------------------------------------------

class TestExemplars:
    def test_exemplar_rendered_only_on_request(self):
        r = msm.Registry()
        h = r.histogram("t_ex_seconds", "x", buckets=(0.1, 1.0))
        h.observe(0.05, trace_id="fast01")
        h.observe(5.0, trace_id="slow99")
        h.observe(0.07)                      # no trace id: keeps fast01
        plain = r.render()
        assert "trace_id" not in plain       # strict 0.0.4 by default
        ex = r.render(exemplars=True)
        assert '# {trace_id="fast01"} 0.05' in ex
        assert '# {trace_id="slow99"} 5' in ex

    def test_scrape_query_param(self):
        r = msm.Registry()
        h = r.histogram("t_q_seconds", "x", buckets=(1.0,))
        h.observe(0.5, trace_id="qq1")
        srv = msm.MetricsServer(0, registry=r).start()
        try:
            base = f"http://127.0.0.1:{srv.port}/metrics"
            plain = urllib.request.urlopen(base).read().decode()
            assert "trace_id" not in plain
            with_ex = urllib.request.urlopen(
                base + "?exemplars=1").read().decode()
            assert 'trace_id="qq1"' in with_ex
        finally:
            srv.close()

    def test_scheduler_latency_carries_exemplar(self):
        r = msm.Registry()

        async def main():
            sched = ContinuousScheduler(lambda lines: list(lines),
                                        registry=r)
            sched.start()
            await sched.submit(["x"], trace_id="lat0001")
            await sched.stop()

        run(main())
        out = r.render(exemplars=True)
        assert 'trace_id="lat0001"' in out


# ---------------------------------------------------------------------------
# StepTimer / TraceWindow fold (obs/profiling.py; common.profiling shims)
# ---------------------------------------------------------------------------

class TestStepTimer:
    def test_shim_import_points_at_obs(self):
        from marian_tpu.common.profiling import StepTimer, TraceWindow
        assert StepTimer.__module__ == "marian_tpu.obs.profiling"
        assert TraceWindow.__module__ == "marian_tpu.obs.profiling"

    def test_phases_aggregate_and_emit_spans(self):
        from marian_tpu.common.profiling import StepTimer
        obs.TRACER.enable()
        st = StepTimer()
        st.phase("data")
        st.phase("dispatch")
        st.phase("data")
        st.stop()
        rep = st.report()
        assert set(rep) == {"data", "dispatch"}
        assert st.counts["data"] == 2
        spans, _ = obs.TRACER.snapshot()
        names = [s.name for s in spans]
        assert names.count("train.data") == 2
        assert names.count("train.dispatch") == 1

    def test_sync_fn_called_before_each_boundary(self):
        """The device-sync honesty fix: sync_fn runs BEFORE the boundary
        timestamp, so async device work drains into the phase that
        issued it (obs/profiling.py module docstring)."""
        from marian_tpu.common.profiling import StepTimer
        calls = []
        st = StepTimer(sync_fn=lambda: calls.append(1))
        st.phase("a")
        st.phase("b")
        st.stop()
        assert len(calls) == 3               # every boundary, stop incl.

    def test_disabled_records_nothing(self):
        from marian_tpu.common.profiling import StepTimer
        st = StepTimer(enabled=False)
        st.phase("a")
        st.stop()
        assert st.report() == {}


# ---------------------------------------------------------------------------
# configure() knobs
# ---------------------------------------------------------------------------

class TestConfigure:
    def test_options_flags(self, tmp_path):
        opts = Options({"trace": True, "trace-ring": 128})
        assert obs.configure(opts) is True
        assert obs.TRACER.enabled and obs.TRACER.capacity == 128
        assert not obs.FLIGHT.armed

    def test_trace_dump_implies_trace(self, tmp_path):
        opts = Options({"trace-dump": str(tmp_path / "dumps")})
        assert obs.configure(opts) is True
        assert obs.FLIGHT.armed
        assert os.path.isdir(tmp_path / "dumps")

    def test_off_by_default(self):
        assert obs.configure(Options({})) is False
        assert not obs.TRACER.enabled
