"""Zero-downtime model lifecycle (marian_tpu/serving/lifecycle/ —
ISSUE 5): registry state machine, bundle watcher, compat refusal, warmed
hot-swap, canary routing + auto-rollback, admin verbs, and the
end-to-end swap-under-traffic contract. Everything tier-1 runs with stub
executors under JAX_PLATFORMS=cpu — no model, no device; the slow tier
drills a real server subprocess killed mid-swap (scripts/chaos.py
--swap)."""

import asyncio
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.lifecycle import (CANARY, FAILED, LIVE, REJECTED,
                                          RETIRED, STAGED, WARMING,
                                          BundleWatcher, LifecycleError,
                                          ModelRegistry, SwapController,
                                          WarmupError, load_golden,
                                          scan_bundles)
from marian_tpu.serving.scheduler import ContinuousScheduler
from marian_tpu.common import lockdep
from marian_tpu.training import bundle as bdl

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """This suite drives the swap/canary/rollback machinery through its
    real thread mix; the shared conftest witness (which conftest arms
    via MARIAN_LOCKDEP=1 process-wide) asserts observed ⊆ static at
    module teardown."""
    yield


def run(coro):
    return asyncio.run(coro)


GEO_A = {"type": "transformer", "dim-emb": 16, "enc-depth": 1}
GEO_B = {"type": "transformer", "dim-emb": 32, "enc-depth": 1}


def commit_bundle(model_path, tag="x", compat=None, member="m.npz"):
    """One tiny committed bundle via the real commit protocol."""
    def write(p):
        with open(p, "w", encoding="utf-8") as fh:
            fh.write(tag)
    return bdl.write_bundle(str(model_path), {member: write},
                            compat=compat)


def tag_stub(tag):
    def translate(lines):
        return [f"{tag}:{ln}" for ln in lines]
    return translate


def seq_factory(calls=None):
    """Executor factory tagging output with the bundle seq (b2:, b3:...)."""
    def factory(bundle_dir, manifest):
        if calls is not None:
            calls.append(bundle_dir)
        return tag_stub(f"b{manifest['seq']}")
    return factory


# ---------------------------------------------------------------------------
# manifest v2: compat block + commit hooks (training/bundle.py satellites)
# ---------------------------------------------------------------------------

class TestManifestCompat:
    def test_compat_block_and_hash(self, tmp_path):
        v = tmp_path / "v.yml"
        v.write_text('"</s>": 0\n')
        a = bdl.compat_block(dict(GEO_A, vocabs=[str(v)]))
        assert a["vocabs"][0]["name"] == "v.yml"
        assert len(a["vocabs"][0]["sha256"]) == 64
        assert bdl.compat_hash(a) != "none"
        assert bdl.compat_hash(None) == "none"

    def test_geometry_mismatch_refused(self):
        ok, why = bdl.compat_ok(bdl.compat_block(GEO_A),
                                bdl.compat_block(GEO_B))
        assert not ok and "config hash" in why

    def test_vocab_content_mismatch_refused(self, tmp_path):
        va, vb = tmp_path / "va.yml", tmp_path / "vb.yml"
        va.write_text('"</s>": 0\n')
        vb.write_text('"</s>": 0\n"<unk>": 1\n')
        a = bdl.compat_block(GEO_A, [str(va)])
        b = bdl.compat_block(GEO_A, [str(vb)])
        ok, why = bdl.compat_ok(a, b)
        assert not ok and "vocab 0" in why

    def test_v1_manifest_fallback_permissive(self):
        # a v1 manifest has no compat block: manifest_compat -> None and
        # the comparison is permissive (documented read-side fallback)
        assert bdl.manifest_compat({"version": 1, "members": {}}) is None
        ok, why = bdl.compat_ok(None, bdl.compat_block(GEO_A))
        assert ok and "v1 manifest" in why

    def test_write_records_compat_and_validates(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        compat = bdl.compat_block(GEO_A)
        bdir = commit_bundle(mp, compat=compat)
        ok, why, manifest = bdl.validate_bundle(bdir)
        assert ok, why
        assert manifest["version"] == bdl.MANIFEST_VERSION == 2
        assert bdl.manifest_compat(manifest) == compat

    def test_future_manifest_version_refused(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        bdir = commit_bundle(mp)
        mpath = os.path.join(bdir, bdl.MANIFEST_NAME)
        manifest = json.load(open(mpath))
        manifest["version"] = 99
        with open(mpath, "w") as fh:
            json.dump(manifest, fh)
        ok, why, _ = bdl.validate_bundle(bdir)
        assert not ok and "unsupported" in why

    def test_commit_hook_fires_and_raising_hook_is_contained(self,
                                                             tmp_path):
        mp = str(tmp_path / "m.npz")
        seen = []

        def good(model_path, bundle_dir, manifest):
            seen.append((model_path, bundle_dir, manifest["seq"]))

        def bad(model_path, bundle_dir, manifest):
            raise RuntimeError("observer bug")

        bdl.add_commit_hook(bad)
        bdl.add_commit_hook(good)
        try:
            bdir = commit_bundle(mp)
        finally:
            bdl.remove_commit_hook(bad)
            bdl.remove_commit_hook(good)
        assert seen == [(mp, bdir, 1)]   # bad hook contained, save landed
        assert bdl.validate_bundle(bdir)[0]

    def test_checkpoint_compat_from_yaml(self):
        from marian_tpu.training.checkpoint import _compat_from_yaml
        got = _compat_from_yaml("type: transformer\ndim-emb: 16\n")
        assert got["config_hash"]
        assert _compat_from_yaml("") is None
        assert _compat_from_yaml(":::not yaml") is None


# ---------------------------------------------------------------------------
# registry state machine
# ---------------------------------------------------------------------------

class TestModelRegistry:
    def test_full_lifecycle_path(self):
        r = ModelRegistry()
        r.register(1, "bundle-00000001")
        for state in (WARMING, CANARY, LIVE, RETIRED, LIVE):
            r.transition(1, state)
        assert r.get(1).state == LIVE

    @pytest.mark.parametrize("path,bad", [
        ((), LIVE),                          # staged -> live skips warming
        ((WARMING,), RETIRED),               # warming -> retired
        ((WARMING, CANARY, FAILED), LIVE),   # failed is terminal
        ((REJECTED,), WARMING),              # rejected is terminal
        ((WARMING, LIVE, RETIRED), CANARY),  # retired only -> live
    ])
    def test_illegal_transitions_raise(self, path, bad):
        r = ModelRegistry()
        r.register(1, "b1")
        for state in path:
            r.transition(1, state)
        with pytest.raises(LifecycleError, match="illegal transition"):
            r.transition(1, bad)

    def test_duplicate_register_raises_until_terminal(self):
        r = ModelRegistry()
        r.register(1, "b1")
        with pytest.raises(LifecycleError, match="already registered"):
            r.register(1, "b1")
        r.transition(1, REJECTED)
        r.register(1, "b1-retry")     # terminal states may be retried

    def test_unknown_version_and_state(self):
        r = ModelRegistry()
        with pytest.raises(LifecycleError, match="unknown model version"):
            r.transition(7, WARMING)
        r.register(1, "b1")
        with pytest.raises(LifecycleError, match="unknown lifecycle"):
            r.transition(1, "zombie")

    def test_snapshot_newest_first(self):
        r = ModelRegistry()
        r.register(1, "b1")
        r.register(2, "b2")
        rows = r.snapshot()
        assert [row["seq"] for row in rows] == [2, 1]
        assert rows[0]["state"] == STAGED

    def test_scan_bundles_flags_damage(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        commit_bundle(mp, tag="one")
        b2 = commit_bundle(mp, tag="two")
        victim = os.path.join(b2, "m.npz")
        os.chmod(victim, 0o644)
        with open(victim, "w") as fh:
            fh.write("corrupt")
        infos = scan_bundles(mp)
        assert [i.seq for i in infos] == [1, 2]
        assert infos[0].ok and not infos[1].ok


# ---------------------------------------------------------------------------
# bundle watcher
# ---------------------------------------------------------------------------

class TestBundleWatcher:
    def _watch(self, mp, got, **kw):
        return BundleWatcher(bdl.bundle_root(str(mp)),
                             lambda bdir, man: got.append((bdir,
                                                           man["seq"])),
                             **kw)

    def test_picks_up_fresh_commit_once(self, tmp_path):
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        assert w.poll_now() is None            # no bundle root yet
        bdir = commit_bundle(mp)
        assert w.poll_now() == bdir
        assert w.poll_now() is None            # no redelivery
        assert got == [(bdir, 1)]

    def test_newest_wins_across_a_gap(self, tmp_path):
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        commit_bundle(mp, tag="one")
        commit_bundle(mp, tag="two")
        w.poll_now()
        assert [seq for _, seq in got] == [2]  # intermediate superseded

    def test_damaged_newest_does_not_shadow_valid_older(self, tmp_path):
        """Two bundles land between polls and the NEWEST is damaged: the
        valid one below it must still be delivered (newest VALID wins) —
        and a later higher seq is still picked up."""
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        b1 = commit_bundle(mp, tag="one")
        b2 = commit_bundle(mp, tag="two")
        victim = os.path.join(b2, "m.npz")
        os.chmod(victim, 0o644)
        with open(victim, "w") as fh:
            fh.write("corrupt")
        assert w.poll_now() == b1              # valid fallback delivered
        b3 = commit_bundle(mp, tag="three")
        assert w.poll_now() == b3
        assert [seq for _, seq in got] == [1, 3]

    def test_invalid_newest_skipped_next_seq_delivered(self, tmp_path):
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        b1 = commit_bundle(mp, tag="one")
        victim = os.path.join(b1, "m.npz")
        os.chmod(victim, 0o644)
        with open(victim, "w") as fh:
            fh.write("corrupt")
        assert w.poll_now() is None            # damaged: skipped loudly
        b2 = commit_bundle(mp, tag="two")
        assert w.poll_now() == b2              # higher seq still lands
        assert got == [(b2, 2)]

    def test_thread_delivers_on_notify(self, tmp_path):
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got, interval=30.0)  # poll too slow to matter
        w.start()
        try:
            commit_bundle(mp)
            w.notify()
            for _ in range(200):
                if got:
                    break
                import time
                time.sleep(0.01)
        finally:
            w.stop()
        assert [seq for _, seq in got] == [1]

    def test_injected_watch_fault_redelivers(self, tmp_path):
        """lifecycle.watch=fail: a transient discovery failure must not
        lose the bundle — the next poll re-delivers it."""
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        bdir = commit_bundle(mp)
        with fp.active("lifecycle.watch=fail"):
            with pytest.raises(fp.InjectedFault):
                w.poll_now()
        assert got == []
        assert w.poll_now() == bdir            # re-delivered, not lost
        assert got == [(bdir, 1)]

    def test_same_tick_commit_not_skipped(self, tmp_path):
        """A commit landing within the same filesystem-timestamp tick as
        the recorded root mtime must still be discovered: while the
        recorded mtime is recent, mtime equality is not trusted."""
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        commit_bundle(mp, tag="one")
        w.poll_now()
        b2 = commit_bundle(mp, tag="two")
        # force the pathological case: root mtime identical to what the
        # previous poll recorded (coarse-granularity filesystems)
        os.utime(bdl.bundle_root(str(mp)),
                 ns=(w._last_mtime_ns, w._last_mtime_ns))
        assert w.poll_now() == b2
        assert [seq for _, seq in got] == [1, 2]

    def test_notify_defeats_stale_mtime_short_circuit(self, tmp_path):
        """Once the recorded mtime is old, equality IS trusted — unless
        notify() pushed, which must force a full listing."""
        import time as _t
        mp = tmp_path / "m.npz"
        got = []
        w = self._watch(mp, got)
        root = bdl.bundle_root(str(mp))
        old_ns = _t.time_ns() - 3_600 * 10**9   # an hour ago
        commit_bundle(mp, tag="one")
        os.utime(root, ns=(old_ns, old_ns))
        w.poll_now()
        b2 = commit_bundle(mp, tag="two")
        os.utime(root, ns=(old_ns, old_ns))     # mtime looks unchanged
        assert w.poll_now() is None             # stale + equal: skipped
        w.notify()
        assert w.poll_now() == b2               # pushed: full listing
        assert [seq for _, seq in got] == [1, 2]


# ---------------------------------------------------------------------------
# warmup + compat refusal + swap controller
# ---------------------------------------------------------------------------

def make_controller(factory=None, live_tag="v1", compat=None, reg=None,
                    **kw):
    ctrl = SwapController(factory or seq_factory(),
                          metrics_registry=reg or msm.Registry(), **kw)
    ctrl.seed_live(0, "boot", tag_stub(live_tag), compat=compat)
    return ctrl


class TestWarmupAndSwap:
    def test_immediate_swap_after_warmup(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        reg = msm.Registry()
        ctrl = make_controller(reg=reg)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == LIVE
        assert ctrl.registry.get(0).state == RETIRED
        assert ctrl.route(["x"]) == ["b1:x"]
        assert reg.get("marian_lifecycle_swaps_total").value == 1
        # marian_model_info: new version 1, retired boot version 0
        text = reg.render()
        assert 'marian_model_info{model_version="bundle-00000001"' in text
        assert ctrl.live_version_name() == "bundle-00000001"

    def test_compat_mismatch_refused_without_loading(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        calls = []
        reg = msm.Registry()
        ctrl = make_controller(factory=seq_factory(calls), reg=reg,
                               compat=bdl.compat_block(GEO_A))
        bdir = commit_bundle(mp, compat=bdl.compat_block(GEO_B))
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == REJECTED and "config hash" in v.error
        assert calls == []             # refused BEFORE loading weights
        assert ctrl.route(["x"]) == ["v1:x"]    # live untouched
        assert reg.get("marian_lifecycle_rejects_total") \
                  .labels("compat").value == 1

    def test_v1_manifest_swaps_permissively(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        ctrl = make_controller(compat=bdl.compat_block(GEO_A))
        bdir = commit_bundle(mp)               # no compat block (v1-style)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == LIVE                 # documented fallback

    def test_warmup_failure_keeps_live(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        reg = msm.Registry()

        def broken_factory(bundle_dir, manifest):
            raise RuntimeError("weights will not load")

        ctrl = make_controller(factory=broken_factory, reg=reg)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == FAILED and "will not load" in v.error
        assert ctrl.route(["x"]) == ["v1:x"]
        assert reg.get("marian_lifecycle_rejects_total") \
                  .labels("warmup").value == 1

    def test_golden_smoke_arity_failure_refuses(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        ctrl = make_controller(factory=lambda b, m: (lambda lines: ["one"]))
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == FAILED and "misalign" in v.error

    def test_injected_warmup_fault_fails_candidate(self, tmp_path):
        """lifecycle.warmup=fail: the candidate fails, the watcher loop
        and the live version survive."""
        mp = str(tmp_path / "m.npz")
        ctrl = make_controller()
        bdir = commit_bundle(mp)
        with fp.active("lifecycle.warmup=fail"):
            v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == FAILED and "injected fault" in v.error
        assert ctrl.route(["x"]) == ["v1:x"]

    def test_injected_swap_fault_fails_install_live_survives(self,
                                                             tmp_path):
        """lifecycle.swap=fail: a failure at the swap commit point leaves
        the old live serving; a later bundle still swaps cleanly."""
        mp = str(tmp_path / "m.npz")
        reg = msm.Registry()
        ctrl = make_controller(reg=reg)
        b1 = commit_bundle(mp, tag="one")
        with fp.active("lifecycle.swap=fail"):
            v = ctrl.ingest(b1, bdl.validate_bundle(b1)[2])
        assert v.state == FAILED
        assert ctrl.route(["x"]) == ["v1:x"]
        assert reg.get("marian_lifecycle_rejects_total") \
                  .labels("install").value == 1
        b2 = commit_bundle(mp, tag="two")
        v2 = ctrl.ingest(b2, bdl.validate_bundle(b2)[2])
        assert v2.state == LIVE
        assert ctrl.route(["x"]) == ["b2:x"]

    def test_warmup_golden_file_loads_and_empty_refused(self, tmp_path):
        g = tmp_path / "golden.txt"
        g.write_text("a b\n\nc d e\n")
        assert load_golden(str(g)) == ["a b", "c d e"]
        (tmp_path / "empty.txt").write_text("\n\n")
        with pytest.raises(WarmupError, match="no sentences"):
            load_golden(str(tmp_path / "empty.txt"))
        assert load_golden(None)       # built-in probe set non-empty


class TestCanary:
    def test_canary_promotes_after_healthy_batches(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        reg = msm.Registry()
        ctrl = make_controller(reg=reg, canary_fraction=0.5,
                               canary_min_batches=4)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == CANARY
        outs = [ctrl.route([f"s{i}"])[0] for i in range(16)]
        assert v.state == LIVE                 # promoted
        assert any(o.startswith("b1:") for o in outs)
        assert any(o.startswith("v1:") for o in outs)   # split routing
        assert ctrl.registry.get(0).state == RETIRED
        assert reg.get("marian_model_requests_total") \
                  .labels("bundle-00000001").value >= 4

    def test_high_error_canary_rolls_back_with_zero_client_failures(
            self, tmp_path):
        """The acceptance-criterion property at unit level: an injected
        high-error canary is auto-rolled-back; every batch still returns
        a live-model answer (failed canary batches are re-served)."""
        mp = str(tmp_path / "m.npz")
        reg = msm.Registry()

        def bad_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"]:          # golden smoke passes, traffic dies
                    raise RuntimeError("canary decode explodes")
                calls["n"] += 1
                return list(lines)
            return translate

        ctrl = make_controller(factory=bad_factory, reg=reg,
                               canary_fraction=1.0,
                               rollback_error_rate=0.5,
                               rollback_min_batches=2)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == CANARY
        outs = [ctrl.route([f"s{i}"])[0] for i in range(8)]
        assert all(o.startswith("v1:") for o in outs)   # zero failures
        assert v.state == FAILED and "failure rate" in v.error
        assert reg.get("marian_lifecycle_rollbacks_total").value == 1
        assert reg.get("marian_model_errors_total") \
                  .labels("bundle-00000001").value >= 2
        # rolled back: canary no longer routed
        assert ctrl.route(["after"])[0] == "v1:after"
        assert ctrl.status()["canary"] is None

    def test_injected_rollback_fault_retries_next_batch(self, tmp_path):
        """lifecycle.rollback=fail@1: the first rollback attempt aborts
        (routing stands), the next canary batch retries and lands it."""
        mp = str(tmp_path / "m.npz")

        def bad_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"]:
                    raise RuntimeError("boom")
                calls["n"] += 1
                return list(lines)
            return translate

        ctrl = make_controller(factory=bad_factory, canary_fraction=1.0,
                               rollback_min_batches=1)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        with fp.active("lifecycle.rollback=fail@1"):
            assert ctrl.route(["a"]) == ["v1:a"]   # rollback aborted...
            assert v.state == CANARY               # ...routing stands
            assert ctrl.route(["b"]) == ["v1:b"]   # retry lands it
            assert fp.hits("lifecycle.rollback") == 2
        assert v.state == FAILED

    def test_p99_regression_rolls_back(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        import time as _t

        def slow_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"]:
                    _t.sleep(0.03)       # ~30ms vs the live stub's ~0ms
                calls["n"] += 1
                return [f"slow:{ln}" for ln in lines]
            return translate

        ctrl = make_controller(factory=slow_factory, canary_fraction=0.5,
                               canary_min_batches=10_000,
                               rollback_p99_factor=3.0)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        for i in range(90):
            ctrl.route([f"s{i}"])
            if v.state == FAILED:
                break
        assert v.state == FAILED and "p99" in v.error

    def test_regressed_live_rolls_back_to_previous(self, tmp_path):
        """Post-swap safety net: a canary-less immediate swap whose new
        live version starts failing rolls back to the retained previous
        version (once)."""
        mp = str(tmp_path / "m.npz")

        def flaky_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"] >= 3:     # healthy through warmup + 2 batches
                    raise RuntimeError("late regression")
                calls["n"] += 1
                return [f"b{manifest['seq']}:{ln}" for ln in lines]
            return translate

        ctrl = make_controller(factory=flaky_factory,
                               rollback_min_batches=2)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == LIVE
        outs = []
        for i in range(8):
            try:
                outs.append(ctrl.route([f"s{i}"])[0])
            except RuntimeError:
                pass                    # failed batches surface normally
        assert v.state == FAILED and "failure rate" in v.error
        assert ctrl.registry.get(0).state == LIVE   # rolled back
        assert ctrl.route(["after"])[0] == "v1:after"

    def test_canary_error_on_promotion_eligible_batch_not_promoted(
            self, tmp_path):
        """A canary batch that ERRORS must never promote that canary in
        the same evaluation — promotion before the re-serve would make
        the failed canary live and turn the promised transparent retry
        into a client-visible error."""
        mp = str(tmp_path / "m.npz")

        def once_bad_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                calls["n"] += 1
                if calls["n"] == 2:      # golden smoke ok, 1st batch dies
                    raise RuntimeError("transient canary failure")
                return [f"b{manifest['seq']}:{ln}" for ln in lines]
            return translate

        ctrl = make_controller(factory=once_bad_factory,
                               canary_fraction=1.0,
                               canary_min_batches=1,
                               rollback_error_rate=1.0,
                               rollback_min_batches=2)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == CANARY
        # errored batch: re-served on live, canary NOT promoted even
        # though it already has canary_min_batches batches
        assert ctrl.route(["a"]) == ["v1:a"]
        assert v.state == CANARY
        # the next HEALTHY batch promotes as usual
        assert ctrl.route(["b"]) == ["b1:b"]
        assert v.state == LIVE

    def test_superseded_canary_retired_and_released(self, tmp_path):
        """A newer candidate arriving mid-canary replaces it: the old
        canary leaves routing terminally (no two versions reporting
        marian_model_info=1 as canary) and drops its executor."""
        mp = str(tmp_path / "m.npz")
        ctrl = make_controller(canary_fraction=0.5,
                               canary_min_batches=10_000)
        b1 = commit_bundle(mp, tag="one")
        v1 = ctrl.ingest(b1, bdl.validate_bundle(b1)[2])
        assert v1.state == CANARY
        b2 = commit_bundle(mp, tag="two")
        v2 = ctrl.ingest(b2, bdl.validate_bundle(b2)[2])
        assert v2.state == CANARY
        assert v1.state == RETIRED and "superseded" in v1.error
        assert v1.executor is None
        st = ctrl.status()
        assert st["canary"] == "bundle-00000002"
        assert [r for r in st["versions"]
                if r["state"] == CANARY] == [st["versions"][0]]
        # routing is intact on both sides of the split
        outs = {ctrl.route([f"s{i}"])[0].split(":")[0] for i in range(8)}
        assert outs == {"v1", "b2"}

    def test_executors_released_when_leaving_rollback_set(self, tmp_path):
        """Only live + canary + the single rollback target stay warm:
        every hot-swap must NOT leak the previous models' executors
        (weeks of swaps would otherwise accumulate whole models)."""
        mp = str(tmp_path / "m.npz")
        ctrl = make_controller()
        boot = ctrl.registry.get(0)
        for tag in ("one", "two", "three"):
            bdir = commit_bundle(mp, tag=tag)
            ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert ctrl.registry.get(3).state == LIVE
        assert ctrl.registry.get(2).state == RETIRED
        assert ctrl.registry.get(2).executor is not None  # rollback target
        assert ctrl.registry.get(1).executor is None      # dropped
        assert boot.executor is None                      # dropped
        assert ctrl.route(["x"]) == ["b3:x"]

    def test_failed_canary_executor_released(self, tmp_path):
        mp = str(tmp_path / "m.npz")

        def bad_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"]:
                    raise RuntimeError("boom")
                calls["n"] += 1
                return list(lines)
            return translate

        ctrl = make_controller(factory=bad_factory, canary_fraction=1.0,
                               rollback_min_batches=1)
        bdir = commit_bundle(mp)
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert ctrl.route(["a"]) == ["v1:a"]
        assert v.state == FAILED
        assert v.executor is None


# ---------------------------------------------------------------------------
# admin verbs + /lifecyclez + readyz (server wiring)
# ---------------------------------------------------------------------------

def make_app(tmp_path, translate=None, **opt):
    from marian_tpu.server.server import ServingApp
    base = {"batch-token-budget": 256, "max-queue": 512,
            "request-timeout": 0.0, "metrics-port": 0,
            "models": [str(tmp_path / "m.npz")], "model-watch": 0.05}
    base.update(opt)
    return ServingApp(Options(base),
                      translate_lines=translate or tag_stub("v1"),
                      registry=msm.Registry(),
                      executor_factory=seq_factory())


class TestAdminAndReadiness:
    def test_lifecyclez_and_admin_verbs_over_http(self, tmp_path):
        mp = str(tmp_path / "m.npz")

        async def scenario():
            app = make_app(tmp_path)
            await app.start()
            srv = msm.MetricsServer(0, registry=app.registry,
                                    ready_fn=app.ready,
                                    routes=app._admin_routes()).start()
            base = f"http://127.0.0.1:{srv.port}"

            def get(path):
                with urllib.request.urlopen(base + path, timeout=5) as fh:
                    return fh.status, fh.read()

            def post(path):
                req = urllib.request.Request(base + path, data=b"",
                                             method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=5) as fh:
                        return fh.status, fh.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            try:
                code, body = get("/lifecyclez")
                state = json.loads(body)
                assert code == 200 and state["live"] == "boot"
                assert state["versions"][0]["state"] == "live"
                # GET on a verb is refused
                with pytest.raises(urllib.error.HTTPError) as ei:
                    get("/admin/pin")
                assert ei.value.code == 405
                # nothing to roll back to yet -> 409, not a crash
                assert post("/admin/rollback")[0] == 409
                # pin -> a fresh commit is rejected, live unchanged
                code, body = post("/admin/pin")
                assert code == 200 and json.loads(body)["ok"]
                bdir = commit_bundle(mp)
                app.lifecycle.ingest(bdir, bdl.validate_bundle(bdir)[2])
                assert app.lifecycle.registry.get(1).state == REJECTED
                assert json.loads(get("/lifecyclez")[1])["pinned"]
                # unpin -> the NEXT commit swaps in
                assert post("/admin/unpin")[0] == 200
                b2 = commit_bundle(mp, tag="two")
                app.lifecycle.ingest(b2, bdl.validate_bundle(b2)[2])
                assert json.loads(get("/lifecyclez")[1])["live"] \
                    == "bundle-00000002"
                # manual rollback flips to the retained previous version
                code, body = post("/admin/rollback")
                assert code == 200 and json.loads(body)["live"] == "boot"
                # and is REVERSIBLE: the displaced version stays retained
                # as the rollback target, so a second verb flips back
                code, body = post("/admin/rollback")
                assert code == 200 and json.loads(body)["live"] \
                    == "bundle-00000002"
            finally:
                srv.close()
                await app.shutdown(drain_timeout=2.0)

        run(scenario())

    def test_readyz_reflects_lifecycle_liveness(self, tmp_path):
        async def scenario():
            app = make_app(tmp_path)
            assert not app.ready()          # not started yet
            await app.start()
            assert app.ready()              # seeded live version
            app.admission.begin_drain()
            assert not app.ready()
            await app.shutdown(drain_timeout=2.0)

        run(scenario())

    def test_boot_adopts_newest_bundle_seq(self, tmp_path):
        mp = str(tmp_path / "m.npz")
        compat = bdl.compat_block(GEO_A)
        commit_bundle(mp, tag="one", compat=compat)

        async def scenario():
            app = make_app(tmp_path)
            await app.start()
            try:
                st = app.lifecycle.status()
                assert st["live"] == "bundle-00000001"
                # the watcher must NOT re-ingest the boot bundle
                assert app.watcher.poll_now() is None
                # and the boot compat chain came from the manifest
                assert app.lifecycle.registry.get(1).compat == compat
            finally:
                await app.shutdown(drain_timeout=2.0)

        run(scenario())

    def test_boot_with_stale_publish_swaps_to_newest(self, tmp_path):
        """A crash between bundle commit and flat publish (ckpt.publish)
        leaves the flat model one version behind the newest bundle. Boot
        must seed the version the flat file actually IS — not the newest
        bundle's name — so the watcher warms and swaps to the newest
        instead of silently serving stale weights with lying telemetry."""
        mp = str(tmp_path / "m.npz")
        compat = bdl.compat_block(GEO_A)
        commit_bundle(mp, tag="one", compat=compat)
        with fp.active("ckpt.publish=fail"):
            with pytest.raises(fp.InjectedFault):
                commit_bundle(mp, tag="two", compat=compat)
        app = make_app(tmp_path)
        try:
            st = app.lifecycle.status()
            assert st["live"] == "bundle-00000001"   # truthful label
            assert app.watcher.poll_now() is not None
            assert app.lifecycle.status()["live"] == "bundle-00000002"
        finally:
            app.close_nowait()


# ---------------------------------------------------------------------------
# end-to-end: hot swap under continuous traffic, zero failed requests
# ---------------------------------------------------------------------------

class TestEndToEndHotSwap:
    def test_swap_under_load_zero_failures_version_flips(self, tmp_path):
        """THE acceptance criterion: while requests flow continuously,
        committing a new valid bundle flips the served version with zero
        failed/shed requests — verified via replies, marian_model_info,
        and the per-version outcome counters."""
        mp = str(tmp_path / "m.npz")
        compat = bdl.compat_block(GEO_A)
        commit_bundle(mp, tag="one", compat=compat)   # boot bundle (seq 1)

        async def scenario():
            app = make_app(tmp_path)
            await app.start()
            replies, flipped_at = [], None
            try:
                for i in range(600):
                    r = await app.handle_text(f"s{i}")
                    replies.append(r)
                    if i == 20:
                        # the training side commits a new bundle; the
                        # in-process commit hook nudges the watcher
                        commit_bundle(mp, tag="two", compat=compat)
                    if flipped_at is None and r.startswith("b2:"):
                        flipped_at = i
                    if flipped_at is not None and i >= flipped_at + 20:
                        break
                    await asyncio.sleep(0.002)
            finally:
                await app.shutdown(drain_timeout=5.0)
            return app, replies, flipped_at

        app, replies, flipped_at = run(scenario())
        # zero failed / shed / empty replies across the swap
        bad = [r for r in replies if r.startswith("!!") or not r]
        assert bad == []
        assert flipped_at is not None, "version never flipped under load"
        # before the flip the boot model answered; after it, bundle 2
        assert replies[0].startswith("v1:")
        assert all(r.startswith("b2:") for r in replies[flipped_at:])
        text = app.registry.render()
        assert ('marian_model_info{model_version="bundle-00000002"'
                in text)
        # per-version outcome counters: every request resolved ok, and
        # the post-swap ones carry the new version label
        assert 'marian_serving_request_outcomes_total{outcome="ok"' \
            in text
        assert ('marian_serving_request_outcomes_total{outcome="ok",'
                'model_version="bundle-00000002"}') in text
        shed = app.registry.get("marian_serving_shed_total")
        assert shed.labels("queue_full").value == 0
        ok_total = sum(
            c.value for key, c in
            app.registry.get("marian_serving_request_outcomes_total")
            ._children.items() if key[0] == "ok")
        assert ok_total == len(replies)

    def test_canary_swap_under_load_with_injected_failures(self,
                                                           tmp_path):
        """Acceptance, canary flavor: a high-error canary under live
        traffic rolls back automatically; clients never see a failure."""
        mp = str(tmp_path / "m.npz")
        compat = bdl.compat_block(GEO_A)
        commit_bundle(mp, tag="one", compat=compat)

        def bad_factory(bundle_dir, manifest):
            calls = {"n": 0}

            def translate(lines):
                if calls["n"]:
                    raise RuntimeError("canary explodes under traffic")
                calls["n"] += 1
                return list(lines)
            return translate

        from marian_tpu.server.server import ServingApp
        app = ServingApp(Options({
            "batch-token-budget": 256, "max-queue": 512,
            "request-timeout": 0.0, "metrics-port": 0,
            "models": [mp], "model-watch": 0.05,
            "canary-fraction": 1.0, "rollback-error-rate": 0.5,
        }), translate_lines=tag_stub("v1"), registry=msm.Registry(),
            executor_factory=bad_factory)

        async def scenario():
            await app.start()
            replies = []
            try:
                for i in range(400):
                    r = await app.handle_text(f"s{i}")
                    replies.append(r)
                    if i == 10:
                        commit_bundle(mp, tag="two", compat=compat)
                    if app.registry.get(
                            "marian_lifecycle_rollbacks_total").value \
                            and i >= 30:
                        break
                    await asyncio.sleep(0.002)
            finally:
                await app.shutdown(drain_timeout=5.0)
            return replies

        replies = run(scenario())
        assert all(r.startswith("v1:") for r in replies)  # zero failures
        assert app.registry.get(
            "marian_lifecycle_rollbacks_total").value == 1
        assert app.lifecycle.registry.get(2).state == FAILED
        assert app.lifecycle.live_version_name() == "bundle-00000001"


# ---------------------------------------------------------------------------
# scheduler outcome labels (metrics satellite)
# ---------------------------------------------------------------------------

class TestOutcomeLabels:
    def test_outcomes_labeled_with_version(self):
        reg = msm.Registry()
        state = {"fail": False}

        def translate(lines):
            if state["fail"]:
                raise ValueError("boom")
            return list(lines)

        async def scenario():
            s = ContinuousScheduler(translate, window_s=0, registry=reg,
                                    version_fn=lambda: "vX")
            s.start()
            await s.submit(["ok"])
            state["fail"] = True
            with pytest.raises(RuntimeError):
                await s.submit(["bad"])
            await s.stop()

        run(scenario())
        text = reg.render()
        assert ('marian_serving_request_outcomes_total{outcome="ok",'
                'model_version="vX"} 1') in text
        assert ('marian_serving_request_outcomes_total{outcome="failure",'
                'model_version="vX"} 1') in text

    def test_version_fn_failure_never_breaks_resolution(self):
        reg = msm.Registry()

        def broken_version():
            raise RuntimeError("label source gone")

        async def scenario():
            s = ContinuousScheduler(lambda lines: list(lines), window_s=0,
                                    registry=reg,
                                    version_fn=broken_version)
            s.start()
            out = await s.submit(["x"])
            await s.stop()
            return out

        assert run(scenario()) == ["x"]
        assert ('marian_serving_request_outcomes_total{outcome="ok",'
                'model_version="unknown"} 1') in reg.render()


# ---------------------------------------------------------------------------
# slow tier: real server killed mid-swap (scripts/chaos.py --swap)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_swap_round_real_server(tmp_path):
    """One randomized --swap chaos round against a REAL tiny-model server
    subprocess: armed kill at a lifecycle point mid-hot-swap, bundles
    never torn, clean restart serving the newest committed bundle."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "chaos.py"),
         "--swap", "--workdir", str(tmp_path), "--rounds", "1",
         "--seed", "1"],
        capture_output=True, text=True, timeout=1500,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, (
        f"chaos --swap failed:\n{proc.stdout[-3000:]}\n"
        f"{proc.stderr[-2000:]}")
    assert "0 failing round(s)" in proc.stdout
