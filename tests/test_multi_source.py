"""Multi-source encoder-decoder tests (config #4: doc-level context via a
second encoder; reference: model_factory.cpp multi-encoder assembly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import transformer as T
from marian_tpu.models.encoder_decoder import create_model


def multi_options(**over):
    base = {
        "type": "multi-transformer",
        "dim-emb": 16, "transformer-heads": 2, "transformer-dim-ffn": 32,
        "enc-depth": 1, "dec-depth": 2,
        "label-smoothing": 0.0,
        "precision": ["float32", "float32"],
        "max-length": 32,
    }
    base.update(over)
    return Options(base)


def make_multi(vocabs=(17, 13, 11), **over):
    opts = multi_options(**over)
    model = create_model(opts, list(vocabs[:-1]), vocabs[-1])
    params = model.init(jax.random.key(0))
    return model, params


def multi_batch(rng, b=2, t1=6, t2=4, tt=5, vocabs=(17, 13, 11)):
    return {
        "src_ids": jnp.asarray(rng.randint(2, vocabs[0], (b, t1)), jnp.int32),
        "src_mask": jnp.ones((b, t1), jnp.float32),
        "src2_ids": jnp.asarray(rng.randint(2, vocabs[1], (b, t2)), jnp.int32),
        "src2_mask": jnp.ones((b, t2), jnp.float32),
        "trg_ids": jnp.asarray(rng.randint(2, vocabs[2], (b, tt)), jnp.int32),
        "trg_mask": jnp.ones((b, tt), jnp.float32),
    }


class TestMultiSource:
    def test_params_have_two_encoders_and_two_context_blocks(self):
        model, params = make_multi()
        names = set(params)
        assert "encoder_l1_self_Wq" in names
        assert "encoder2_l1_self_Wq" in names
        assert "encoder_Wemb" in names and "encoder2_Wemb" in names
        assert "decoder_l1_context_Wq" in names
        assert "decoder_l1_context2_Wq" in names
        assert "decoder_l2_context2_Wo" in names

    def test_loss_uses_both_sources(self, rng):
        model, params = make_multi()
        batch = multi_batch(rng)
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        # gradient must flow into BOTH encoders
        for enc in ("encoder_l1_self_Wq", "encoder2_l1_self_Wq",
                    "encoder2_Wemb", "decoder_l1_context2_Wq"):
            assert float(jnp.sum(jnp.abs(grads[enc]))) > 0, enc

    def test_second_source_changes_output(self, rng):
        model, params = make_multi()
        batch = multi_batch(rng)
        l1, _ = model.loss(params, batch, None, train=False)
        batch2 = dict(batch)
        batch2["src2_ids"] = jnp.asarray(
            rng.randint(2, 13, batch["src2_ids"].shape), jnp.int32)
        l2, _ = model.loss(params, batch2, None, train=False)
        assert abs(float(l1) - float(l2)) > 1e-6

    def test_teacher_forcing_matches_incremental(self, rng):
        model, params = make_multi()
        batch = multi_batch(rng)
        src = (batch["src_ids"], batch["src2_ids"])
        masks = (batch["src_mask"], batch["src2_mask"])
        enc = model.encode_for_decode(params, src, masks)
        assert isinstance(enc, tuple) and len(enc) == 2
        tf = T.decode_train(model.cfg, params, enc, masks,
                            batch["trg_ids"], batch["trg_mask"], train=False)
        state = model.start_state(params, enc, masks, max_len=5)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(5):
            logits, state = model.step(params, state, prev, masks)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(tf[:, t]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_beam_search_multi_source(self, rng):
        from marian_tpu.translator.beam_search import BeamConfig, beam_search_jit
        model, params = make_multi()
        batch = multi_batch(rng)
        src = (batch["src_ids"], batch["src2_ids"])
        masks = (batch["src_mask"], batch["src2_mask"])
        cfg = BeamConfig(beam_size=2, max_length=6)
        tokens, scores, lengths, norm, _ = beam_search_jit(
            model, [params], [1.0], cfg, src, masks)
        assert tokens.shape == (2, 2, 6)
        assert np.all(np.isfinite(np.asarray(norm)))

    def test_batch_to_arrays_emits_extra_streams(self, rng):
        from marian_tpu.data.batch_generator import SubBatch, CorpusBatch
        from marian_tpu.models.encoder_decoder import batch_to_arrays
        import dataclasses as dc
        subs = []
        for t in (5, 4, 6):
            ids = rng.randint(0, 9, (2, t)).astype(np.int32)
            subs.append(SubBatch(ids=ids, mask=np.ones((2, t), np.float32)))
        cb = CorpusBatch(sub=subs, sentence_ids=np.arange(2))
        arrays = batch_to_arrays(cb)
        assert "src2_ids" in arrays and arrays["src2_ids"].shape == (2, 4)
        assert arrays["trg_ids"].shape == (2, 6)
