"""Multi-source encoder-decoder tests (config #4: doc-level context via a
second encoder; reference: model_factory.cpp multi-encoder assembly)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import transformer as T
from marian_tpu.models.encoder_decoder import create_model


def multi_options(**over):
    base = {
        "type": "multi-transformer",
        "dim-emb": 16, "transformer-heads": 2, "transformer-dim-ffn": 32,
        "enc-depth": 1, "dec-depth": 2,
        "label-smoothing": 0.0,
        "precision": ["float32", "float32"],
        "max-length": 32,
    }
    base.update(over)
    return Options(base)


def make_multi(vocabs=(17, 13, 11), **over):
    opts = multi_options(**over)
    model = create_model(opts, list(vocabs[:-1]), vocabs[-1])
    params = model.init(jax.random.key(0))
    return model, params


def multi_batch(rng, b=2, t1=6, t2=4, tt=5, vocabs=(17, 13, 11)):
    return {
        "src_ids": jnp.asarray(rng.randint(2, vocabs[0], (b, t1)), jnp.int32),
        "src_mask": jnp.ones((b, t1), jnp.float32),
        "src2_ids": jnp.asarray(rng.randint(2, vocabs[1], (b, t2)), jnp.int32),
        "src2_mask": jnp.ones((b, t2), jnp.float32),
        "trg_ids": jnp.asarray(rng.randint(2, vocabs[2], (b, tt)), jnp.int32),
        "trg_mask": jnp.ones((b, tt), jnp.float32),
    }


class TestMultiSource:
    def test_params_have_two_encoders_and_two_context_blocks(self):
        model, params = make_multi()
        names = set(params)
        assert "encoder_l1_self_Wq" in names
        assert "encoder2_l1_self_Wq" in names
        assert "encoder_Wemb" in names and "encoder2_Wemb" in names
        assert "decoder_l1_context_Wq" in names
        assert "decoder_l1_context2_Wq" in names
        assert "decoder_l2_context2_Wo" in names

    def test_loss_uses_both_sources(self, rng):
        model, params = make_multi()
        batch = multi_batch(rng)
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        # gradient must flow into BOTH encoders
        for enc in ("encoder_l1_self_Wq", "encoder2_l1_self_Wq",
                    "encoder2_Wemb", "decoder_l1_context2_Wq"):
            assert float(jnp.sum(jnp.abs(grads[enc]))) > 0, enc

    def test_second_source_changes_output(self, rng):
        model, params = make_multi()
        batch = multi_batch(rng)
        l1, _ = model.loss(params, batch, None, train=False)
        batch2 = dict(batch)
        batch2["src2_ids"] = jnp.asarray(
            rng.randint(2, 13, batch["src2_ids"].shape), jnp.int32)
        l2, _ = model.loss(params, batch2, None, train=False)
        assert abs(float(l1) - float(l2)) > 1e-6

    def test_teacher_forcing_matches_incremental(self, rng):
        model, params = make_multi()
        batch = multi_batch(rng)
        src = (batch["src_ids"], batch["src2_ids"])
        masks = (batch["src_mask"], batch["src2_mask"])
        enc = model.encode_for_decode(params, src, masks)
        assert isinstance(enc, tuple) and len(enc) == 2
        tf = T.decode_train(model.cfg, params, enc, masks,
                            batch["trg_ids"], batch["trg_mask"], train=False)
        state = model.start_state(params, enc, masks, max_len=5)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(5):
            logits, state = model.step(params, state, prev, masks)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(tf[:, t]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_beam_search_multi_source(self, rng):
        from marian_tpu.translator.beam_search import BeamConfig, beam_search_jit
        model, params = make_multi()
        batch = multi_batch(rng)
        src = (batch["src_ids"], batch["src2_ids"])
        masks = (batch["src_mask"], batch["src2_mask"])
        cfg = BeamConfig(beam_size=2, max_length=6)
        tokens, scores, lengths, norm, _, _ws = beam_search_jit(
            model, [params], [1.0], cfg, src, masks)
        assert tokens.shape == (2, 2, 6)
        assert np.all(np.isfinite(np.asarray(norm)))

    def test_batch_to_arrays_emits_extra_streams(self, rng):
        from marian_tpu.data.batch_generator import SubBatch, CorpusBatch
        from marian_tpu.models.encoder_decoder import batch_to_arrays
        import dataclasses as dc
        subs = []
        for t in (5, 4, 6):
            ids = rng.randint(0, 9, (2, t)).astype(np.int32)
            subs.append(SubBatch(ids=ids, mask=np.ones((2, t), np.float32)))
        cb = CorpusBatch(sub=subs, sentence_ids=np.arange(2))
        arrays = batch_to_arrays(cb)
        assert "src2_ids" in arrays and arrays["src2_ids"].shape == (2, 4)
        assert arrays["trg_ids"].shape == (2, 6)


class TestMultiSourceDrivers:
    """The task drivers must assemble the same multi-encoder model that
    training used (regression: Translate/Rescorer used to pass only the
    first vocab, silently decoding with a single-encoder network)."""

    def _vocab_yaml(self, tmp_path, name, words):
        p = tmp_path / name
        lines = ["</s>: 0", "<unk>: 1"]
        lines += [f"{w}: {i}" for i, w in enumerate(words, start=2)]
        p.write_text("\n".join(lines) + "\n")
        return str(p)

    def test_translator_builds_all_encoders(self, tmp_path, rng):
        import yaml
        from marian_tpu.common import io as mio
        from marian_tpu.translator.translator import Translate

        v1 = self._vocab_yaml(tmp_path, "v1.yml", ["a", "b", "c"])
        v2 = self._vocab_yaml(tmp_path, "v2.yml", ["x", "y"])
        vt = self._vocab_yaml(tmp_path, "vt.yml", ["u", "v", "w"])
        opts = multi_options(**{
            "models": [], "model": str(tmp_path / "m.npz"),
            "vocabs": [v1, v2, vt], "beam-size": 2, "max-length": 16,
            "mini-batch": 2, "maxi-batch": 1, "input": ["f1", "f2"],
        })
        model = create_model(opts, [5, 4], 5)
        params = model.init(jax.random.key(0))
        cfg_yaml = yaml.safe_dump(dict(opts.items())
                                  if hasattr(opts, "items") else {})
        mio.save_model(str(tmp_path / "m.npz"),
                       {k: np.asarray(v) for k, v in params.items()},
                       config_yaml=cfg_yaml)

        f1 = tmp_path / "in1.txt"
        f2 = tmp_path / "in2.txt"
        f1.write_text("a b\nc a\n")
        f2.write_text("x y\ny x\n")
        opts = opts.with_(input=[str(f1), str(f2)],
                          output=str(tmp_path / "out.txt"))
        tr = Translate(opts)
        assert getattr(tr.model.cfg, "n_encoders", 1) == 2
        tr.run()
        out = (tmp_path / "out.txt").read_text().splitlines()
        assert len(out) == 2

    def test_rescorer_builds_all_encoders(self, tmp_path, rng):
        import yaml
        from marian_tpu.common import io as mio
        from marian_tpu.rescorer import Rescorer

        v1 = self._vocab_yaml(tmp_path, "v1.yml", ["a", "b", "c"])
        v2 = self._vocab_yaml(tmp_path, "v2.yml", ["x", "y"])
        vt = self._vocab_yaml(tmp_path, "vt.yml", ["u", "v", "w"])
        model = create_model(multi_options(), [5, 4], 5)
        params = model.init(jax.random.key(0))
        mio.save_model(str(tmp_path / "m.npz"),
                       {k: np.asarray(v) for k, v in params.items()},
                       config_yaml=yaml.safe_dump({"type": "multi-transformer"}))
        s1 = tmp_path / "s1.txt"; s1.write_text("a b\nc a\n")
        s2 = tmp_path / "s2.txt"; s2.write_text("x y\ny x\n")
        st = tmp_path / "st.txt"; st.write_text("u v\nw u\n")
        opts = multi_options(**{
            "model": str(tmp_path / "m.npz"), "models": [],
            "vocabs": [v1, v2, vt],
            "train-sets": [str(s1), str(s2), str(st)],
            "mini-batch": 2,
        })
        r = Rescorer(opts)
        assert getattr(r.model.cfg, "n_encoders", 1) == 2
        scores = r.run(stream=open(tmp_path / "scores.txt", "w"))
        assert len(scores) == 2


class TestMultiSourceFactored:
    def test_per_encoder_factor_tables(self):
        """_vocab_info must keep one FactorTables per source stream."""
        from marian_tpu.models.encoder_decoder import _vocab_info

        class FakeFactored:
            factored = False  # plain streams here; tuple shape is the point
            def __len__(self):
                return 7

        sizes, factors = _vocab_info([FakeFactored(), FakeFactored()])
        assert sizes == (7, 7)
        assert isinstance(factors, tuple) and len(factors) == 2


class TestMultiS2S:
    """--type multi-s2s: multiple bi-RNN encoders, per-encoder Bahdanau
    attention, concatenated contexts (reference: model_factory.cpp
    multi-encoder s2s assembly)."""

    def _make(self, vocabs=(17, 13, 11), **over):
        base = {"type": "multi-s2s", "dim-emb": 16, "dim-rnn": 24,
                "enc-depth": 1, "dec-depth": 2, "enc-cell": "gru",
                "dec-cell": "gru", "label-smoothing": 0.0,
                "precision": ["float32", "float32"], "max-length": 32}
        base.update(over)
        opts = Options(base)
        model = create_model(opts, list(vocabs[:-1]), vocabs[-1])
        params = model.init(jax.random.key(0))
        return model, params

    def test_params_have_two_encoders_and_attentions(self):
        model, params = self._make()
        names = set(params)
        assert "encoder_bi_Wx" in names or any(
            n.startswith("encoder_bi") for n in names)
        assert any(n.startswith("encoder2_bi") for n in names)
        assert "Wemb" in names and "Wemb2" in names
        assert "decoder_att_U" in names and "decoder_att2_U" in names
        # ff_state consumes the CONCATENATED mean contexts
        assert params["ff_state_W"].shape[0] == 2 * 2 * 24
        assert params["ff_logit_l1_W2"].shape[0] == 2 * 2 * 24

    def test_loss_uses_both_sources(self, rng):
        model, params = self._make()
        batch = multi_batch(rng)
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        for name in ("Wemb2", "decoder_att2_U"):
            assert float(jnp.sum(jnp.abs(grads[name]))) > 0, name
        # second source changes the loss
        batch2 = dict(batch)
        batch2["src2_ids"] = jnp.asarray(
            rng.randint(2, 13, batch["src2_ids"].shape), jnp.int32)
        l2, _ = model.loss(params, batch2, None, train=False)
        assert abs(float(loss) - float(l2)) > 1e-6

    def test_teacher_forcing_matches_incremental(self, rng):
        from marian_tpu.models import s2s as S
        model, params = self._make()
        batch = multi_batch(rng)
        src = (batch["src_ids"], batch["src2_ids"])
        masks = (batch["src_mask"], batch["src2_mask"])
        cp = S.cast_params(params, model.cfg.compute_dtype)
        enc = model.encode_for_decode(params, src, masks)
        assert isinstance(enc, tuple) and len(enc) == 2
        tf = S.decode_train(model.cfg, cp, enc, masks,
                            batch["trg_ids"], batch["trg_mask"], train=False)
        state = model.start_state(params, enc, masks, max_len=5)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(5):
            logits, state = model.step(params, state, prev, masks)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(tf[:, t]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_beam_search_runs(self, rng):
        from marian_tpu.translator.beam_search import BeamConfig, beam_search_jit
        model, params = self._make()
        batch = multi_batch(rng)
        src = (batch["src_ids"], batch["src2_ids"])
        masks = (batch["src_mask"], batch["src2_mask"])
        tokens, _, _, norm, _, _ws = beam_search_jit(
            model, [params], [1.0], BeamConfig(beam_size=2, max_length=6),
            src, masks)
        assert tokens.shape == (2, 2, 6)
        assert np.all(np.isfinite(np.asarray(norm)))

    def test_training_reduces_loss(self, rng):
        from marian_tpu.training.graph_group import GraphGroup
        from marian_tpu.common import prng
        model, params = self._make()
        opts = Options({"type": "multi-s2s", "learn-rate": 0.05,
                        "optimizer": "adam", "cost-type": "ce-mean-words",
                        "clip-norm": 1.0, "seed": 3, "devices": ["0"]})
        gg = GraphGroup(model, opts)
        gg.initialize(prng.root_key(3), params)
        batch = multi_batch(rng)
        first = last = None
        for step in range(8):
            out = gg.update(dict(batch), step + 1, jax.random.key(step))
            val = float(out.loss_sum)
            first = val if first is None else first
            last = val
        assert last < first
