"""Persisted XLA compile cache as a bundle member
(marian_tpu/serving/lifecycle/compile_cache.py — ISSUE 20 tentpole):
key derivation + strict matching, pack/adopt roundtrip with the event
ledger, refusal paths (key mismatch, path traversal, missing member),
and THE acceptance: a cache-backed swap warmup cuts warmup-to-live wall
time >= 5x, keeps the marian_compile_backend_seconds_total
{trigger=swap-warmup} ledger ~flat, and leaves a jitwit-strict window
with zero post-warm compiles.

All on CPU: jax's persistent cache content-addresses CPU executables
exactly like TPU ones, and enable() zeroes the persistence thresholds
so the tiny tier-1 programs persist too.
"""

import json
import os
import zipfile

import pytest

import jax
import jax.numpy as jnp

from marian_tpu import obs
from marian_tpu.common import jitwit
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.lifecycle import compile_cache as cc
from marian_tpu.serving.lifecycle.warmup import warm_executor
from marian_tpu.training import bundle as bdl


@pytest.fixture(autouse=True)
def _restore_cache_config():
    """Every test leaves the process cache-disabled: jax's persistent
    cache config restored, the memoized cache instance dropped, and the
    module's enabled-dir cleared — so no later suite silently writes
    executables into a deleted tmp dir."""
    saved = {k: jax.config._read(k) for k in
             ("jax_compilation_cache_dir",
              "jax_persistent_cache_min_compile_time_secs",
              "jax_persistent_cache_min_entry_size_bytes")}
    yield
    cc._enabled_dir = None
    for k, v in saved.items():
        jax.config.update(k, v)
    try:
        from jax._src.compilation_cache import reset_cache
        reset_cache()
    except Exception:
        pass


def write_tiny_bundle(model_path, extra_members=None):
    def w(p):
        with open(p, "w", encoding="utf-8") as fh:
            fh.write("m")
    members = {"m.npz": w}
    members.update(extra_members or {})
    return bdl.write_bundle(str(model_path), members)


def heavy_factory(bundle_dir, manifest):
    """An executor whose first translate pays a REAL compile (30 fused
    tanh/matmul iterations — ~0.5s of XLA work on CPU): jit-on-first-
    call, so the compile lands inside warmup's golden smoke under the
    swap-warmup trigger, exactly like a real model's serving buckets."""
    def _body(x):
        for _ in range(40):
            x = jnp.tanh(x @ x.T) @ x
        return x
    jf = jax.jit(_body)
    x = jnp.ones((96, 96), jnp.float32)

    def translate(lines):
        jf(x).block_until_ready()
        return list(lines)
    return translate


def events():
    e = cc._events()
    return {k: e.labels(k).value for k in
            ("packed", "adopted", "miss", "key-mismatch", "error")}


# ---------------------------------------------------------------------------
# cache key derivation + matching
# ---------------------------------------------------------------------------

class TestCacheKey:
    def test_key_fields(self):
        key = cc.cache_key("deadbeef")
        assert key is not None
        for field in ("chip", "platform", "n_devices", "jax",
                      "flags_sha", "compat"):
            assert key[field], field
        assert key["platform"] == "cpu"
        assert key["compat"] == "deadbeef"

    def test_key_matches_strict_fields(self):
        key = cc.cache_key("")
        ok, why = cc.key_matches(dict(key), key)
        assert ok and not why
        # a cache built for different silicon must never be adopted
        for field in ("chip", "platform", "n_devices", "jax",
                      "flags_sha"):
            bad = dict(key)
            bad[field] = "tpu-v99"
            ok, why = cc.key_matches(bad, key)
            assert not ok and field in why

    def test_compat_compared_only_when_both_recorded(self):
        key = cc.cache_key("aaa")
        # v1 manifests carry no compat: permissive, like bundle compat_ok
        assert cc.key_matches(dict(key, compat=""), key)[0]
        assert cc.key_matches(key, dict(key, compat=""))[0]
        ok, why = cc.key_matches(dict(key, compat="bbb"), key)
        assert not ok and "compat" in why


# ---------------------------------------------------------------------------
# pack / adopt roundtrip + refusal paths (the event ledger)
# ---------------------------------------------------------------------------

class TestPackAdopt:
    def test_pack_without_enable_raises(self, tmp_path):
        writer = cc.pack_member()
        with pytest.raises(RuntimeError, match="no persistent cache"):
            writer(str(tmp_path / "xla_cache.zip"))

    def test_roundtrip(self, tmp_path):
        src = tmp_path / "cache-src"
        assert cc.enable(str(src))
        assert cc.active_dir() == str(src)
        (src / "sub").mkdir()
        (src / "sub" / "entry-1").write_text("compiled bits")
        before = events()
        bdir = write_tiny_bundle(
            tmp_path / "m.npz", {cc.CACHE_MEMBER: cc.pack_member()})
        assert events()["packed"] == before["packed"] + 1
        with zipfile.ZipFile(os.path.join(bdir, cc.CACHE_MEMBER)) as zf:
            names = set(zf.namelist())
        assert cc.KEY_FILE in names and "sub/entry-1" in names
        # fresh process shape: nothing enabled, adopt from the bundle
        cc._enabled_dir = None
        adopted, dest = cc.adopt(bdir)
        assert adopted
        assert cc.active_dir() == dest
        assert open(os.path.join(dest, "sub", "entry-1")).read() \
            == "compiled bits"
        assert events()["adopted"] == before["adopted"] + 1

    def test_adopt_merges_into_enabled_dir(self, tmp_path):
        """A server already running with --compile-cache keeps its
        accumulated entries: adoption merges INTO the live dir (the
        warmup.py call passes into_dir=active_dir())."""
        src = tmp_path / "producer"
        assert cc.enable(str(src))
        (src / "entry-a").write_text("a")
        bdir = write_tiny_bundle(
            tmp_path / "m.npz", {cc.CACHE_MEMBER: cc.pack_member()})
        live = tmp_path / "live"
        assert cc.enable(str(live))
        (live / "entry-b").write_text("b")
        adopted, dest = cc.adopt(bdir, into_dir=cc.active_dir())
        assert adopted and dest == str(live)
        assert cc.active_dir() == str(live)
        assert (live / "entry-a").exists() and (live / "entry-b").exists()

    def test_missing_member_is_a_counted_miss(self, tmp_path):
        bdir = write_tiny_bundle(tmp_path / "m.npz")
        before = events()
        adopted, why = cc.adopt(bdir)
        assert not adopted and "no compile-cache member" in why
        assert events()["miss"] == before["miss"] + 1

    def test_key_mismatch_refused(self, tmp_path):
        """A cache recorded on different silicon is never installed —
        the refusal is visible in the ledger, not a silent jax re-key."""
        bdir = tmp_path / "bundle"
        bdir.mkdir()
        key = cc.cache_key("")
        key["chip"] = "tpu-v99"
        with zipfile.ZipFile(bdir / cc.CACHE_MEMBER, "w") as zf:
            zf.writestr(cc.KEY_FILE, json.dumps(key))
            zf.writestr("entry-1", "alien bits")
        before = events()
        adopted, why = cc.adopt(str(bdir))
        assert not adopted and "chip mismatch" in why
        assert events()["key-mismatch"] == before["key-mismatch"] + 1
        assert cc.active_dir() is None

    def test_member_without_key_record_is_an_error(self, tmp_path):
        bdir = tmp_path / "bundle"
        bdir.mkdir()
        with zipfile.ZipFile(bdir / cc.CACHE_MEMBER, "w") as zf:
            zf.writestr("entry-1", "bits")
        before = events()
        adopted, why = cc.adopt(str(bdir))
        assert not adopted and cc.KEY_FILE in why
        assert events()["error"] == before["error"] + 1

    def test_path_traversal_member_refused(self, tmp_path):
        bdir = tmp_path / "bundle"
        bdir.mkdir()
        with zipfile.ZipFile(bdir / cc.CACHE_MEMBER, "w") as zf:
            zf.writestr(cc.KEY_FILE, json.dumps(cc.cache_key("")))
            zf.writestr("../evil", "escape")
        before = events()
        adopted, why = cc.adopt(str(bdir))
        assert not adopted and "escapes" in why
        assert events()["error"] == before["error"] + 1
        assert not (tmp_path / "evil").exists()


# ---------------------------------------------------------------------------
# THE acceptance: cache-backed swap warmup is load+verify, not full jit
# ---------------------------------------------------------------------------

class TestCachedWarmup:
    def test_cached_swap_cuts_warmup_5x_and_ledger_stays_flat(
            self, tmp_path):
        """Cold warmup pays the full jit; a bundle carrying the packed
        cache warms >= 5x faster, the swap-warmup compile ledger
        (marian_compile_backend_seconds_total{trigger=swap-warmup})
        stays ~flat, and a jitwit strict window over post-warm traffic
        sees zero compiles (ISSUE 20 acceptance)."""
        import gc
        import time

        reg = msm.Registry()
        obs.PERF.enable(reg)

        def warm(model_path):
            bundle_dir, manifest = bdl.latest_valid_bundle(
                str(model_path))
            gc.collect()   # a mid-timing GC pause would skew the ratio
            t0 = time.perf_counter()
            ex = warm_executor(bundle_dir, manifest, heavy_factory,
                               golden=["g"])
            return ex, time.perf_counter() - t0

        def ledger():
            return obs.PERF.m_backend_s.labels("swap-warmup").value

        # -- cold: no cache member; enable a live dir so compiles persist
        cc.enable(str(tmp_path / "live-cache"))
        write_tiny_bundle(tmp_path / "m1.npz")
        _ex1, t_cold = warm(tmp_path / "m1.npz")
        ledger_cold = ledger()
        assert ledger_cold > 0          # the compile was attributed

        # -- pack the now-populated cache into the NEXT bundle
        write_tiny_bundle(
            tmp_path / "m2.npz", {cc.CACHE_MEMBER: cc.pack_member()})

        # -- fresh-process shape: executables dropped, cache disabled;
        # best-of-two fresh warm runs so a one-off scheduler/GC stall on
        # a loaded CI box can't fake a regression — the cold run stays
        # single (noise there only makes the assertion harder to pass)
        t_warm = float("inf")
        for _ in range(2):
            jax.clear_caches()
            cc._enabled_dir = None
            jax.config.update("jax_compilation_cache_dir", None)
            ex2, t = warm(tmp_path / "m2.npz")
            t_warm = min(t_warm, t)
        ledger_warm = (ledger() - ledger_cold) / 2

        assert t_cold >= 5 * t_warm, \
            f"cache-backed warmup not >=5x faster: cold {t_cold:.3f}s " \
            f"vs warm {t_warm:.3f}s"
        assert ledger_warm < ledger_cold / 5, \
            f"swap-warmup compile ledger not ~flat across the " \
            f"cache-backed swap: cold {ledger_cold:.3f}s vs warm " \
            f"{ledger_warm:.3f}s"
        # post-warm traffic retraces nothing: the strict-window contract
        with jitwit.strict() as w:
            assert ex2(["a", "b"]) == ["a", "b"]
        assert w.compiles == []

    def test_event_series_registered(self):
        """marian_compile_cache_events_total is the series the fleet
        runbook pages on — a rename breaks this census first."""
        e = cc._events()
        e.labels("adopted").inc(0)
        assert "marian_compile_cache_events_total" \
            in msm.REGISTRY.render()
