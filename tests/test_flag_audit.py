"""No silently-ignored flags (VERDICT r1): every flag the parser accepts is
either read somewhere in the package at runtime or registered in
config_parser.UNIMPLEMENTED_FLAGS with a warn/error action. audit_flags then
enforces the registry at startup."""

import pathlib
import re

import pytest

from marian_tpu.common import config_parser as cp
from marian_tpu.common.options import Options

PKG = pathlib.Path(cp.__file__).resolve().parent.parent


def _parsed_flags():
    parser = cp.ConfigParser("training")
    names = set(parser.flags.keys())
    for mode in ("translation", "scoring", "embedding"):
        try:
            names |= set(cp.ConfigParser(mode).flags.keys())
        except Exception:
            pass
    return names


def _package_source_without_parser():
    chunks = []
    for p in PKG.rglob("*.py"):
        if p.name in ("config_parser.py",):
            continue
        chunks.append(p.read_text(encoding="utf-8"))
    return "\n".join(chunks)


# Flags fully handled inside the parser itself (meta flags, mappings).
PARSER_INTERNAL = {
    "config", "dump-config", "authors", "cite", "build-info", "version",
    "no-shuffle", "task", "interpolate-env-vars", "relative-paths",
    # canonical-map sources: parse() copies their value onto the target key
    *cp._CANONICAL.keys(),
}


def test_every_flag_read_or_registered():
    src = _package_source_without_parser()
    # aliases.py / validator read flags too — they count as readers
    missing = []
    for name in sorted(_parsed_flags()):
        if name in PARSER_INTERNAL or name in cp.UNIMPLEMENTED_FLAGS:
            continue
        if f'"{name}"' in src or f"'{name}'" in src:
            continue
        missing.append(name)
    assert not missing, (
        "flags parsed but neither read anywhere nor registered in "
        f"UNIMPLEMENTED_FLAGS (silent no-ops): {missing}")


def test_error_flags_raise():
    parser = cp.ConfigParser("training")
    opts = Options({"transformer-pool": True})
    with pytest.raises(ValueError, match="transformer-pool"):
        cp.audit_flags(opts, parser)


def test_error_unless_allows_default_value():
    # factors-combine concat is implemented now; exercise the error-unless
    # mechanism itself with a synthetic registry entry
    parser = cp.ConfigParser("training")
    cp.audit_flags(Options({"factors-combine": "concat"}), parser)  # no raise
    entry = {"maxi-batch-sort": ("error-unless", "trg", "synthetic test")}
    old = dict(cp.UNIMPLEMENTED_FLAGS)
    cp.UNIMPLEMENTED_FLAGS.update(entry)
    try:
        cp.audit_flags(Options({"maxi-batch-sort": "trg"}), parser)
        with pytest.raises(ValueError, match="maxi-batch-sort"):
            cp.audit_flags(Options({"maxi-batch-sort": "src"}), parser)
    finally:
        cp.UNIMPLEMENTED_FLAGS.clear()
        cp.UNIMPLEMENTED_FLAGS.update(old)


def test_warn_flags_do_not_raise():
    parser = cp.ConfigParser("training")
    cp.audit_flags(Options({"workspace": 9000, "cpu-threads": 4}), parser)


def test_default_values_pass_silently():
    parser = cp.ConfigParser("training")
    defaults = Options(parser.defaults())
    cp.audit_flags(defaults, parser)
