"""--stacked-params: depth-stacked training storage without pipeline
sharding (training/graph_group.py::_maybe_stack — removes the
--scan-layers per-step restack; VERDICT r2 weak #3 made structural)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.training.graph_group import GraphGroup


def _gg(**over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 2, "dec-depth": 2,
            "tied-embeddings-all": True, "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16,
            "learn-rate": 0.02, "optimizer": "adam", "clip-norm": 0.0,
            "exponential-smoothing": 1e-3}
    base.update(over)
    opts = Options(base)
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(13))
    return gg


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


class TestStackedParams:
    def test_storage_is_stacked_checkpoint_stays_flat(self):
        gg = _gg(**{"stacked-params": True})
        assert any("_stack_" in k for k in gg.params)
        assert not any("_l1_" in k for k in gg.params)
        exported = gg.export_params()
        assert not any("_stack_" in k for k in exported)
        assert any("_l1_" in k for k in exported)
        # optimizer state follows the stacked layout; checkpoint IO flat
        assert any("_stack_" in k for k in gg.opt_state["m"])
        assert not any("_stack_" in k for k in gg.optimizer_arrays())

    def test_trajectory_bitwise_equals_flat_storage(self):
        """The scan consumes the same [L,...] values whether restacked
        per step or stored stacked — losses must match bitwise. Scan is
        pinned ON for both sides: flat storage would otherwise run the
        unrolled stack (scan defaults off since r4) and scanned-vs-
        unrolled differ in float fusion order, which is not what this
        test pins."""
        key = prng.stream(prng.root_key(13), prng.STREAM_DROPOUT)
        losses = {}
        for flag in (False, True):
            gg = _gg(**{"stacked-params": flag, "scan-layers": True})
            ls = []
            for i in range(4):
                out = gg.update(_batch(i), i + 1, key)
                ls.append(float(out.loss_sum))
            losses[flag] = ls
        assert losses[True] == losses[False]

    def test_cli_default_guided_alignment_none_string_is_off(self):
        """The CLI default for --guided-alignment is the STRING 'none';
        it must not refuse stacking (latent since the pipe>1 path)."""
        gg = _gg(**{"stacked-params": True, "guided-alignment": "none"})
        assert any("_stack_" in k for k in gg.params)

    def test_refuses_real_guided_alignment(self, tmp_path):
        p = tmp_path / "a.align"
        p.write_text("0-0\n")
        with pytest.raises(ValueError, match="guided alignment"):
            _gg(**{"stacked-params": True, "guided-alignment": str(p)})

    def test_refuses_tied_layers(self):
        with pytest.raises(ValueError, match="stacked-params"):
            _gg(**{"stacked-params": True,
                   "transformer-tied-layers": [1, 1]})

    def test_refuses_non_transformer(self):
        with pytest.raises(ValueError, match="transformer family"):
            _gg(**{"stacked-params": True, "type": "s2s", "dim-rnn": 32,
                   "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
                   "dec-cell": "gru", "tied-embeddings-all": False,
                   "tied-embeddings": True})