"""Paged-serving observability plane (ISSUE 14): per-row serve.row /
serve.round span tracing, KV-pool occupancy telemetry, the /poolz live
inspector and its flight-recorder embedding, the #trace reply-metadata
row breakdown, and the zero-overhead raising-lock guard extended over
the engine round path. Runs under JAX_PLATFORMS=cpu with the tiny real
transformer (MARIAN_POOL_AUDIT=1 is armed process-wide by conftest, so
every engine round here is audited).

The acceptance-critical properties covered tier-1:
- a mid-decode-joining request's /tracez tree shows join→rounds→EOS
  (serve.row under the serve.request root) and an evicted request shows
  join→evict with a retriable outcome, with trace-id cross-links to the
  serve.round spans;
- with tracing disabled, the engine round path acquires no tracer/perf
  lock and allocates no ring (the ISSUE 8 contract, extended);
- the /poolz page map agrees with the pool auditor's view under live
  traffic and across a quiesce (zero discrepancies), and a pool-audit
  flight dump embeds it;
- metric census + promlint over a REAL /metrics scrape with every new
  pool/row/round series (MT-METRIC-UNTESTED stays green).
"""

import asyncio
import importlib.util
import json
import os
import time
import urllib.request

import pytest

from marian_tpu import obs
from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.obs.poolz import check_consistency, pool_routes, snapshot
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.promlint import lint_metrics_text
from marian_tpu.server.server import ServingApp
from marian_tpu.translator.beam_iteration import PagedBeamEngine
from marian_tpu.translator.prefix_cache import PrefixCache

from tests.test_iteration import TEXTS, make_engine, tiny  # noqa: F401
from tests.test_quiesce import make_sched, wait_for

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one page of the tiny engine (page_len 4): 2 (K+V) x dec_depth 2 x
# heads 2 x page_len 4 x dh 8 x 4 bytes (test_quiesce.PAGE_BYTES)
PAGE_BYTES = 2 * 2 * 2 * 4 * 8 * 4


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """pool_state/poolz snapshots read KVPool._lock and
    PagedDecodeEngine._lock from the HTTP threads while the worker
    mutates; the shared witness pins the observed acquisition orders
    inside the static lattice."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _ownership_witness(ownership_witness):
    """The /poolz traffic in this suite drives real claims/releases;
    the shared witness asserts the observed ownership pairings stay
    inside the static graph (ISSUE 15)."""
    yield


@pytest.fixture(autouse=True)
def _reset_obs():
    yield
    obs.TRACER.reset()
    obs.FLIGHT.disarm()
    obs.PERF.reset()
    fp.reset_for_tests()


def run(coro):
    return asyncio.run(coro)


def make_beam_engine(tiny, registry=None, prefix=None, **kw):
    model, params, vocab = tiny
    args = dict(max_rows=4, page_len=4, src_len_cap=8,
                max_length_cap=12, registry=registry,
                prefix_cache=prefix, beam_size=2)
    args.update(kw)
    return PagedBeamEngine(model, params, vocab, vocab, **args)


class _RaisingLock:
    """Any acquisition fails the test (the ISSUE 8 proof object)."""

    def __enter__(self):
        raise AssertionError("lock acquired on the disabled-tracer "
                             "engine round path")

    def __exit__(self, *exc):
        pass

    def acquire(self, *a, **kw):
        raise AssertionError("lock acquired on the disabled-tracer "
                             "engine round path")

    def release(self):
        pass


# ---------------------------------------------------------------------------
# pool_state / /poolz vs the auditor (tentpole piece 2+3)
# ---------------------------------------------------------------------------

class TestPoolState:
    def test_page_map_agrees_with_auditor_under_traffic(self, tiny):
        """The acceptance cross-check: mid-decode, the exported page
        map must satisfy the same accounting invariants the auditor
        enforces — and the auditor itself must agree the pool is
        clean."""
        eng = make_engine(tiny)
        eng.admit_and_step([(0, TEXTS[0]), (1, TEXTS[1])])
        eng.admit_and_step([(2, TEXTS[2])])
        assert eng.audit(context="test") == []
        st = eng.pool_state()
        assert st["enabled"] and st["engine"] == "PagedDecodeEngine"
        assert check_consistency(st) == []
        # the map reflects the live claims: 3 rows x 3 pages each
        assert st["rows"]["active"] == 3
        assert sum(len(r["pages"]) for r in st["rows"]["slots"]) \
            == st["pool"]["used_pages"]
        assert st["pool"]["occupancy"] == pytest.approx(
            st["pool"]["used_pages"] / st["pool"]["usable_pages"])
        # refcount summary: fresh claims are all sole-owner
        assert st["pool"]["cow_alias_ratio"] == 0.0
        assert st["pool"]["refcount_max"] == 1
        # counters + last audit verdict rode along
        assert st["counters"]["rounds"] == 2
        assert st["counters"]["joins"] == 3
        assert st["counters"]["mid_decode_joins"] == 1
        assert st["counters"]["audits"] >= 2
        assert st["last_audit"]["clean"] is True
        json.dumps(st)              # must be JSON-serializable as-is

    def test_beam_cow_page_map_shows_sharing(self, tiny):
        """Beam COW state: aliased full pages appear with refcount >= 2
        and two owners; the map still reconciles with the auditor."""
        eng = make_beam_engine(tiny)
        eng.admit_and_step([(0, TEXTS[0])])
        # step until a full page exists and hypotheses alias it
        for _ in range(6):
            if eng.idle():
                break
            eng.admit_and_step([])
        st = eng.pool_state()
        assert check_consistency(st) == []
        assert eng.audit(context="test") == []
        assert st["beam"]["beam_size"] == 2 and st["beam"]["cow"]
        if not eng.idle():
            shared = [e for e in st["pages"].values() if e["refs"] >= 2]
            assert st["pool"]["shared_pages"] == len(shared)
            for e in shared:
                assert len(e["owners"]) == e["refs"]
        # fork traffic was counted
        assert st["counters"]["forks"] >= 1
        assert st["counters"]["pages_copied"] >= 1

    def test_consistency_checker_catches_drift(self, tiny):
        """check_consistency is a real oracle, not a rubber stamp: a
        doctored page map (the export-side mirror of refcount drift)
        is flagged."""
        eng = make_engine(tiny)
        eng.admit_and_step([(0, TEXTS[0])])
        st = eng.pool_state()
        assert check_consistency(st) == []
        page = next(iter(st["pages"]))
        st["pages"][page]["refs"] += 1
        bad = check_consistency(st)
        assert bad and "owner reference" in bad[0]

    def test_snapshot_reports_disabled_cleanly(self, tiny):
        assert snapshot(None)["enabled"] is False

        class _ReqSched:
            batching_mode = "request"
        assert snapshot(_ReqSched())["enabled"] is False
        assert snapshot(_ReqSched())["batching_mode"] == "request"

    def test_poolz_route_roundtrip_and_quiesce_agreement(self, tiny):
        """/poolz over real HTTP against a live iteration scheduler:
        the page map cross-checks against KVPool.audit under traffic,
        and stays in agreement across a quiesce re-point (the
        acceptance's zero-discrepancies clause)."""
        sched, eng, reg = make_sched(tiny)
        srv = msm.MetricsServer(0, registry=reg,
                                routes=pool_routes(lambda: sched)).start()

        def poolz(query=""):
            return json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/poolz{query}").read())

        try:
            async def main():
                sched.start()
                f1 = sched.submit(TEXTS[:2])
                await asyncio.sleep(0.05)
                mid = poolz("?check=1")      # scraped MID-decode
                f2 = sched.submit([TEXTS[2]])
                r1, r2 = await f1, await f2
                # quiesce re-point onto a fresh engine, then re-scrape
                eng2 = make_engine(tiny)
                op = sched.request_quiesce(
                    lambda: sched.install_engine(eng2),
                    deadline_s=5.0, reason="test-swap", wait=False)
                assert await wait_for(op.event.is_set)
                assert op.ok
                post = poolz("?check=1")
                await sched.stop()
                return mid, post, eng2

            mid, post, eng2 = run(main())
            assert mid["enabled"] is True
            assert mid["consistency"] == []
            assert mid["rows"]["active"] >= 1
            assert mid["scheduler"]["quiescing"] == 0
            # post-quiesce: the route resolves THROUGH the scheduler —
            # it must now report the fresh engine's (empty) pool, and
            # that view must agree with the fresh engine's auditor
            assert post["consistency"] == []
            assert post["rows"]["active"] == 0
            assert post["pool"]["used_pages"] == 0
            assert eng2.audit(context="test") == []
            assert post["last_audit"] is None \
                or post["last_audit"]["clean"]
        finally:
            srv.close()

    def test_pool_audit_flight_dump_embeds_page_map(self, tiny,
                                                    tmp_path):
        """Acceptance: a pool-audit flight dump embeds the page map at
        incident time. Wire a real ServingApp (iteration mode) so the
        `pool` snapshot provider registration is what gets tested, then
        fire the refcount-corruption drill so the auditor trips for
        real."""
        obs.TRACER.enable()
        obs.FLIGHT.arm(str(tmp_path))
        eng = make_engine(tiny)
        app = ServingApp(Options({"metrics-port": 0, "port": 0,
                                  "batching-mode": "iteration",
                                  "beam-size": 1}),
                         translate_lines=lambda lines: list(lines),
                         engine=eng)
        try:
            async def main():
                await app.start()
                # arm for EVERY round (@*) before the row even joins:
                # the drill no-ops while no refcount is live, then
                # corrupts the first round that has one. Arming after
                # the join (the old fail@1) raced the engine thread on
                # a loaded box — the row could finish before the single
                # hit landed on live state.
                with fp.active("pool.refcount_corrupt=fail@*"):
                    f = app.scheduler.submit([TEXTS[4]])
                    with pytest.raises(Exception):
                        await f
                await app.scheduler.stop()

            run(main())
            deadline = time.time() + 5.0
            dumps = []
            while time.time() < deadline:
                dumps = sorted(p for p in os.listdir(tmp_path)
                               if p.startswith("flight-")
                               and p.endswith(".json")
                               and "pool-audit" in p)
                if dumps:
                    break
                time.sleep(0.02)
            assert dumps, "no pool-audit flight dump written"
            payload = json.loads((tmp_path / dumps[0]).read_text())
            pool = payload["pool"]
            assert pool["enabled"] is True
            assert "pages" in pool and "counters" in pool
            assert pool["last_audit"]["clean"] is False
            # the injected corruption is visible in the embedded map's
            # own cross-check — exactly what a post-mortem needs
            assert check_consistency(pool) != []
        finally:
            app.close_nowait()

    def test_poolviz_renders_and_checks(self, tiny, capsys):
        spec = importlib.util.spec_from_file_location(
            "poolviz", os.path.join(ROOT, "scripts", "poolviz.py"))
        pv = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pv)
        eng = make_engine(tiny)
        eng.admit_and_step([(0, TEXTS[0]), (1, TEXTS[1])])
        eng.audit(context="test")
        st = eng.pool_state()
        path = os.path.join(ROOT, "/tmp", "poolz.json")
        path = "/tmp/poolviz_test.json"
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(st, fh)
        assert pv.main([path, "--check"]) == 0
        out = capsys.readouterr().out
        assert "pages claimed" in out
        assert "page map" in out
        assert "last audit (test): clean" in out
        assert "agrees with itself" in out
        # a doctored dump exits 1 (the post-mortem discrepancy path)
        st["pages"][next(iter(st["pages"]))]["refs"] += 3
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(st, fh)
        assert pv.main([path, "--check"]) == 1
        os.unlink(path)

    def test_poolviz_unreachable_url_exits_2_without_traceback(
            self, capsys):
        """ISSUE 15 satellite: `poolviz --check` against a dead server
        must exit 2 with one clear error line, not a traceback (exit 1
        stays reserved for real page-map discrepancies)."""
        spec = importlib.util.spec_from_file_location(
            "poolviz", os.path.join(ROOT, "scripts", "poolviz.py"))
        pv = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(pv)
        # a port nothing listens on: bind-then-close reserves one
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        rc = pv.main([f"http://127.0.0.1:{port}/poolz", "--check"])
        captured = capsys.readouterr()
        assert rc == 2
        assert "poolviz: cannot load" in captured.err
        assert "Traceback" not in captured.err
        # a missing file takes the same loud-exit path
        assert pv.main(["/no/such/poolz-dump.json"]) == 2
        assert "cannot load" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# per-row lifecycle tracing (tentpole piece 1)
# ---------------------------------------------------------------------------

class TestRowSpans:
    def test_mid_decode_join_tree_and_round_crosslinks(self, tiny):
        """Acceptance: a mid-decode-joining request's tree shows
        join→rounds→EOS (serve.row under the serve.request root, with
        ttfj/bucket/rounds), serve.round spans cross-link the row's
        trace id, and serve.queue ends at JOIN time (the PR 14
        queue_ms fix, now pinned at the span level too)."""
        obs.TRACER.enable()
        sched, eng, reg = make_sched(tiny)

        async def main():
            sched.start()
            f1 = sched.submit([TEXTS[4]], trace_id="rowaaa1")
            await wait_for(lambda: sched.m_joins.value >= 1)
            f2 = sched.submit([TEXTS[1]], trace_id="rowbbb2")
            await f1
            await f2
            await sched.stop()

        run(main())
        spans, _ = obs.TRACER.snapshot()
        rows = {s.trace_id: s for s in spans if s.name == "serve.row"}
        assert set(rows) >= {"rowaaa1", "rowbbb2"}
        roots = {s.trace_id: s for s in spans
                 if s.name == "serve.request"}
        for tid in ("rowaaa1", "rowbbb2"):
            r = rows[tid]
            assert r.parent_id == roots[tid].span_id
            assert r.attrs["outcome"] == "eos"
            assert r.attrs["rounds"] >= 1
            assert r.attrs["ttfj_ms"] >= 0.0
            assert r.attrs["bucket"] >= 1
        # the second request joined a RUNNING decode
        assert rows["rowbbb2"].attrs["mid_decode"] is True
        assert rows["rowaaa1"].attrs["mid_decode"] is False
        # serve.round spans cross-link their rows' trace ids, and the
        # page traffic attrs are present
        rounds = [s for s in spans if s.name == "serve.round"]
        assert rounds
        linked = [s for s in rounds
                  if "rowbbb2" in s.attrs.get("traces", [])]
        assert linked, "no serve.round cross-links the joining row"
        shared = [s for s in linked
                  if "rowaaa1" in s.attrs.get("traces", [])]
        assert shared, "no round shows both rows decoding together"
        for s in rounds:
            assert {"rows", "bucket", "steps", "tokens",
                    "pages_claimed", "pages_freed",
                    "pages_copied"} <= set(s.attrs)
        # joining rounds account the joiner's pages as claimed
        join_round = next(s for s in rounds if s.attrs["joined"] >= 1)
        assert join_round.attrs["pages_claimed"] >= 1
        # serve.queue ends at JOIN: the row span STARTS when the queue
        # span ends (regression: inheriting the running decode's
        # dispatch accounting would stretch queue past the join)
        for tid in ("rowaaa1", "rowbbb2"):
            q = next(s for s in spans if s.name == "serve.queue"
                     and s.trace_id == tid)
            assert q.end_t is not None
            assert q.end_t <= rows[tid].start + 0.050
            # and the queue did NOT swallow the decode: the row decoded
            # for multiple rounds after the queue span closed
            assert rows[tid].duration() > 0.0

    def test_evicted_request_tree_and_meta_breakdown(self, tiny):
        """Acceptance (evict half): a quiesce-deadline eviction shows
        join→evict with a retriable outcome on the row span, and the
        reply metadata carries the row breakdown (rounds, ttfj_ms,
        prefix_hit, evictions)."""
        obs.TRACER.enable()
        sched, eng, reg = make_sched(tiny)
        meta = {}

        async def main():
            sched.start()
            f = sched.submit([TEXTS[4]], meta=meta, trace_id="evict01")
            await wait_for(lambda: sched.m_joins.value >= 1)
            eng2 = make_engine(tiny)
            op = sched.request_quiesce(
                lambda: sched.install_engine(eng2),
                deadline_s=0.0, reason="test-evict", wait=False)
            with pytest.raises(Exception) as ei:
                await f
            assert "retry" in str(ei.value)
            assert await wait_for(op.event.is_set)
            await sched.stop()

        run(main())
        spans, _ = obs.TRACER.snapshot()
        row = next(s for s in spans if s.name == "serve.row"
                   and s.trace_id == "evict01")
        assert row.attrs["outcome"] == "quiesce"
        assert row.attrs["retriable"] is True
        assert meta["outcome"] == "evicted"
        assert meta["evictions"] == 1
        assert meta["rounds"] >= 1
        assert meta["prefix_hit"] == 0
        assert meta["ttfj_ms"] >= 0.0
        assert sched.m_quiesce_evictions.value == 1

    def test_prefix_hit_flag_and_fork_event(self, tiny):
        """A prefix-cache replay marks prefix_hit in the metadata
        without a join; a live COW fork joins AND flags it, with the
        prefix.fork instant on the timeline."""
        obs.TRACER.enable()
        model, params, vocab = tiny
        cache = PrefixCache(max_entries=8, version="v1")
        eng = make_engine(tiny, prefix_cache=cache)
        sched, eng, reg = make_sched(tiny, engine=eng)
        meta_cold, meta_fork, meta_hit = {}, {}, {}

        async def main():
            sched.start()
            f1 = sched.submit([TEXTS[0]], meta=meta_cold,
                              trace_id="pcold01")
            await wait_for(lambda: sched.m_joins.value >= 1)
            # same source while the leader decodes: live COW fork
            f2 = sched.submit([TEXTS[0]], meta=meta_fork,
                              trace_id="pfork01")
            await f1
            await f2
            # exact repeat after completion: replay hit, no decode
            f3 = sched.submit([TEXTS[0]], meta=meta_hit,
                              trace_id="phit001")
            await f3
            await sched.stop()

        run(main())
        assert meta_cold["prefix_hit"] == 0
        assert meta_hit["prefix_hit"] == 1
        assert meta_hit["rounds"] == 0          # replay: no decode round
        _, events = obs.TRACER.snapshot()
        names = [e["name"] for e in events]
        assert "prefix.hit" in names
        if meta_fork["prefix_hit"]:             # fork raced the finish
            assert "prefix.fork" in names
            spans, _ = obs.TRACER.snapshot()
            frow = next(s for s in spans if s.name == "serve.row"
                        and s.trace_id == "pfork01")
            assert frow.attrs.get("prefix_fork") is True


# ---------------------------------------------------------------------------
# the zero-overhead contract, extended over the engine round path
# ---------------------------------------------------------------------------

class TestRoundPathOverheadGuard:
    def test_disabled_no_ring_no_lock_on_round_path(self, tiny):
        """ISSUE 14 acceptance: with tracing disabled (and no perf
        accounting), a full iteration round — join, decode steps, EOS,
        page telemetry accounting — acquires neither the tracer lock
        nor the perf meter's lock and allocates no ring. The pool/
        engine locks are the round's own concurrency discipline and
        deliberately NOT under this guard."""
        assert not obs.enabled()
        obs.PERF.reset()
        assert not obs.PERF.enabled
        saved, saved_perf = obs.TRACER._lock, obs.PERF._lock
        obs.TRACER._lock = _RaisingLock()
        obs.PERF._lock = _RaisingLock()
        try:
            sched, eng, reg = make_sched(tiny)
            meta = {}

            async def main():
                sched.start()
                f1 = sched.submit(TEXTS[:2], meta=meta)
                await asyncio.sleep(0.05)
                f2 = sched.submit([TEXTS[2]])   # mid-decode join
                r1, r2 = await f1, await f2
                await sched.stop()
                return r1, r2

            r1, r2 = run(main())
            assert len(r1) == 2 and len(r2) == 1
            # the tracing-independent reply metadata still filled in
            assert meta["outcome"] == "ok" and meta["rounds"] >= 1
        finally:
            obs.TRACER._lock = saved
            obs.PERF._lock = saved_perf
        assert obs.TRACER._ring is None
        assert obs.TRACER._events is None


# ---------------------------------------------------------------------------
# reply-protocol row breakdown through the real server frame path
# ---------------------------------------------------------------------------

class TestReplyRowBreakdown:
    def test_trace_header_reply_carries_row_breakdown(self, tiny):
        eng = make_engine(tiny)
        app = ServingApp(Options({"metrics-port": 0, "port": 0,
                                  "batching-mode": "iteration",
                                  "beam-size": 1}),
                         translate_lines=lambda lines: list(lines),
                         engine=eng)

        async def main():
            await app.start()
            try:
                return await app.handle_text(
                    "#trace:rowmeta1\n" + TEXTS[0])
            finally:
                await app.shutdown(drain_timeout=5)

        reply = run(main())
        meta_line, _, body = reply.partition("\n")
        assert meta_line.startswith("#trace:rowmeta1 ")
        assert "outcome=ok" in meta_line
        assert "rounds=" in meta_line
        assert "ttfj_ms=" in meta_line
        assert "prefix_hit=0" in meta_line
        assert "evictions=0" in meta_line
        assert body  # the translation came back
        # loadgen's parser understands the extended line
        spec = importlib.util.spec_from_file_location(
            "loadgen", os.path.join(ROOT, "scripts", "loadgen.py"))
        lg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lg)
        meta, _ = lg.split_reply_meta(reply)
        assert meta["trace_id"] == "rowmeta1"
        assert "ttfj_s" in meta and "queue_s" in meta
        assert int(meta["rounds"]) >= 1


# ---------------------------------------------------------------------------
# metric census + promlint over a REAL /metrics scrape
# ---------------------------------------------------------------------------

class TestMetricCensus:
    # every series this PR added (MT-METRIC-UNTESTED's corpus)
    NEW_SERIES = (
        "marian_serving_kv_pool_occupancy_ratio",
        "marian_serving_kv_pool_pages_shared",
        "marian_serving_kv_pool_refcount_max",
        "marian_serving_kv_pool_cow_alias_ratio",
        "marian_serving_kv_pool_pages_claimed_total",
        "marian_serving_kv_pool_pages_freed_total",
        "marian_serving_kv_pool_pages_aliased_total",
        "marian_serving_kv_pool_pages_copied_total",
        "marian_serving_kv_pool_bytes_copied_total",
        "marian_serving_kv_pool_bytes_aliased_total",
        "marian_serving_cow_forks_total",
        "marian_serving_engine_rounds_total",
        "marian_prefix_held_pages",
        "marian_prefix_reclaimable_pages",
    )

    def test_census_and_promlint_over_real_scrape(self, tiny):
        """Every new pool/row/round series is declared, emitted and
        scrapeable over real HTTP, and the whole exposition passes
        promlint with the new series present. The beam engine +
        prefix cache drive the COW/alias/copied series with real
        nonzero traffic."""
        reg = msm.Registry()
        cache = PrefixCache(max_entries=8, version="v1", registry=reg)
        eng = make_beam_engine(tiny, registry=reg, prefix=cache)
        eng.decode_texts([TEXTS[0], TEXTS[1]])
        eng.decode_texts([TEXTS[0]])            # replay hit
        srv = msm.MetricsServer(0, registry=reg).start()
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics").read().decode()
        finally:
            srv.close()
        assert lint_metrics_text(text) == []
        for name in self.NEW_SERIES:
            assert name in text, name
        # the COW plane saw real traffic, not just declared series
        assert "marian_serving_cow_forks_total 0\n" not in text
        assert "marian_serving_kv_pool_pages_copied_total 0\n" \
            not in text
        assert "marian_serving_kv_pool_pages_aliased_total 0\n" \
            not in text

    def test_byte_counters_price_pages_in_page_bytes(self, tiny):
        reg = msm.Registry()
        eng = make_beam_engine(tiny, registry=reg)
        eng.decode_texts([TEXTS[0]])
        copied = reg.get(
            "marian_serving_kv_pool_pages_copied_total").value
        bytes_copied = reg.get(
            "marian_serving_kv_pool_bytes_copied_total").value
        assert copied >= 1
        assert bytes_copied == copied * eng.page_bytes
        assert eng.page_bytes == PAGE_BYTES

    def test_occupancy_and_alias_gauges_track_live_state(self, tiny):
        reg = msm.Registry()
        eng = make_beam_engine(tiny, registry=reg)
        assert reg.get(
            "marian_serving_kv_pool_occupancy_ratio").value == 0.0
        eng.admit_and_step([(0, TEXTS[0])])
        occ = reg.get("marian_serving_kv_pool_occupancy_ratio").value
        assert occ == pytest.approx(
            eng.pool.used_pages() / eng.pool.usable_pages)
        for _ in range(6):
            if eng.idle():
                break
            eng.admit_and_step([])
        if not eng.idle():
            # full pages are aliased across the 2 hypotheses by now
            assert reg.get(
                "marian_serving_kv_pool_cow_alias_ratio").value \
                == pytest.approx(eng.cow_alias_ratio())
        while not eng.idle():
            eng.admit_and_step([])
        assert reg.get(
            "marian_serving_kv_pool_pages_shared").value == 0


# ---------------------------------------------------------------------------
# static-analysis pins (mtlint span-family scope over the engines)
# ---------------------------------------------------------------------------

class TestStaticAnalysisPins:
    def test_span_family_covers_translator_engines(self):
        """ISSUE 14 satellite: the span-hygiene family's scope covers
        marian_tpu/translator/ (the paged engines) and the serving
        scheduler — a future dirs= narrowing must not silently drop
        the row/round span code out of the MT-SPAN gates."""
        from marian_tpu.analysis.core import Config
        from pathlib import Path
        cfg = Config.load(Path(ROOT))
        for rel in ("marian_tpu/translator/iteration.py",
                    "marian_tpu/translator/beam_iteration.py",
                    "marian_tpu/serving/scheduler.py",
                    "marian_tpu/obs/poolz.py"):
            assert cfg.family_applies("span", rel), rel
            assert not cfg.excluded(rel), rel
