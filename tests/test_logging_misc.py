"""Coverage for the small common/ pieces that had none: logging
(Marian-format lines, --log/--valid-log files, --quiet), Timer, and the
initializer library (layers/initializers.py)."""

import logging as pylogging
import re

import jax
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import logging as mlog


@pytest.fixture(autouse=True)
def _restore_loggers():
    yield
    # leave the module in its default state for later tests
    mlog.create_loggers(None)


class TestLogging:
    def test_marian_line_format(self, capsys):
        mlog.create_loggers(None)
        mlog.info("Hello {} {}", "a", 1)
        err = capsys.readouterr().err
        # [2026-07-30 12:34:56] Hello a 1
        assert re.search(r"^\[\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}\] "
                         r"Hello a 1$", err.strip())

    def test_log_files_and_valid_prefix(self, tmp_path):
        lf = tmp_path / "train.log"
        vf = tmp_path / "valid.log"
        mlog.create_loggers(Options({"log": str(lf),
                                     "valid-log": str(vf)}))
        mlog.info("general line")
        mlog.log_valid("info", "bleu {}", 33.3)
        for h in pylogging.getLogger("marian.general").handlers:
            h.flush()
        for h in pylogging.getLogger("marian.valid").handlers:
            h.flush()
        assert "general line" in lf.read_text()
        vtext = vf.read_text()
        assert "[valid] bleu 33.3" in vtext

    def test_quiet_suppresses_stderr(self, capsys):
        mlog.create_loggers(Options({"quiet": True}))
        mlog.info("should not appear")
        assert capsys.readouterr().err == ""

    def test_bad_placeholder_degrades(self, capsys):
        mlog.create_loggers(None)
        mlog.info("only {} one", "x", "extra")   # too many args
        assert "only x one" in capsys.readouterr().err


class TestTensorboardScalars:
    def test_writes_events_at_display_and_validation(self, tmp_path):
        """--tensorboard DIR: train scalars at each display boundary and
        valid/<metric> at registration (beyond the reference; uses
        torch's SummaryWriter, already in the image)."""
        pytest.importorskip("torch.utils.tensorboard")
        import os
        from marian_tpu.common import Options
        from marian_tpu.training.scheduler import Scheduler
        from marian_tpu.training.training_state import TrainingState
        tb = tmp_path / "tb"
        opts = Options({"disp-freq": "2u", "tensorboard": str(tb),
                        "cost-type": "ce-mean-words",
                        "valid-metrics": ["cross-entropy"]})
        sched = Scheduler(opts, TrainingState())
        for i in range(4):
            sched.update(3.0 * 10, 10.0, 2)
        sched.register_validation("cross-entropy", 2.5)
        sched.close()           # the train driver's shutdown flush
        events = [f for f in os.listdir(tb) if "tfevents" in f]
        assert events, "no TensorBoard event file written"
        assert os.path.getsize(tb / events[0]) > 0

    def test_bare_flag_defaults_next_to_model(self, tmp_path):
        pytest.importorskip("torch.utils.tensorboard")
        import os
        from marian_tpu.common import Options
        from marian_tpu.training.scheduler import Scheduler
        from marian_tpu.training.training_state import TrainingState
        # bare --tensorboard parses to "" (nargs='?') — still means ON,
        # defaulting to <model>.tb like --profile's convention
        opts = Options({"disp-freq": "1u", "tensorboard": "",
                        "model": str(tmp_path / "m.npz")})
        sched = Scheduler(opts, TrainingState())
        sched.update(3.0, 1.0, 1)
        sched.close()
        assert os.path.isdir(tmp_path / "m.npz.tb")

    def test_disabled_without_flag(self):
        from marian_tpu.common import Options
        from marian_tpu.training.scheduler import Scheduler
        from marian_tpu.training.training_state import TrainingState
        sched = Scheduler(Options({"disp-freq": "2u"}), TrainingState())
        assert sched._tb is None
        sched.close()           # no-op without a writer


class TestTimer:
    def test_elapsed_monotonic(self):
        from marian_tpu.common.timer import Timer
        import time
        t = Timer()
        time.sleep(0.01)
        e1 = t.elapsed()
        assert e1 >= 0.01
        t.start()
        assert t.elapsed() < e1


class TestInitializers:
    def test_glorot_uniform_bounds_and_shape(self):
        from marian_tpu.layers import initializers as I
        w = I.glorot_uniform(jax.random.key(0), (64, 32))
        assert w.shape == (64, 32)
        limit = float(np.sqrt(6.0 / (64 + 32)))
        a = np.asarray(w)
        assert a.max() <= limit + 1e-6 and a.min() >= -limit - 1e-6
        # draws actually fill the range (not degenerate)
        assert a.std() > limit / 4

    def test_glorot_normal_std(self):
        from marian_tpu.layers import initializers as I
        w = np.asarray(I.glorot_normal(jax.random.key(1), (256, 256)))
        want = np.sqrt(2.0 / 512)
        assert w.std() == pytest.approx(want, rel=0.15)

    def test_zeros_ones(self):
        from marian_tpu.layers import initializers as I
        assert float(np.asarray(I.zeros((2, 3))).sum()) == 0.0
        assert float(np.asarray(I.ones((2, 3))).sum()) == 6.0