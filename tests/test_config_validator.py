"""Config validation guards (common/config_validator.py — reference:
src/common/config_validator.cpp :: ConfigValidator::validateOptions).
Each rule gets a positive and a negative pin so refusals stay loud and
valid configs stay accepted."""

import pytest

from marian_tpu.common import Options
from marian_tpu.common.config_validator import validate_options


def _train_opts(**over):
    base = {"type": "transformer", "dim-emb": 64, "transformer-heads": 8,
            "train-sets": ["a.src", "a.trg"],
            "vocabs": ["v.src", "v.trg"],
            "label-smoothing": 0.1, "cost-type": "ce-mean-words"}
    base.update(over)
    return Options(base)


class TestTraining:
    def test_valid_config_passes(self):
        validate_options(_train_opts(), "training")

    def test_heads_divisibility(self):
        with pytest.raises(ValueError, match="divisible"):
            validate_options(_train_opts(**{"dim-emb": 65}), "training")

    def test_unknown_type(self):
        with pytest.raises(ValueError, match="Unknown model"):
            validate_options(_train_opts(type="gpt5"), "training")

    def test_missing_train_sets(self):
        with pytest.raises(ValueError, match="train-sets"):
            validate_options(_train_opts(**{"train-sets": []}), "training")

    def test_vocab_count_mismatch(self):
        with pytest.raises(ValueError, match="must match"):
            validate_options(_train_opts(vocabs=["v.src"]), "training")

    def test_label_smoothing_range(self):
        with pytest.raises(ValueError, match="label-smoothing"):
            validate_options(_train_opts(**{"label-smoothing": 1.0}),
                             "training")
        validate_options(_train_opts(**{"label-smoothing": 0.0}),
                         "training")

    def test_lm_refuses_guided_alignment(self):
        with pytest.raises(ValueError, match="cross-attention"):
            validate_options(
                _train_opts(type="transformer-lm",
                            **{"train-sets": ["a.trg"],
                               "vocabs": ["v.trg"],
                               "guided-alignment": "a.align"}), "training")
        # the CLI default STRING "none" must pass
        validate_options(
            _train_opts(type="transformer-lm",
                        **{"train-sets": ["a.trg"], "vocabs": ["v.trg"],
                           "guided-alignment": "none"}), "training")

    def test_right_left_refuses_alignment_and_word_weighting(self):
        with pytest.raises(ValueError, match="right-left"):
            validate_options(
                _train_opts(**{"right-left": True,
                               "guided-alignment": "a.align"}), "training")
        with pytest.raises(ValueError, match="right-left"):
            validate_options(
                _train_opts(**{"right-left": True,
                               "data-weighting": "w.txt",
                               "data-weighting-type": "word"}), "training")
        validate_options(_train_opts(**{"right-left": True}), "training")

    def test_cost_type(self):
        with pytest.raises(ValueError, match="cost-type"):
            validate_options(_train_opts(**{"cost-type": "hinge"}),
                             "training")


class TestTranslation:
    def test_requires_model(self):
        with pytest.raises(ValueError, match="models"):
            validate_options(Options({"type": "transformer",
                                      "dim-emb": 64,
                                      "transformer-heads": 8}),
                             "translation")

    def test_ensemble_weight_count(self):
        with pytest.raises(ValueError, match="weights"):
            validate_options(Options({"type": "transformer", "dim-emb": 64,
                                      "transformer-heads": 8,
                                      "models": ["a.npz", "b.npz"],
                                      "weights": [0.5]}), "translation")

    def test_beam_size_positive(self):
        with pytest.raises(ValueError, match="beam-size"):
            validate_options(Options({"type": "transformer", "dim-emb": 64,
                                      "transformer-heads": 8,
                                      "models": ["a.npz"],
                                      "beam-size": 0}), "translation")