"""Deep-RNN s2s model tests (config #3 family): cell zoo math, SSRU parallel
scan vs sequential oracle, teacher-forcing vs incremental-decode consistency,
depth/skip/layer-norm variants, beam search integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import s2s as S
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.ops import rnn as R


def s2s_options(**over):
    base = {
        "type": "s2s",
        "dim-emb": 12, "dim-rnn": 16,
        "enc-type": "bidirectional",
        "enc-cell": "gru", "enc-cell-depth": 1, "enc-depth": 1,
        "dec-cell": "gru", "dec-cell-base-depth": 2,
        "dec-cell-high-depth": 1, "dec-depth": 1,
        "label-smoothing": 0.0,
        "precision": ["float32", "float32"],
        "max-length": 64,
    }
    base.update(over)
    return Options(base)


def make_model(vocab=19, **over):
    opts = s2s_options(**over)
    model = create_model(opts, vocab, vocab)
    params = model.init(jax.random.key(0))
    return model, params


def fake_batch(rng, b=3, ts=7, tt=9, vocab=19):
    src = rng.randint(2, vocab, size=(b, ts)).astype(np.int32)
    trg = rng.randint(2, vocab, size=(b, tt)).astype(np.int32)
    src_mask = np.ones((b, ts), np.float32)
    trg_mask = np.ones((b, tt), np.float32)
    for i in range(b):
        ls = rng.randint(3, ts)
        src[i, ls:] = 0
        src_mask[i, ls + 1:] = 0
    return {"src_ids": jnp.asarray(src), "src_mask": jnp.asarray(src_mask),
            "trg_ids": jnp.asarray(trg), "trg_mask": jnp.asarray(trg_mask)}


# ---------------------------------------------------------------------------
# Cells
# ---------------------------------------------------------------------------

class TestCells:
    @pytest.mark.parametrize("kind", ["gru", "lstm", "ssru"])
    def test_step_shapes_and_finite(self, kind, rng):
        cell = R.make_cell(kind, 6, 8)
        params = {}
        cell.init(jax.random.key(0), params, "c")
        x = jnp.asarray(rng.randn(4, 6), jnp.float32)
        xp = cell.x_proj(params, "c", x)
        out, st = cell.step(params, "c", xp, cell.init_state(4, jnp.float32))
        assert out.shape == (4, 8)
        for k in cell.state_keys:
            assert st[k].shape == (4, 8)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_ssru_parallel_scan_matches_sequential(self, rng):
        """associative_scan linear recurrence == step-by-step loop."""
        cell = R.make_cell("ssru", 6, 8)
        params = {}
        cell.init(jax.random.key(1), params, "c")
        xs = jnp.asarray(rng.randn(2, 10, 6), jnp.float32)
        mask = jnp.ones((2, 10), jnp.float32)
        out_par, fin_par = R.run_layer([("c", cell)], params, xs, mask)

        # sequential oracle
        st = cell.init_state(2, jnp.float32)
        outs = []
        for t in range(10):
            xp = cell.x_proj(params, "c", xs[:, t])
            o, st = cell.step(params, "c", xp, st)
            outs.append(o)
        out_seq = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(out_par), np.asarray(out_seq),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(fin_par["c"]),
                                   np.asarray(st["c"]), rtol=1e-5, atol=1e-5)

    def test_masked_layer_carries_state_through_pads(self, rng):
        cell = R.make_cell("gru", 4, 5)
        params = {}
        cell.init(jax.random.key(2), params, "c")
        xs = jnp.asarray(rng.randn(1, 6, 4), jnp.float32)
        mask_full = jnp.ones((1, 6), jnp.float32)
        mask_cut = mask_full.at[0, 4:].set(0.0)
        out_cut, fin_cut = R.run_layer([("c", cell)], params, xs, mask_cut)
        out_full, _ = R.run_layer([("c", cell)], params, xs, mask_full)
        # up to the cut, outputs identical; after it, zeros
        np.testing.assert_allclose(np.asarray(out_cut[:, :4]),
                                   np.asarray(out_full[:, :4]), rtol=1e-6)
        assert np.all(np.asarray(out_cut[:, 4:]) == 0.0)
        # final state == state at the cut
        np.testing.assert_allclose(np.asarray(fin_cut["h"][0]),
                                   np.asarray(out_cut[0, 3]), rtol=1e-6)


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

class TestS2SModel:
    def test_param_names(self):
        model, params = make_model(enc_depth=2, dec_depth=2,
                                   **{"enc-cell-depth": 2,
                                      "dec-cell-base-depth": 3})
        names = set(params)
        for want in ("Wemb", "Wemb_dec", "encoder_bi_W", "encoder_bi_r_U",
                     "encoder_bi_cell2_U", "ff_state_W", "decoder_cell1_W",
                     "decoder_cell2_W", "decoder_cell3_U", "decoder_att_W",
                     "decoder_att_v", "ff_logit_l1_W0", "ff_logit_l2_W"):
            assert want in names, want

    @pytest.mark.parametrize("kw", [
        {},
        {"dec-cell": "lstm", "enc-cell": "lstm"},
        {"dec-cell": "ssru", "enc-cell": "ssru"},
        {"enc-depth": 2, "dec-depth": 2, "skip": True},
        {"enc-type": "alternating", "enc-depth": 3},
        {"layer-normalization": True},
        {"enc-cell-depth": 2, "dec-cell-base-depth": 3,
         "dec-cell-high-depth": 2, "dec-depth": 2},
        {"tied-embeddings-all": True},
        {"tied-embeddings": True},
    ])
    def test_loss_finite_and_grads_flow(self, kw, rng):
        model, params = make_model(**kw)
        batch = fake_batch(rng)

        def loss_fn(p):
            total, aux = model.loss(p, batch, key=jax.random.key(3),
                                    train=True)
            return total

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        gnorm = sum(float(jnp.sum(g * g)) for g in grads.values())
        assert gnorm > 0.0
        for name, g in grads.items():
            assert np.all(np.isfinite(np.asarray(g))), name

    @pytest.mark.parametrize("kw", [
        {},
        {"dec-cell": "lstm"},
        {"dec-cell": "ssru"},
        {"enc-depth": 2, "dec-depth": 2, "skip": True},
        {"dec-cell-base-depth": 3, "dec-cell-high-depth": 2, "dec-depth": 2},
        {"layer-normalization": True},
    ])
    def test_teacher_forcing_matches_incremental(self, kw, rng):
        """decode_train logits[t] == step-by-step decode logits at t when fed
        the gold prefix — the strongest structural correctness check."""
        model, params = make_model(**kw)
        batch = fake_batch(rng, b=2, ts=6, tt=5)
        cp = params  # f32 already
        enc = model.encode_for_decode(cp, batch["src_ids"], batch["src_mask"])
        tf_logits = S.decode_train(model.cfg, cp, enc, batch["src_mask"],
                                   batch["trg_ids"], batch["trg_mask"],
                                   train=False)
        state = model.start_state(cp, enc, batch["src_mask"], max_len=5)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(5):
            logits, state = model.step(cp, state, prev, batch["src_mask"])
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(tf_logits[:, t]),
                rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_alignment_shape(self, rng):
        model, params = make_model()
        batch = fake_batch(rng, b=2, ts=6, tt=5)
        enc = model.encode_for_decode(params, batch["src_ids"],
                                      batch["src_mask"])
        logits, align = S.decode_train(
            model.cfg, params, enc, batch["src_mask"], batch["trg_ids"],
            batch["trg_mask"], train=False, return_alignment=True)
        assert align.shape == (2, 5, 6)
        s = np.asarray(align).sum(axis=-1)
        np.testing.assert_allclose(s, np.ones_like(s), rtol=1e-4)

    def test_beam_search_runs_on_s2s(self, rng):
        from marian_tpu.translator.beam_search import BeamConfig, beam_search_jit
        model, params = make_model()
        batch = fake_batch(rng, b=2, ts=6)
        cfg = BeamConfig(beam_size=3, max_length=7, normalize=0.6)
        tokens, scores, lengths, norm_scores, _, _ws = beam_search_jit(
            model, [params], [1.0], cfg, batch["src_ids"], batch["src_mask"])
        assert tokens.shape == (2, 3, 7)
        assert np.all(np.isfinite(np.asarray(norm_scores)))
        # beams are sorted by score
        s = np.asarray(scores)
        assert np.all(np.diff(s, axis=1) <= 1e-6)

    def test_greedy_decode_runs(self, rng):
        from marian_tpu.translator.greedy import greedy_decode
        model, params = make_model()
        batch = fake_batch(rng, b=2, ts=6)
        out = greedy_decode(model, params, batch["src_ids"],
                            batch["src_mask"], max_len=8)
        assert out.shape[0] == 2 and out.shape[1] <= 8
