"""Unit tests for the runtime lockdep witness (common/lockdep.py) — the
dynamic half of mtlint's lock analysis (ISSUE 6).

conftest.py arms MARIAN_LOCKDEP=1 for the whole test process, so
make_lock/make_rlock here return witnessed wrappers. The witness state is
process-global (that is the point — it accumulates across a whole suite),
so every test runs inside a sandbox that snapshots and restores it:
the serving/lifecycle suites' module-teardown cross-check must still see
exactly what their own threads did, not this file's synthetic locks.
"""

from __future__ import annotations

import os
import threading

import pytest

from marian_tpu.common import lockdep

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def sandbox():
    with lockdep._WITNESS_LOCK:
        saved_edges = dict(lockdep._EDGES)
        saved_nodes = set(lockdep._NODES)
    lockdep.reset()
    yield
    with lockdep._WITNESS_LOCK:
        lockdep._EDGES.clear()
        lockdep._EDGES.update(saved_edges)
        lockdep._NODES.clear()
        lockdep._NODES.update(saved_nodes)


class TestFactories:
    def test_disabled_returns_plain_locks(self, monkeypatch):
        monkeypatch.delenv(lockdep.ENV_VAR, raising=False)
        assert not lockdep.enabled()
        lk = lockdep.make_lock("X.y")
        rk = lockdep.make_rlock("X.z")
        assert not isinstance(lk, lockdep._WitnessedLock)
        assert not isinstance(rk, lockdep._WitnessedLock)
        with lk, rk:                      # still real locks
            pass

    def test_enabled_wraps_and_records_node(self, sandbox):
        assert lockdep.enabled()          # conftest armed it
        lk = lockdep.make_lock("T.a")
        assert isinstance(lk, lockdep._WitnessedLock)
        with lk:
            pass
        assert "T.a" in lockdep.observed_nodes()

    def test_cross_thread_release_refused(self, sandbox):
        # legal for a plain threading.Lock, poison to the per-thread
        # held-stack model: the acquirer's stack would keep the lock
        # forever and record phantom edges — fail loudly instead
        lk = lockdep.make_lock("T.sig")
        t = threading.Thread(target=lk.acquire)
        t.start()
        t.join()
        with pytest.raises(RuntimeError, match="cross-thread release"):
            lk.release()
        assert not lk.locked()        # the inner lock WAS released

    def test_locked_and_explicit_acquire_release(self, sandbox):
        lk = lockdep.make_lock("T.a")
        assert lk.acquire(timeout=1)
        assert lk.locked()
        lk.release()
        assert not lk.locked()


class TestEdgeRecording:
    def test_nested_acquisition_records_edge(self, sandbox):
        a, b = lockdep.make_lock("T.a"), lockdep.make_lock("T.b")
        with a:
            with b:
                pass
        assert ("T.a", "T.b") in lockdep.observed_edges()
        assert ("T.b", "T.a") not in lockdep.observed_edges()

    def test_sequential_acquisition_records_nothing(self, sandbox):
        a, b = lockdep.make_lock("T.a"), lockdep.make_lock("T.b")
        with a:
            pass
        with b:
            pass
        assert lockdep.observed_edges() == {}

    def test_reentrant_rlock_no_self_edge(self, sandbox):
        r = lockdep.make_rlock("T.r")
        with r:
            with r:
                pass
        assert ("T.r", "T.r") not in lockdep.observed_edges()

    def test_reentrant_reacquire_under_other_lock_no_reverse_edge(
            self, sandbox):
        # with a(RLock): with b: with a: — the re-acquire cannot block
        # (the thread already owns a), so it must not invent b->a, which
        # with the real a->b would report a false observed CYCLE
        a = lockdep.make_rlock("T.a")
        b = lockdep.make_lock("T.b")
        with a:
            with b:
                with a:
                    pass
        assert ("T.a", "T.b") in lockdep.observed_edges()
        assert ("T.b", "T.a") not in lockdep.observed_edges()
        assert lockdep.observed_cycles() == []

    def test_blocking_reacquire_of_plain_lock_raises(self, sandbox):
        # a blocking re-acquire of a plain Lock the thread already holds
        # can never succeed — the witness fails loudly instead of
        # hanging the process
        a = lockdep.make_lock("T.a")
        with a:
            with pytest.raises(RuntimeError, match="self-deadlock"):
                a.acquire()
            assert a.acquire(blocking=False) is False  # legal, no hang
            # a timed acquire is recoverable (False after the timeout) —
            # the witness must not turn it into a crash
            assert a.acquire(timeout=0.01) is False
        with a:                       # still usable after the refusal
            pass

    def test_sibling_instance_same_name_nests_without_raising(
            self, sandbox):
        # two INSTANCES of the same class's lock share a static identity
        # but may legally nest — the self-deadlock guard keys on the
        # lock instance, not the name (and the nesting stays edge-free,
        # mirroring the one-node-per-identity static model)
        a1 = lockdep.make_lock("T.s")
        a2 = lockdep.make_lock("T.s")
        with a1:
            with a2:                  # plain Lock, different instance
                pass
        assert ("T.s", "T.s") not in lockdep.observed_edges()
        assert lockdep.observed_cycles() == []

    def test_failed_acquire_records_nothing(self, sandbox):
        a = lockdep.make_lock("T.a")
        b = lockdep.make_lock("T.b")
        b._inner.acquire()                # someone else holds b
        with a:
            assert b.acquire(blocking=False) is False
        b._inner.release()
        assert ("T.a", "T.b") not in lockdep.observed_edges()

    def test_edges_attributed_to_thread(self, sandbox):
        a, b = lockdep.make_lock("T.a"), lockdep.make_lock("T.b")

        def work():
            with a:
                with b:
                    pass

        t = threading.Thread(target=work, name="edge-thread")
        t.start()
        t.join()
        assert lockdep.observed_edges()[("T.a", "T.b")] == "edge-thread"


class TestVerdict:
    def test_observed_cycle_detected(self, sandbox):
        a, b = lockdep.make_lock("T.a"), lockdep.make_lock("T.b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert lockdep.observed_cycles() == [["T.a", "T.b"]]
        violations = lockdep.check({"T.a", "T.b"},
                                   {("T.a", "T.b"), ("T.b", "T.a")})
        assert any("CYCLE" in v for v in violations)

    def test_unknown_node_and_edge_flagged(self, sandbox):
        a, b = lockdep.make_lock("T.a"), lockdep.make_lock("T.b")
        with a:
            with b:
                pass
        violations = lockdep.check({"T.a"}, set())
        assert any("'T.b'" in v and "unknown to the static graph" in v
                   for v in violations)
        assert any("T.a -> T.b" in v for v in violations)

    def test_clean_when_static_covers_observed(self, sandbox):
        a, b = lockdep.make_lock("T.a"), lockdep.make_lock("T.b")
        with a:
            with b:
                pass
        assert lockdep.check({"T.a", "T.b"}, {("T.a", "T.b")}) == []


class TestAgainstRealStaticGraph:
    """End-to-end contract: locks named with their static identities
    cross-check against the graph callgraph.py builds from the real
    tree — the exact mechanism the tier-1 serving/lifecycle witness
    fixtures assert on."""

    def test_modeled_edge_passes(self, sandbox):
        # SwapController._lock -> ModelRegistry._lock is a real edge of
        # the serving lattice (docs/lock_order.dot)
        outer = lockdep.make_rlock("SwapController._lock")
        inner = lockdep.make_lock("ModelRegistry._lock")
        with outer:
            with inner:
                pass
        assert lockdep.check_against_static(ROOT) == []

    def test_unmodeled_edge_fails(self, sandbox):
        # the REVERSE order is absent from the static graph: the witness
        # must call it out (and would, were real code ever to do this)
        outer = lockdep.make_lock("ModelRegistry._lock")
        inner = lockdep.make_rlock("SwapController._lock")
        with outer:
            with inner:
                pass
        violations = lockdep.check_against_static(ROOT)
        assert any("ModelRegistry._lock -> SwapController._lock" in v
                   for v in violations)

    def test_unknown_lock_name_fails(self, sandbox):
        with lockdep.make_lock("NoSuchClass._lock"):
            pass
        violations = lockdep.check_against_static(ROOT)
        assert any("NoSuchClass._lock" in v for v in violations)
