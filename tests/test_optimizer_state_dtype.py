"""--optimizer-state-dtype bfloat16: Adam first-moment storage compression
(optimizers/optimizers.py — beyond the reference; optax mu_dtype
precedent: math in f32, m stored bf16, v kept f32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.optimizers.optimizers import (OptimizerConfig, apply_update,
                                              init_state)
from marian_tpu.training.graph_group import GraphGroup


def _gg(state_dtype):
    opts = Options({"type": "transformer", "dim-emb": 16,
                    "transformer-heads": 2, "transformer-dim-ffn": 32,
                    "enc-depth": 1, "dec-depth": 1,
                    "tied-embeddings-all": True, "label-smoothing": 0.0,
                    "precision": ["float32", "float32"], "max-length": 16,
                    "learn-rate": 0.02, "optimizer": "adam",
                    "clip-norm": 0.0, "exponential-smoothing": 0.0,
                    "optimizer-state-dtype": state_dtype})
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(11))
    return gg


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


class TestStateDtype:
    def test_m_is_bf16_v_stays_f32(self):
        cfg = OptimizerConfig(name="adam", state_dtype="bfloat16")
        p = {"w": jnp.ones((4, 4), jnp.float32)}
        st = init_state(cfg, p)
        assert st["m"]["w"].dtype == jnp.bfloat16
        assert st["v"]["w"].dtype == jnp.float32
        st2, out = apply_update(cfg, st, p,
                                {"w": jnp.full((4, 4), 0.1)}, 0.01)
        assert st2["m"]["w"].dtype == jnp.bfloat16
        assert st2["v"]["w"].dtype == jnp.float32
        assert out["w"].dtype == jnp.float32

    def test_rejects_unknown_dtype(self):
        with pytest.raises(ValueError, match="optimizer-state-dtype"):
            OptimizerConfig.from_options(
                Options({"optimizer-state-dtype": "int4"}))

    def test_trajectory_close_to_f32(self):
        """bf16 m rounds the first moment, not the update math — after a
        few steps the loss trajectory stays within bf16-rounding distance
        of the f32 run."""
        key = prng.stream(prng.root_key(11), prng.STREAM_DROPOUT)
        losses = {}
        for dt in ("float32", "bfloat16"):
            gg = _gg(dt)
            ls = []
            for i in range(5):
                out = gg.update(_batch(i), i + 1, key)
                ls.append(float(out.loss_sum) / max(float(out.labels), 1.0))
            losses[dt] = ls
        np.testing.assert_allclose(losses["bfloat16"], losses["float32"],
                                   rtol=2e-2)
        assert losses["bfloat16"] != losses["float32"]  # it IS doing bf16

    def test_checkpoint_roundtrip_restores_bf16(self, tmp_path):
        """m is stored f32 in the npz (numpy has no bf16) and restored to
        the configured dtype on load."""
        key = prng.stream(prng.root_key(11), prng.STREAM_DROPOUT)
        gg = _gg("bfloat16")
        gg.update(_batch(0), 1, key)
        flat = gg.optimizer_arrays()
        m_keys = [k for k in flat if k.startswith("m:")]
        assert m_keys and all(flat[k].dtype == np.float32 for k in m_keys)

        gg2 = _gg("bfloat16")
        gg2.load_optimizer_arrays(flat)
        for k in m_keys:
            name = k.split(":", 1)[1]
            assert gg2.opt_state["m"][name].dtype == jnp.bfloat16
        # and an f32 run loading the same file keeps f32
        gg3 = _gg("float32")
        gg3.load_optimizer_arrays(flat)
        name = m_keys[0].split(":", 1)[1]
        assert gg3.opt_state["m"][name].dtype == jnp.float32