"""Expert-parallel MoE FFN (--transformer-moe-experts) and pipeline
('pipe') depth-sharded parameter storage — the TPU extensions that complete
the dp/tp/sp/pp/ep sharding matrix (the reference scales only by data
parallelism; SURVEY §2.7)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.models import transformer as T
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.training.graph_group import GraphGroup


def _opts(mesh=None, n=1, **kw):
    base = {"type": "transformer", "dim-emb": 32, "transformer-heads": 4,
            "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
            "tied-embeddings-all": True,
            "precision": ["float32", "float32"],
            "label-smoothing": 0.1, "cost-type": "ce-mean-words",
            "learn-rate": 3e-4, "optimizer": "adam", "clip-norm": 1.0,
            "devices": [str(i) for i in range(n)], "seed": 7}
    base.update(kw)
    if mesh:
        base["mesh"] = mesh
    return Options(base)


def _batch(rng, v=64, b=8, ts=12, tt=12):
    return {
        "src_ids": jnp.asarray(rng.randint(2, v, (b, ts)), jnp.int32),
        "src_mask": jnp.ones((b, ts), jnp.float32),
        "trg_ids": jnp.asarray(rng.randint(2, v, (b, tt)), jnp.int32),
        "trg_mask": jnp.ones((b, tt), jnp.float32),
    }


class TestMoEMath:
    def test_forward_and_aux(self, rng):
        o = _opts(**{"transformer-moe-experts": 4})
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        assert params["encoder_l1_moe_W1"].shape == (4, 32, 64)
        total, aux = model.loss(params, _batch(rng), None, train=False)
        assert np.isfinite(float(total))
        # balanced-ish router at init: aux near 1 (perfect balance = 1.0)
        assert 0.5 < float(aux["moe_aux"]) / 4 < 2.0   # 4 MoE layers

    def test_router_gradients_flow(self, rng):
        o = _opts(**{"transformer-moe-experts": 4})
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        g = jax.grad(lambda p: model.loss(p, _batch(rng), None,
                                          train=False)[0])(params)
        assert float(jnp.sum(jnp.abs(g["encoder_l1_moe_gate"]))) > 0
        assert float(jnp.sum(jnp.abs(g["decoder_l2_moe_W2"]))) > 0

    def test_top1_switch_routing(self, rng):
        o = _opts(**{"transformer-moe-experts": 4,
                     "transformer-moe-top-k": 1})
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        total, _ = model.loss(params, _batch(rng), None, train=False)
        assert np.isfinite(float(total))

    def test_capacity_overflow_falls_through_residual(self, rng):
        """With capacity factor ~0, every token overflows → the MoE update
        is (near-)zero and the layer reduces to the residual stream."""
        x = jnp.asarray(rng.randn(2, 8, 32), jnp.float32)
        o = _opts(**{"transformer-moe-experts": 4})
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        cfg = model.cfg
        import dataclasses
        tiny = dataclasses.replace(cfg, moe_capacity_factor=1e-9)
        out, _ = T._moe_ffn(tiny, params, "encoder_l1_moe", x, train=True)
        # capacity clamps to 1 slot per expert: at most E tokens routed
        nonzero_tokens = int((jnp.abs(out).sum(-1) > 1e-6).sum())
        assert nonzero_tokens <= 4
        full = dataclasses.replace(cfg, moe_capacity_factor=8.0)
        out_full, _ = T._moe_ffn(full, params, "encoder_l1_moe", x,
                                 train=True)
        assert int((jnp.abs(out_full).sum(-1) > 1e-6).sum()) == 16

    def test_decode_matches_teacher_forcing(self, rng):
        o = _opts(**{"transformer-moe-experts": 4})
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        v = 64
        src = jnp.asarray(rng.randint(2, v, (2, 5)), jnp.int32)
        mask = jnp.ones((2, 5), jnp.float32)
        trg = jnp.asarray(rng.randint(2, v, (2, 4)), jnp.int32)
        enc = model.encode_for_decode(params, src, mask)
        tf = T.decode_train(model.cfg, T.cast_params(
            params, model.cfg.compute_dtype), enc, mask, trg,
            jnp.ones((2, 4), jnp.float32), train=False)
        state = model.start_state(params, enc, mask, max_len=4)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(4):
            logits, state = model.step(params, state, prev, mask)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(tf[:, t]),
                                       rtol=2e-3, atol=2e-3)
            prev = trg[:, t:t + 1]


class TestPadExclusion:
    def test_pads_claim_no_capacity_or_aux(self, rng):
        """Padding tokens must not displace real tokens from expert
        capacity nor skew the load-balance statistics."""
        import dataclasses
        o = _opts(**{"transformer-moe-experts": 4})
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        cfg = dataclasses.replace(model.cfg, moe_capacity_factor=1.0)
        x = jnp.asarray(rng.randn(1, 8, 32), jnp.float32)
        mask_full = jnp.ones((1, 8), jnp.float32)
        mask_half = mask_full.at[:, 4:].set(0.0)
        out_f, aux_f = T._moe_ffn(cfg, params, "encoder_l1_moe", x,
                                  train=True, mask=mask_full)
        out_h, aux_h = T._moe_ffn(cfg, params, "encoder_l1_moe", x,
                                  train=True, mask=mask_half)
        # masked positions produce exactly zero MoE output
        assert float(jnp.abs(out_h[:, 4:]).max()) == 0.0
        # real-token outputs are unaffected by pads' previous claims:
        # with only 4 real tokens and capacity for 8*1.0*2/4=4 per
        # expert, none of the real tokens can overflow
        assert float(jnp.abs(out_h[:, :4]).sum()) > 0
        assert np.isfinite(float(aux_h)) and float(aux_h) > 0


class TestStackRoundTrip:
    def test_stack_unstack_identity(self):
        o = _opts()
        model = create_model(o, 64, 64)
        params = model.init(jax.random.key(0))
        stacked = T.stack_layer_params(model.cfg, params)
        assert any("_stack_" in k for k in stacked)
        assert not any("_l1_" in k for k in stacked)
        back = T.unstack_layer_params(model.cfg, stacked)
        assert set(back) == set(params)
        for k in params:
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(params[k]))


@pytest.mark.slow
class TestShardedEquivalence:
    """8-virtual-CPU-device mesh (conftest) equivalences."""

    def _loss_after(self, o, batch, steps=2, micro=False):
        model = create_model(o, 64, 64)
        gg = GraphGroup(model, o)
        gg.initialize(prng.root_key(7))
        out = None
        for s in range(steps):
            payload = [dict(b) for b in batch] if micro else dict(batch)
            out = gg.update(payload, s + 1, jax.random.key(3 + s))
        return float(out.loss_sum), gg

    def test_pipe_matches_single(self, rng):
        b = _batch(rng)
        single, _ = self._loss_after(_opts(n=1), b)
        piped, gg = self._loss_after(
            _opts(mesh=["data:2", "model:2", "pipe:2"], n=8), b)
        assert gg._stacked
        assert abs(single - piped) / abs(single) < 1e-5

    def test_expert_pipe_matches_single(self, rng):
        b = _batch(rng)
        kw = {"transformer-moe-experts": 4}
        single, _ = self._loss_after(_opts(n=1, **kw), b)
        sharded, _ = self._loss_after(
            _opts(mesh=["data:2", "pipe:2", "expert:2"], n=8, **kw), b)
        assert abs(single - sharded) / abs(single) < 1e-5

    def test_stacked_checkpoint_is_marian_flat(self, rng, tmp_path):
        from marian_tpu.common.io import load_model
        from marian_tpu.training.checkpoint import save_checkpoint
        o = _opts(mesh=["data:2", "model:2", "pipe:2"], n=8)
        model = create_model(o, 64, 64)
        gg = GraphGroup(model, o)
        gg.initialize(prng.root_key(7))
        gg.update(_batch(rng), 1, jax.random.key(1))
        path = str(tmp_path / "m.npz")
        from marian_tpu.training.training_state import TrainingState
        save_checkpoint(path, gg.export_params(), "{}", gg,
                        TrainingState())
        items, _cfg = load_model(path)
        assert any(k.startswith("encoder_l1_") for k in items)
        assert not any("_stack_" in k for k in items)
        opt = np.load(path + ".optimizer.npz")
        assert any(":encoder_l2_" in k or k.startswith("m:encoder_l2_")
                   for k in opt.files)

    def test_pipe_with_fused_delay(self, rng):
        """Depth-stacked storage composes with the in-jit --optimizer-delay
        micro-batch scan (stacked params inside the delay scan body)."""
        b = _batch(rng)
        b2 = {k: jnp.roll(v, 1, axis=0) for k, v in b.items()}
        single, _ = self._loss_after(
            _opts(n=1, **{"optimizer-delay": 2}), [dict(b), dict(b2)],
            steps=1, micro=True)
        piped, gg = self._loss_after(
            _opts(mesh=["data:2", "model:2", "pipe:2"], n=8,
                  **{"optimizer-delay": 2}), [dict(b), dict(b2)],
            steps=1, micro=True)
        assert gg._stacked and gg._fused_delay is not None
        assert abs(single - piped) / abs(single) < 1e-5

    def test_pipe_refuses_tied_layers(self):
        o = _opts(mesh=["data:2", "model:2", "pipe:2"], n=8,
                  **{"transformer-tied-layers": [1, 1]})
        model = create_model(o, 64, 64)
        gg = GraphGroup(model, o)
        with pytest.raises(ValueError, match="tied"):
            gg.initialize(prng.root_key(0))
