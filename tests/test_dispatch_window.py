"""--dispatch-window: K full optimizer updates inside ONE jitted dispatch
(lax.scan over a leading window axis — parallel/zero.py build_train_step
n_updates>1). The lever amortizes per-dispatch host latency (a network-
tunneled chip, host-bound pods); the reference has no equivalent because
its SyncGraphGroup host loop runs per update
(src/training/graph_group_sync.cpp :: SyncGraphGroup::update)."""

import jax
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data import Corpus, DefaultVocab
from marian_tpu.data.batch_generator import BatchGenerator
from marian_tpu.models.encoder_decoder import batch_to_arrays, create_model
from marian_tpu.training import GraphGroup, Train, TrainingState

from tests.test_training import train_options


def _fixed_batches(src, tgt, n):
    vs = DefaultVocab.build(open(src).read().splitlines())
    vt = DefaultVocab.build(open(tgt).read().splitlines())
    c = Corpus([src, tgt], [vs, vt],
               Options({"max-length": 64, "shuffle": "none"}))
    bg = BatchGenerator(c, mini_batch=2, maxi_batch=1, prefetch=False,
                        shuffle_batches=False, pad_batch=True,
                        batch_multiple=8)
    batches = [batch_to_arrays(b) for b in list(bg)[:n]]
    assert len(batches) == n
    # the scanned window needs one shared padded shape — pad every leaf's
    # time dim to the widest bucket among the picked batches (mask-correct:
    # batch_to_arrays pads with zeros/EOS-masked columns)
    import jax.numpy as jnp
    w = {k: max(b[k].shape[1] for b in batches) for k in batches[0]}
    batches = [{k: jnp.pad(v, ((0, 0), (0, w[k] - v.shape[1])))
                for k, v in b.items()} for b in batches]
    return (vs, vt), batches


class TestDispatchWindow:
    def test_window_equals_sequential_updates(self, tmp_corpus, tmp_path):
        """K=3 scanned updates must reproduce 3 sequential update() calls
        exactly (same step numbers; both paths derive sub-step keys from
        the same raw stream key by absolute step)."""
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt)
        (vs, vt), batches = _fixed_batches(src, tgt, 3)
        model = create_model(opts, len(vs), len(vt))
        key, rng = jax.random.key(0), jax.random.key(9)

        gg_w = GraphGroup(model, opts.with_(**{"dispatch-window": 3}),
                          donate=False)
        gg_w.initialize(key)
        outs = gg_w.update_window([dict(b) for b in batches], 1, rng)
        assert len(outs) == 3

        gg_s = GraphGroup(model, opts, donate=False)
        gg_s.initialize(key)
        # update() folds the raw stream key by step-1 in-jit, so passing
        # rng to both paths yields identical sub-step keys
        seq = [gg_s.update(dict(b), 1 + i, rng)
               for i, b in enumerate(batches)]

        # per-sub-update metrics line up with the sequential trajectory
        for o_w, o_s in zip(outs, seq):
            np.testing.assert_allclose(np.asarray(o_w.loss_sum),
                                       np.asarray(o_s.loss_sum),
                                       rtol=1e-5)
            np.testing.assert_allclose(np.asarray(o_w.grad_norm),
                                       np.asarray(o_s.grad_norm),
                                       rtol=1e-4)
        for k in gg_s.params:
            if k.endswith("_bk"):
                continue  # zero-gradient leaves: pure float noise
            np.testing.assert_allclose(np.asarray(gg_w.params[k]),
                                       np.asarray(gg_s.params[k]),
                                       rtol=5e-4, atol=5e-6, err_msg=k)

    def test_window_composes_with_ema_and_clipping(self, tmp_corpus,
                                                   tmp_path):
        """Optimizer-state features (EMA, clip, dynamic scaling stats) live
        in the scan carry — the windowed trajectory must track sequential
        with them enabled."""
        src, tgt, _ = tmp_corpus
        over = {"exponential-smoothing": 0.01, "clip-norm": 0.5}
        opts = train_options(tmp_path, src, tgt, **over)
        (vs, vt), batches = _fixed_batches(src, tgt, 2)
        model = create_model(opts, len(vs), len(vt))
        key, rng = jax.random.key(1), jax.random.key(5)

        gg_w = GraphGroup(model, opts.with_(**{"dispatch-window": 2}),
                          donate=False)
        gg_w.initialize(key)
        gg_w.update_window([dict(b) for b in batches], 1, rng)

        gg_s = GraphGroup(model, opts, donate=False)
        gg_s.initialize(key)
        for i, b in enumerate(batches):
            gg_s.update(dict(b), 1 + i, rng)

        sm_w, sm_s = gg_w.smoothed(), gg_s.smoothed()
        for k in sm_s:
            if k.endswith("_bk"):
                continue
            np.testing.assert_allclose(np.asarray(sm_w[k]),
                                       np.asarray(sm_s[k]),
                                       rtol=5e-4, atol=5e-6, err_msg=k)

    def test_window_with_delay_refused(self, tmp_corpus, tmp_path):
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt,
                             **{"dispatch-window": 4, "optimizer-delay": 2.0})
        vs = DefaultVocab.build(open(src).read().splitlines())
        model = create_model(opts, len(vs), len(vs))
        with pytest.raises(ValueError, match="dispatch-window"):
            GraphGroup(model, opts)  # loud refusal, matching the CLI help

    def test_after_batches_not_overshot(self, tmp_corpus, tmp_path):
        """An update-counted hard limit must cap the window fill: with
        --after-batches 5 and window 4, the final window is partial and
        training stops at exactly 5 updates (the unwindowed contract),
        not at the next multiple of the window."""
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt,
                             **{"dispatch-window": 4, "after-batches": 5})
        Train(opts).run()
        st = TrainingState.load(str(tmp_path / "model.npz.progress.yml"))
        assert st.batches == 5

    def test_trigger_crossing_mid_window(self):
        """A save/valid freq boundary that falls INSIDE a dispatched
        window must still fire at the drain (should_*_since range test),
        and never before all K applied updates are accounted."""
        from marian_tpu.training.scheduler import Scheduler
        from marian_tpu.training.training_state import TrainingState
        sch = Scheduler(Options({"save-freq": "3u", "valid-freq": "5u",
                                 "disp-freq": "100u", "quiet": True}),
                        TrainingState())
        before_b, before_l = sch.state.batches, sch.state.labels_total
        for _ in range(4):                        # one window of K=4
            sch.update(0.0, 10, 2)
        assert sch.state.batches == 4
        assert sch.should_save_since(before_b, before_l)       # 3 in (0,4]
        assert not sch.should_validate_since(before_b, before_l)  # 5 not
        before_b, before_l = sch.state.batches, sch.state.labels_total
        for _ in range(4):                        # next window: updates 5-8
            sch.update(0.0, 10, 2)
        assert sch.should_save_since(before_b, before_l)       # 6 in (4,8]
        assert sch.should_validate_since(before_b, before_l)   # 5 in (4,8]

    def test_train_loop_end_to_end(self, tmp_corpus, tmp_path):
        """Full Train.run() with --dispatch-window 2: the loop groups
        same-shape batches, flushes stragglers at epoch end, and the
        progress count matches the updates applied."""
        src, tgt, _ = tmp_corpus
        opts = train_options(tmp_path, src, tgt,
                             **{"dispatch-window": 2, "after-batches": 6})
        Train(opts).run()
        st = TrainingState.load(str(tmp_path / "model.npz.progress.yml"))
        assert st.batches >= 6


class TestLabelsLimitWindowCap:
    """--after Nt (labels-counted) must cap the dispatch-window fill:
    r4-advisor finding (window could overshoot a labels stop by K-1
    updates) + r5 review (first window, before any per-update label
    count is observed, must cap at ONE update)."""

    def _sched(self, after):
        from marian_tpu.common.options import Options
        from marian_tpu.training.scheduler import Scheduler
        from marian_tpu.training.training_state import TrainingState
        opts = Options({"after": after, "disp-freq": "1000u",
                        "learn-rate": 1e-3})
        return Scheduler(opts, TrainingState())

    def test_first_window_caps_at_one_update(self):
        s = self._sched("300t")
        assert s.updates_remaining() == 1

    def test_estimate_tracks_max_labels_per_update(self):
        s = self._sched("300t")
        for _ in range(3):
            s.update(0.0, labels=50, sentences=4)
        # 150 labels consumed, 150 remain, max 50/update → 3 updates
        assert s.updates_remaining() == 3

    def test_no_labels_limit_returns_none(self):
        s = self._sched("0e")
        s.update(0.0, labels=50, sentences=4)
        assert s.updates_remaining() is None
