"""In-repo BPE subword fallback (VERDICT r3 #4): ``--sentencepiece``-style
workflows — train directly on raw text, the vocab is learned, subword
units below the word level — must work in THIS image, where the
sentencepiece wheel is absent (reference: src/data/sentencepiece_vocab.cpp
vendors the SPM library so the capability never depends on the
environment). tests/test_spm_e2e.py keeps the skip-marker for real-SPM
byte compatibility; this file exercises the always-available path."""

import json
import pathlib

import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.bpe_vocab import BPEVocab, train_bpe
from marian_tpu.data.vocab import EOS_ID, UNK_ID, create_vocab

CORPUS = [
    "the lowland owls howl loudly",
    "the lowest owl howls in the lowlands",
    "low lights glow in the lowland night",
    "owls glow lowly under low light",
] * 4


def _model(tmp_path, vocab_size=64, alphas=(), seed=7):
    path = str(tmp_path / "test.spm")
    src = tmp_path / "corpus.txt"
    src.write_text("\n".join(CORPUS) + "\n")
    opts = Options({"dim-vocabs": [vocab_size], "seed": seed,
                    **({"sentencepiece-alphas": list(alphas)}
                       if alphas else {})})
    return BPEVocab(path, options=opts, train_paths=[str(src)])


class TestTrainer:
    def test_learns_frequent_merges(self, tmp_path):
        v = _model(tmp_path)
        # "low" recurs across words → must become a single piece
        pieces = set(v._pieces)
        assert any("low" in p for p in pieces)
        assert len(v) <= 64
        assert v._pieces[EOS_ID] == "</s>" and v._pieces[UNK_ID] == "<unk>"

    def test_deterministic(self, tmp_path):
        p, m = train_bpe(iter(CORPUS), 64)
        p2, m2 = train_bpe(iter(CORPUS), 64)
        assert p == p2 and m == m2

    def test_decremented_pair_stays_mergeable(self):
        """Lazy-heap regression: a pair whose count only ever FALLS
        (here (▁a,x) drops when (x,y) merges first inside '▁axy') must
        still be selected at its reduced count — push-on-increment-only
        orphans it once its init-time heap entry goes stale."""
        lines = ["xy"] * 5 + ["axy"] * 3 + ["ax"] * 4
        _, merges = train_bpe(iter(lines), 64)
        assert merges.index(("x", "y")) < merges.index(("▁a", "x"))

    def test_roundtrip(self, tmp_path):
        v = _model(tmp_path)
        for line in ("the owls howl", "low light glows"):
            ids = v.encode(line)
            assert ids[-1] == EOS_ID
            assert v.decode(ids) == line
        # unseen characters → <unk> pieces, no crash
        ids = v.encode("zebra+quartz")
        assert UNK_ID in ids

    def test_subword_not_word_level(self, tmp_path):
        v = _model(tmp_path)
        # an unseen-but-composable word must encode as multiple known
        # sub-word pieces, not one <unk> (the whole point of subwords)
        ids = v.encode("lowlight", add_eos=False)
        assert len(ids) >= 2 and UNK_ID not in ids
        assert v.decode(ids) == "lowlight"

    def test_bpe_dropout_sampling(self, tmp_path):
        v = _model(tmp_path, alphas=(0.5,))
        segs = {tuple(v.encode("the lowland owls", inference=False))
                for _ in range(20)}
        assert len(segs) > 1                   # sampled segmentations
        # inference path is deterministic (no dropout)
        one = {tuple(v.encode("the lowland owls", inference=True))
               for _ in range(5)}
        assert len(one) == 1

    def test_refuses_real_spm_binary(self, tmp_path):
        path = tmp_path / "real.spm"
        path.write_bytes(b"\x0a\x13\x08\x01binary-protobuf-ish")
        with pytest.raises(RuntimeError, match="sentencepiece"):
            BPEVocab(str(path), options=Options({}))

    def test_factory_dispatches_spm_extension(self, tmp_path):
        src = tmp_path / "c.txt"
        src.write_text("\n".join(CORPUS) + "\n")
        v = create_vocab(str(tmp_path / "f.spm"),
                         Options({"dim-vocabs": [64]}),
                         train_paths=[str(src)])
        try:
            import sentencepiece  # noqa: F401
            pytest.skip("real sentencepiece present — fallback not used")
        except ImportError:
            pass
        assert isinstance(v, BPEVocab)


class TestNativeEncoder:
    """The C++ encode hot path (native/bpe_encoder.cpp — the reference
    tokenizes through vendored C++ SentencePiece) must be id-identical
    to the Python merge loop."""

    def test_matches_python_encoder(self, tmp_path):
        v = _model(tmp_path)
        if v._native is None:
            pytest.skip("native toolchain unavailable")
        lines = CORPUS + [
            "lowlight owls", "unseen zebra words", "a", "",
            "  doubled   spaces\tand tabs ",
            "ünïcödé wörds çömpösé tøø",
            # Python str.split() splits on Unicode whitespace (NBSP,
            # ideographic space, line sep) — parity includes that set
            "low light", "low　light", "low light",
            "low\x1dlight", "low\x85light",
            # embedded NUL is DATA to Python, not a terminator
            "low\x00light owls",
        ]
        for line in lines:
            native = v._native.encode(line, add_eos=True)
            v._native, saved = None, v._native
            try:
                python = v.encode(line, add_eos=True)
            finally:
                v._native = saved
            assert native == python, line

    def test_used_only_without_dropout(self, tmp_path):
        v = _model(tmp_path, alphas=(0.5,))
        if v._native is None:
            pytest.skip("native toolchain unavailable")
        # training-time encode samples (Python path); inference encode is
        # deterministic and may take the native path — both must decode
        # back to the original text
        for _ in range(5):
            assert v.decode(v.encode("the lowland owls howl")) \
                == "the lowland owls howl"
        assert v.decode(v.encode("the lowland owls howl",
                                 inference=True)) \
            == "the lowland owls howl"


@pytest.mark.slow
def test_raw_text_to_train_to_decode_e2e(tmp_path):
    """The capability itself: raw parallel text + nonexistent .spm vocab
    paths → vocabs train from data → model trains → beam decode returns
    text (no pre-built vocab anywhere)."""
    from marian_tpu.data import BatchGenerator, Corpus
    from marian_tpu.models.encoder_decoder import (batch_to_arrays,
                                                   create_model)
    from marian_tpu.training.graph_group import GraphGroup
    from marian_tpu.translator.beam_search import BeamSearch
    from marian_tpu.common import prng
    import jax

    src = tmp_path / "t.src"
    trg = tmp_path / "t.trg"
    src.write_text("\n".join(CORPUS) + "\n")
    trg.write_text("\n".join(l.upper() for l in CORPUS) + "\n")
    opts = Options({
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 1, "dec-depth": 1,
        "tied-embeddings": True, "dim-vocabs": [64, 64],
        "precision": ["float32", "float32"], "max-length": 32,
        "learn-rate": 0.05, "optimizer": "adam", "clip-norm": 1.0,
        "cost-type": "ce-mean-words", "label-smoothing": 0.1,
        "mini-batch": 8, "maxi-batch": 2, "shuffle": "none", "seed": 11,
    })
    vocabs = [create_vocab(str(tmp_path / f"v{i}.spm"), opts,
                           stream_index=i, train_paths=[p])
              for i, p in enumerate([str(src), str(trg)])]
    corpus = Corpus([str(src), str(trg)], vocabs, opts)
    model = create_model(opts, vocabs[0], vocabs[1])
    gg = GraphGroup(model, opts)
    key = prng.root_key(11)
    gg.initialize(prng.stream(key, prng.STREAM_INIT))
    losses = []
    step = 0
    n_updates = 40
    while step < n_updates:
        for batch in BatchGenerator(corpus, opts, prefetch=False):
            out = gg.update(batch_to_arrays(batch), step + 1, key)
            losses.append(out.loss_sum / max(out.labels, 1.0))
            step += 1
            if step >= n_updates:
                break
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    bs = BeamSearch(model, [gg.export_params()], None,
                    Options({"beam-size": 4, "max-length": 32}), vocabs[1])
    line = CORPUS[0]
    ids = vocabs[0].encode(line)
    src_ids = np.asarray([ids], np.int32)
    mask = np.ones_like(src_ids, np.float32)
    nbest = bs.search(src_ids, mask)
    text = vocabs[1].decode(nbest[0][0]["tokens"])
    assert isinstance(text, str) and len(text) > 0
