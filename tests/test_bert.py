"""BERT family (masked LM + classifier) — reference src/models/bert.h
(SURVEY.md §2.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common.options import Options
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.models.encoder_decoder import create_model


def _vocab(words, specials=("[MASK]",)):
    m = {"</s>": 0, "<unk>": 1}
    for i, w in enumerate(list(specials) + list(words)):
        m[w] = i + 2
    return DefaultVocab(m)


def _opts(mtype="bert", **kw):
    return Options({
        "type": mtype,
        "dim-emb": 32, "transformer-heads": 4, "transformer-dim-ffn": 64,
        "enc-depth": 2, "dec-depth": 2,
        "precision": ["float32", "float32"],
        "cost-type": "ce-mean-words",
        "max-length": 32, **{k.replace("_", "-"): v for k, v in kw.items()}})


def _batch(vocab_size, b=8, t=12, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(3, vocab_size, (b, t)), jnp.int32),
        "src_mask": jnp.ones((b, t), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(3, vocab_size, (b, t)), jnp.int32),
        "trg_mask": jnp.ones((b, t), jnp.float32),
    }


class TestMaskedLM:
    def test_loss_finite_and_masking_rate(self):
        v = _vocab([f"w{i}" for i in range(20)])
        model = create_model(_opts(), v, v)
        params = model.init(jax.random.key(0))
        batch = _batch(len(v))
        total, aux = model.loss(params, batch, jax.random.key(1), train=True)
        assert np.isfinite(float(total))
        # ~15% of tokens masked (binomial, loose bounds)
        frac = float(aux["labels"]) / batch["src_ids"].size
        assert 0.05 < frac < 0.3

    def test_mask_symbol_used(self):
        v = _vocab([f"w{i}" for i in range(20)])
        model = create_model(_opts(), v, v)
        ids = jnp.asarray(np.full((4, 16), 5), jnp.int32)
        mask = jnp.ones((4, 16), jnp.float32)
        masked, weights = model._mask_inputs(ids, mask, jax.random.key(3))
        changed = np.asarray(masked != ids)
        sel = np.asarray(weights) > 0
        assert sel.any()
        # 80% of selected become [MASK]
        mask_id = v["[MASK]"]
        frac_masked = (np.asarray(masked)[sel] == mask_id).mean()
        assert 0.5 < frac_masked <= 1.0
        # unselected positions never change
        assert not changed[~sel].any()

    def test_mlm_training_reduces_loss(self):
        v = _vocab([f"w{i}" for i in range(12)])
        opts = _opts(learn_rate=1e-3, optimizer="adam", clip_norm=0.0)
        model = create_model(opts, v, v)
        params = model.init(jax.random.key(0))
        batch = _batch(len(v), b=16, t=8, seed=1)

        def loss_fn(p, key):
            total, aux = model.loss(p, batch, key, train=True)
            return total / aux["labels"]

        g = jax.jit(jax.value_and_grad(loss_fn))
        first = None
        for step in range(30):
            val, grads = g(params, jax.random.key(step % 3))
            params = jax.tree_util.tree_map(lambda p_, g_: p_ - 0.01 * g_,
                                            params, grads)
            if first is None:
                first = float(val)
        assert float(val) < first


class TestClassifier:
    def test_learns_first_token_rule(self):
        """Classify by the first token — a few steps should overfit."""
        v = _vocab([f"w{i}" for i in range(10)])
        lv = DefaultVocab({"</s>": 0, "<unk>": 1, "A": 2, "B": 3})
        opts = _opts("bert-classifier", learn_rate=1e-2)
        model = create_model(opts, v, lv)
        params = model.init(jax.random.key(0))
        rs = np.random.RandomState(0)
        ids = rs.randint(3, len(v), (16, 6)).astype(np.int32)
        labels = np.where(ids[:, 0] % 2 == 0, 2, 3).astype(np.int32)
        batch = {
            "src_ids": jnp.asarray(ids),
            "src_mask": jnp.ones(ids.shape, jnp.float32),
            "trg_ids": jnp.asarray(
                np.stack([labels, np.zeros_like(labels)], 1)),
            "trg_mask": jnp.ones((16, 2), jnp.float32),
        }

        def loss_fn(p):
            total, aux = model.loss(p, batch, None, train=False)
            return total / aux["labels"]

        g = jax.jit(jax.value_and_grad(loss_fn))
        for _ in range(60):
            val, grads = g(params)
            params = jax.tree_util.tree_map(lambda p_, g_: p_ - 0.05 * g_,
                                            params, grads)
        pred = model.predict_classes(params, batch["src_ids"],
                                     batch["src_mask"])
        assert (np.asarray(pred) == labels).mean() >= 0.9

    def test_padding_rows_excluded(self):
        v = _vocab([f"w{i}" for i in range(10)])
        lv = DefaultVocab({"</s>": 0, "<unk>": 1, "A": 2})
        model = create_model(_opts("bert-classifier"), v, lv)
        params = model.init(jax.random.key(0))
        batch = _batch(len(v), b=4, t=6)
        batch["src_mask"] = batch["src_mask"].at[2:].set(0.0)  # padding rows
        total, aux = model.loss(params, batch, None, train=False)
        assert float(aux["labels"]) == 2.0


class TestTrainCLI:
    def test_bert_pretraining_e2e(self, tmp_path):
        """marian-train --type bert on a monolingual file."""
        import os
        import yaml
        from marian_tpu.cli import marian_train
        lines = ["a b c d", "b c d a", "c d a b", "d a b c"] * 3
        (tmp_path / "mono.txt").write_text("\n".join(lines) + "\n")
        # vocab must contain [MASK]
        vmap = {"</s>": 0, "<unk>": 1, "[MASK]": 2,
                "a": 3, "b": 4, "c": 5, "d": 6}
        with open(tmp_path / "v.yml", "w") as fh:
            yaml.safe_dump(vmap, fh)
        model = str(tmp_path / "bert.npz")
        marian_train.main([
            "--type", "bert",
            "--train-sets", str(tmp_path / "mono.txt"),
            "--vocabs", str(tmp_path / "v.yml"),
            "--model", model,
            "--dim-emb", "32", "--transformer-heads", "4",
            "--transformer-dim-ffn", "64", "--enc-depth", "1",
            "--dec-depth", "1",
            "--precision", "float32", "float32",
            "--mini-batch", "8", "--learn-rate", "0.005",
            "--after-batches", "8", "--disp-freq", "4u",
            "--save-freq", "100u", "--seed", "2", "--max-length", "20",
            "--quiet", "--cost-type", "ce-mean-words",
        ])
        assert os.path.exists(model)
