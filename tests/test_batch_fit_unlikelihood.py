"""--mini-batch-fit empirical budget search + --unlikelihood-loss
(reference: GraphGroup::collectStats; layers/loss.h unlikelihood)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.layers.loss import cross_entropy_loss
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.training.graph_group import GraphGroup

from test_model import fake_batch


@pytest.fixture
def rng():
    return np.random.RandomState(17)


class TestMiniBatchFit:
    def test_search_converges_to_cap_when_memory_suffices(self):
        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "learn-rate": 0.01, "optimizer": "adam", "clip-norm": 0.0,
            "cost-type": "ce-mean-words", "max-length": 16,
        })
        model = create_model(opts, 31, 31)
        gg = GraphGroup(model, opts)
        gg.initialize(jax.random.key(0))
        from marian_tpu.training.batch_fit import fit_mini_batch_words
        fitted = fit_mini_batch_words(gg, opts, 31, cap=1024)
        # CPU never OOMs at these sizes → the search must hit the cap
        assert fitted == 1024


class TestUnlikelihood:
    def test_sign_selects_objective(self, rng):
        b, t, v = 2, 4, 12
        logits = jnp.asarray(rng.randn(b, t, v), jnp.float32)
        labels = jnp.asarray(rng.randint(0, v, (b, t)), jnp.int32)
        mask = jnp.ones((b, t), jnp.float32)
        pos_w = jnp.ones((b, t), jnp.float32)
        neg_w = -jnp.ones((b, t), jnp.float32)
        rl_pos = cross_entropy_loss(logits, labels, mask, 0.0, pos_w,
                                    unlikelihood=True)
        rl_base = cross_entropy_loss(logits, labels, mask, 0.0)
        np.testing.assert_allclose(float(rl_pos.loss_sum),
                                   float(rl_base.loss_sum), rtol=1e-6)
        rl_neg = cross_entropy_loss(logits, labels, mask, 0.0, neg_w,
                                    unlikelihood=True)
        # unlikelihood of the same tokens is a different, finite number
        assert np.isfinite(float(rl_neg.loss_sum))
        assert float(rl_neg.loss_sum) != pytest.approx(
            float(rl_base.loss_sum))

    def test_unlikelihood_pushes_probability_down(self, rng):
        """Gradient descent on -log(1-p) must DECREASE p(label)."""
        v = 8
        logits = jnp.zeros((1, 1, v), jnp.float32)
        labels = jnp.asarray([[3]], jnp.int32)
        mask = jnp.ones((1, 1), jnp.float32)
        neg_w = -jnp.ones((1, 1), jnp.float32)

        def loss(lg):
            return cross_entropy_loss(lg, labels, mask, 0.0, neg_w,
                                      unlikelihood=True).loss_sum

        g = jax.grad(loss)(logits)
        lg2 = logits - 1.0 * g
        p0 = jax.nn.softmax(logits[0, 0])[3]
        p1 = jax.nn.softmax(lg2[0, 0])[3]
        assert float(p1) < float(p0)

    def test_model_level_flag(self, rng):
        opts = Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "precision": ["float32", "float32"],
            "max-length": 32, "unlikelihood-loss": True,
        })
        model = create_model(opts, 23, 23)
        params = model.init(jax.random.key(0))
        batch = dict(fake_batch(rng, b=2, ts=5, tt=6, vocab=23))
        batch["data_weights"] = jnp.asarray(
            rng.choice([-1.0, 1.0], (2, 6)), jnp.float32)
        total, aux = model.loss(params, batch, key=None, train=False)
        assert np.isfinite(float(total))
