"""--scan-layers: lax.scan over the layer stack must be numerically
equivalent to the unrolled stack (same ops, same dropout keys), while
compiling O(1) HLO in depth. Reference behavior pinned: transformer.h
unrolls layers; the scan is the TPU-first re-design of the same math."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models.encoder_decoder import create_model


def _batch(rng, v, b=2, ts=5, tt=6):
    return {
        "src_ids": jnp.asarray(rng.randint(2, v, (b, ts)), jnp.int32),
        "src_mask": jnp.ones((b, ts), jnp.float32),
        "trg_ids": jnp.asarray(rng.randint(2, v, (b, tt)), jnp.int32),
        "trg_mask": jnp.ones((b, tt), jnp.float32),
    }


def _opts(**over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 3, "dec-depth": 3,
            "tied-embeddings-all": True, "label-smoothing": 0.1,
            "precision": ["float32", "float32"], "max-length": 32,
            "dim-vocabs": [31, 31]}
    base.update(over)
    return Options(base)


@pytest.mark.parametrize("autoreg", ["self-attention", "average-attention",
                                     "rnn"])
def test_scan_matches_unrolled_loss_and_grads(rng, autoreg):
    v = 31
    batch = _batch(rng, v)
    opts_on = _opts(**{"scan-layers": True,
                       "transformer-decoder-autoreg": autoreg})
    opts_off = _opts(**{"scan-layers": False,
                        "transformer-decoder-autoreg": autoreg})
    m_on = create_model(opts_on, v, v)
    m_off = create_model(opts_off, v, v)
    params = m_on.init(jax.random.key(3))

    def loss(model, p):
        return model.loss(p, batch, None, train=False)[0]

    l_on, g_on = jax.value_and_grad(lambda p: loss(m_on, p))(params)
    l_off, g_off = jax.value_and_grad(lambda p: loss(m_off, p))(params)
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-6)
    for k in g_off:
        np.testing.assert_allclose(np.asarray(g_on[k]),
                                   np.asarray(g_off[k]),
                                   rtol=5e-5, atol=1e-6, err_msg=k)


def test_scan_matches_unrolled_with_dropout(rng):
    """Same PRNG key per layer index → identical dropout masks → identical
    stochastic loss."""
    v = 31
    batch = _batch(rng, v)
    extra = {"transformer-dropout": 0.2, "transformer-dropout-attention": 0.1,
             "transformer-dropout-ffn": 0.1}
    m_on = create_model(_opts(**{"scan-layers": True, **extra}), v, v)
    m_off = create_model(_opts(**{"scan-layers": False, **extra}), v, v)
    params = m_on.init(jax.random.key(3))
    key = jax.random.key(11)
    l_on = m_on.loss(params, batch, key, train=True)[0]
    l_off = m_off.loss(params, batch, key, train=True)[0]
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-6)


def test_scan_with_gradient_checkpointing(rng):
    v = 31
    batch = _batch(rng, v)
    m = create_model(_opts(**{"scan-layers": True,
                              "gradient-checkpointing": True}), v, v)
    params = m.init(jax.random.key(0))
    m_ref = create_model(_opts(**{"scan-layers": False}), v, v)
    key = jax.random.key(5)
    l, g = jax.value_and_grad(
        lambda p: m.loss(p, batch, key, train=True)[0])(params)
    l_ref = m_ref.loss(params, batch, key, train=True)[0]
    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-6)
    assert all(np.all(np.isfinite(np.asarray(v))) for v in g.values())


def test_tied_layers_fall_back_and_train(rng):
    """--transformer-tied-layers shares leaves across layers — scanning
    would stack the same tensor; must fall back to the unrolled stack."""
    from marian_tpu.models import transformer as T
    v = 31
    opts = _opts(**{"scan-layers": True,
                    "transformer-tied-layers": [1, 1, 1]})
    m = create_model(opts, v, v)
    params = m.init(jax.random.key(0))
    assert T._stacked_layer_params(m.cfg, params, "decoder_l", 3) is None
    l = m.loss(params, _batch(rng, v), None, train=False)[0]
    assert np.isfinite(float(l))


def test_alignment_path_falls_back(rng):
    """Guided alignment needs one layer's attention weights — unrolled."""
    from marian_tpu.models import transformer as T
    v = 31
    m = create_model(_opts(**{"scan-layers": True,
                              "guided-alignment": "align.txt"}), v, v)
    params = m.init(jax.random.key(0))
    b = _batch(rng, v)
    out, align = T.decode_train(
        m.cfg, params,
        T.encode(m.cfg, params, b["src_ids"], b["src_mask"]),
        b["src_mask"], b["trg_ids"], b["trg_mask"], train=False,
        return_alignment=True)
    assert align is not None and align.shape == (2, 6, 5)


def test_int8_decode_scans_and_matches_unrolled(rng):
    """Int8 (QTensor) decoder weights stack as pytrees, so the scanned
    decode step applies to quantized models too — and must match the
    unrolled int8 path exactly (same int8 kernels per layer)."""
    from marian_tpu.ops.quantization import quantize_params, wrap_quantized
    from marian_tpu.models import transformer as T
    v = 31
    m_on = create_model(_opts(**{"scan-layers": True}), v, v,
                        inference=True)
    m_off = create_model(_opts(**{"scan-layers": False}), v, v,
                         inference=True)
    params = m_on.init(jax.random.key(2))
    qp = wrap_quantized({k: jnp.asarray(a) for k, a in
                         quantize_params({k: np.asarray(x)
                                          for k, x in params.items()}
                                         ).items()})
    src = jnp.asarray(np.random.RandomState(0).randint(2, v, (2, 5)),
                      jnp.int32)
    mask = jnp.ones((2, 5), jnp.float32)
    trg = jnp.asarray(np.random.RandomState(1).randint(2, v, (2, 4)),
                      jnp.int32)

    def roll(model):
        enc = model.encode_for_decode(qp, src, mask)
        state = model.start_state(qp, enc, mask, max_len=4)
        prev = jnp.zeros((2, 1), jnp.int32)
        outs = []
        for t in range(4):
            logits, state = model.step(qp, state, prev, mask)
            outs.append(np.asarray(logits))
            prev = trg[:, t:t + 1]
        return state, np.stack(outs)

    st_on, out_on = roll(m_on)
    st_off, out_off = roll(m_off)
    assert "stack_self_k" in st_on          # scan actually engaged
    assert "l1_self_k" in st_off
    np.testing.assert_allclose(out_on, out_off, rtol=2e-4, atol=2e-4)
