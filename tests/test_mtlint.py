"""mtlint (marian_tpu/analysis) — per-rule positive/negative snippets,
suppression + baseline round-trip, CLI exit codes, and THE TIER-1 GATE:
the analyzer over the real marian_tpu/ tree with the checked-in baseline
must be clean (ISSUE 2 acceptance).

Snippets are parsed from strings — no fixture files on disk; the analysis
layer is stdlib-only, so none of this needs jax.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

from marian_tpu.analysis.cli import main as mtlint_main
from marian_tpu.analysis.core import (RULESET_VERSION, Config, Source,
                                      apply_baseline, collect_sources,
                                      load_baseline, load_result_cache,
                                      run_lint, save_result_cache,
                                      write_baseline, _read_toml_tables)
from marian_tpu.analysis.rules import all_rules

ROOT = Path(__file__).resolve().parents[1]


def lint_text(code: str, rel: str = "marian_tpu/ops/snippet.py",
              families=None, config: Config = None):
    """Run rules over one in-memory snippet; returns findings (inline
    suppressions honored, baseline not applied)."""
    cfg = config or Config(root=ROOT)
    src = Source(ROOT / rel, rel, text=code)
    findings = []
    for rule in all_rules():
        if families and rule.family not in families:
            continue
        if not cfg.family_applies(rule.family, rel):
            continue
        if rule.scope == "project":
            findings.extend(rule.check_project([src], cfg))
        else:
            findings.extend(rule.check(src, cfg))
    return [f for f in findings if not src.suppressed(f)]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------

class TestTraceSafety:
    def test_if_on_traced_param(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n")
        assert "MT-TRACE-COND" in rule_ids(fs)
        assert fs[0].line == 4

    def test_while_on_derived_value(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    y = x * 2\n"
            "    while y < 10:\n"
            "        y = y + 1\n"
            "    return y\n")
        assert "MT-TRACE-COND" in rule_ids(fs)

    def test_cast_and_item(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    a = int(x)\n"
            "    b = x.item()\n"
            "    return a + b\n")
        assert rule_ids(fs) == ["MT-TRACE-CAST"]
        assert len(fs) == 2

    def test_numpy_inside_jit(self):
        fs = lint_text(
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        assert "MT-TRACE-NUMPY" in rule_ids(fs)

    def test_np_dtype_constants_ok(self):
        fs = lint_text(
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.astype(np.float32)\n")
        assert fs == []

    def test_static_argnums_honored(self):
        fs = lint_text(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    if n > 0:\n"
            "        return x * n\n"
            "    return x\n")
        assert fs == []

    def test_static_argnames_and_scalar_annotation(self):
        fs = lint_text(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('mode',))\n"
            "def f(x, mode, rate: float = 0.1):\n"
            "    if mode == 'train' and rate > 0:\n"
            "        return x * rate\n"
            "    return x\n")
        assert fs == []

    def test_shape_and_none_tests_ok(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x, mask=None):\n"
            "    if mask is None:\n"
            "        mask = x\n"
            "    if x.ndim == 2:\n"
            "        d = int(x.shape[0])\n"
            "        return x + d\n"
            "    return x * mask\n")
        assert fs == []

    def test_wrapped_jit_binding(self):
        fs = lint_text(
            "import jax\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "step = jax.jit(f)\n")
        assert "MT-TRACE-COND" in rule_ids(fs)

    def test_plain_function_untouched(self):
        fs = lint_text(
            "def f(x):\n"
            "    if x > 0:\n"
            "        return float(x)\n"
            "    return 0.0\n")
        assert fs == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

class TestHostSync:
    REL = "marian_tpu/training/snippet.py"

    def test_unsynced_timer(self):
        fs = lint_text(
            "import time\n"
            "def bench(fn, x):\n"
            "    t0 = time.perf_counter()\n"
            "    y = fn(x)\n"
            "    dt = time.perf_counter() - t0\n"
            "    return y, dt\n", rel=self.REL, families=["host-sync"])
        assert rule_ids(fs) == ["MT-SYNC-TIMER"]

    def test_block_until_ready_clears_timer(self):
        fs = lint_text(
            "import time, jax\n"
            "def bench(fn, x):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.block_until_ready(fn(x))\n"
            "    dt = time.perf_counter() - t0\n"
            "    return y, dt\n", rel=self.REL, families=["host-sync"])
        assert fs == []

    def test_transfers(self):
        fs = lint_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    a = np.asarray(x)\n"
            "    b = x.tolist()\n"
            "    print(x)\n"
            "    return a, b\n", rel=self.REL, families=["host-sync"])
        assert rule_ids(fs) == ["MT-SYNC-TRANSFER"]
        assert len(fs) == 3

    def test_literal_np_array_ok(self):
        fs = lint_text(
            "import numpy as np\n"
            "def f():\n"
            "    print('loaded')\n"
            "    return np.array([1, 2, 3])\n",
            rel=self.REL, families=["host-sync"])
        assert fs == []

    def test_cold_dirs_not_checked(self):
        fs = lint_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n",
            rel="marian_tpu/common/snippet.py", families=["host-sync"])
        assert fs == []


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

class TestDonation:
    def test_read_after_donate(self):
        fs = lint_text(
            "import jax\n"
            "def train(p, b):\n"
            "    return p\n"
            "step = jax.jit(train, donate_argnums=(0,))\n"
            "def loop(p, batches):\n"
            "    for b in batches:\n"
            "        out = step(p, b)\n"
            "    return p\n", families=["donation"])
        assert rule_ids(fs) == ["MT-DONATE-READ"]

    def test_rebinding_is_clean(self):
        fs = lint_text(
            "import jax\n"
            "def train(p, b):\n"
            "    return p\n"
            "step = jax.jit(train, donate_argnums=(0,))\n"
            "def loop(p, batches):\n"
            "    for b in batches:\n"
            "        p = step(p, b)\n"
            "    return p\n", families=["donation"])
        assert fs == []

    def test_conditional_donation_still_flagged(self):
        fs = lint_text(
            "import jax\n"
            "def train(p, b):\n"
            "    return p\n"
            "donate = True\n"
            "step = jax.jit(train, donate_argnums=(0,) if donate else ())\n"
            "def once(p, b):\n"
            "    out = step(p, b)\n"
            "    return out, p.keys()\n", families=["donation"])
        assert rule_ids(fs) == ["MT-DONATE-READ"]


# ---------------------------------------------------------------------------
# dtype hygiene
# ---------------------------------------------------------------------------

class TestDtype:
    def test_literal_with_unpinned_array(self):
        fs = lint_text(
            "import jax\n"
            "def f(mask: jax.Array):\n"
            "    return (1.0 - mask) * -1e9\n", families=["dtype"])
        assert rule_ids(fs) == ["MT-DTYPE-LITERAL"]

    def test_astype_pin_clears_literal(self):
        fs = lint_text(
            "import jax\n"
            "def f(logits: jax.Array, mask: jax.Array):\n"
            "    return (1.0 - mask.astype(logits.dtype)) * -1e9\n",
            families=["dtype"])
        assert fs == []

    def test_scalar_annotation_not_array(self):
        fs = lint_text(
            "def f(x: 'jax.Array', rate: float):\n"
            "    keep = 1.0 - rate\n"
            "    return x / keep\n", families=["dtype"])
        assert fs == []

    def test_ctor_without_dtype(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n)), jnp.array([0.5])\n",
            families=["dtype"])
        assert rule_ids(fs) == ["MT-DTYPE-ARRAY"]
        assert len(fs) == 2

    def test_ctor_with_dtype_ok(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n, dt):\n"
            "    a = jnp.zeros((n, n), jnp.float32)\n"
            "    b = jnp.array([0.5], dtype=dt)\n"
            "    c = jnp.asarray(n)\n"
            "    return a, b, c\n", families=["dtype"])
        assert fs == []

    def test_dtype_dirs_scoped(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))\n",
            rel="marian_tpu/data/snippet.py", families=["dtype"])
        assert fs == []


# ---------------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------------

GUARDED_CLASS = (
    "import threading\n"
    "class Sched:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._queued = 0   # guarded-by: _lock\n"
    "    def bad_read(self):\n"
    "        return self._queued\n"
    "    def good_read(self):\n"
    "        with self._lock:\n"
    "            return self._queued\n"
    "    def held_helper(self):  # mtlint: holds _lock\n"
    "        self._queued += 1\n")


class TestGuardedBy:
    REL = "marian_tpu/serving/snippet.py"

    def test_unlocked_access_flagged_once(self):
        fs = lint_text(GUARDED_CLASS, rel=self.REL, families=["guarded-by"])
        assert rule_ids(fs) == ["MT-LOCK-GUARD"]
        assert len(fs) == 1 and fs[0].line == 7  # only bad_read

    def test_init_exempt_and_with_block_ok(self):
        clean = GUARDED_CLASS.replace(
            "    def bad_read(self):\n        return self._queued\n", "")
        assert lint_text(clean, rel=self.REL,
                         families=["guarded-by"]) == []

    def test_unknown_lock(self):
        fs = lint_text(
            "class C:\n"
            "    def __init__(self):\n"
            "        self._n = 0   # guarded-by: _missing\n",
            rel=self.REL, families=["guarded-by"])
        assert rule_ids(fs) == ["MT-LOCK-UNKNOWN"]

    def test_scoped_to_threaded_dirs(self):
        fs = lint_text(GUARDED_CLASS, rel="marian_tpu/ops/snippet.py",
                       families=["guarded-by"])
        assert fs == []


# ---------------------------------------------------------------------------
# metrics hygiene
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_registered_never_emitted(self):
        fs = lint_text(
            "class S:\n"
            "    def __init__(self, r):\n"
            "        self.m_used = r.counter('used_total', 'u')\n"
            "        self.m_dead = r.counter('dead_total', 'd')\n"
            "    def work(self):\n"
            "        self.m_used.inc()\n", families=["metrics"])
        assert rule_ids(fs) == ["MT-METRIC-UNUSED"]
        assert "dead_total" in fs[0].message

    def test_labels_chain_counts_as_emission(self):
        fs = lint_text(
            "class S:\n"
            "    def __init__(self, r):\n"
            "        self.m_shed = r.counter('shed_total', 's', "
            "labels=('reason',))\n"
            "    def work(self):\n"
            "        self.m_shed.labels('full').inc()\n",
            families=["metrics"])
        assert fs == []

    def test_emitted_never_registered(self):
        fs = lint_text(
            "class S:\n"
            "    def work(self):\n"
            "        self.m_ghost.inc()\n", families=["metrics"])
        assert rule_ids(fs) == ["MT-METRIC-UNREG"]

    def test_direct_construction_flagged(self):
        fs = lint_text(
            "from marian_tpu.serving.metrics import Counter\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self.m_direct = Counter('direct_total', 'd')\n"
            "    def work(self):\n"
            "        self.m_direct.inc()\n", families=["metrics"])
        assert rule_ids(fs) == ["MT-METRIC-UNREG"]
        assert "bypassing the registry" in fs[0].message

    # -- MT-METRIC-UNTESTED (RULESET v5, ISSUE 9) ---------------------------

    UNTESTED_SNIPPET = (
        "class S:\n"
        "    def __init__(self, r):\n"
        "        self.m_x = r.counter('orphan_series_total', 'x')\n"
        "    def work(self):\n"
        "        self.m_x.inc()\n")

    def test_untested_metric_flagged(self, tmp_path):
        # a root with no tests/ dir: the coverage corpus is empty, so
        # every registered name is a finding
        cfg = Config(root=tmp_path)
        fs = lint_text(self.UNTESTED_SNIPPET, families=["metrics"],
                       config=cfg)
        assert rule_ids(fs) == ["MT-METRIC-UNTESTED"]
        assert "orphan_series_total" in fs[0].message

    def test_untested_metric_covered_by_tests_string(self, tmp_path):
        (tmp_path / "tests").mkdir()
        (tmp_path / "tests" / "test_scrape.py").write_text(
            "def test_scrape(r):\n"
            "    assert 'orphan_series_total' in r.render()\n",
            encoding="utf-8")
        cfg = Config(root=tmp_path)
        fs = lint_text(self.UNTESTED_SNIPPET, families=["metrics"],
                       config=cfg)
        assert fs == []

    def test_untested_name_in_comment_does_not_count(self, tmp_path):
        (tmp_path / "tests").mkdir()
        (tmp_path / "tests" / "test_scrape.py").write_text(
            "# we should cover orphan_series_total some day\n"
            "def test_nothing():\n"
            "    pass\n", encoding="utf-8")
        cfg = Config(root=tmp_path)
        fs = lint_text(self.UNTESTED_SNIPPET, families=["metrics"],
                       config=cfg)
        assert rule_ids(fs) == ["MT-METRIC-UNTESTED"]


class TestSpanHygiene:
    """MT-SPAN-* (span_hygiene.py — ISSUE 8): manual start_span/end
    pairs must close on all paths, and no attributes after close."""

    def test_never_closed_flagged(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    do_work()\n", families=["span"])
        assert rule_ids(fs) == ["MT-SPAN-UNCLOSED"]
        assert "never closed" in fs[0].message

    def test_conditional_close_flagged(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f(ok):\n"
            "    sp = TRACER.start_span('x')\n"
            "    if ok:\n"
            "        TRACER.end(sp)\n", families=["span"])
        assert rule_ids(fs) == ["MT-SPAN-UNCLOSED"]
        assert "all paths" in fs[0].message

    def test_finally_close_ok(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    try:\n"
            "        work()\n"
            "    finally:\n"
            "        TRACER.end(sp)\n", families=["span"])
        assert fs == []

    def test_straight_line_close_ok(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    work()\n"
            "    TRACER.end(sp)\n", families=["span"])
        assert fs == []

    def test_nonexistent_method_end_is_not_a_close(self):
        """Span has no end() method — `sp.end()` raises AttributeError
        at runtime, so the lint must NOT count it as a close."""
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    sp.end()\n", families=["span"])
        assert rule_ids(fs) == ["MT-SPAN-UNCLOSED"]

    def test_keyword_end_counts_as_close(self):
        """RULESET v5: Tracer.end's parameter is named ``span`` —
        ``end(span=sp)`` is a close, not an escape."""
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    try:\n"
            "        work()\n"
            "    finally:\n"
            "        TRACER.end(span=sp)\n", families=["span"])
        assert fs == []

    def test_self_guard_close_ok(self):
        """`if sp is not None: end(sp)` is the close idiom, not a branch
        (the scheduler's bspan pattern)."""
        fs = lint_text(
            "from marian_tpu.obs import TRACER, enabled\n"
            "def f():\n"
            "    sp = TRACER.start_span('x') if enabled() else None\n"
            "    try:\n"
            "        work()\n"
            "    finally:\n"
            "        if sp is not None:\n"
            "            TRACER.end(sp)\n", families=["span"])
        assert fs == []

    def test_escaped_span_skipped(self):
        """Returned / stored / passed-on spans have their lifetime owned
        elsewhere — out of local-analysis scope (server.handle_frame)."""
        for tail in ("    return sp\n",
                     "    self.sp = sp\n",
                     "    finish(sp)\n"):
            fs = lint_text(
                "from marian_tpu.obs import TRACER\n"
                "def f(self):\n"
                "    sp = TRACER.start_span('x')\n" + tail,
                families=["span"])
            assert fs == [], tail

    def test_attr_after_close_flagged(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    TRACER.end(sp)\n"
            "    sp.set_attrs(late=1)\n", families=["span"])
        assert "MT-SPAN-LATE" in rule_ids(fs)

    def test_attrs_subscript_after_close_flagged(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    TRACER.end(sp)\n"
            "    sp.attrs['late'] = 1\n", families=["span"])
        assert "MT-SPAN-LATE" in rule_ids(fs)

    def test_attr_before_close_ok(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    sp = TRACER.start_span('x')\n"
            "    sp.set_attrs(early=1)\n"
            "    TRACER.end(sp)\n", families=["span"])
        assert fs == []

    def test_with_span_cm_ok(self):
        fs = lint_text(
            "from marian_tpu.obs import TRACER\n"
            "def f():\n"
            "    with TRACER.span('x') as sp:\n"
            "        sp.set_attrs(k=1)\n", families=["span"])
        assert fs == []


# ---------------------------------------------------------------------------
# ownership (MT-OWN-*) — ISSUE 15
# ---------------------------------------------------------------------------

OWN_PREAMBLE = "class E:\n"


class TestOwnershipLeak:
    def lint(self, body):
        return lint_text(OWN_PREAMBLE + body, families=["ownership"])

    def test_acquired_never_released_flagged(self):
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 2)\n"
            "        self.work()\n")
        assert rule_ids(fs) == ["MT-OWN-LEAK"]
        assert "not released or transferred" in fs[0].message

    def test_release_on_every_path_clean(self):
        fs = self.lint(
            "    def f(self, ok):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 2)\n"
            "        if ok:\n"
            "            self.pool.release(owner)\n"
            "        else:\n"
            "            self.pool.release(owner)\n")
        assert fs == []

    def test_early_return_path_flagged(self):
        fs = self.lint(
            "    def f(self, ok):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 2)\n"
            "        if ok:\n"
            "            return None\n"
            "        self.pool.release(owner)\n")
        assert rule_ids(fs) == ["MT-OWN-LEAK"]

    def test_exception_edge_leak_flagged(self):
        # a later registered acquire can raise PoolExhausted while the
        # share's references are held — the exception edge leaks
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.share(owner, self.fulls)\n"
            "        self.pool.claim_extra(owner, 1)\n"
            "        self.pool.release(owner)\n")
        assert rule_ids(fs) == ["MT-OWN-LEAK"]
        assert "exception path" in fs[0].message

    def test_except_release_and_reraise_clean(self):
        # the engines' fork idiom: the handler gives the references
        # back before re-raising
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.share(owner, self.fulls)\n"
            "        try:\n"
            "            self.pool.claim_extra(owner, 1)\n"
            "        except PoolExhausted:\n"
            "            self.pool.release(owner)\n"
            "            raise\n"
            "        self.pool.release(owner)\n")
        assert fs == []

    def test_finally_release_clean(self):
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 1)\n"
            "        try:\n"
            "            self.step()\n"
            "        finally:\n"
            "            self.pool.release(owner)\n")
        assert fs == []

    def test_explicit_raise_while_held_flagged(self):
        fs = self.lint(
            "    def f(self, bad):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 1)\n"
            "        if bad:\n"
            "            raise ValueError('bad')\n"
            "        self.pool.release(owner)\n")
        assert rule_ids(fs) == ["MT-OWN-LEAK"]

    def test_inline_ok_suppresses(self):
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 2)  "
            "# mtlint: ok -- released by the loop below\n")
        assert fs == []

    def test_unbound_file_handle_flagged_with_form_clean(self):
        fs = lint_text(
            "def f(p):\n"
            "    fh = open(p)\n"
            "    return fh.read()\n", families=["ownership"])
        assert rule_ids(fs) == ["MT-OWN-LEAK"]
        fs = lint_text(
            "def f(p):\n"
            "    with open(p) as fh:\n"
            "        return fh.read()\n", families=["ownership"])
        assert fs == []
        fs = lint_text(
            "def f(p):\n"
            "    fh = open(p)\n"
            "    try:\n"
            "        return fh.read()\n"
            "    finally:\n"
            "        fh.close()\n", families=["ownership"])
        assert fs == []

    def test_nondaemon_thread_must_join_daemon_exempt(self):
        fs = lint_text(
            "import threading\n"
            "def f(w):\n"
            "    t = threading.Thread(target=w)\n"
            "    t.start()\n", families=["ownership"])
        assert rule_ids(fs) == ["MT-OWN-LEAK"]
        fs = lint_text(
            "import threading\n"
            "def f(w):\n"
            "    t = threading.Thread(target=w, daemon=True)\n"
            "    t.start()\n", families=["ownership"])
        assert fs == []
        fs = lint_text(
            "import threading\n"
            "def f(w):\n"
            "    t = threading.Thread(target=w)\n"
            "    t.start()\n"
            "    t.join()\n", families=["ownership"])
        assert fs == []


class TestOwnershipDouble:
    def lint(self, body):
        return lint_text(OWN_PREAMBLE + body, families=["ownership"])

    def test_double_release_flagged(self):
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 1)\n"
            "        self.pool.release(owner)\n"
            "        self.pool.release(owner)\n")
        assert rule_ids(fs) == ["MT-OWN-DOUBLE"]
        assert fs[0].line == 6        # the SECOND release

    def test_release_after_transfer_flagged(self):
        # the static mirror of KVPool.release's loud runtime error:
        # a transferred owner is gone
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 1)\n"
            "        self.pool.transfer(owner, self.dst)\n"
            "        self.pool.release(owner)\n")
        assert rule_ids(fs) == ["MT-OWN-DOUBLE"]

    def test_branch_exclusive_releases_clean(self):
        fs = self.lint(
            "    def f(self, ok):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 1)\n"
            "        if ok:\n"
            "            self.pool.release(owner)\n"
            "        else:\n"
            "            self.pool.transfer(owner, self.dst)\n")
        assert fs == []

    def test_loop_scoped_owner_cleanup_not_double(self):
        # the beam exception-cleanup shape: `owner` names a DIFFERENT
        # owner each iteration — releasing per iteration is not DOUBLE
        fs = self.lint(
            "    def f(self, claimed):\n"
            "        for owner, _ in claimed:\n"
            "            self.pool.release(owner)\n")
        assert fs == []


class TestOwnershipEscape:
    def lint(self, body):
        return lint_text(OWN_PREAMBLE + body, families=["ownership"])

    def test_store_into_self_flagged(self):
        fs = self.lint(
            "    def f(self):\n"
            "        ex = ThreadPoolExecutor(max_workers=2)\n"
            "        self._ex = ex\n")
        assert rule_ids(fs) == ["MT-OWN-ESCAPE"]

    def test_store_with_transfers_annotation_clean(self):
        fs = self.lint(
            "    def f(self):\n"
            "        ex = ThreadPoolExecutor(max_workers=2)\n"
            "        self._ex = ex  # mtlint: transfers -- closed in "
            "close()\n")
        assert fs == []

    def test_direct_ctor_store_flagged_and_annotatable(self):
        fs = self.lint(
            "    def f(self):\n"
            "        self._ex = ThreadPoolExecutor(max_workers=2)\n")
        assert rule_ids(fs) == ["MT-OWN-ESCAPE"]
        fs = self.lint(
            "    def f(self):\n"
            "        self._ex = ThreadPoolExecutor(max_workers=2)  "
            "# mtlint: transfers -- shut down in close()\n")
        assert fs == []

    def test_closure_capture_flagged(self):
        fs = self.lint(
            "    def f(self, submit):\n"
            "        ex = ThreadPoolExecutor(max_workers=2)\n"
            "        submit(lambda: ex.submit(self.work))\n")
        assert rule_ids(fs) == ["MT-OWN-ESCAPE"]
        assert "closure" in fs[0].message

    def test_shutdown_before_exit_clean(self):
        fs = self.lint(
            "    def f(self):\n"
            "        ex = ThreadPoolExecutor(max_workers=2)\n"
            "        try:\n"
            "            self.work(ex.submit)\n"
            "        finally:\n"
            "            ex.shutdown()\n")
        assert fs == []


class TestOwnershipTransfer:
    def lint(self, body):
        return lint_text(OWN_PREAMBLE + body, families=["ownership"])

    def test_exit_held_for_caller_owner_flagged(self):
        # the _claim_pages wrapper shape: acquired for the caller's
        # owner, still held at return
        fs = self.lint(
            "    def get(self, key):\n"
            "        return self.pool.claim(key, 2)\n")
        assert rule_ids(fs) == ["MT-OWN-TRANSFER"]
        assert "owns: caller" in fs[0].message

    def test_owns_caller_annotation_clean(self):
        fs = self.lint(
            "    def get(self, key):  # owns: caller -- joins the "
            "claims table\n"
            "        return self.pool.claim(key, 2)\n")
        assert fs == []

    def test_release_of_callers_handle_flagged(self):
        # the _evict shape: releasing what the caller handed in
        fs = self.lint(
            "    def drop(self, key):\n"
            "        self.pool.release(key)\n")
        assert rule_ids(fs) == ["MT-OWN-TRANSFER"]
        assert "owns: callee" in fs[0].message

    def test_owns_callee_annotation_clean(self):
        fs = self.lint(
            "    def drop(self, key):  # owns: callee -- the row "
            "exit\n"
            "        self.pool.release(key)\n")
        assert fs == []

    def test_retable_reorder_diff_no_false_positive(self):
        """The beam reorder's drain-and-swap/transfer idiom verbatim
        (condensed): transient hold owner, exception-safe claim,
        retable incref/decref diffs on table-held owners, final
        release of the hold — must be CLEAN."""
        fs = self.lint(
            "    def reorder(self, key, rows):\n"
            "        tmp = ('cow', key)\n"
            "        self.pool.share(tmp, self.aliased, row_cap=False)\n"
            "        try:\n"
            "            fresh = self.pool.claim_extra(tmp, 2,\n"
            "                                          row_cap=False)\n"
            "        except PoolExhausted:\n"
            "            self.pool.release(tmp)\n"
            "            raise\n"
            "        for slot, row in rows:\n"
            "            self.pool.retable(self.owner_of(key, slot), row)\n"
            "        self.pool.release(tmp)\n")
        assert fs == []

    def test_prefix_adoption_path_no_false_positive(self):
        """The prefix-cache adoption shape: transfer-or-release under
        the `# owns: callee` annotation — must be CLEAN."""
        fs = self.lint(
            "    def finish(self, key, row_key):  # owns: callee -- "
            "adoption\n"
            "        if self.prefix.adopt(self.pool, key, row_key,\n"
            "                             [], 't') == 0:\n"
            "            self.pool.release(row_key)\n")
        assert fs == []

    def test_transfer_of_local_then_done_clean(self):
        fs = self.lint(
            "    def f(self):\n"
            "        owner = object()\n"
            "        self.pool.claim(owner, 1)\n"
            "        self.pool.transfer(owner, self.cache_owner)\n")
        assert fs == []


# ---------------------------------------------------------------------------
# suppression, config, baseline, CLI, gate
# ---------------------------------------------------------------------------

class TestFaultHygiene:
    """MT-FAULT-* (fault_hygiene.py — ISSUE 4): every fault_point() call
    site uses a declared catalog name, and every declared point is
    exercised by at least one test (mirrors the metrics-hygiene shape)."""

    CATALOG = ("from typing import Dict\n"
               "CATALOG: Dict[str, str] = {\n"
               "    'ckpt.commit': 'the commit point',\n"
               "    'data.batch.next': 'pipeline',\n"
               "}\n")
    SITES = ("from marian_tpu.common import faultpoints as fp\n"
             "def save():\n"
             "    fp.fault_point('ckpt.commit')\n")

    def _lint(self, tmp_path, files, tests=None):
        cfg = Config(root=tmp_path)
        tdir = tmp_path / "tests"
        tdir.mkdir(exist_ok=True)
        for name, content in (tests or {}).items():
            (tdir / name).write_text(content, encoding="utf-8")
        srcs = [Source(tmp_path / rel, rel, text=code)
                for rel, code in files.items()]
        rule = next(r for r in all_rules() if r.family == "faults")
        return rule.check_project(srcs, cfg)

    def test_unknown_call_site_flagged(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/x.py":
                "def f():\n    fault_point('no.such.name')\n"},
            tests={"test_x.py": "ckpt.commit data.batch.next"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNKNOWN"]
        assert "no.such.name" in fs[0].message

    def test_untested_call_site_flagged(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES},
            tests={"test_x.py": "only data.batch.next is exercised"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNTESTED"]
        assert "ckpt.commit" in fs[0].message
        assert fs[0].path == "marian_tpu/ckpt.py"   # anchored at the site

    def test_catalog_entry_without_site_or_test_flagged(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES},
            tests={"test_x.py": "arms ckpt.commit=kill@2"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNTESTED"]
        assert "data.batch.next" in fs[0].message
        assert fs[0].path.endswith("faultpoints.py")  # anchored at catalog

    def test_fully_covered_tree_is_clean(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES,
            "marian_tpu/data.py":
                "from marian_tpu.common import faultpoints as fp\n"
                "def g():\n    fp.fault_point('data.batch.next')\n"},
            tests={"test_x.py":
                   "MARIAN_FAULTS='ckpt.commit=kill@2,"
                   "data.batch.next=fail'"})
        assert fs == []

    def test_name_in_comment_is_not_coverage(self, tmp_path):
        """Only string constants in test files count as exercising a
        fault point — '# we deliberately skip ckpt.commit' must not
        satisfy the rule."""
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES},
            tests={"test_x.py":
                   "# we deliberately do not drill ckpt.commit\n"
                   "X = 'data.batch.next=fail'\n"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNTESTED"]
        assert "ckpt.commit" in fs[0].message

    def test_snippet_without_registry_is_silent(self, tmp_path):
        """Trees with no fault registry at all (every other rule's
        snippet tests) must not drown in fault findings."""
        fs = self._lint(tmp_path,
                        {"marian_tpu/ops/x.py": "def f():\n    pass\n"})
        assert fs == []


# ---------------------------------------------------------------------------
# jit compile-cache hygiene (MT-JIT-*, ISSUE 17)
# ---------------------------------------------------------------------------

def _jit_project(files: dict):
    """Run the project-scope jit rule over an in-memory multi-file tree
    (rel -> code); returns unsuppressed findings."""
    cfg = Config(root=ROOT)
    srcs = [Source(ROOT / rel, rel, text=code)
            for rel, code in files.items()]
    rule = next(r for r in all_rules() if r.family == "jit")
    by = {s.rel: s for s in srcs}
    return [f for f in rule.check_project(srcs, cfg)
            if not by[f.path].suppressed(f)]


class TestJitClosure:
    REL = "marian_tpu/ops/snippet.py"

    def test_self_attr_read_in_traced_body_flagged(self):
        fs = lint_text(
            "import jax\n"
            "class Engine:\n"
            "    def make(self):\n"
            "        return jax.jit(lambda p: self.model.step(p))\n",
            rel=self.REL, families=["jit"])
        assert rule_ids(fs) == ["MT-JIT-CLOSURE-VARYING"]
        assert "self.model" in fs[0].message

    def test_hoisted_local_clean(self):
        fs = lint_text(
            "import jax\n"
            "class Engine:\n"
            "    def make(self):\n"
            "        model = self.model\n"
            "        return jax.jit(lambda p: model.step(p))\n",
            rel=self.REL, families=["jit"])
        assert fs == []

    def test_capture_rebound_after_creation_flagged(self):
        fs = lint_text(
            "import jax\n"
            "def make():\n"
            "    k = 1\n"
            "    fn = jax.jit(lambda x: x + k)\n"
            "    k = 2\n"
            "    return fn\n",
            rel=self.REL, families=["jit"])
        assert rule_ids(fs) == ["MT-JIT-CLOSURE-VARYING"]
        assert "'k'" in fs[0].message


class TestJitStaticUnbounded:
    REL = "marian_tpu/ops/snippet.py"

    FACTORY = ("import jax\n"
               "ROW_BUCKETS = (1, 2, 4)\n"
               "def make_step(rb):{ann}\n"
               "    def step(x):\n"
               "        return x[:rb]\n"
               "    return jax.jit(step)\n")

    def test_unannotated_factory_axis_flagged(self):
        fs = lint_text(self.FACTORY.format(ann=""),
                       rel=self.REL, families=["jit"])
        assert rule_ids(fs) == ["MT-JIT-STATIC-UNBOUNDED"]
        assert "make_step(rb)" in fs[0].message

    def test_annotated_factory_clean(self):
        fs = lint_text(self.FACTORY.format(ann="  # buckets: ROW_BUCKETS"),
                       rel=self.REL, families=["jit"])
        assert fs == []

    def test_unknown_registry_name_flagged(self):
        fs = lint_text(self.FACTORY.format(ann="  # buckets: NO_SUCH_TABLE"),
                       rel=self.REL, families=["jit"])
        assert rule_ids(fs) == ["MT-JIT-STATIC-UNBOUNDED"]
        assert "NO_SUCH_TABLE" in fs[0].message

    def test_virtual_registry_accepted(self):
        fs = lint_text(self.FACTORY.format(ann="  # buckets: POW2"),
                       rel=self.REL, families=["jit"])
        assert fs == []

    def test_static_float_literal_at_call_site_flagged(self):
        fs = lint_text(
            "import jax\n"
            "def step(x, n):\n"
            "    return x\n"
            "step = jax.jit(step, static_argnums=(1,))\n"
            "def drive(z):\n"
            "    return step(z, 2.5)\n",
            rel=self.REL, families=["jit"])
        assert rule_ids(fs) == ["MT-JIT-STATIC-UNBOUNDED"]

    def test_bucket_derived_static_clean(self):
        fs = lint_text(
            "import jax\n"
            "from marian_tpu.ops.pallas.kv_pool import ROW_BUCKETS, "
            "bucket_rows\n"
            "def step(x, n):\n"
            "    return x\n"
            "step = jax.jit(step, static_argnums=(1,))\n"
            "def drive(z, rows):\n"
            "    return step(z, bucket_rows(rows, ROW_BUCKETS))\n",
            rel=self.REL, families=["jit"])
        assert fs == []


class TestJitWeakType:
    REL = "marian_tpu/ops/snippet.py"

    def test_traced_scalar_literal_flagged(self):
        fs = lint_text(
            "import jax\n"
            "def step(x, n):\n"
            "    return x\n"
            "step = jax.jit(step, static_argnums=(1,))\n"
            "def drive(n):\n"
            "    return step(1.5, n)\n",
            rel=self.REL, families=["jit"])
        assert rule_ids(fs) == ["MT-JIT-WEAKTYPE"]

    def test_wrapped_scalar_clean(self):
        fs = lint_text(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def step(x, n):\n"
            "    return x\n"
            "step = jax.jit(step, static_argnums=(1,))\n"
            "def drive(n):\n"
            "    return step(jnp.asarray(1.5), n)\n",
            rel=self.REL, families=["jit"])
        assert fs == []


class TestJitUnwarmed:
    ENGINE = ("import jax\n"
              "class Eng:\n"
              "    def decode_texts(self, lines):\n"
              "        fn = jax.jit(lambda x: x)\n"
              "        return fn(lines)\n")
    SERVING = ("def handle(engine):\n"
               "    return engine.decode_texts(['x'])\n")
    WARMUP = ("def warm(executor):\n"
              "    return executor.decode_texts(['x'])\n")

    def test_serving_reachable_unwarmed_flagged(self):
        fs = _jit_project({
            "marian_tpu/translator/snip_eng.py": self.ENGINE,
            "marian_tpu/serving/snip_srv.py": self.SERVING})
        assert "MT-JIT-UNWARMED" in rule_ids(fs)
        unw = [f for f in fs if f.rule == "MT-JIT-UNWARMED"]
        assert len(unw) == 1 and "decode_texts" in unw[0].message

    def test_warmup_covered_site_clean(self):
        fs = _jit_project({
            "marian_tpu/translator/snip_eng.py": self.ENGINE,
            "marian_tpu/serving/snip_srv.py": self.SERVING,
            "marian_tpu/serving/lifecycle/warmup.py": self.WARMUP})
        assert [f for f in fs if f.rule == "MT-JIT-UNWARMED"] == []

    def test_site_not_on_serving_path_clean(self):
        fs = _jit_project({
            "marian_tpu/translator/snip_eng.py": self.ENGINE})
        assert [f for f in fs if f.rule == "MT-JIT-UNWARMED"] == []


class TestSuppression:
    def test_ok_comment(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))  # mtlint: ok -- reason here\n",
            families=["dtype"])
        assert fs == []

    def test_disable_family_prefix(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))  # mtlint: disable=MT-DTYPE\n",
            families=["dtype"])
        assert fs == []

    def test_disable_other_rule_does_not_suppress(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))  # mtlint: disable=MT-TRACE-COND\n",
            families=["dtype"])
        assert rule_ids(fs) == ["MT-DTYPE-ARRAY"]


class TestConfig:
    def test_toml_subset_reader(self):
        tables = _read_toml_tables(
            '[tool.mtlint]\nexclude = ["a/b"]\n'
            '[tool.mtlint.rules.dtype]\ndirs = [\n  "x/y",\n  "z",\n]\n'
            'enabled = true\n'
            '[other.section]\nk = "v"  # comment\n')
        assert tables["tool.mtlint"]["exclude"] == ["a/b"]
        assert tables["tool.mtlint.rules.dtype"]["dirs"] == ["x/y", "z"]
        assert tables["tool.mtlint.rules.dtype"]["enabled"] is True

    def test_pyproject_loaded(self):
        cfg = Config.load(ROOT)
        assert "marian_tpu/ops" in cfg.rule_dirs["dtype"]
        assert "marian_tpu/serving" in cfg.rule_dirs["guarded-by"]
        # ISSUE 12 pin: the paged engines + prefix cache live in
        # translator/ — their locks must stay inside the race gate
        assert "marian_tpu/translator" in cfg.rule_dirs["guarded-by"]
        assert cfg.excluded("marian_tpu/analysis/core.py")

    def test_prefix_cache_lock_discovered(self):
        """ISSUE 12 satellite: the static analysis discovers the new
        PrefixCache._lock (lockdep witness + lock_order.dot depend on
        it) and the committed graph names it."""
        dot = (ROOT / "docs" / "lock_order.dot").read_text()
        assert '"PrefixCache._lock"' in dot
        src = (ROOT / "marian_tpu" / "translator"
               / "prefix_cache.py").read_text()
        assert 'lockdep.make_lock("PrefixCache._lock")' in src
        assert "guarded-by: _lock" in src

    def test_every_advertised_rule_id_has_an_owner(self):
        families = {r.family for r in all_rules()}
        assert families == {"trace-safety", "host-sync", "donation",
                            "dtype", "guarded-by", "metrics", "faults",
                            "lock-order", "lock-blocking", "guard-escape",
                            "span", "ownership", "jit"}


BAD_OPS = ("import jax.numpy as jnp\n"
           "def f(n):\n"
           "    return jnp.zeros((n, n))\n")


def _mini_tree(tmp_path: Path) -> Path:
    (tmp_path / "pyproject.toml").write_text(
        "[tool.mtlint]\n", encoding="utf-8")
    pkg = tmp_path / "marian_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(BAD_OPS, encoding="utf-8")
    return tmp_path


class TestBaseline:
    def test_round_trip(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        findings = run_lint([root / "marian_tpu"], cfg)
        assert rule_ids(findings) == ["MT-DTYPE-ARRAY"]
        bl_path = root / "baseline.json"
        write_baseline(findings, bl_path)
        new, old = apply_baseline(
            run_lint([root / "marian_tpu"], cfg), load_baseline(bl_path))
        assert new == [] and len(old) == 1

    def test_baseline_survives_line_shift(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        bl_path = root / "baseline.json"
        write_baseline(run_lint([root / "marian_tpu"], cfg), bl_path)
        bad = root / "marian_tpu" / "ops" / "bad.py"
        bad.write_text("import jax.numpy as jnp\n\n\n" + BAD_OPS.split(
            "\n", 1)[1], encoding="utf-8")
        new, old = apply_baseline(
            run_lint([root / "marian_tpu"], cfg), load_baseline(bl_path))
        assert new == [] and len(old) == 1

    def test_second_identical_violation_not_absorbed(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        bl_path = root / "baseline.json"
        write_baseline(run_lint([root / "marian_tpu"], cfg), bl_path)
        bad = root / "marian_tpu" / "ops" / "bad.py"
        bad.write_text(BAD_OPS + "def g(n):\n"
                       "    return jnp.zeros((n, n))\n", encoding="utf-8")
        new, old = apply_baseline(
            run_lint([root / "marian_tpu"], cfg), load_baseline(bl_path))
        assert len(new) == 1 and len(old) == 1


class TestCli:
    def test_exit_codes_and_update(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        argv = [str(root / "marian_tpu"), "--root", str(root),
                "--baseline", str(root / "bl.json")]
        assert mtlint_main(argv) == 1          # findings, no baseline yet
        assert mtlint_main(argv + ["--update-baseline"]) == 0
        assert mtlint_main(argv) == 0          # clean against baseline
        capsys.readouterr()

    def test_json_format(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--format", "json", "--no-baseline"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert payload["findings"][0]["rule"] == "MT-DTYPE-ARRAY"
        assert payload["findings"][0]["path"] == "marian_tpu/ops/bad.py"

    def test_sarif_format(self, tmp_path, capsys):
        """ISSUE 15 satellite: SARIF 2.1.0 output — the shape GitHub
        code scanning ingests to render findings as inline annotations
        (ruleId + physicalLocation with 1-based startColumn, rule
        metadata carrying the owning family)."""
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--format", "sarif", "--no-baseline"])
        log = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert log["version"] == "2.1.0"
        run = log["runs"][0]
        assert run["tool"]["driver"]["name"] == "mtlint"
        results = run["results"]
        assert results, "findings must surface as SARIF results"
        r0 = results[0]
        assert r0["ruleId"] == "MT-DTYPE-ARRAY"
        loc = r0["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "marian_tpu/ops/bad.py"
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1      # SARIF is 1-based
        declared = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {r["ruleId"] for r in results} <= declared
        assert run["invocations"][0]["executionSuccessful"] is True

    def test_sarif_clean_tree_and_parse_errors(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        (root / "marian_tpu" / "ops" / "bad.py").write_text(
            "x = 1\n", encoding="utf-8")
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--format", "sarif", "--no-baseline"])
        log = json.loads(capsys.readouterr().out)
        assert rc == 0 and log["runs"][0]["results"] == []
        # a parse error must fail the invocation, not vanish
        (root / "marian_tpu" / "ops" / "broken.py").write_text(
            "def f(:\n", encoding="utf-8")
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--format", "sarif", "--no-baseline"])
        out = capsys.readouterr().out
        log = json.loads(out)
        inv = log["runs"][0]["invocations"][0]
        assert rc == 2
        assert inv["executionSuccessful"] is False
        assert inv["toolExecutionNotifications"]

    def test_sarif_respects_baseline(self, tmp_path, capsys):
        """Baselined findings stay out of the SARIF results — CI
        annotations show only NEW debt, matching text/json verdicts."""
        root = _mini_tree(tmp_path)
        argv = [str(root / "marian_tpu"), "--root", str(root),
                "--baseline", str(root / "bl.json")]
        assert mtlint_main(argv + ["--update-baseline"]) == 0
        capsys.readouterr()
        rc = mtlint_main(argv + ["--format", "sarif"])
        log = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert log["runs"][0]["results"] == []

    def test_rules_filter(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--rules", "guarded-by", "--no-baseline"])
        capsys.readouterr()
        assert rc == 0

    def test_script_entry_point(self, tmp_path):
        root = _mini_tree(tmp_path)
        proc = subprocess.run(
            [sys.executable, str(ROOT / "scripts" / "mtlint.py"),
             str(root / "marian_tpu"), "--root", str(root),
             "--no-baseline", "--format", "json"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        assert json.loads(proc.stdout)["findings"]


class TestTier1Gate:
    """THE gate: the real tree must be clean against the checked-in
    baseline. A finding here means new code tripped a rule — fix it (or,
    for a deliberate pattern, annotate `# mtlint: ok -- reason`); do not
    grow the baseline."""

    def test_tree_clean_against_baseline(self):
        cfg = Config.load(ROOT)
        errors = []
        findings = run_lint([ROOT / "marian_tpu"], cfg, errors=errors)
        assert errors == [], f"mtlint could not parse: {errors}"
        baseline = load_baseline(ROOT / "marian_tpu" / "analysis"
                                 / "baseline.json")
        assert baseline, "checked-in baseline missing or empty"
        new, _old = apply_baseline(findings, baseline)
        assert new == [], (
            "mtlint found new violations (run `python -m "
            "marian_tpu.analysis` for details; see "
            "docs/STATIC_ANALYSIS.md):\n"
            + "\n".join(f.render() for f in new))

    def test_baseline_not_stale(self):
        """Every baseline entry still matches a real finding — entries
        whose code was fixed must be removed (--update-baseline), keeping
        the debt ledger honest."""
        cfg = Config.load(ROOT)
        findings = run_lint([ROOT / "marian_tpu"], cfg)
        current = {f.key() for f in findings}
        baseline = load_baseline(ROOT / "marian_tpu" / "analysis"
                                 / "baseline.json")
        stale = [k for k in baseline if k not in current]
        assert stale == [], (
            f"baseline entries no longer match any finding (fixed code — "
            f"regenerate with scripts/mtlint.py --update-baseline): {stale}")


class TestHostSyncNestedDefs:
    REL = "marian_tpu/training/snippet.py"

    def test_nested_sync_does_not_clear_outer_timer(self):
        fs = lint_text(
            "import time, jax\n"
            "def bench(fn, x):\n"
            "    def _later(y):\n"
            "        return jax.block_until_ready(y)\n"
            "    t0 = time.perf_counter()\n"
            "    y = fn(x)\n"
            "    dt = time.perf_counter() - t0\n"
            "    return y, dt, _later\n", rel=self.REL,
            families=["host-sync"])
        assert rule_ids(fs) == ["MT-SYNC-TIMER"]

    def test_nested_timer_not_attributed_to_outer(self):
        fs = lint_text(
            "import time\n"
            "def outer(fn, x):\n"
            "    t0 = time.perf_counter()\n"
            "    def cb():\n"
            "        return time.perf_counter()\n"
            "    y = fn(x)\n"
            "    return y, t0, cb\n", rel=self.REL,
            families=["host-sync"])
        assert fs == []


# ---------------------------------------------------------------------------
# lock-order (MT-LOCK-ORDER / MT-LOCK-NAME) — ISSUE 6
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_opposite_orders_cycle(self):
        fs = lint_text(
            "import threading\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._l1 = threading.Lock()\n"
            "        self._l2 = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._l1:\n"
            "            with self._l2:\n"
            "                pass\n"
            "    def rev(self):\n"
            "        with self._l2:\n"
            "            with self._l1:\n"
            "                pass\n", families=["lock-order"])
        assert rule_ids(fs) == ["MT-LOCK-ORDER"]
        assert "A._l1" in fs[0].message and "A._l2" in fs[0].message

    def test_cycle_through_call_chain(self):
        # fwd holds _x and CALLS _inner which takes _y (edge x->y only
        # via interprocedural held-set propagation); rev takes y then x
        fs = lint_text(
            "import threading\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self._x = threading.Lock()\n"
            "        self._y = threading.Lock()\n"
            "    def fwd(self):\n"
            "        with self._x:\n"
            "            self._inner()\n"
            "    def _inner(self):\n"
            "        with self._y:\n"
            "            pass\n"
            "    def rev(self):\n"
            "        with self._y:\n"
            "            with self._x:\n"
            "                pass\n", families=["lock-order"])
        assert rule_ids(fs) == ["MT-LOCK-ORDER"]
        assert "B.fwd" in fs[0].message    # the example holder chain

    def test_consistent_order_clean(self):
        fs = lint_text(
            "import threading\n"
            "class A:\n"
            "    def __init__(self):\n"
            "        self._l1 = threading.Lock()\n"
            "        self._l2 = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._l1:\n"
            "            with self._l2:\n"
            "                pass\n"
            "    def g(self):\n"
            "        with self._l1:\n"
            "            with self._l2:\n"
            "                pass\n", families=["lock-order"])
        assert fs == []

    def test_reentrant_rlock_no_self_edge(self):
        # the SwapController pattern: a public method re-enters a helper
        # that takes the same RLock — reentrancy, not a cycle
        fs = lint_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n", families=["lock-order"])
        assert fs == []

    def test_reentrant_reacquire_under_other_lock_clean(self):
        # outer holds _lock (RLock) then _aux and calls a helper that
        # re-enters _lock: the re-acquire cannot block, so no
        # _aux->_lock edge — which with the real _lock->_aux would be a
        # false static deadlock on the legal SwapController re-entry
        fs = lint_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "        self._aux = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            with self._aux:\n"
            "                self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n", families=["lock-order"])
        assert fs == []

    def test_plain_lock_self_reacquire_flagged(self):
        # re-entry is only safe for an RLock: a plain Lock re-acquired
        # through a call chain that already holds it can never succeed
        fs = lint_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._lock:\n"
            "            self.inner()\n"
            "    def inner(self):\n"
            "        with self._lock:\n"
            "            pass\n", families=["lock-order"])
        assert rule_ids(fs) == ["MT-LOCK-ORDER"]
        assert "self-deadlock" in fs[0].message
        assert "C.outer" in fs[0].message  # the example holder chain

    def test_plain_lock_nested_reacquire_flagged(self):
        fs = lint_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            with self._lock:\n"
            "                pass\n", families=["lock-order"])
        assert rule_ids(fs) == ["MT-LOCK-ORDER"]
        assert "self-deadlock" in fs[0].message

    def test_lockdep_name_mismatch(self):
        fs = lint_text(
            "from marian_tpu.common import lockdep\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = lockdep.make_lock('Wrong.name')\n",
            families=["lock-order"])
        assert rule_ids(fs) == ["MT-LOCK-NAME"]
        assert "'C._lock'" in fs[0].message

    def test_lockdep_name_correct_clean(self):
        fs = lint_text(
            "from marian_tpu.common import lockdep\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = lockdep.make_lock('C._lock')\n",
            families=["lock-order"])
        assert fs == []

    def test_same_class_name_in_two_modules_is_ambiguous(self):
        # lock identities are `Class.attr` with no module qualifier: two
        # same-named classes would silently merge into ONE node in the
        # order graph and the witness (false cycles, or a real runtime
        # edge vacuously whitelisted) — flagged at the later declaration
        code = ("import threading\n"
                "class Dup:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n")
        srcs = [Source(ROOT / "marian_tpu/a_mod.py", "marian_tpu/a_mod.py",
                       text=code),
                Source(ROOT / "marian_tpu/b_mod.py", "marian_tpu/b_mod.py",
                       text=code)]
        rule = next(r for r in all_rules() if r.family == "lock-order")
        fs = rule.check_project(srcs, Config(root=ROOT))
        assert [f.rule for f in fs] == ["MT-LOCK-NAME"]
        assert "ambiguous lock identity 'Dup._lock'" in fs[0].message
        assert fs[0].path == "marian_tpu/b_mod.py"  # first declarant wins


# ---------------------------------------------------------------------------
# lock-blocking (MT-LOCK-BLOCKING) — ISSUE 6
# ---------------------------------------------------------------------------

LOCK_PREAMBLE = ("import threading, time\n"
                 "class C:\n"
                 "    def __init__(self):\n"
                 "        self._lock = threading.Lock()\n")


class TestLockBlocking:
    def test_sleep_under_lock(self):
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n", families=["lock-blocking"])
        assert rule_ids(fs) == ["MT-LOCK-BLOCKING"]
        assert "C._lock" in fs[0].message

    def test_blocking_reachable_through_callee(self):
        # the warmup-off-the-serving-path shape: the blocking call is in
        # a helper; only the interprocedural held-set sees it
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self):\n"
            "        with self._lock:\n"
            "            self._slow()\n"
            "    def _slow(self):\n"
            "        time.sleep(1)\n", families=["lock-blocking"])
        assert rule_ids(fs) == ["MT-LOCK-BLOCKING"]
        assert "C.f" in fs[0].message     # example holder chain

    def test_sleep_after_release_clean(self):
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self):\n"
            "        with self._lock:\n"
            "            pass\n"
            "        time.sleep(1)\n", families=["lock-blocking"])
        assert fs == []

    def test_context_manager_before_lock_item_clean(self):
        # `with open(p) as f, self._lock:` opens the file BEFORE the
        # lock is acquired — not a blocking op under the lock
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self, p):\n"
            "        with open(p) as f, self._lock:\n"
            "            pass\n", families=["lock-blocking"])
        assert fs == []

    def test_context_manager_after_lock_item_flagged(self):
        # reversed item order: the open really does run under the lock
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self, p):\n"
            "        with self._lock, open(p) as f:\n"
            "            pass\n", families=["lock-blocking"])
        assert rule_ids(fs) == ["MT-LOCK-BLOCKING"]
        assert "file open" in fs[0].message

    def test_untimed_future_result_under_lock(self):
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self, fut):\n"
            "        with self._lock:\n"
            "            return fut.result()\n", families=["lock-blocking"])
        assert rule_ids(fs) == ["MT-LOCK-BLOCKING"]

    def test_result_with_timeout_clean(self):
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self, fut):\n"
            "        with self._lock:\n"
            "            return fut.result(timeout=5)\n",
            families=["lock-blocking"])
        assert fs == []

    def test_thread_target_does_not_inherit_lock(self):
        # spawn edge: the worker runs on its own thread where the
        # spawner's lock is NOT held
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self):\n"
            "        with self._lock:\n"
            "            threading.Thread(target=self._worker).start()\n"
            "    def _worker(self):\n"
            "        time.sleep(1)\n", families=["lock-blocking"])
        assert fs == []

    def test_awaited_call_exempt(self):
        fs = lint_text(
            LOCK_PREAMBLE +
            "    async def f(self, ev):\n"
            "        with self._lock:\n"
            "            await ev.wait()\n", families=["lock-blocking"])
        assert fs == []

    def test_inline_ok_acknowledgment(self):
        fs = lint_text(
            LOCK_PREAMBLE +
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)  # mtlint: ok -- deliberate drill\n",
            families=["lock-blocking"])
        assert fs == []


# ---------------------------------------------------------------------------
# guard-escape (MT-GUARD-ESCAPE) — ISSUE 6
# ---------------------------------------------------------------------------

ESCAPE_REL = "marian_tpu/serving/snippet.py"
ESCAPE_PREAMBLE = ("import threading\n"
                   "class D:\n"
                   "    def __init__(self):\n"
                   "        self._lock = threading.Lock()\n"
                   "        self._pending = {}   # guarded-by: _lock\n")


class TestGuardEscape:
    def lint(self, body):
        return lint_text(ESCAPE_PREAMBLE + body, rel=ESCAPE_REL,
                         families=["guard-escape"])

    def test_returning_guarded_container(self):
        fs = self.lint(
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return self._pending\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]
        assert "returns the guarded container" in fs[0].message

    def test_returning_copy_clean(self):
        fs = self.lint(
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return dict(self._pending)\n")
        assert fs == []

    def test_alias_outliving_with(self):
        fs = self.lint(
            "    def peek(self):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        return len(snap)\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]
        assert "aliases the guarded container" in fs[0].message

    def test_alias_of_copy_clean(self):
        fs = self.lint(
            "    def peek(self):\n"
            "        with self._lock:\n"
            "            snap = dict(self._pending)\n"
            "        return len(snap)\n")
        assert fs == []

    def test_alias_used_only_inside_with_clean(self):
        fs = self.lint(
            "    def peek(self):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "            return len(snap)\n")
        assert fs == []

    def test_drain_and_swap_clean(self):
        # the standard flush idiom: detach under the lock, then work on
        # the now-exclusively-owned container without holding it
        fs = self.lint(
            "    def flush(self):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "            self._pending = {}\n"
            "        return len(snap)\n")
        assert fs == []

    def test_conditional_swap_still_flagged(self):
        # a rebind buried in an if-branch does not dominate the with's
        # exit: on the other path the alias is still the live container
        fs = self.lint(
            "    def flush(self, really):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "            if really:\n"
            "                self._pending = {}\n"
            "        return len(snap)\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]

    def test_swap_before_alias_still_flagged(self):
        # rebound FIRST, the alias points at the NEW, still-shared dict
        fs = self.lint(
            "    def flush(self):\n"
            "        with self._lock:\n"
            "            self._pending = {}\n"
            "            snap = self._pending\n"
            "        return len(snap)\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]

    def test_alias_reused_under_reacquired_lock_clean(self):
        # release-then-reacquire: the post-with read happens inside a
        # later with on the SAME lock — protected, same exemption the
        # closure path grants
        fs = self.lint(
            "    def poke(self):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        with self._lock:\n"
            "            return len(snap)\n")
        assert fs == []

    def test_alias_rebound_before_use_clean(self):
        fs = self.lint(
            "    def peek(self):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        snap = {}\n"
            "        return len(snap)\n")
        assert fs == []

    def test_augassign_on_alias_flagged(self):
        # `snap |= {...}` has a Store-ctx target but mutates the live
        # container in place — a use, not a detaching rebind
        fs = self.lint(
            "    def grow(self):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        snap |= {'k': 1}\n"
            "        return len(snap)\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]

    def test_conditional_post_with_rebind_still_flagged(self):
        # a rebind inside an if-branch does not dominate the later read:
        # on the flag-false path `snap` is still the live container
        fs = self.lint(
            "    def peek(self, flag):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        if flag:\n"
            "            snap = {}\n"
            "        return len(snap)\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]

    def test_rebind_in_one_arm_read_in_other_flagged(self):
        # an if-body rebind does not cover the orelse read: they are
        # mutually exclusive arms of the same branch
        fs = self.lint(
            "    def peek(self, flag):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        if flag:\n"
            "            snap = {}\n"
            "        else:\n"
            "            return len(snap)\n"
            "        return 0\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]

    def test_read_dominated_by_branch_rebind_clean(self):
        # the read in the SAME branch as the rebind is covered by it
        fs = self.lint(
            "    def peek(self, flag):\n"
            "        with self._lock:\n"
            "            snap = self._pending\n"
            "        if flag:\n"
            "            snap = {}\n"
            "            return len(snap)\n"
            "        return 0\n")
        assert fs == []

    def test_closure_capture_under_lock(self):
        fs = self.lint(
            "    def defer(self, submit):\n"
            "        with self._lock:\n"
            "            submit(lambda: len(self._pending))\n")
        assert rule_ids(fs) == ["MT-GUARD-ESCAPE"]
        assert "captured by a closure" in fs[0].message

    def test_closure_retaking_lock_clean(self):
        fs = self.lint(
            "    def defer(self, submit):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                with self._lock:\n"
            "                    return len(self._pending)\n"
            "            submit(cb)\n")
        assert fs == []

    def test_scalar_snapshot_clean(self):
        # returning an int/bool under the lock is a value copy, not a
        # shared mutable escaping
        fs = lint_text(
            "import threading\n"
            "class D:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._count = 0   # guarded-by: _lock\n"
            "    def count(self):\n"
            "        with self._lock:\n"
            "            return self._count\n", rel=ESCAPE_REL,
            families=["guard-escape"])
        assert fs == []


# ---------------------------------------------------------------------------
# the incremental result cache (cli --changed / --cache) — ISSUE 6
# ---------------------------------------------------------------------------

class TestIncrementalCache:
    def test_unchanged_file_served_from_cache(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        cache = load_result_cache(tmp_path / "c.json", cfg)
        first = run_lint([root / "marian_tpu"], cfg, cache=cache)
        assert rule_ids(first) == ["MT-DTYPE-ARRAY"]
        # poison the cached verdict: a hit must come back verbatim,
        # which proves the file was NOT re-analyzed
        cache["files"]["marian_tpu/ops/bad.py"]["findings"][0][
            "message"] = "FROM-THE-CACHE"
        second = run_lint([root / "marian_tpu"], cfg, cache=cache)
        assert [f.message for f in second] == ["FROM-THE-CACHE"]

    def test_changed_file_reanalyzed(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        cache = load_result_cache(tmp_path / "c.json", cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        cache["files"]["marian_tpu/ops/bad.py"]["findings"][0][
            "message"] = "FROM-THE-CACHE"
        bad = root / "marian_tpu" / "ops" / "bad.py"
        bad.write_text(BAD_OPS + "\n", encoding="utf-8")
        fs = run_lint([root / "marian_tpu"], cfg, cache=cache)
        assert fs and fs[0].message != "FROM-THE-CACHE"

    def test_cache_round_trips_through_disk(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        path = tmp_path / "c.json"
        cache = load_result_cache(path, cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        save_result_cache(path, cache)
        loaded = load_result_cache(path, cfg)
        assert loaded["files"] == cache["files"]

    def test_ruleset_version_bump_invalidates(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        path = tmp_path / "c.json"
        cache = load_result_cache(path, cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        cache["ruleset"] = RULESET_VERSION - 1
        save_result_cache(path, cache)
        assert load_result_cache(path, cfg)["files"] == {}

    def test_config_change_invalidates(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        path = tmp_path / "c.json"
        cache = load_result_cache(path, cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        save_result_cache(path, cache)
        assert load_result_cache(path, cfg,
                                 rule_filter=["dtype"])["files"] == {}

    def test_project_scope_rules_bypass_cache(self, tmp_path):
        # cross-file rules must re-run even on a full cache hit: their
        # verdict depends on files OTHER than the cached one
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        cache = load_result_cache(tmp_path / "c.json", cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        cached_rules = {f["rule"]
                        for ent in cache["files"].values()
                        for f in ent["findings"]}
        for rule in all_rules():
            if rule.scope == "project":
                assert not (cached_rules & set(rule.ids))

    def _git(self, root, *args):
        return subprocess.run(
            ["git", "-C", str(root), "-c", "user.email=t@t",
             "-c", "user.name=t"] + list(args),
            capture_output=True, text=True, timeout=60)

    def test_changed_skips_clean_git_tree(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--update-baseline"])    # committed state passes
        assert rc == 0
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed"])
        capsys.readouterr()
        assert rc == 0            # findings baselined, nothing is dirty

    def test_changed_no_baseline_never_skips(self, tmp_path, capsys):
        # --no-baseline changes the verdict itself: a clean tree must
        # still surface the baselined findings, not exit 0 via the skip
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--update-baseline"])
        assert rc == 0
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--no-baseline", "--changed"])
        capsys.readouterr()
        assert rc == 1

    def test_changed_lints_dirty_files(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        bad = root / "marian_tpu" / "ops" / "bad.py"
        bad.write_text(BAD_OPS + "\n", encoding="utf-8")
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed"])
        capsys.readouterr()
        assert rc == 1

    def test_changed_runs_on_config_only_change(self, tmp_path, capsys):
        # [tool.mtlint] changes lint results without dirtying any .py
        # under the lint paths — the skip must not swallow it (the
        # cache's config fingerprint never engages on the skip path)
        root = _mini_tree(tmp_path)
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        (root / "pyproject.toml").write_text(
            "[tool.mtlint]\n# tweaked\n", encoding="utf-8")
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed"])
        capsys.readouterr()
        assert rc == 1            # bad.py findings computed, not skipped

    def test_changed_sees_new_untracked_directory(self, tmp_path, capsys):
        # `git status --porcelain` collapses an untracked dir to one
        # `?? dir/` line unless -uall is passed — a brand-new subpackage
        # full of violations must not read as "nothing dirty"
        root = _mini_tree(tmp_path)
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        new = root / "marian_tpu" / "newpkg"
        new.mkdir()
        (new / "bad.py").write_text(BAD_OPS + "\n", encoding="utf-8")
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed"])
        capsys.readouterr()
        assert rc == 1

    def test_changed_runs_on_baseline_only_change(self, tmp_path, capsys):
        # the exit code depends on the baseline: shrinking it must not
        # be swallowed by the clean-tree skip
        root = _mini_tree(tmp_path)
        bl = root / "baseline.json"
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--baseline", str(bl), "--update-baseline"])
        capsys.readouterr()
        assert rc == 0 and bl.exists()
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        write_baseline([], bl)        # ratchet the debt down, only change
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--baseline", str(bl), "--changed"])
        capsys.readouterr()
        assert rc == 1        # the finding is no longer absorbed

    def test_changed_update_baseline_never_skips(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        bl = root / "marian_tpu" / "analysis" / "baseline.json"
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed", "--update-baseline"])
        capsys.readouterr()
        assert rc == 0 and bl.exists()    # written, not skipped

    def test_changed_json_skip_is_parseable(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--update-baseline"])
        assert rc == 0
        capsys.readouterr()
        assert self._git(root, "init", "-q").returncode == 0
        self._git(root, "add", "-A")
        assert self._git(root, "commit", "-qm", "seed").returncode == 0
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed", "--format", "json"])
        out = capsys.readouterr().out
        payload = json.loads(out)
        assert rc == 0 and payload["findings"] == [] and payload["skipped"]

    def test_cache_flag_does_not_swallow_paths(self, tmp_path, capsys):
        # --cache used to take an optional FILE (nargs='?') and silently
        # consumed a following positional lint path; now it is a pure
        # flag and the path stays a path
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--cache",
                          "--root", str(root), "--no-baseline"])
        capsys.readouterr()
        assert rc == 1                              # bad.py WAS linted
        assert (root / ".mtlint-cache.json").exists()

    def test_fingerprint_covers_rule_sources(self):
        import json as _json
        from marian_tpu.analysis.core import config_fingerprint, ruleset_hash
        fp = _json.loads(config_fingerprint(Config(root=ROOT), None))
        assert fp["rule_sources"] == ruleset_hash()

    def test_cache_prunes_deleted_files_scanned_only(self, tmp_path):
        root = _mini_tree(tmp_path)
        other = root / "marian_tpu" / "other"
        other.mkdir()
        (other / "ok.py").write_text("x = 1\n", encoding="utf-8")
        cfg = Config(root=root)
        path = tmp_path / "c.json"
        cache = load_result_cache(path, cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        assert set(cache["files"]) == {"marian_tpu/ops/bad.py",
                                       "marian_tpu/other/ok.py"}
        (root / "marian_tpu" / "ops" / "bad.py").unlink()
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        assert set(cache["files"]) == {"marian_tpu/other/ok.py"}
        # a subset run must not evict entries outside its prefix
        (other / "ok.py").unlink()
        run_lint([root / "marian_tpu" / "ops"], cfg, cache=cache)
        assert set(cache["files"]) == {"marian_tpu/other/ok.py"}

    def test_corrupt_cache_entry_falls_back_to_analysis(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        path = tmp_path / "c.json"
        cache = load_result_cache(path, cfg)
        run_lint([root / "marian_tpu"], cfg, cache=cache)
        for ent in cache["files"].values():     # schema-drifted entries
            for d in ent["findings"]:
                d["no_such_field"] = 1
        save_result_cache(path, cache)
        cache = load_result_cache(path, cfg)
        fs = run_lint([root / "marian_tpu"], cfg, cache=cache)
        assert rule_ids(fs) == ["MT-DTYPE-ARRAY"]   # re-analyzed, no crash

    def test_changed_without_git_fails_open(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)     # not a git repo: full run
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--changed"])
        capsys.readouterr()
        assert rc == 1


# ---------------------------------------------------------------------------
# the lock-order graph artifacts over the REAL tree — ISSUE 6 acceptance
# ---------------------------------------------------------------------------

class TestLockGraphArtifacts:
    def test_real_tree_lock_graph_acyclic(self):
        """ISSUE 6 acceptance: a cycle-free lock-order graph for
        marian_tpu/ — the controller->registry->scheduler->metrics
        lattice has one global order."""
        from marian_tpu.analysis.callgraph import build_cached
        cfg = Config.load(ROOT)
        g = build_cached(collect_sources([ROOT / "marian_tpu"], cfg))
        assert g.lock_cycles() == []
        # and the serving lattice is actually modeled, not vacuously empty
        edges = {(e.src, e.dst) for e in g.lock_edges()}
        assert ("SwapController._lock", "ModelRegistry._lock") in edges
        # the witness's own plumbing lock is instrumentation, not part
        # of the modeled lattice
        assert not any("lockdep" in q for q in g.locks)

    def test_dot_snapshot_fresh(self, capsys):
        """docs/lock_order.dot must match what the CLI renders today —
        regenerate with `python -m marian_tpu.analysis --format dot >
        docs/lock_order.dot` after changing any lock usage."""
        rc = mtlint_main(["--format", "dot", "--root", str(ROOT)])
        out = capsys.readouterr().out
        assert rc == 0
        snapshot = (ROOT / "docs" / "lock_order.dot").read_text(
            encoding="utf-8")
        assert out == snapshot, (
            "docs/lock_order.dot is stale — regenerate: python -m "
            "marian_tpu.analysis --format dot > docs/lock_order.dot")

    def test_ownership_dot_snapshot_fresh(self, capsys):
        """ISSUE 15 acceptance: docs/ownership.dot must match what the
        CLI renders today — regenerate with `python -m
        marian_tpu.analysis --format ownership-dot > docs/ownership.dot`
        after changing any KVPool/prefix-cache verb usage."""
        rc = mtlint_main(["--format", "ownership-dot", "--root",
                          str(ROOT)])
        out = capsys.readouterr().out
        assert rc == 0
        snapshot = (ROOT / "docs" / "ownership.dot").read_text(
            encoding="utf-8")
        assert out == snapshot, (
            "docs/ownership.dot is stale — regenerate: python -m "
            "marian_tpu.analysis --format ownership-dot > "
            "docs/ownership.dot")

    def test_ownership_graph_models_the_serving_plane(self):
        """The committed graph is not vacuous: the engines' claim
        wrapper, both _evict overrides, and the prefix-cache adoption
        path are all sites, and the wrapper pairs with the eviction
        release the way real traffic exercises it (the exact pairings
        the runtime witness observes in tier-1)."""
        from marian_tpu.analysis.ownership import static_ownership_graph
        g = static_ownership_graph(ROOT)
        sites = g.sites["kv-pages"]
        claim = "marian_tpu/translator/iteration.py::_claim_pages"
        evict = "marian_tpu/translator/iteration.py::_evict"
        adopt = "marian_tpu/translator/prefix_cache.py::adopt"
        assert {"acquire"} == sites[claim]
        assert "transfer" in sites[adopt]
        assert {"release", "transfer"} == sites[evict]
        assert (claim, evict) in g.pairs["kv-pages"]
        assert (claim, adopt) in g.pairs["kv-pages"]


# ---------------------------------------------------------------------------
# baseline ratchet: the debt ledger may only shrink — ISSUE 6
# ---------------------------------------------------------------------------

class TestBaselineRatchet:
    # Entry count per rule family as of ISSUE 6. Lower these when debt is
    # paid down (and ONLY lower them): a new deliberate finding gets an
    # inline `# mtlint: ok -- reason` at the site, never a baseline entry.
    CEILING = {"host-sync": 16, "ownership": 2}
    # ISSUE 15: within the ownership family the ledger is ALSO capped per
    # rule — the two baselined MT-OWN-ESCAPE entries are the long-lived
    # executor handles (serving scheduler, checkpoint writer) whose
    # shutdown lives with the owning object's close(); leaks, doubles,
    # and unannotated boundary crossings may never be baselined at all.
    RULE_CEILING = {
        "MT-OWN-LEAK": 0,
        "MT-OWN-DOUBLE": 0,
        "MT-OWN-ESCAPE": 2,
        "MT-OWN-TRANSFER": 0,
    }
    # ISSUE 17: the jit family starts — and stays — at zero baselined
    # debt. MT-JIT-UNWARMED and MT-JIT-CLOSURE-VARYING may NEVER be
    # baselined (an unwarmed serving jit compiles on a live request; a
    # varying closure retraces silently — both are incidents, not
    # debt); the other two are held at zero so the family's ledger can
    # only be paid at the site (`# mtlint: ok -- reason`), never parked.
    JIT_RULE_CEILING = {
        "MT-JIT-CLOSURE-VARYING": 0,
        "MT-JIT-STATIC-UNBOUNDED": 0,
        "MT-JIT-WEAKTYPE": 0,
        "MT-JIT-UNWARMED": 0,
    }

    def test_baseline_never_grows(self):
        data = json.loads(
            (ROOT / "marian_tpu" / "analysis" / "baseline.json").read_text(
                encoding="utf-8"))
        family_of = {rid: r.family for r in all_rules() for rid in r.ids}
        counts = {}
        for f in data["findings"]:
            fam = family_of.get(f["rule"])
            assert fam is not None, \
                f"baseline rule {f['rule']} has no owning family"
            counts[fam] = counts.get(fam, 0) + 1
        for fam, n in sorted(counts.items()):
            assert n <= self.CEILING.get(fam, 0), (
                f"baseline grew: {n} {fam!r} entries vs ratchet ceiling "
                f"{self.CEILING.get(fam, 0)} — fix the finding or "
                f"acknowledge it inline with `# mtlint: ok -- reason`; "
                f"the baseline is shrink-only")

    def test_ownership_baseline_never_grows_per_rule(self):
        """ISSUE 15: per-rule ceilings for the ownership family — every
        MT-OWN rule id has an explicit ceiling here, so a new baselined
        leak/double/transfer can never ride in under the family total."""
        data = json.loads(
            (ROOT / "marian_tpu" / "analysis" / "baseline.json").read_text(
                encoding="utf-8"))
        own_ids = {rid for r in all_rules() if r.family == "ownership"
                   for rid in r.ids}
        assert own_ids == set(self.RULE_CEILING), \
            "RULE_CEILING must name every MT-OWN rule id exactly"
        counts = {}
        for f in data["findings"]:
            if f["rule"] in own_ids:
                counts[f["rule"]] = counts.get(f["rule"], 0) + 1
        for rid, n in sorted(counts.items()):
            assert n <= self.RULE_CEILING[rid], (
                f"baseline grew: {n} {rid} entries vs per-rule ceiling "
                f"{self.RULE_CEILING[rid]} — fix the finding; ownership "
                f"debt is shrink-only per rule")
        assert sum(counts.values()) <= self.CEILING["ownership"]

    def test_jit_baseline_never_grows_per_rule(self):
        """ISSUE 17: the jit family's per-rule ceilings are all zero —
        every MT-JIT rule id is named explicitly so a baselined
        compile-cache incident can never ride in at all."""
        data = json.loads(
            (ROOT / "marian_tpu" / "analysis" / "baseline.json").read_text(
                encoding="utf-8"))
        jit_ids = {rid for r in all_rules() if r.family == "jit"
                   for rid in r.ids}
        assert jit_ids == set(self.JIT_RULE_CEILING), \
            "JIT_RULE_CEILING must name every MT-JIT rule id exactly"
        for f in data["findings"]:
            assert f["rule"] not in jit_ids, (
                f"baseline contains {f['rule']} — compile-cache findings "
                f"are never baselined: fix the site or acknowledge it "
                f"inline with `# mtlint: ok -- reason`")
