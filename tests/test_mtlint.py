"""mtlint (marian_tpu/analysis) — per-rule positive/negative snippets,
suppression + baseline round-trip, CLI exit codes, and THE TIER-1 GATE:
the analyzer over the real marian_tpu/ tree with the checked-in baseline
must be clean (ISSUE 2 acceptance).

Snippets are parsed from strings — no fixture files on disk; the analysis
layer is stdlib-only, so none of this needs jax.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

from marian_tpu.analysis.cli import main as mtlint_main
from marian_tpu.analysis.core import (Config, Source, apply_baseline,
                                      load_baseline, run_lint,
                                      write_baseline, _read_toml_tables)
from marian_tpu.analysis.rules import all_rules

ROOT = Path(__file__).resolve().parents[1]


def lint_text(code: str, rel: str = "marian_tpu/ops/snippet.py",
              families=None, config: Config = None):
    """Run rules over one in-memory snippet; returns findings (inline
    suppressions honored, baseline not applied)."""
    cfg = config or Config(root=ROOT)
    src = Source(ROOT / rel, rel, text=code)
    findings = []
    for rule in all_rules():
        if families and rule.family not in families:
            continue
        if not cfg.family_applies(rule.family, rel):
            continue
        if rule.scope == "project":
            findings.extend(rule.check_project([src], cfg))
        else:
            findings.extend(rule.check(src, cfg))
    return [f for f in findings if not src.suppressed(f)]


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# trace-safety
# ---------------------------------------------------------------------------

class TestTraceSafety:
    def test_if_on_traced_param(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n")
        assert "MT-TRACE-COND" in rule_ids(fs)
        assert fs[0].line == 4

    def test_while_on_derived_value(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    y = x * 2\n"
            "    while y < 10:\n"
            "        y = y + 1\n"
            "    return y\n")
        assert "MT-TRACE-COND" in rule_ids(fs)

    def test_cast_and_item(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    a = int(x)\n"
            "    b = x.item()\n"
            "    return a + b\n")
        assert rule_ids(fs) == ["MT-TRACE-CAST"]
        assert len(fs) == 2

    def test_numpy_inside_jit(self):
        fs = lint_text(
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        assert "MT-TRACE-NUMPY" in rule_ids(fs)

    def test_np_dtype_constants_ok(self):
        fs = lint_text(
            "import jax, numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.astype(np.float32)\n")
        assert fs == []

    def test_static_argnums_honored(self):
        fs = lint_text(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    if n > 0:\n"
            "        return x * n\n"
            "    return x\n")
        assert fs == []

    def test_static_argnames_and_scalar_annotation(self):
        fs = lint_text(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnames=('mode',))\n"
            "def f(x, mode, rate: float = 0.1):\n"
            "    if mode == 'train' and rate > 0:\n"
            "        return x * rate\n"
            "    return x\n")
        assert fs == []

    def test_shape_and_none_tests_ok(self):
        fs = lint_text(
            "import jax\n"
            "@jax.jit\n"
            "def f(x, mask=None):\n"
            "    if mask is None:\n"
            "        mask = x\n"
            "    if x.ndim == 2:\n"
            "        d = int(x.shape[0])\n"
            "        return x + d\n"
            "    return x * mask\n")
        assert fs == []

    def test_wrapped_jit_binding(self):
        fs = lint_text(
            "import jax\n"
            "def f(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "step = jax.jit(f)\n")
        assert "MT-TRACE-COND" in rule_ids(fs)

    def test_plain_function_untouched(self):
        fs = lint_text(
            "def f(x):\n"
            "    if x > 0:\n"
            "        return float(x)\n"
            "    return 0.0\n")
        assert fs == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

class TestHostSync:
    REL = "marian_tpu/training/snippet.py"

    def test_unsynced_timer(self):
        fs = lint_text(
            "import time\n"
            "def bench(fn, x):\n"
            "    t0 = time.perf_counter()\n"
            "    y = fn(x)\n"
            "    dt = time.perf_counter() - t0\n"
            "    return y, dt\n", rel=self.REL, families=["host-sync"])
        assert rule_ids(fs) == ["MT-SYNC-TIMER"]

    def test_block_until_ready_clears_timer(self):
        fs = lint_text(
            "import time, jax\n"
            "def bench(fn, x):\n"
            "    t0 = time.perf_counter()\n"
            "    y = jax.block_until_ready(fn(x))\n"
            "    dt = time.perf_counter() - t0\n"
            "    return y, dt\n", rel=self.REL, families=["host-sync"])
        assert fs == []

    def test_transfers(self):
        fs = lint_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    a = np.asarray(x)\n"
            "    b = x.tolist()\n"
            "    print(x)\n"
            "    return a, b\n", rel=self.REL, families=["host-sync"])
        assert rule_ids(fs) == ["MT-SYNC-TRANSFER"]
        assert len(fs) == 3

    def test_literal_np_array_ok(self):
        fs = lint_text(
            "import numpy as np\n"
            "def f():\n"
            "    print('loaded')\n"
            "    return np.array([1, 2, 3])\n",
            rel=self.REL, families=["host-sync"])
        assert fs == []

    def test_cold_dirs_not_checked(self):
        fs = lint_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n",
            rel="marian_tpu/common/snippet.py", families=["host-sync"])
        assert fs == []


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

class TestDonation:
    def test_read_after_donate(self):
        fs = lint_text(
            "import jax\n"
            "def train(p, b):\n"
            "    return p\n"
            "step = jax.jit(train, donate_argnums=(0,))\n"
            "def loop(p, batches):\n"
            "    for b in batches:\n"
            "        out = step(p, b)\n"
            "    return p\n", families=["donation"])
        assert rule_ids(fs) == ["MT-DONATE-READ"]

    def test_rebinding_is_clean(self):
        fs = lint_text(
            "import jax\n"
            "def train(p, b):\n"
            "    return p\n"
            "step = jax.jit(train, donate_argnums=(0,))\n"
            "def loop(p, batches):\n"
            "    for b in batches:\n"
            "        p = step(p, b)\n"
            "    return p\n", families=["donation"])
        assert fs == []

    def test_conditional_donation_still_flagged(self):
        fs = lint_text(
            "import jax\n"
            "def train(p, b):\n"
            "    return p\n"
            "donate = True\n"
            "step = jax.jit(train, donate_argnums=(0,) if donate else ())\n"
            "def once(p, b):\n"
            "    out = step(p, b)\n"
            "    return out, p.keys()\n", families=["donation"])
        assert rule_ids(fs) == ["MT-DONATE-READ"]


# ---------------------------------------------------------------------------
# dtype hygiene
# ---------------------------------------------------------------------------

class TestDtype:
    def test_literal_with_unpinned_array(self):
        fs = lint_text(
            "import jax\n"
            "def f(mask: jax.Array):\n"
            "    return (1.0 - mask) * -1e9\n", families=["dtype"])
        assert rule_ids(fs) == ["MT-DTYPE-LITERAL"]

    def test_astype_pin_clears_literal(self):
        fs = lint_text(
            "import jax\n"
            "def f(logits: jax.Array, mask: jax.Array):\n"
            "    return (1.0 - mask.astype(logits.dtype)) * -1e9\n",
            families=["dtype"])
        assert fs == []

    def test_scalar_annotation_not_array(self):
        fs = lint_text(
            "def f(x: 'jax.Array', rate: float):\n"
            "    keep = 1.0 - rate\n"
            "    return x / keep\n", families=["dtype"])
        assert fs == []

    def test_ctor_without_dtype(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n)), jnp.array([0.5])\n",
            families=["dtype"])
        assert rule_ids(fs) == ["MT-DTYPE-ARRAY"]
        assert len(fs) == 2

    def test_ctor_with_dtype_ok(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n, dt):\n"
            "    a = jnp.zeros((n, n), jnp.float32)\n"
            "    b = jnp.array([0.5], dtype=dt)\n"
            "    c = jnp.asarray(n)\n"
            "    return a, b, c\n", families=["dtype"])
        assert fs == []

    def test_dtype_dirs_scoped(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))\n",
            rel="marian_tpu/data/snippet.py", families=["dtype"])
        assert fs == []


# ---------------------------------------------------------------------------
# guarded-by
# ---------------------------------------------------------------------------

GUARDED_CLASS = (
    "import threading\n"
    "class Sched:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._queued = 0   # guarded-by: _lock\n"
    "    def bad_read(self):\n"
    "        return self._queued\n"
    "    def good_read(self):\n"
    "        with self._lock:\n"
    "            return self._queued\n"
    "    def held_helper(self):  # mtlint: holds _lock\n"
    "        self._queued += 1\n")


class TestGuardedBy:
    REL = "marian_tpu/serving/snippet.py"

    def test_unlocked_access_flagged_once(self):
        fs = lint_text(GUARDED_CLASS, rel=self.REL, families=["guarded-by"])
        assert rule_ids(fs) == ["MT-LOCK-GUARD"]
        assert len(fs) == 1 and fs[0].line == 7  # only bad_read

    def test_init_exempt_and_with_block_ok(self):
        clean = GUARDED_CLASS.replace(
            "    def bad_read(self):\n        return self._queued\n", "")
        assert lint_text(clean, rel=self.REL,
                         families=["guarded-by"]) == []

    def test_unknown_lock(self):
        fs = lint_text(
            "class C:\n"
            "    def __init__(self):\n"
            "        self._n = 0   # guarded-by: _missing\n",
            rel=self.REL, families=["guarded-by"])
        assert rule_ids(fs) == ["MT-LOCK-UNKNOWN"]

    def test_scoped_to_threaded_dirs(self):
        fs = lint_text(GUARDED_CLASS, rel="marian_tpu/ops/snippet.py",
                       families=["guarded-by"])
        assert fs == []


# ---------------------------------------------------------------------------
# metrics hygiene
# ---------------------------------------------------------------------------

class TestMetrics:
    def test_registered_never_emitted(self):
        fs = lint_text(
            "class S:\n"
            "    def __init__(self, r):\n"
            "        self.m_used = r.counter('used_total', 'u')\n"
            "        self.m_dead = r.counter('dead_total', 'd')\n"
            "    def work(self):\n"
            "        self.m_used.inc()\n", families=["metrics"])
        assert rule_ids(fs) == ["MT-METRIC-UNUSED"]
        assert "dead_total" in fs[0].message

    def test_labels_chain_counts_as_emission(self):
        fs = lint_text(
            "class S:\n"
            "    def __init__(self, r):\n"
            "        self.m_shed = r.counter('shed_total', 's', "
            "labels=('reason',))\n"
            "    def work(self):\n"
            "        self.m_shed.labels('full').inc()\n",
            families=["metrics"])
        assert fs == []

    def test_emitted_never_registered(self):
        fs = lint_text(
            "class S:\n"
            "    def work(self):\n"
            "        self.m_ghost.inc()\n", families=["metrics"])
        assert rule_ids(fs) == ["MT-METRIC-UNREG"]

    def test_direct_construction_flagged(self):
        fs = lint_text(
            "from marian_tpu.serving.metrics import Counter\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self.m_direct = Counter('direct_total', 'd')\n"
            "    def work(self):\n"
            "        self.m_direct.inc()\n", families=["metrics"])
        assert rule_ids(fs) == ["MT-METRIC-UNREG"]
        assert "bypassing the registry" in fs[0].message


# ---------------------------------------------------------------------------
# suppression, config, baseline, CLI, gate
# ---------------------------------------------------------------------------

class TestFaultHygiene:
    """MT-FAULT-* (fault_hygiene.py — ISSUE 4): every fault_point() call
    site uses a declared catalog name, and every declared point is
    exercised by at least one test (mirrors the metrics-hygiene shape)."""

    CATALOG = ("from typing import Dict\n"
               "CATALOG: Dict[str, str] = {\n"
               "    'ckpt.commit': 'the commit point',\n"
               "    'data.batch.next': 'pipeline',\n"
               "}\n")
    SITES = ("from marian_tpu.common import faultpoints as fp\n"
             "def save():\n"
             "    fp.fault_point('ckpt.commit')\n")

    def _lint(self, tmp_path, files, tests=None):
        cfg = Config(root=tmp_path)
        tdir = tmp_path / "tests"
        tdir.mkdir(exist_ok=True)
        for name, content in (tests or {}).items():
            (tdir / name).write_text(content, encoding="utf-8")
        srcs = [Source(tmp_path / rel, rel, text=code)
                for rel, code in files.items()]
        rule = next(r for r in all_rules() if r.family == "faults")
        return rule.check_project(srcs, cfg)

    def test_unknown_call_site_flagged(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/x.py":
                "def f():\n    fault_point('no.such.name')\n"},
            tests={"test_x.py": "ckpt.commit data.batch.next"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNKNOWN"]
        assert "no.such.name" in fs[0].message

    def test_untested_call_site_flagged(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES},
            tests={"test_x.py": "only data.batch.next is exercised"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNTESTED"]
        assert "ckpt.commit" in fs[0].message
        assert fs[0].path == "marian_tpu/ckpt.py"   # anchored at the site

    def test_catalog_entry_without_site_or_test_flagged(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES},
            tests={"test_x.py": "arms ckpt.commit=kill@2"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNTESTED"]
        assert "data.batch.next" in fs[0].message
        assert fs[0].path.endswith("faultpoints.py")  # anchored at catalog

    def test_fully_covered_tree_is_clean(self, tmp_path):
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES,
            "marian_tpu/data.py":
                "from marian_tpu.common import faultpoints as fp\n"
                "def g():\n    fp.fault_point('data.batch.next')\n"},
            tests={"test_x.py":
                   "MARIAN_FAULTS='ckpt.commit=kill@2,"
                   "data.batch.next=fail'"})
        assert fs == []

    def test_name_in_comment_is_not_coverage(self, tmp_path):
        """Only string constants in test files count as exercising a
        fault point — '# we deliberately skip ckpt.commit' must not
        satisfy the rule."""
        fs = self._lint(tmp_path, {
            "marian_tpu/common/faultpoints.py": self.CATALOG,
            "marian_tpu/ckpt.py": self.SITES},
            tests={"test_x.py":
                   "# we deliberately do not drill ckpt.commit\n"
                   "X = 'data.batch.next=fail'\n"})
        assert [f.rule for f in fs] == ["MT-FAULT-UNTESTED"]
        assert "ckpt.commit" in fs[0].message

    def test_snippet_without_registry_is_silent(self, tmp_path):
        """Trees with no fault registry at all (every other rule's
        snippet tests) must not drown in fault findings."""
        fs = self._lint(tmp_path,
                        {"marian_tpu/ops/x.py": "def f():\n    pass\n"})
        assert fs == []


class TestSuppression:
    def test_ok_comment(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))  # mtlint: ok -- reason here\n",
            families=["dtype"])
        assert fs == []

    def test_disable_family_prefix(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))  # mtlint: disable=MT-DTYPE\n",
            families=["dtype"])
        assert fs == []

    def test_disable_other_rule_does_not_suppress(self):
        fs = lint_text(
            "import jax.numpy as jnp\n"
            "def f(n):\n"
            "    return jnp.zeros((n, n))  # mtlint: disable=MT-TRACE-COND\n",
            families=["dtype"])
        assert rule_ids(fs) == ["MT-DTYPE-ARRAY"]


class TestConfig:
    def test_toml_subset_reader(self):
        tables = _read_toml_tables(
            '[tool.mtlint]\nexclude = ["a/b"]\n'
            '[tool.mtlint.rules.dtype]\ndirs = [\n  "x/y",\n  "z",\n]\n'
            'enabled = true\n'
            '[other.section]\nk = "v"  # comment\n')
        assert tables["tool.mtlint"]["exclude"] == ["a/b"]
        assert tables["tool.mtlint.rules.dtype"]["dirs"] == ["x/y", "z"]
        assert tables["tool.mtlint.rules.dtype"]["enabled"] is True

    def test_pyproject_loaded(self):
        cfg = Config.load(ROOT)
        assert "marian_tpu/ops" in cfg.rule_dirs["dtype"]
        assert "marian_tpu/serving" in cfg.rule_dirs["guarded-by"]
        assert cfg.excluded("marian_tpu/analysis/core.py")

    def test_every_advertised_rule_id_has_an_owner(self):
        families = {r.family for r in all_rules()}
        assert families == {"trace-safety", "host-sync", "donation",
                            "dtype", "guarded-by", "metrics", "faults"}


BAD_OPS = ("import jax.numpy as jnp\n"
           "def f(n):\n"
           "    return jnp.zeros((n, n))\n")


def _mini_tree(tmp_path: Path) -> Path:
    (tmp_path / "pyproject.toml").write_text(
        "[tool.mtlint]\n", encoding="utf-8")
    pkg = tmp_path / "marian_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(BAD_OPS, encoding="utf-8")
    return tmp_path


class TestBaseline:
    def test_round_trip(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        findings = run_lint([root / "marian_tpu"], cfg)
        assert rule_ids(findings) == ["MT-DTYPE-ARRAY"]
        bl_path = root / "baseline.json"
        write_baseline(findings, bl_path)
        new, old = apply_baseline(
            run_lint([root / "marian_tpu"], cfg), load_baseline(bl_path))
        assert new == [] and len(old) == 1

    def test_baseline_survives_line_shift(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        bl_path = root / "baseline.json"
        write_baseline(run_lint([root / "marian_tpu"], cfg), bl_path)
        bad = root / "marian_tpu" / "ops" / "bad.py"
        bad.write_text("import jax.numpy as jnp\n\n\n" + BAD_OPS.split(
            "\n", 1)[1], encoding="utf-8")
        new, old = apply_baseline(
            run_lint([root / "marian_tpu"], cfg), load_baseline(bl_path))
        assert new == [] and len(old) == 1

    def test_second_identical_violation_not_absorbed(self, tmp_path):
        root = _mini_tree(tmp_path)
        cfg = Config(root=root)
        bl_path = root / "baseline.json"
        write_baseline(run_lint([root / "marian_tpu"], cfg), bl_path)
        bad = root / "marian_tpu" / "ops" / "bad.py"
        bad.write_text(BAD_OPS + "def g(n):\n"
                       "    return jnp.zeros((n, n))\n", encoding="utf-8")
        new, old = apply_baseline(
            run_lint([root / "marian_tpu"], cfg), load_baseline(bl_path))
        assert len(new) == 1 and len(old) == 1


class TestCli:
    def test_exit_codes_and_update(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        argv = [str(root / "marian_tpu"), "--root", str(root),
                "--baseline", str(root / "bl.json")]
        assert mtlint_main(argv) == 1          # findings, no baseline yet
        assert mtlint_main(argv + ["--update-baseline"]) == 0
        assert mtlint_main(argv) == 0          # clean against baseline
        capsys.readouterr()

    def test_json_format(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--format", "json", "--no-baseline"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert payload["findings"][0]["rule"] == "MT-DTYPE-ARRAY"
        assert payload["findings"][0]["path"] == "marian_tpu/ops/bad.py"

    def test_rules_filter(self, tmp_path, capsys):
        root = _mini_tree(tmp_path)
        rc = mtlint_main([str(root / "marian_tpu"), "--root", str(root),
                          "--rules", "guarded-by", "--no-baseline"])
        capsys.readouterr()
        assert rc == 0

    def test_script_entry_point(self, tmp_path):
        root = _mini_tree(tmp_path)
        proc = subprocess.run(
            [sys.executable, str(ROOT / "scripts" / "mtlint.py"),
             str(root / "marian_tpu"), "--root", str(root),
             "--no-baseline", "--format", "json"],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        assert json.loads(proc.stdout)["findings"]


class TestTier1Gate:
    """THE gate: the real tree must be clean against the checked-in
    baseline. A finding here means new code tripped a rule — fix it (or,
    for a deliberate pattern, annotate `# mtlint: ok -- reason`); do not
    grow the baseline."""

    def test_tree_clean_against_baseline(self):
        cfg = Config.load(ROOT)
        errors = []
        findings = run_lint([ROOT / "marian_tpu"], cfg, errors=errors)
        assert errors == [], f"mtlint could not parse: {errors}"
        baseline = load_baseline(ROOT / "marian_tpu" / "analysis"
                                 / "baseline.json")
        assert baseline, "checked-in baseline missing or empty"
        new, _old = apply_baseline(findings, baseline)
        assert new == [], (
            "mtlint found new violations (run `python -m "
            "marian_tpu.analysis` for details; see "
            "docs/STATIC_ANALYSIS.md):\n"
            + "\n".join(f.render() for f in new))

    def test_baseline_not_stale(self):
        """Every baseline entry still matches a real finding — entries
        whose code was fixed must be removed (--update-baseline), keeping
        the debt ledger honest."""
        cfg = Config.load(ROOT)
        findings = run_lint([ROOT / "marian_tpu"], cfg)
        current = {f.key() for f in findings}
        baseline = load_baseline(ROOT / "marian_tpu" / "analysis"
                                 / "baseline.json")
        stale = [k for k in baseline if k not in current]
        assert stale == [], (
            f"baseline entries no longer match any finding (fixed code — "
            f"regenerate with scripts/mtlint.py --update-baseline): {stale}")


class TestHostSyncNestedDefs:
    REL = "marian_tpu/training/snippet.py"

    def test_nested_sync_does_not_clear_outer_timer(self):
        fs = lint_text(
            "import time, jax\n"
            "def bench(fn, x):\n"
            "    def _later(y):\n"
            "        return jax.block_until_ready(y)\n"
            "    t0 = time.perf_counter()\n"
            "    y = fn(x)\n"
            "    dt = time.perf_counter() - t0\n"
            "    return y, dt, _later\n", rel=self.REL,
            families=["host-sync"])
        assert rule_ids(fs) == ["MT-SYNC-TIMER"]

    def test_nested_timer_not_attributed_to_outer(self):
        fs = lint_text(
            "import time\n"
            "def outer(fn, x):\n"
            "    t0 = time.perf_counter()\n"
            "    def cb():\n"
            "        return time.perf_counter()\n"
            "    y = fn(x)\n"
            "    return y, t0, cb\n", rel=self.REL,
            families=["host-sync"])
        assert fs == []
