"""--transformer-decoder-autoreg variants (reference: src/models/transformer.h
:: AverageAttention/LayerAAN and DecoderLayerRNN with SSRU): train+decode
parity for average-attention and rnn, and hard errors for unknown modes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import transformer as T
from marian_tpu.models.encoder_decoder import create_model

from test_model import tiny_model, fake_batch


AUTOREG = ["average-attention", "rnn"]


@pytest.fixture
def rng():
    return np.random.RandomState(7)


class TestAutoregVariants:
    @pytest.mark.parametrize("mode", AUTOREG)
    def test_params_exist_and_no_self_attention(self, mode):
        model, params = tiny_model(
            vocab=17, **{"transformer-decoder-autoreg": mode,
                         "transformer-dim-aan": 32})
        names = set(params)
        assert not any(n.startswith("decoder") and "_self_Wq" in n
                       for n in names)
        assert any("encoder_l1_self_Wq" in n for n in names)
        marker = "_aan_" if mode == "average-attention" else "_rnn_"
        assert any(marker in n for n in names)

    @pytest.mark.parametrize("mode", AUTOREG)
    def test_step_matches_teacher_forcing(self, rng, mode):
        """Incremental decode (running-sum AAN cache / SSRU cell state) must
        reproduce the full-sequence training path on the gold prefix."""
        model, params = tiny_model(
            vocab=17, **{"transformer-decoder-autoreg": mode,
                         "transformer-dim-aan": 32,
                         "transformer-rnn-projection": mode == "rnn"})
        batch = fake_batch(rng, b=3, ts=6, tt=7, vocab=17)
        enc = model.encode_for_decode(params, batch["src_ids"],
                                      batch["src_mask"])
        full = T.decode_train(model.cfg, params, enc, batch["src_mask"],
                              batch["trg_ids"], batch["trg_mask"],
                              train=False)
        state = model.start_state(params, enc, batch["src_mask"], max_len=8)
        prev = jnp.zeros((3, 1), jnp.int32)
        for t in range(batch["trg_ids"].shape[1]):
            logits, state = model.step(params, state, prev,
                                       batch["src_mask"])
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t, :]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    @pytest.mark.parametrize("mode", AUTOREG)
    def test_trains(self, rng, mode):
        """Loss is finite and decreases over a few SGD-ish steps."""
        model, params = tiny_model(
            vocab=17, **{"transformer-decoder-autoreg": mode,
                         "transformer-dim-aan": 32})
        batch = fake_batch(rng, b=4, ts=6, tt=7, vocab=17)

        @jax.jit
        def step(p):
            def loss_fn(pp):
                total, aux = model.loss(pp, batch, key=None, train=False)
                return total / jnp.maximum(aux["labels"], 1.0)
            l, g = jax.value_and_grad(loss_fn)(p)
            return l, {k: v - 0.5 * g[k] for k, v in p.items()}

        losses = []
        for _ in range(5):
            l, params = step(params)
            losses.append(float(l))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_beam_search_runs_with_aan(self, rng):
        """The beam reorders the AAN running-sum cache via the carried
        suffixes; a beam-3 decode must run and terminate."""
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = tiny_model(
            vocab=17, **{"transformer-decoder-autoreg": "average-attention",
                         "transformer-dim-aan": 32})
        opts = Options({"beam-size": 3, "normalize": 0.6, "max-length": 16})
        bs = BeamSearch(model, [params], None, opts, None)
        batch = fake_batch(rng, b=2, ts=5, tt=6, vocab=17)
        out = bs.search(batch["src_ids"], batch["src_mask"])
        assert len(out) == 2 and all(len(nb) == 1 for nb in out)

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="not implemented"):
            tiny_model(vocab=17,
                       **{"transformer-decoder-autoreg": "nonsense"})


class TestTiedLayers:
    def test_albert_style_sharing(self, rng):
        """--transformer-tied-layers 1 1: both layers share layer-1 params;
        decode must still match teacher forcing (state stays per-layer)."""
        model, params = tiny_model(
            vocab=17, **{"transformer-tied-layers": [1, 1]})
        assert not any("_l2_" in n for n in params)
        # full masks: past a sentence's EOS the teacher-forced and
        # step-by-step paths legitimately differ (train masks padded keys,
        # the incremental cache has no such notion), so compare unpadded
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, 17, (2, 5)), jnp.int32),
            "src_mask": jnp.ones((2, 5), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, 17, (2, 6)), jnp.int32),
            "trg_mask": jnp.ones((2, 6), jnp.float32),
        }
        enc = model.encode_for_decode(params, batch["src_ids"],
                                      batch["src_mask"])
        full = T.decode_train(model.cfg, params, enc, batch["src_mask"],
                              batch["trg_ids"], batch["trg_mask"],
                              train=False)
        state = model.start_state(params, enc, batch["src_mask"], max_len=8)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(batch["trg_ids"].shape[1]):
            logits, state = model.step(params, state, prev,
                                       batch["src_mask"])
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t, :]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_forward_reference_raises(self):
        with pytest.raises(ValueError, match="tied-layers"):
            tiny_model(vocab=17, **{"transformer-tied-layers": [2, 2]})
