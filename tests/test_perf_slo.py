"""Performance & capacity observability plane (ISSUE 9): live
chip-seconds/token + MFU + headroom accounting (obs/perf.py), per-bucket
compile telemetry through warmup and the scheduler, the SLO burn-rate
engine (obs/slo.py) with /sloz and flight-dump integration, process
self-metrics, the Prometheus text-format lint, and loadgen --sweep
against a real CPU TCP server. Everything runs with stub translate
functions under JAX_PLATFORMS=cpu.

Acceptance-critical tier-1 properties:
- a slow-translate MARIAN_FAULTS fault drives the fast-burn SLO alert →
  timeline event + flight dump containing SLO state;
- the lifecycle swap observes warmup compile telemetry per shape bucket
  and ZERO steady-state recompile events;
- a scheduler run on CPU exports chip-seconds/token and headroom gauges
  that loadgen --sweep reads back;
- disabled mode adds no lock acquisitions on the batch path (the
  raising-lock guard in test_obs.py now covers PerfMeter._lock too).
"""

import asyncio
import importlib.util
import json
import os
import threading
import time
import urllib.request

import pytest

from marian_tpu import obs
from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.obs.perf import PerfMeter, width_bucket_key
from marian_tpu.obs.slo import SloEngine, maybe_build_engine, slo_routes
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.lifecycle import SwapController
from marian_tpu.serving.lifecycle.warmup import (DEFAULT_GOLDEN,
                                                 WarmupError,
                                                 golden_buckets,
                                                 smoke_buckets,
                                                 warm_executor)
from marian_tpu.serving.promlint import lint_metrics_text
from marian_tpu.serving.scheduler import ContinuousScheduler
from marian_tpu.server.server import ServingApp, _make_tcp_handler
from marian_tpu.training import bundle as bdl

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """PerfMeter._lock / SloEngine._lock join the running lattice here;
    the shared conftest witness asserts observed ⊆ static at module
    teardown."""
    yield


@pytest.fixture(autouse=True)
def _reset_obs():
    yield
    obs.TRACER.reset()
    obs.FLIGHT.disarm()
    obs.PERF.reset()
    fp.reset_for_tests()


def run(coro):
    return asyncio.run(coro)


def enable_perf(registry=None):
    obs.PERF.reset()
    obs.PERF.enable(registry=registry or msm.REGISTRY, hook_jax=False)
    return obs.PERF


# ---------------------------------------------------------------------------
# PerfMeter core math
# ---------------------------------------------------------------------------

class TestPerfMeter:
    def test_record_batch_updates_integrals_and_rates(self):
        r = msm.Registry()
        p = enable_perf(r)
        p.record_batch("vA", rows=4, width=8, src_tokens=20,
                       trg_tokens=18, device_s=0.5)
        p.record_batch("vA", rows=2, width=8, src_tokens=10,
                       trg_tokens=9, device_s=0.25)
        assert r.get("marian_perf_device_seconds_total") \
                .labels("vA").value == pytest.approx(0.75)
        assert r.get("marian_perf_tokens_total").labels("vA").value == 30
        assert r.get("marian_perf_trg_tokens_total") \
                .labels("vA").value == 27
        cspt = r.get("marian_perf_chip_seconds_per_token") \
                .labels("vA").value
        assert cspt == pytest.approx(0.75 / 30)
        assert r.get("marian_perf_tokens_per_second") \
                .labels("vA").value > 0
        assert 0 < r.get("marian_perf_device_busy_ratio").value <= 1

    def test_busy_and_throughput_decay_at_idle(self):
        """busy/tokens-per-second are scrape-time over the window: an
        idle replica must read 0, not the last burst's rate — else the
        autoscaler sees phantom saturation (review fix)."""
        r = msm.Registry()
        p = enable_perf(r)
        p.window_s = 0.05
        p.record_batch("v", rows=1, width=8, src_tokens=10,
                       trg_tokens=10, device_s=0.05)
        assert r.get("marian_perf_device_busy_ratio").value > 0.5
        time.sleep(0.12)                 # the burst ages out of the window
        assert r.get("marian_perf_device_busy_ratio").value == 0.0
        assert r.get("marian_perf_tokens_per_second") \
                .labels("v").value == 0.0
        # the COST gauge deliberately holds its last value (a $/token
        # figure does not decay)
        assert r.get("marian_perf_chip_seconds_per_token") \
                .labels("v").value > 0

    def test_stalled_batch_bills_stall_window(self):
        """A watchdog-stalled device call never returns through the
        timing fence — the stall window itself must be billed as device
        time so repeated stalls do not read as an idle replica
        (review fix)."""
        r = msm.Registry()
        enable_perf(r)

        async def main():
            sched = ContinuousScheduler(lambda lines: list(lines),
                                        stall_timeout=0.1, registry=r,
                                        window_s=0)
            sched.start()
            with fp.active("serving.translate=hang:5"):
                from marian_tpu.serving.scheduler import DispatchStalled
                with pytest.raises(DispatchStalled):
                    await sched.submit(["victim"])
            await sched.stop()

        run(main())
        assert r.get("marian_perf_device_seconds_total") \
                .labels("unversioned").value >= 0.1
        # but NO tokens: the stalled batch delivered nothing, so the
        # throughput/cost signals spike instead of reading "healthy"
        assert r.get("marian_perf_tokens_total") \
                .labels("unversioned").value == 0

    def test_mfu_against_explicit_peak(self):
        r = msm.Registry()
        p = enable_perf(r)
        p.set_geometry(emb=64, ffn=256, enc_depth=2, dec_depth=2,
                       vocab=1000, beam=2, n_devices=1, peak_flops=1e9)
        assert r.get("marian_perf_roofline_peak_flops").value == 1e9
        assert r.get("marian_perf_devices").value == 1
        p.record_batch("vA", rows=2, width=16, src_tokens=20,
                       trg_tokens=20, device_s=1.0)
        from marian_tpu.common.flops import transformer_serve_flops
        # trg_width = average generated length = trg_tokens / rows
        expect = transformer_serve_flops(64, 256, 2, 2, 1000,
                                         src_tokens=20, trg_tokens=20,
                                         src_width=16, trg_width=10,
                                         beam=2) / 1e9
        assert r.get("marian_perf_mfu").labels("vA").value \
            == pytest.approx(expect, rel=1e-6)

    def test_mfu_zero_without_known_peak(self):
        r = msm.Registry()
        p = enable_perf(r)
        # CPU probe: device_kind has no 'tpu' → peak None → mfu 0
        p.set_geometry(emb=64, ffn=256, enc_depth=1, dec_depth=1,
                       vocab=100, device_kind="cpu", n_devices=1)
        p.record_batch("vA", rows=1, width=8, src_tokens=5,
                       trg_tokens=5, device_s=0.1)
        assert r.get("marian_perf_mfu").labels("vA").value == 0.0
        assert r.get("marian_perf_roofline_peak_flops").value == 0.0

    def test_headroom_idle_busy_and_queue_pressure(self):
        r = msm.Registry()
        p = enable_perf(r)
        p.window_s = 10.0
        depth = {"n": 0}
        p.set_capacity_inputs(lambda: depth["n"], 100)
        assert p.headroom() == pytest.approx(1.0)       # idle, empty queue
        # saturate the window: 10s of device time in a 10s window
        p.record_batch("v", rows=1, width=8, src_tokens=10,
                       trg_tokens=10, device_s=10.0)
        assert p.headroom() == pytest.approx(0.0, abs=1e-3)
        p.reset()
        p = enable_perf(r)
        p.set_capacity_inputs(lambda: depth["n"], 100)
        depth["n"] = 50                                  # half-full queue
        assert p.headroom() == pytest.approx(0.5, abs=1e-6)
        depth["n"] = 100
        assert p.headroom() == pytest.approx(0.0, abs=1e-6)
        # the exported gauge samples the same function at scrape time
        assert "marian_capacity_headroom_ratio 0" in r.render()

    def test_headroom_unbounded_queue_prices_debt_per_sentence(self):
        r = msm.Registry()
        p = enable_perf(r)
        p.window_s = 10.0
        p.set_capacity_inputs(lambda: 100, 0)     # unbounded admission
        # 0.1 device-seconds per SENTENCE (depth counts sentences, so
        # the price must too) → 100 queued = 10s of work = one full
        # window horizon → pressure 1.0
        p.record_batch("v", rows=10, width=8, src_tokens=100,
                       trg_tokens=100, device_s=1.0)
        assert p.headroom() == pytest.approx(0.0, abs=1e-6)

    def test_per_version_cost_gauges_not_blended(self):
        """A hot-swap's new version must not inherit the old version's
        window samples in its cost gauge (review fix: the rolling sums
        are per version label)."""
        r = msm.Registry()
        p = enable_perf(r)
        p.record_batch("vOld", rows=1, width=8, src_tokens=10,
                       trg_tokens=10, device_s=1.0)     # 0.1 s/token
        p.record_batch("vNew", rows=1, width=8, src_tokens=10,
                       trg_tokens=10, device_s=0.1)     # 0.01 s/token
        assert r.get("marian_perf_chip_seconds_per_token") \
                .labels("vOld").value == pytest.approx(0.1)
        assert r.get("marian_perf_chip_seconds_per_token") \
                .labels("vNew").value == pytest.approx(0.01)
        st = p.state()
        assert st["versions"]["vNew"]["chip_seconds_per_token"] \
            == pytest.approx(0.01)

    def test_disabled_record_is_noop(self):
        p = PerfMeter()
        p.record_batch("v", 1, 8, 5, 5, 0.1)      # no metrics attrs: would
        p.record_train_window(10, 10, 1, 1.0)     # raise if not guarded
        assert p.headroom() == pytest.approx(1.0)
        assert p.state() == {"enabled": False}

    def test_train_window_chip_seconds_and_mfu(self):
        r = msm.Registry()
        p = enable_perf(r)
        p.set_geometry(emb=32, ffn=64, enc_depth=1, dec_depth=1,
                       vocab=200, n_devices=2, peak_flops=1e9)
        p.record_train_window(labels=100, src_words=120, sentences=10,
                              dt=2.0)
        assert r.get("marian_train_chip_seconds_per_token").value \
            == pytest.approx(2.0 * 2 / 100)
        assert r.get("marian_train_mfu").value > 0


# ---------------------------------------------------------------------------
# compile telemetry: warmup buckets vs steady-state recompiles
# ---------------------------------------------------------------------------

class TestCompileTelemetry:
    def test_golden_buckets_grouping(self):
        groups = golden_buckets(list(DEFAULT_GOLDEN))
        # "hello" (2) and "a b c d" (5) land in w8; the 10-token probe
        # in w16 — the built-in golden set warms two buckets
        assert list(groups) == [8, 16]
        assert groups[8] == ["hello", "a b c d"]

    def test_warm_bucket_then_dispatch_no_recompile(self):
        r = msm.Registry()
        p = enable_perf(r)
        obs.TRACER.enable()
        p.warm_bucket("v1", width_bucket_key(8), 0.2, "swap-warmup")
        p.record_batch("v1", rows=2, width=8, src_tokens=6,
                       trg_tokens=6, device_s=0.01)
        assert p.steady_recompiles() == 0
        _, events = obs.TRACER.snapshot()
        assert not [e for e in events if e["name"] == "perf.recompile"]
        assert r.get("marian_compile_total") \
                .labels("swap-warmup", "w8").value == 1
        assert r.get("marian_compile_seconds_total") \
                .labels("swap-warmup", "w8").value == pytest.approx(0.2)

    def test_unwarmed_bucket_is_steady_state_recompile_once(self):
        r = msm.Registry()
        p = enable_perf(r)
        obs.TRACER.enable()
        p.record_batch("v1", rows=1, width=32, src_tokens=20,
                       trg_tokens=20, device_s=0.7)
        p.record_batch("v1", rows=1, width=32, src_tokens=20,
                       trg_tokens=20, device_s=0.1)   # second hit: warm now
        assert p.steady_recompiles() == 1
        assert r.get("marian_compile_total") \
                .labels("steady-state", "w32").value == 1
        _, events = obs.TRACER.snapshot()
        rec = [e for e in events if e["name"] == "perf.recompile"]
        assert len(rec) == 1
        assert rec[0]["attrs"]["bucket"] == "w32"
        assert rec[0]["attrs"]["model_version"] == "v1"

    def test_smoke_buckets_calls_per_bucket_and_arity(self):
        r = msm.Registry()
        p = enable_perf(r)
        calls = []

        def executor(lines):
            calls.append(list(lines))
            return list(lines)

        smoke_buckets(executor, list(DEFAULT_GOLDEN), "vX",
                      "boot-warmup", "here")
        assert len(calls) == 2                   # one call per bucket
        assert r.get("marian_compile_total") \
                .labels("boot-warmup", "w8").value == 1
        assert r.get("marian_compile_total") \
                .labels("boot-warmup", "w16").value == 1
        with pytest.raises(WarmupError):
            smoke_buckets(lambda lines: ["too", "many", "outputs", "!"],
                          ["hello"], "vX", "boot-warmup", "here")

    def test_warm_executor_single_call_without_perf(self):
        assert not obs.PERF.enabled
        calls = []

        def factory(bundle_dir, manifest):
            def translate(lines):
                calls.append(list(lines))
                return list(lines)
            return translate

        warm_executor("/b", None, factory, list(DEFAULT_GOLDEN))
        # perf plane off → the historical ONE combined smoke call
        assert calls == [list(DEFAULT_GOLDEN)]


# ---------------------------------------------------------------------------
# ACCEPTANCE: lifecycle swap — per-bucket warmup telemetry, zero
# steady-state recompiles
# ---------------------------------------------------------------------------

class TestSwapCompileTelemetry:
    def test_swap_warms_buckets_and_traffic_never_recompiles(self,
                                                             tmp_path):
        r = msm.Registry()
        p = enable_perf(r)
        obs.TRACER.enable()
        mp = str(tmp_path / "m.npz")

        def factory(bundle_dir, manifest):
            return lambda lines: [f"b{manifest['seq']}:{ln}"
                                  for ln in lines]

        ctrl = SwapController(factory, metrics_registry=r)
        ctrl.seed_live(0, "boot", lambda lines: [f"v1:{ln}"
                                                 for ln in lines])
        bdir = bdl.write_bundle(
            mp, {"m.npz": lambda pth: open(pth, "w").close()})
        v = ctrl.ingest(bdir, bdl.validate_bundle(bdir)[2])
        assert v.state == "live"
        name = os.path.basename(bdir)
        # warmup compile telemetry PER SHAPE BUCKET, trigger=swap-warmup
        assert r.get("marian_compile_total") \
                .labels("swap-warmup", "w8").value == 1
        assert r.get("marian_compile_total") \
                .labels("swap-warmup", "w16").value == 1
        assert r.get("marian_compile_seconds_total") \
                .labels("swap-warmup", "w8").value > 0

        async def traffic():
            sched = ContinuousScheduler(ctrl.route, registry=r,
                                        version_fn=ctrl.live_version_name,
                                        window_s=0)
            sched.start()
            # every sentence lands in a warmed bucket (w8 or w16)
            await sched.submit(["x y z", "a b"])
            await sched.submit(
                ["one two three four five six seven eight nine"])
            await sched.stop()

        run(traffic())
        # ZERO steady-state recompile events after the warmed swap
        assert p.steady_recompiles() == 0
        _, events = obs.TRACER.snapshot()
        assert not [e for e in events if e["name"] == "perf.recompile"]
        # and the capacity integrals carry the new version's label
        assert r.get("marian_perf_device_seconds_total") \
                .labels(name).value > 0
        assert r.get("marian_perf_tokens_total").labels(name).value \
            == 4 + 3 + 10             # whitespace tokens + EOS each


class TestBootWarmup:
    def test_boot_warmup_matches_scheduler_version_label(self):
        """--warmup-on-boot without a lifecycle: buckets must be warmed
        under the scheduler's own version label ('unversioned'), else
        every warmed bucket still reads as a steady-state recompile —
        the exact false incident the flag exists to prevent."""
        r = msm.Registry()
        p = enable_perf(r)
        obs.TRACER.enable()

        async def main():
            app = ServingApp(
                Options({"metrics-port": 0, "max-queue": 64,
                         "warmup-on-boot": True}),
                translate_lines=lambda lines: [ln.upper()
                                               for ln in lines],
                registry=r)
            await app.start()
            try:
                # golden buckets are w8 and w16; traffic lands in both
                await app.handle_text("a b c")
                await app.handle_text(
                    "one two three four five six seven eight nine")
            finally:
                await app.shutdown(drain_timeout=2)

        run(main())
        assert r.get("marian_compile_total") \
                .labels("boot-warmup", "w8").value == 1
        assert r.get("marian_compile_total") \
                .labels("boot-warmup", "w16").value == 1
        assert p.steady_recompiles() == 0
        _, events = obs.TRACER.snapshot()
        assert not [e for e in events if e["name"] == "perf.recompile"]

    def test_boot_warmup_runs_even_with_perf_off(self):
        """--warmup-on-boot is about warm jit caches, not telemetry: it
        must run (executor called per golden bucket) even when
        --perf-accounting is off — only the compile telemetry is
        skipped."""
        assert not obs.PERF.enabled
        calls = []

        async def main():
            app = ServingApp(
                Options({"metrics-port": 0, "max-queue": 64,
                         "warmup-on-boot": True}),
                translate_lines=lambda lines: (calls.append(list(lines))
                                               or list(lines)),
                registry=msm.Registry())
            await app.start()
            await app.shutdown(drain_timeout=2)

        run(main())
        # one warmup call per golden width bucket, before any traffic
        assert calls == [["hello", "a b c d"],
                         ["the quick brown fox jumps over the lazy dog"]]


# ---------------------------------------------------------------------------
# scheduler exports (CPU stub): chip-seconds/token + headroom
# ---------------------------------------------------------------------------

class TestSchedulerPerfExports:
    def test_batch_path_exports_capacity_gauges(self):
        r = msm.Registry()
        p = enable_perf(r)

        def slowish(lines):
            time.sleep(0.01)
            return [ln.upper() for ln in lines]

        async def main():
            sched = ContinuousScheduler(slowish, registry=r,
                                        version_fn=lambda: "vCPU",
                                        window_s=0)
            p.set_capacity_inputs(sched.queued_units, 64)
            sched.start()
            for i in range(3):
                await sched.submit([f"w{i} w w", f"v{i} v"])
            await sched.stop()

        run(main())
        text = r.render()
        assert 'marian_perf_chip_seconds_per_token{model_version="vCPU"}' \
            in text
        cspt = r.get("marian_perf_chip_seconds_per_token") \
                .labels("vCPU").value
        assert cspt > 0
        assert r.get("marian_perf_device_seconds_total") \
                .labels("vCPU").value >= 0.03
        hr = p.headroom()
        assert 0.0 <= hr <= 1.0
        assert "marian_capacity_headroom_ratio" in text
        # device seconds are measured on the worker thread to the result
        # fence — the serve.batch span of a traced run carries them too
        assert lint_metrics_text(text) == []

    def test_bisection_device_time_still_accounted(self):
        r = msm.Registry()
        enable_perf(r)
        state = {"n": 0}

        def poison(lines):
            state["n"] += 1
            if "bad" in lines:
                raise ValueError("poison")
            return list(lines)

        async def main():
            sched = ContinuousScheduler(poison, registry=r, window_s=0.01)
            sched.start()
            f1 = sched.submit(["good one"])
            f2 = sched.submit(["bad"])
            assert await f1 == ["good one"]
            with pytest.raises(RuntimeError):
                await f2
            await sched.stop()

        run(main())
        # the failed + bisected batch's device time was spent and is
        # integrated (labels: version_fn default "unversioned")
        assert r.get("marian_perf_device_seconds_total") \
                .labels("unversioned").value > 0


# ---------------------------------------------------------------------------
# SLO engine: burn-rate math
# ---------------------------------------------------------------------------

def outcomes_counter(r):
    return r.counter("marian_serving_request_outcomes_total", "",
                     labels=("outcome", "model_version"))


def latency_hist(r):
    return r.histogram("marian_serving_request_latency_seconds", "")


class TestSloEngineMath:
    def test_availability_burn_and_budget(self):
        r = msm.Registry()
        c = outcomes_counter(r)
        clock = {"t": 0.0}
        eng = SloEngine(registry=r, availability=0.99, window_s=10,
                        clock=lambda: clock["t"])
        eng.tick(now=0.0)        # baseline: pre-engine history excluded
        c.labels("ok", "v").inc(99)
        c.labels("failure", "v").inc(1)
        st = eng.tick(now=1.0)
        av = st["objectives"]["availability"]
        # 1% bad on a 1% budget → burn exactly 1.0
        assert av["burn"]["10s"] == pytest.approx(1.0)
        assert not av["fast_burn"] and not av["slow_burn"]
        # burn 1.0 consumes budget at exactly the sustainable rate
        assert av["budget_remaining"] == pytest.approx(0.0, abs=1e-6)
        assert r.get("marian_slo_burn_rate") \
                .labels("availability", "10s").value \
            == pytest.approx(1.0)
        assert r.get("marian_slo_objective_target") \
                .labels("availability").value == pytest.approx(0.99)
        assert r.get("marian_slo_budget_remaining_ratio") \
                .labels("availability").value == pytest.approx(0.0,
                                                               abs=1e-6)

    def test_windowed_burn_recovers_as_errors_age_out(self):
        r = msm.Registry()
        c = outcomes_counter(r)
        clock = {"t": 0.0}
        eng = SloEngine(registry=r, availability=0.9, window_s=10,
                        clock=lambda: clock["t"])
        eng.tick(now=0.0)
        c.labels("failure", "v").inc(10)          # a burst of pure errors
        st = eng.tick(now=1.0)
        assert st["objectives"]["availability"]["burn"]["10s"] \
            == pytest.approx(10.0)                # 100% bad / 10% budget
        # 30s later the short window holds only fresh, clean traffic
        c.labels("ok", "v").inc(100)
        eng.tick(now=20.0)
        st = eng.tick(now=40.0)
        assert st["objectives"]["availability"]["burn"]["10s"] \
            == pytest.approx(0.0)
        # the slow (100s) window still remembers the burst
        assert st["objectives"]["availability"]["burn"]["100s"] > 0

    def test_latency_objective_reads_histogram_buckets(self):
        r = msm.Registry()
        h = latency_hist(r)
        eng = SloEngine(registry=r, p99_ms=250, window_s=10,
                        clock=lambda: 0.0)
        eng.tick(now=0.0)        # baseline
        for _ in range(98):
            h.observe(0.05)                        # under target
        h.observe(0.5)
        h.observe(2.0)                             # two breaches / 100
        st = eng.tick(now=1.0)
        lat = st["objectives"]["latency_p99"]
        # 2% over target on a 1% budget → burn 2.0
        assert lat["burn"]["10s"] == pytest.approx(2.0)

    def test_fast_burn_fires_event_alert_and_flight_dump(self, tmp_path):
        r = msm.Registry()
        c = outcomes_counter(r)
        obs.TRACER.enable()
        obs.FLIGHT.arm(str(tmp_path))
        eng = SloEngine(registry=r, availability=0.999, window_s=10,
                        clock=lambda: 0.0)
        obs.FLIGHT.add_snapshot_provider("slo", eng.state)
        try:
            eng.tick(now=0.0)
            c.labels("failure", "v").inc(50)       # 100% bad: burn 1000x
            eng.tick(now=1.0)
            assert r.get("marian_slo_alerts_total") \
                    .labels("availability", "fast").value == 1
            _, events = obs.TRACER.snapshot()
            names = [e["name"] for e in events]
            assert "slo.fast_burn" in names
            # the async dump lands shortly after
            deadline = time.time() + 5
            dumps = []
            while not dumps and time.time() < deadline:
                dumps = [f for f in os.listdir(tmp_path)
                         if f.startswith("flight-")
                         and "slo-fast-burn" in f]
                time.sleep(0.02)
            assert dumps, "fast-burn flight dump never appeared"
            payload = json.loads((tmp_path / dumps[0]).read_text())
            # the dump shows the PROMISE being broken, not just latencies
            assert payload["extra"]["slo"]["objectives"]["availability"][
                "fast_burn"] is True
            assert payload["slo"]["objectives"]["availability"][
                "target"] == 0.999
            # recovery emits the falling-edge event and no second alert
            c.labels("ok", "v").inc(100000)
            eng.tick(now=2.0)
            eng.tick(now=150.0)
            _, events = obs.TRACER.snapshot()
            assert "slo.recovered" in [e["name"] for e in events]
            assert r.get("marian_slo_alerts_total") \
                    .labels("availability", "fast").value == 1
        finally:
            obs.FLIGHT.remove_snapshot_provider("slo")

    def test_maybe_build_engine_flags(self):
        assert maybe_build_engine(Options({})) is None
        eng = maybe_build_engine(Options({"slo-p99-ms": 100,
                                          "slo-window": 5}),
                                 registry=msm.Registry())
        assert eng is not None and eng.window_s == 5
        with pytest.raises(ValueError):
            SloEngine(registry=msm.Registry())


# ---------------------------------------------------------------------------
# ACCEPTANCE: slow-translate fault → fast-burn → dump with SLO state
# ---------------------------------------------------------------------------

class TestSlowTranslateDrivesFastBurn:
    def test_injected_slow_decode_breaks_latency_slo(self, tmp_path):
        """MARIAN_FAULTS serving.translate=hang:0.05@* makes every device
        call slow; with --slo-p99-ms 10 declared, the burn-rate engine
        must raise the fast-burn alert, stamp the timeline, and dump
        flight state that shows the latency promise being broken."""
        # the process-wide registry, like production: the flight dump's
        # metrics member must hold the promise-breaking histogram
        obs.TRACER.enable()
        obs.FLIGHT.arm(str(tmp_path))
        eng = SloEngine(p99_ms=10, window_s=10, clock=time.monotonic)
        obs.FLIGHT.add_snapshot_provider("slo", eng.state)
        try:
            async def main():
                sched = ContinuousScheduler(lambda lines: list(lines),
                                            window_s=0)
                sched.start()
                eng.tick()
                with fp.active("serving.translate=hang:0.05@*"):
                    for i in range(4):
                        await sched.submit([f"slow {i}"])
                await sched.stop()

            run(main())
            st = eng.tick()
            lat = st["objectives"]["latency_p99"]
            assert lat["fast_burn"] is True      # 100% breach / 1% budget
            _, events = obs.TRACER.snapshot()
            assert "slo.fast_burn" in [e["name"] for e in events]
            deadline = time.time() + 5
            dumps = []
            while not dumps and time.time() < deadline:
                # .json only: the recorder writes a .<name>.json.tmp
                # and os.replace()s it into place — matching the tmp
                # name races the rename and read_text() gets ENOENT
                dumps = [f for f in os.listdir(tmp_path)
                         if "slo-fast-burn" in f
                         and f.endswith(".json")]
                time.sleep(0.02)
            assert dumps
            payload = json.loads((tmp_path / dumps[0]).read_text())
            assert payload["slo"]["objectives"]["latency_p99"][
                "fast_burn"] is True
            assert "marian_serving_request_latency_seconds" \
                in payload["metrics"]
        finally:
            obs.FLIGHT.remove_snapshot_provider("slo")


# ---------------------------------------------------------------------------
# /sloz endpoint
# ---------------------------------------------------------------------------

class TestSlozEndpoint:
    def test_sloz_roundtrip_with_engine_and_perf(self):
        r = msm.Registry()
        enable_perf(r)
        c = outcomes_counter(r)
        c.labels("ok", "v").inc(10)
        eng = SloEngine(registry=r, availability=0.99, window_s=10)
        eng.tick()
        srv = msm.MetricsServer(0, registry=r,
                                routes=slo_routes(lambda: eng)).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/sloz").read())
            assert doc["slo"]["enabled"] is True
            assert "availability" in doc["slo"]["objectives"]
            assert doc["perf"]["enabled"] is True
            assert "headroom" in doc["perf"]
        finally:
            srv.close()

    def test_sloz_disabled_still_answers(self):
        srv = msm.MetricsServer(0, registry=msm.Registry(),
                                routes=slo_routes(lambda: None)).start()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/sloz").read())
            assert doc["slo"] == {"enabled": False}
        finally:
            srv.close()

    def test_serving_app_routes_sloz_and_stops_engine(self):
        async def main():
            app = ServingApp(
                Options({"metrics-port": 0, "max-queue": 16,
                         "slo-p99-ms": 100.0, "slo-eval-interval": 0.1}),
                translate_lines=lambda lines: list(lines),
                registry=msm.Registry())
            assert app.slo is not None
            await app.start()
            assert app.slo._thread is not None
            await app.shutdown(drain_timeout=2)
            assert app.slo._thread is None

        run(main())


# ---------------------------------------------------------------------------
# process self-metrics + Prometheus text-format lint of a real scrape
# ---------------------------------------------------------------------------

class TestProcessMetricsAndPromlint:
    def test_process_metrics_registered_and_sane(self):
        r = msm.Registry()
        msm.register_process_metrics(r)
        text = r.render()
        for name in ("process_start_time_seconds",
                     "process_uptime_seconds",
                     "process_resident_memory_bytes",
                     "process_open_fds"):
            assert name in text
        assert r.get("process_resident_memory_bytes").value > 1e6
        assert r.get("process_open_fds").value > 0
        assert 0 <= r.get("process_uptime_seconds").value < 1e7

    def test_real_scrape_lints_clean_default_and_exemplars(self):
        r = msm.Registry()
        h = r.histogram("t_lat_seconds", "x", buckets=(0.1, 1.0),
                        labels=("lane",))
        h.labels("a").observe(0.05, trace_id="ex01")
        h.labels("a").observe(5.0)
        r.counter("t_ok_total", "x").inc(3)
        g = r.gauge("t_depth", "x")
        g.set(7)
        srv = msm.MetricsServer(0, registry=r).start()
        try:
            base = f"http://127.0.0.1:{srv.port}/metrics"
            plain = urllib.request.urlopen(base).read().decode()
            assert lint_metrics_text(plain) == []
            # process self-metrics rode along with the server start
            assert "process_open_fds" in plain
            with_ex = urllib.request.urlopen(
                base + "?exemplars=1").read().decode()
            assert 'trace_id="ex01"' in with_ex
            assert lint_metrics_text(with_ex, allow_exemplars=True) == []
            # and the exemplar form is a violation under strict 0.0.4
            assert any("exemplar" in p
                       for p in lint_metrics_text(with_ex))
        finally:
            srv.close()

    @pytest.mark.parametrize("bad,why", [
        ("up 1", "no preceding # TYPE"),
        ("# TYPE m counter\nm{le=} 1", "malformed labels"),
        ("# TYPE m counter\nm notanumber", "unparseable value"),
        ("# TYPE m counter\nm 1\nm 1", "duplicate series"),
        ("# TYPE h histogram\nh_bucket{le=\"1\"} 2\n"
         "h_bucket{le=\"+Inf\"} 1\nh_sum 1\nh_count 1",
         "not cumulative"),
        ("# TYPE h histogram\nh_bucket{le=\"1\"} 1\nh_sum 1\nh_count 1",
         "missing +Inf"),
        ("# TYPE h histogram\nh_bucket{le=\"+Inf\"} 2\nh_sum 1\n"
         "h_count 1", "!= _count"),
        ("# TYPE m counter\nm{a=\"x\" b=\"y\"} 1", "malformed labels"),
        ("# TYPE m counter\nm{a=\"x\"b=\"y\"} 1", "malformed labels"),
    ])
    def test_lint_catches_classic_breakage(self, bad, why):
        probs = lint_metrics_text(bad)
        assert probs, why
        assert any(why.split()[0] in p or why in p for p in probs), \
            (why, probs)

    def test_lint_allows_trailing_comma_labels(self):
        # legal per the text format; parsers accept it
        assert lint_metrics_text(
            "# TYPE m counter\nm{a=\"1\",} 1") == []


# ---------------------------------------------------------------------------
# ACCEPTANCE: loadgen --sweep reads the gauges back over a real server
# ---------------------------------------------------------------------------

def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "loadgen", os.path.join(ROOT, "scripts", "loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestLoadgenSweep:
    def test_sweep_capacity_table_against_cpu_server(self, capsys):
        registry = msm.REGISTRY        # loadgen scrapes the real surface
        enable_perf(registry)
        started = threading.Event()
        info = {}

        def server_thread():
            async def main():
                app = ServingApp(
                    Options({"metrics-port": 0, "max-queue": 256,
                             "batch-token-budget": 256}),
                    translate_lines=lambda lines: [ln.upper()
                                                   for ln in lines])
                obs.PERF.set_capacity_inputs(app.scheduler.queued_units,
                                             256)
                await app.start()
                server = await asyncio.start_server(
                    _make_tcp_handler(app), "127.0.0.1", 0)
                info["port"] = server.sockets[0].getsockname()[1]
                info["loop"] = asyncio.get_event_loop()
                info["stop"] = asyncio.Event()
                started.set()
                async with server:
                    await info["stop"].wait()
                await app.shutdown(drain_timeout=2)

            asyncio.run(main())

        t = threading.Thread(target=server_thread, daemon=True)
        t.start()
        assert started.wait(10)
        metrics_srv = msm.MetricsServer(0, registry=registry).start()
        try:
            loadgen = _load_loadgen()
            rc = loadgen.main([
                "--port", str(info["port"]), "--transport", "tcp",
                "--metrics-port", str(metrics_srv.port),
                "--sweep", "20,40", "--duration", "0.5",
                "--sentences", "2", "--words", "4"])
            assert rc == 0
        finally:
            metrics_srv.close()
            info["loop"].call_soon_threadsafe(info["stop"].set)
            t.join(timeout=10)
        out = capsys.readouterr().out
        assert "chip_s/tok" in out and "headroom" in out \
            and "hr_gauge" in out
        rows = [ln for ln in out.splitlines()
                if ln.strip().startswith(("20", "40"))]
        assert len(rows) == 2
        # chip-seconds/token + both headroom readings (step-local and
        # the server's rolling gauge) read back as real numbers
        for ln in rows:
            cspt = float(ln.split()[-3])
            assert cspt > 0
            for col in (-2, -1):
                hr = float(ln.split()[col])
                assert 0.0 <= hr <= 1.0
        assert "capacity:" in out


# ---------------------------------------------------------------------------
# metric census: every registered series is exercised by a test
# (MT-METRIC-UNTESTED's corpus — see analysis/rules/metrics_hygiene.py)
# ---------------------------------------------------------------------------

class TestMetricCensus:
    def test_training_scheduler_series_render(self):
        from marian_tpu.training.scheduler import Scheduler
        from marian_tpu.training.training_state import TrainingState
        enable_perf()
        obs.PERF.set_geometry(emb=16, ffn=32, enc_depth=1, dec_depth=1,
                              vocab=50, n_devices=1, peak_flops=1e9)
        sched = Scheduler(Options({"disp-freq": "1u"}), TrainingState())
        sched.update(2.5, labels=10, sentences=2, src_words=12, lr=0.1)
        text = msm.REGISTRY.render()
        for name in ("marian_train_cost", "marian_train_words_per_second",
                     "marian_train_learn_rate",
                     "marian_train_updates_total",
                     "marian_train_labels_total",
                     "marian_train_chip_seconds_per_token",
                     "marian_train_mfu"):
            assert name in text, name
        assert msm.REGISTRY.get(
            "marian_train_chip_seconds_per_token").value > 0

    def test_step_timer_phase_series_render(self):
        from marian_tpu.common.profiling import StepTimer
        st = StepTimer()
        st.phase("data")
        st.phase("dispatch")
        st.stop()
        st.report()
        assert "marian_step_phase_seconds" in msm.REGISTRY.render()

    def test_lifecycle_controller_series_render(self):
        r = msm.Registry()
        ctrl = SwapController(lambda d, m: (lambda lines: list(lines)),
                              metrics_registry=r)
        ctrl.seed_live(0, "boot", lambda lines: list(lines))
        ctrl.route(["x"])
        text = r.render()
        for name in ("marian_lifecycle_warming",
                     "marian_model_latency_seconds",
                     "marian_model_requests_total"):
            assert name in text, name

    def test_compile_backend_series_registered(self):
        r = msm.Registry()
        enable_perf(r)
        # the jax listener path is environment-dependent; the series
        # itself must exist (and stay parseable) regardless
        obs.PERF.m_backend_s.labels("steady-state").inc(0.0)
        assert "marian_compile_backend_seconds_total" in r.render()
