"""Quiesce-based lifecycle + brownout degradation for paged iteration
serving (ISSUE 11): the scheduler's quiesce protocol (stop joins, drain
under --quiesce-deadline, evict-with-retry, re-point the engine at a
step boundary), SwapController composition in iteration mode
(swap-under-load, temporal canary, auto-rollback), the brownout ladder
(signal-driven degradation levels), the KV-pool invariant auditor with
its corruption drills, the watchdog-trip mid-round contract, and the
loadgen retry satellite. Runs under JAX_PLATFORMS=cpu with the tiny
real transformer (MARIAN_POOL_AUDIT=1 is armed process-wide by
conftest, so every engine round here is audited)."""

import asyncio
import importlib.util
import os
import threading

import pytest

from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.ops.pallas.kv_pool import KVPool, PoolCorruption
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.admission import AdmissionController, Overloaded
from marian_tpu.serving.brownout import BrownoutController
from marian_tpu.serving.lifecycle import LIVE, SwapController
from marian_tpu.serving.scheduler import (ContinuousScheduler,
                                          DispatchStalled, RowEvicted)
from marian_tpu.training import bundle as bdl
from marian_tpu.translator.iteration import (EngineExecutor,
                                             PagedDecodeEngine)

from tests.test_beam_search import tiny_model
from tests.test_iteration import TEXTS, make_engine, tiny  # noqa: F401

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one page of the tiny engine (page_len 4): 2 (K+V) x dec_depth 2 x
# heads 2 x page_len 4 x dh 8 x 4 bytes
PAGE_BYTES = 2 * 2 * 2 * 4 * 8 * 4


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """Quiesce/brownout cross the watcher, loop, worker, brownout and
    metrics threads; the shared witness asserts every observed lock
    acquisition order stays inside the static lattice."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _ownership_witness(ownership_witness):
    """Quiesce drains/evictions release what joins acquired; the shared
    witness asserts those observed pairings stay inside the static
    ownership graph (ISSUE 15)."""
    yield


def run(coro):
    return asyncio.run(coro)


def make_sched(tiny, registry=None, engine=None, **kw):
    reg = registry if registry is not None else msm.Registry()
    eng = engine if engine is not None else make_engine(tiny,
                                                        registry=reg)
    sched = ContinuousScheduler(None, registry=reg,
                                batching_mode="iteration", engine=eng,
                                window_s=0.0, **kw)
    return sched, eng, reg


def solo_outputs(tiny, texts):
    return [make_engine(tiny, max_rows=1).decode_texts([t])[0]
            for t in texts]


async def wait_for(pred, timeout=20.0, interval=0.01):
    loop = asyncio.get_event_loop()
    dl = loop.time() + timeout
    while not pred():
        if loop.time() >= dl:
            return False
        await asyncio.sleep(interval)
    return True


# ---------------------------------------------------------------------------
# the pool invariant auditor (tentpole piece 3)
# ---------------------------------------------------------------------------

class TestPoolAuditor:
    def test_kvpool_audit_clean_and_violations(self):
        p = KVPool(8, 4)
        assert p.audit() == []
        p.claim("a", 2)
        p.claim("b", 3)
        assert p.audit() == []
        p.release("a")
        assert p.audit() == []
        # leak: drop a claim without returning its pages. With the
        # refcounted pool (ISSUE 12) this surfaces as the orphaned
        # refcounts themselves (phantom refcount — no table reference
        # names the page), a sharper report than the old count-only
        # "leaked" line
        p._claims.pop("b")
        bad = p.audit()
        assert bad and all("phantom refcount" in v for v in bad)
        # a leak the refcount map cannot see (refs dropped too) still
        # trips the page-accounting total
        for pg in list(p._refs):
            del p._refs[pg]
        bad = p.audit()
        assert any("leaked" in v for v in bad)
        # double-free: a page both free and claimed
        p2 = KVPool(8, 4)
        pages = p2.claim("a", 2)
        p2._free.extend(reversed(pages))
        bad = p2.audit()
        assert any("double-free" in v for v in bad)

    def test_engine_audit_clean_through_decode(self, tiny):
        eng = make_engine(tiny)
        assert eng.audit(context="test") == []
        eng.admit_and_step([(0, TEXTS[0]), (1, TEXTS[1])])
        assert eng.audit(context="test") == []
        guard = 0
        while not eng.idle():
            eng.admit_and_step([])
            guard += 1
            assert guard < 100
        assert eng.audit(context="test") == []
        assert eng.pool.free_pages() == eng.pool.usable_pages

    def test_double_free_drill_detected(self, tiny):
        """The pool.double_free catalog point corrupts REAL pool state;
        the continuous audit (MARIAN_POOL_AUDIT=1) must catch it and
        fail the round with the retriable PoolCorruption."""
        reg = msm.Registry()
        eng = make_engine(tiny, registry=reg)
        eng.admit_and_step([(0, TEXTS[0])])       # an active row to corrupt
        with fp.active("pool.double_free=fail@1"):
            with pytest.raises(PoolCorruption, match="audit failed"):
                eng.admit_and_step([])
        assert PoolCorruption.retriable
        assert reg.get(
            "marian_serving_pool_audit_failures_total").value >= 1
        assert reg.get("marian_serving_pool_audits_total").value >= 1

    def test_table_corrupt_drill_detected(self, tiny):
        eng = make_engine(tiny)
        eng.admit_and_step([(0, TEXTS[0])])
        with fp.active("pool.table_corrupt=fail@1"):
            with pytest.raises(PoolCorruption,
                               match="table corruption"):
                eng.admit_and_step([])

    def test_row_exit_leak_detector(self, tiny, monkeypatch):
        """The always-on leak check at row exit: a release that returns
        the wrong page count is reported even without MARIAN_POOL_AUDIT."""
        reg = msm.Registry()
        eng = make_engine(tiny, registry=reg)
        eng.admit_and_step([(0, TEXTS[0])])
        real_release = eng.pool.release
        monkeypatch.setattr(eng.pool, "release",
                            lambda key: real_release(key) - 1)
        eng._evict(0)
        assert reg.get(
            "marian_serving_pool_audit_failures_total").value >= 1

    def test_fatal_reject_names_page_requirement(self, tiny):
        """ISSUE 11 satellite: the never-fitting FATAL reject must
        report the computed page requirement vs the pool's capacity —
        operator-actionable, not opaque."""
        eng = make_engine(tiny, pool_bytes=1 * PAGE_BYTES)
        assert eng.pool.usable_pages == 1
        res = eng.admit_and_step([(0, TEXTS[0])])   # cap 12 -> 3 pages
        assert res.rejected[0][1] == "too_large"
        detail = res.reject_detail[0]
        assert "3 KV" in detail and "1 allocatable" in detail
        assert "--kv-pool-bytes" in detail

    def test_fatal_reject_detail_reaches_the_client(self, tiny):
        eng = make_engine(tiny, pool_bytes=1 * PAGE_BYTES)
        sched, eng, reg = make_sched(tiny, engine=eng)

        async def main():
            sched.start()
            f = sched.submit([TEXTS[0]])
            with pytest.raises(RuntimeError,
                               match=r"cannot be admitted.*3 KV"):
                await asyncio.wait_for(f, timeout=20)
            await sched.stop()

        run(main())


# ---------------------------------------------------------------------------
# the quiesce protocol (tentpole piece 1, scheduler level)
# ---------------------------------------------------------------------------

class TestQuiesce:
    def test_drain_then_install_swaps_engine(self, tiny):
        """A quiesce with a generous deadline drains every active row
        on the OLD engine (zero client-visible failures), audits it
        clean, installs the new engine at an empty-join-set boundary,
        and resumes joins on the new engine."""
        sched, eng_a, reg = make_sched(tiny)
        eng_b = make_engine(tiny)
        holder = {}

        async def main():
            sched.start()
            f1 = sched.submit(TEXTS[:2])
            await asyncio.sleep(0.05)
            op = sched.request_quiesce(
                lambda: sched.install_engine(eng_b), 30.0,
                "test-swap", wait=False)
            holder["r1"] = await f1
            assert await wait_for(op.event.is_set)
            holder["op"] = op
            f2 = sched.submit([TEXTS[2]])
            holder["r2"] = await f2
            await sched.stop()

        run(main())
        op = holder["op"]
        assert op.ok and op.install_ok and op.evicted == 0
        assert sched.engine is eng_b
        solo = solo_outputs(tiny, TEXTS[:3])
        assert holder["r1"] == solo[:2]          # drained on the old engine
        assert holder["r2"] == [solo[2]]         # served by the new engine
        # the old engine exited with zero leaked pages
        assert eng_a.pool.free_pages() == eng_a.pool.usable_pages
        assert sched.m_quiesces.value == 1
        assert sched.m_quiesce_evictions.value == 0
        text = reg.render()
        assert "marian_serving_quiesces_total 1" in text
        assert "marian_serving_quiescing 0" in text

    def test_deadline_evicts_with_retry_and_frees_pages(self, tiny):
        """Rows past --quiesce-deadline are evicted with the retriable
        RowEvicted (!!SERVER-RETRY), their pages freed; the install
        still happens and the resend succeeds on the new engine."""
        sched, eng_a, reg = make_sched(tiny)
        eng_b = make_engine(tiny)
        holder = {}

        async def main():
            sched.start()
            f1 = sched.submit([TEXTS[4]])
            # wait until the row actually JOINED (compile included)
            assert await wait_for(lambda: sched.m_joins.value >= 1)
            op = sched.request_quiesce(
                lambda: sched.install_engine(eng_b), 0.0,
                "test-evict", wait=False)
            with pytest.raises(RowEvicted, match="quiesce deadline"):
                await asyncio.wait_for(f1, timeout=20)
            assert await wait_for(op.event.is_set)
            holder["op"] = op
            holder["r2"] = await sched.submit([TEXTS[4]])
            await sched.stop()

        run(main())
        assert holder["op"].evicted >= 1
        assert holder["op"].install_ok
        assert sched.engine is eng_b
        assert RowEvicted.retriable
        assert eng_a.pool.free_pages() == eng_a.pool.usable_pages
        assert eng_a.audit(context="test") == []
        assert sched.m_quiesce_evictions.value >= 1
        assert holder["r2"] == solo_outputs(tiny, [TEXTS[4]])
        # the evicted request resolved with the 'evicted' outcome label
        out = reg.get("marian_serving_request_outcomes_total")
        assert any(k[0] == "evicted" and c.value >= 1
                   for k, c in out.children().items())

    def test_kill_mid_quiesce_faultpoint_recovers(self, tiny):
        """serving.quiesce sits at the quiesce boundary; a 'fail' there
        aborts ONE completion attempt (supervision recovers and the
        next round finishes the quiesce). kill mode is the chaos
        schedule's kill-mid-quiesce drill (scripts/chaos.py
        --iteration)."""
        sched, eng_a, reg = make_sched(tiny)
        eng_b = make_engine(tiny)

        async def main():
            sched.start()
            with fp.active("serving.quiesce=fail@1"):
                op = sched.request_quiesce(
                    lambda: sched.install_engine(eng_b), 5.0,
                    "test-kill", wait=False)
                assert await wait_for(op.event.is_set)
                assert fp.hits("serving.quiesce") >= 2
            await sched.stop()
            return op

        op = run(main())
        assert op.ok and sched.engine is eng_b

    def test_cancelled_quiesce_never_installs(self, tiny):
        """A waiter that gives up withdraws its op (cancel_quiesce —
        request_quiesce does this on wait-budget expiry): the install
        must never run late against a possibly-released target; joins
        resume on the old engine."""
        sched, eng_a, reg = make_sched(tiny)
        eng_b = make_engine(tiny)

        async def main():
            sched.start()
            op = sched.request_quiesce(
                lambda: sched.install_engine(eng_b), 30.0,
                "withdrawn", wait=False)
            sched.cancel_quiesce(op)
            r = await asyncio.wait_for(sched.submit([TEXTS[1]]),
                                       timeout=30)
            assert await wait_for(op.event.is_set)
            await sched.stop()
            return r

        r = run(main())
        assert sched.engine is eng_a       # install never ran
        assert r == solo_outputs(tiny, [TEXTS[1]])
        assert sched.m_quiesces.value == 0

    def test_stop_releases_pending_quiesce_waiters(self, tiny):
        sched, eng_a, reg = make_sched(tiny)

        async def main():
            sched.start()
            await sched.stop()
            # worker gone: a pending op must still release its waiter
            op = sched.request_quiesce(lambda: None, 0.1, "dangling",
                                       wait=False)
            await sched.stop()
            return op

        op = run(main())
        assert op.event.is_set() and not op.ok


# ---------------------------------------------------------------------------
# SwapController x PagedDecodeEngine composition (tentpole piece 1)
# ---------------------------------------------------------------------------

def commit_bundle(model_path, tag="x", member="m.npz"):
    def write(p):
        with open(p, "w", encoding="utf-8") as fh:
            fh.write(tag)
    return bdl.write_bundle(str(model_path), {member: write})


def make_iter_controller(tiny, sched, reg, built=None, **kw):
    """SwapController wired for iteration mode over real tiny engines:
    the factory builds a fresh engine per bundle (content ignored — the
    quiesce/health machinery under test is model-agnostic)."""
    def factory(bundle_dir, manifest):
        ex = EngineExecutor(make_engine(tiny))
        if built is not None:
            built.append(ex)
        return ex

    ctrl = SwapController(factory, metrics_registry=reg,
                          golden=["w1 w2"], **kw)
    ctrl.seed_live(0, "boot", EngineExecutor(sched.engine))
    ctrl.attach_iteration(sched, quiesce_deadline=20.0)
    sched.version_fn = ctrl.live_version_name
    return ctrl


def ingest_in_thread(ctrl, bdir):
    manifest = bdl.validate_bundle(bdir)[2]
    t = threading.Thread(target=ctrl.ingest, args=(bdir, manifest),
                         daemon=True)
    t.start()
    return t


class TestLifecycleIteration:
    def test_swap_under_load_zero_failures(self, tiny, tmp_path):
        """The acceptance shape in miniature: requests decoding while a
        bundle is ingested on the watcher thread; the swap quiesces at
        a step boundary, every in-flight request completes (deadline is
        generous — zero evictions), the live version flips, and the old
        engine exits audit-clean with zero leaked pages."""
        reg = msm.Registry()
        sched, eng_a, _ = make_sched(tiny, registry=reg)
        ctrl = make_iter_controller(tiny, sched, reg)
        mp = str(tmp_path / "m.npz")
        holder = {}

        async def main():
            sched.start()
            futs = [sched.submit([TEXTS[i]]) for i in range(3)]
            assert await wait_for(lambda: sched.m_joins.value >= 1)
            t = ingest_in_thread(ctrl, commit_bundle(mp))
            holder["results"] = await asyncio.gather(
                *futs, return_exceptions=True)
            assert await wait_for(lambda: not t.is_alive(), timeout=60)
            holder["r2"] = await sched.submit([TEXTS[0]])
            await sched.stop()

        run(main())
        # zero client-visible failures: every request resolved ok
        solo = solo_outputs(tiny, TEXTS[:3])
        assert holder["results"] == [[s] for s in solo]
        assert holder["r2"] == [solo[0]]
        assert ctrl.live_version_name() == "bundle-00000001"
        live = ctrl.live_version()
        assert live.state == LIVE
        assert sched.engine is live.executor.engine
        assert sched.engine is not eng_a
        # the drained boot engine leaked nothing
        assert eng_a.pool.free_pages() == eng_a.pool.usable_pages
        assert eng_a.audit(context="test") == []
        assert reg.get("marian_lifecycle_swaps_total").value == 1
        assert sched.m_quiesces.value == 1

    def test_auto_rollback_on_round_failures(self, tiny, tmp_path):
        """A regressed NEW live engine: rounds fail, victims are
        evicted RETRIABLY (a warm rollback target exists), the
        controller's windowed health trips, and dispatch quiesce-rolls
        back to the previous engine — the resend succeeds there."""
        reg = msm.Registry()
        sched, eng_a, _ = make_sched(tiny, registry=reg)
        built = []
        ctrl = make_iter_controller(tiny, sched, reg, built=built,
                                    rollback_min_batches=2)
        mp = str(tmp_path / "m.npz")
        holder = {}

        async def main():
            sched.start()
            t = ingest_in_thread(ctrl, commit_bundle(mp))
            assert await wait_for(lambda: not t.is_alive(), timeout=60)
            assert ctrl.live_version_name() == "bundle-00000001"
            # break the new live engine: every round now raises
            bad = built[-1].engine

            def boom(*a, **k):
                raise RuntimeError("regressed weights")
            bad.admit_and_step = boom
            evicted = []
            for _ in range(3):
                try:
                    await asyncio.wait_for(sched.submit([TEXTS[1]]),
                                           timeout=20)
                except RowEvicted as e:
                    evicted.append(e)
                if ctrl.live_version_name() == "boot":
                    break
            assert await wait_for(
                lambda: ctrl.live_version_name() == "boot"
                and sched.engine is eng_a, timeout=20)
            holder["evicted"] = evicted
            holder["r"] = await asyncio.wait_for(
                sched.submit([TEXTS[1]]), timeout=30)
            await sched.stop()

        run(main())
        assert holder["evicted"]          # retriable, not hard failures
        assert holder["r"] == solo_outputs(tiny, [TEXTS[1]])
        assert reg.get("marian_lifecycle_rollbacks_total").value == 1

    def test_temporal_canary_promotes_in_place(self, tiny, tmp_path):
        """Iteration-mode canary is TEMPORAL: the candidate takes all
        joins for its evaluation window (one quiesce), healthy rounds
        promote it in place — no second engine re-point."""
        reg = msm.Registry()
        sched, eng_a, _ = make_sched(tiny, registry=reg)
        built = []
        ctrl = make_iter_controller(tiny, sched, reg, built=built,
                                    canary_fraction=0.25,
                                    canary_min_batches=3)
        mp = str(tmp_path / "m.npz")

        async def main():
            sched.start()
            t = ingest_in_thread(ctrl, commit_bundle(mp))
            assert await wait_for(lambda: not t.is_alive(), timeout=60)
            # the canary engine serves ALL joins during evaluation
            assert sched.engine is built[-1].engine
            r = await asyncio.wait_for(sched.submit([TEXTS[0]]),
                                       timeout=30)
            assert r == solo_outputs(tiny, [TEXTS[0]])
            # enough healthy rounds ran while decoding: promoted
            assert await wait_for(
                lambda: ctrl.live_version_name() == "bundle-00000001",
                timeout=20)
            await sched.stop()

        run(main())
        assert sched.engine is built[-1].engine
        assert sched.m_quiesces.value == 1       # promote = registry flip only
        assert reg.get("marian_lifecycle_swaps_total").value == 1


# ---------------------------------------------------------------------------
# the brownout ladder (tentpole piece 2)
# ---------------------------------------------------------------------------

class TestBrownoutLadder:
    def test_escalates_holds_and_cools(self):
        """Unit ladder walk with a fake clock: sustained pressure
        escalates one rung per hold window; sustained health cools one
        rung per cool window; every transition applies + counts."""
        reg = msm.Registry()
        applied = []
        hr = [1.0]
        bc = BrownoutController(apply_fn=applied.append,
                                headroom_fn=lambda: hr[0],
                                burn_fn=None, registry=reg,
                                headroom_floor=0.2, burn_threshold=0.0,
                                hold_s=10.0, cool_s=20.0,
                                clock=lambda: 0.0)
        assert bc.tick(0.0) == 0
        hr[0] = 0.05
        assert bc.tick(1.0) == 0          # pressure starts, not held yet
        assert bc.tick(11.0) == 1         # held 10s -> tighten
        assert bc.tick(12.0) == 1         # next rung needs its own hold
        assert bc.tick(21.0) == 2         # -> evict
        assert bc.tick(31.0) == 3         # -> shed
        assert bc.tick(41.0) == 3         # max level holds
        hr[0] = 0.9
        assert bc.tick(42.0) == 3         # healthy starts
        assert bc.tick(62.0) == 2         # cooled 20s -> down one
        assert bc.tick(82.0) == 1
        assert bc.tick(102.0) == 0
        assert applied == [1, 2, 3, 2, 1, 0]
        text = reg.render()
        assert "marian_brownout_level 0" in text
        assert 'marian_brownout_transitions_total{direction="up"} 3' \
            in text
        assert 'marian_brownout_transitions_total{direction="down"} 3' \
            in text
        st = bc.state()
        assert st["level"] == 0 and st["name"] == "normal"

    def test_burn_signal_escalates(self):
        burn = [0.0]
        bc = BrownoutController(apply_fn=lambda lvl: None,
                                headroom_fn=lambda: 1.0,
                                burn_fn=lambda: burn[0],
                                registry=msm.Registry(),
                                burn_threshold=14.4, hold_s=5.0)
        assert bc.tick(0.0) == 0
        burn[0] = 20.0
        bc.tick(1.0)
        assert bc.tick(6.5) == 1

    def test_stop_resets_level(self):
        applied = []
        bc = BrownoutController(apply_fn=applied.append,
                                headroom_fn=lambda: 0.0,
                                registry=msm.Registry(), hold_s=0.0)
        bc.tick(0.0)
        bc.tick(1.0)
        assert bc.level() >= 1
        bc.stop()
        assert bc.level() == 0 and applied[-1] == 0

    def test_admission_sheds_low_priority_at_level3(self):
        reg = msm.Registry()
        adm = AdmissionController(0, lambda: 0, registry=reg)
        adm.set_brownout(3, min_priority=1)
        adm.admit(1, priority=1)          # high lane keeps serving
        with pytest.raises(Overloaded, match="brownout"):
            adm.admit(1, priority=0)
        assert reg.get("marian_serving_shed_total") \
                  .labels("brownout").value == 1
        adm.set_brownout(0)
        adm.admit(1, priority=0)          # ladder off: lane admitted

    def test_cap_scale_applied_at_level1(self, tiny):
        sched, eng, reg = make_sched(tiny)
        base = eng.decode_cap(4)
        sched.set_brownout_level(1, cap_factor=0.5)
        assert eng.decode_cap(4) < base
        sched.set_brownout_level(0)
        assert eng.decode_cap(4) == base

    def test_level2_evicts_low_priority_for_queued_high(self, tiny):
        """The eviction rung: a low-priority row holding the whole pool
        is evicted (retriably) so queued high-priority work can join.
        The victim's decode cap is deliberately deep (48 steps) so its
        row is reliably still mid-decode when the high lane queues."""
        eng = make_engine(tiny, pool_bytes=12 * PAGE_BYTES,
                          max_length_cap=48, max_length_factor=8.0)
        assert eng.pool.usable_pages == 12        # exactly one 48-cap row
        sched, eng, reg = make_sched(tiny, engine=eng)
        holder = {}

        async def main():
            sched.start()
            f_low = sched.submit([TEXTS[4]], priority=0)
            assert await wait_for(lambda: sched.m_joins.value >= 1,
                                  interval=0.001)
            sched.set_brownout_level(2)
            f_high = sched.submit([TEXTS[1]], priority=5)
            with pytest.raises(RowEvicted, match="brownout"):
                await asyncio.wait_for(f_low, timeout=20)
            holder["high"] = await asyncio.wait_for(f_high, timeout=20)
            await sched.stop()

        run(main())
        assert holder["high"]
        assert sched.m_brownout_evictions.value >= 1


# ---------------------------------------------------------------------------
# watchdog trip mid-round (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

class TestWatchdogMidRound:
    def test_stall_evicts_rows_retriably_and_rebuild_is_clean(self, tiny):
        """Pins the fate of IN-FLIGHT rows across an engine_factory
        rebuild (only the rebuild itself was tested before): rows are
        evicted with a retriable error, the replacement engine starts
        with a fully free pool and a clean audit, and the next request
        decodes normally."""
        rebuilt = []

        def factory():
            e = make_engine(tiny)
            rebuilt.append(e)
            return e

        sched, eng_a, reg = make_sched(tiny, engine_factory=factory)
        holder = {}

        async def main():
            sched.start()
            warm = await sched.submit([TEXTS[0]])   # jits compiled
            assert warm == solo_outputs(tiny, [TEXTS[0]])
            # arm the watchdog only past the first-compile round — a
            # cold jit legitimately exceeds any useful stall timeout
            # (the victim stays in the warmed row bucket for the same
            # reason: a NEW bucket would compile, not stall)
            sched.stall_timeout = 1.0
            f1 = sched.submit([TEXTS[4]])           # row mid-decode
            assert await wait_for(lambda: sched.m_joins.value >= 2,
                                  interval=0.001)
            fp.activate("serving.translate=hang:8")
            try:
                with pytest.raises(DispatchStalled):
                    await asyncio.wait_for(f1, timeout=20)
            finally:
                fp.deactivate()
            assert rebuilt
            # the REBUILT engine compiles its jits on first use, which
            # would legitimately exceed the tight test timeout — disarm
            # (operators size --dispatch-stall-timeout above worst-case
            # compile; see docs/ROBUSTNESS.md)
            sched.stall_timeout = 0.0
            holder["r2"] = await asyncio.wait_for(
                sched.submit([TEXTS[1]]), timeout=30)
            await sched.stop()

        run(main())
        assert DispatchStalled.retriable
        new = rebuilt[-1]
        assert sched.engine is new
        # the replacement engine: all pages free, audit clean
        assert new.pool.free_pages() == new.pool.usable_pages
        assert new.audit(context="test") == []
        assert holder["r2"] == solo_outputs(tiny, [TEXTS[1]])
        assert sched.m_watchdog.value == 1


# ---------------------------------------------------------------------------
# loadgen --retries (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def _load_loadgen():
    spec = importlib.util.spec_from_file_location(
        "loadgen_quiesce", os.path.join(ROOT, "scripts", "loadgen.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestLoadgenRetry:
    def test_backoff_is_capped_and_jittered(self):
        lg = _load_loadgen()
        # deterministic jitter: base * 2^n, x[0.5, 1.5)
        assert lg.retry_backoff_s(0, 0.1, jitter=lambda: 0.0) \
            == pytest.approx(0.05)
        assert lg.retry_backoff_s(1, 0.1, jitter=lambda: 0.5) \
            == pytest.approx(0.2)
        # the cap bounds any attempt index
        assert lg.retry_backoff_s(10, 0.1, jitter=lambda: 0.5) \
            == pytest.approx(lg.RETRY_CAP_S)

    def test_send_with_retries_counts_and_succeeds(self):
        lg = _load_loadgen()
        replies = ["!!SERVER-RETRY evicted", "!!SERVER-RETRY evicted",
                   "translated"]

        async def fake(host, port, text):
            # transports return (reply, ttft_s) since --stream (ISSUE 16)
            return replies.pop(0), None

        reply, n, ttft = run(lg.send_with_retries(fake, "h", 0, "t",
                                                  retries=3, base_s=0.001))
        assert reply == "translated" and n == 2 and ttft is None

    def test_send_with_retries_budget_exhausted(self):
        lg = _load_loadgen()

        async def always_retry(host, port, text):
            return ("#trace:t1 outcome=evicted queue_ms=0.0 "
                    "service_ms=0.0 model_version=v\n!!SERVER-RETRY x",
                    None)

        reply, n, _ = run(lg.send_with_retries(always_retry, "h", 0, "t",
                                               retries=2, base_s=0.001))
        # meta header is stripped for the retry decision, preserved in
        # the final reply; the budget bounds the attempts
        assert n == 2 and "!!SERVER-RETRY" in reply

    def test_default_is_single_shot(self):
        lg = _load_loadgen()
        calls = []

        async def fake(host, port, text):
            calls.append(text)
            return "!!SERVER-RETRY x", None

        reply, n, _ = run(lg.send_with_retries(fake, "h", 0, "t",
                                               retries=0))
        assert len(calls) == 1 and n == 0


# ---------------------------------------------------------------------------
# server surface: priority header, validation, metric census
# ---------------------------------------------------------------------------

class TestServerSurface:
    def test_priority_header_parses_and_stacks(self):
        from marian_tpu.server.server import (split_priority_header,
                                              split_trace_header)
        assert split_priority_header("#priority:3\nhello") == (3, "hello")
        assert split_priority_header("#priority:-1\nx") == (-1, "x")
        # clamped: a client-controlled int must not mint unbounded lanes
        assert split_priority_header("#priority:5000\nx") == (9, "x")
        assert split_priority_header("#priority:-5000\nx") == (-9, "x")
        assert split_priority_header("hello") == (None, "hello")
        malformed = "#priority:high\nx"
        assert split_priority_header(malformed) == (None, malformed)
        tid, body = split_trace_header("#trace:abc\n#priority:2\nhi")
        assert tid == "abc"
        prio, body = split_priority_header(body)
        assert prio == 2 and body == "hi"

    def test_iteration_composes_with_model_watch(self):
        """ISSUE 11: --model-watch is no longer refused in iteration
        mode (the quiesce protocol is what made it composable); the
        rest of the restricted surface still fails loudly."""
        from marian_tpu.server.server import ServingApp
        ServingApp._validate_iteration_options(Options({
            "batching-mode": "iteration", "beam-size": 1,
            "model-watch": 1.0}))
        # ISSUE 12: beam>1 iteration is now SERVED (COW page sharing),
        # not refused — only nonsensical beam configs fail at boot
        ServingApp._validate_iteration_options(Options({
            "batching-mode": "iteration", "beam-size": 2,
            "model-watch": 1.0}))
        with pytest.raises(ValueError, match="beam-size"):
            # (0 means "unset" by the repo's falsy-flag convention and
            # resolves to the default — a NEGATIVE beam is the
            # explicit-nonsense case)
            ServingApp._validate_iteration_options(Options({
                "batching-mode": "iteration", "beam-size": -1}))
        with pytest.raises(ValueError, match="iteration-rows"):
            ServingApp._validate_iteration_options(Options({
                "batching-mode": "iteration", "beam-size": 8,
                "iteration-rows": 4}))

    def test_metric_census(self, tiny):
        """Every ISSUE 11 series is declared and scrapeable
        (MT-METRIC-UNTESTED keeps this census honest)."""
        reg = msm.Registry()
        make_sched(tiny, registry=reg)
        BrownoutController(apply_fn=lambda lvl: None, registry=reg)
        text = reg.render()
        for name in ("marian_serving_quiesces_total",
                     "marian_serving_quiesce_evictions_total",
                     "marian_serving_quiescing",
                     "marian_serving_brownout_evictions_total",
                     "marian_serving_pool_audits_total",
                     "marian_serving_pool_audit_failures_total",
                     "marian_brownout_level",
                     "marian_brownout_transitions_total"):
            assert name in text, name

    def test_sloz_includes_brownout_state(self):
        from marian_tpu.obs import slo as mslo
        bc = BrownoutController(apply_fn=lambda lvl: None,
                                registry=msm.Registry())
        routes = mslo.slo_routes(lambda: None, lambda: bc)
        code, body, ctype = routes["/sloz"]("GET", "")
        assert code == 200 and b'"brownout"' in body \
            and b'"level": 0' in body
        # and the always-answers contract without a ladder
        routes = mslo.slo_routes(lambda: None)
        code, body, _ = routes["/sloz"]("GET", "")
        assert code == 200 and b'"enabled": false' in body
