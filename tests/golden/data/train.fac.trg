small|c0 red|c0 has|c0 tree|c0 child|c0 man|c0 house|c0 house|c0 man|c0
blue|c0 cat|c0 big|c0 sees|c0 young|c0
loves|c0 tree|c0 woman|c0 the|c0 dog|c0
fast|c0 the|c0 blue|c0 red|c0 child|c0 blue|c0
loves|c0 small|c0 man|c0 big|c0 young|c0 young|c0 old|c0 fast|c0 red|c0
blue|c0 woman|c0 dog|c0 fast|c0 red|c0 the|c0 the|c0 the|c0 house|c0
woman|c0 house|c0 child|c0 big|c0 old|c0 old|c0
the|c0 has|c0 child|c0 fast|c0 has|c0
woman|c0 young|c0 sees|c0 blue|c0 the|c0 old|c0 loves|c0 child|c0 the|c0
old|c0 house|c0 the|c0 house|c0 red|c0 young|c0
blue|c0 big|c0 the|c0
old|c0 man|c0 young|c0 young|c0 red|c0 fast|c0 fast|c0
woman|c0 red|c0 child|c0 blue|c0 sees|c0 man|c0 loves|c0
house|c0 the|c0 blue|c0
red|c0 woman|c0 house|c0 fast|c0 loves|c0 small|c0 has|c0 small|c0 child|c0
sees|c0 the|c0 red|c0
small|c0 small|c0 old|c0 old|c0
small|c0 sees|c0 tree|c0 blue|c0
blue|c0 big|c0 house|c0 house|c0 blue|c0
child|c0 cat|c0 sees|c0 dog|c0 tree|c0 tree|c0 cat|c0 red|c0 man|c0
fast|c0 man|c0 old|c0 dog|c0 the|c0 old|c0 man|c0
tree|c0 cat|c0 child|c0 woman|c0 has|c0
old|c0 sees|c0 red|c0 house|c0 big|c0 loves|c0
small|c0 small|c0 sees|c0 the|c0
blue|c0 the|c0 the|c0 loves|c0 the|c0 the|c0
the|c0 the|c0 woman|c0 fast|c0 tree|c0 sees|c0
man|c0 house|c0 child|c0 has|c0
cat|c0 the|c0 man|c0 young|c0 blue|c0 child|c0 big|c0
the|c0 young|c0 man|c0 tree|c0 old|c0 big|c0
the|c0 the|c0 cat|c0 old|c0 woman|c0 man|c0 old|c0 loves|c0 child|c0
cat|c0 loves|c0 big|c0 young|c0 red|c0
the|c0 the|c0 red|c0 the|c0 big|c0 old|c0 dog|c0 woman|c0 cat|c0
has|c0 the|c0 child|c0 the|c0 woman|c0 young|c0 old|c0
child|c0 woman|c0 red|c0 sees|c0
house|c0 woman|c0 red|c0
cat|c0 young|c0 blue|c0 tree|c0 the|c0 child|c0 has|c0
child|c0 cat|c0 dog|c0
man|c0 the|c0 woman|c0 loves|c0 sees|c0 dog|c0 the|c0 young|c0
tree|c0 young|c0 young|c0 cat|c0 big|c0 cat|c0 man|c0 man|c0
dog|c0 blue|c0 fast|c0 the|c0 sees|c0 dog|c0 the|c0 big|c0 child|c0
has|c0 blue|c0 woman|c0 fast|c0 young|c0 young|c0
small|c0 fast|c0 tree|c0
red|c0 woman|c0 child|c0 young|c0 man|c0 dog|c0 woman|c0 fast|c0
dog|c0 house|c0 the|c0 young|c0 the|c0 man|c0 sees|c0 house|c0 fast|c0
small|c0 cat|c0 man|c0 tree|c0 the|c0 cat|c0 the|c0 big|c0 fast|c0
big|c0 cat|c0 old|c0 man|c0 red|c0 young|c0 small|c0 big|c0 cat|c0
has|c0 sees|c0 fast|c0 sees|c0 loves|c0 small|c0
old|c0 fast|c0 tree|c0 has|c0
tree|c0 the|c0 dog|c0 woman|c0
the|c0 tree|c0 woman|c0 young|c0 the|c0
cat|c0 old|c0 house|c0 the|c0 sees|c0 the|c0 dog|c0 cat|c0 old|c0
small|c0 old|c0 woman|c0 man|c0
the|c0 tree|c0 tree|c0 the|c0 red|c0 dog|c0 tree|c0
has|c0 has|c0 woman|c0
house|c0 loves|c0 the|c0 old|c0 man|c0
tree|c0 cat|c0 old|c0 young|c0
red|c0 big|c0 has|c0 big|c0 small|c0 tree|c0 child|c0
house|c0 woman|c0 old|c0 dog|c0 small|c0 has|c0 cat|c0 the|c0
has|c0 small|c0 child|c0 sees|c0 loves|c0 the|c0
loves|c0 fast|c0 child|c0 woman|c0 young|c0 the|c0 small|c0
child|c0 woman|c0 child|c0 young|c0
cat|c0 dog|c0 house|c0
sees|c0 big|c0 small|c0 the|c0 child|c0
big|c0 sees|c0 the|c0
loves|c0 has|c0 the|c0
the|c0 child|c0 the|c0 young|c0
man|c0 house|c0 blue|c0 the|c0 old|c0 woman|c0 small|c0
woman|c0 loves|c0 woman|c0
tree|c0 dog|c0 the|c0 the|c0
cat|c0 red|c0 house|c0 big|c0 cat|c0 old|c0
fast|c0 big|c0 blue|c0 old|c0 cat|c0 young|c0 fast|c0
the|c0 has|c0 the|c0 woman|c0
big|c0 tree|c0 cat|c0 big|c0 tree|c0 the|c0 sees|c0
sees|c0 the|c0 loves|c0 loves|c0 young|c0
has|c0 the|c0 tree|c0 big|c0
man|c0 the|c0 the|c0 fast|c0 the|c0 blue|c0
blue|c0 blue|c0 big|c0 fast|c0
has|c0 red|c0 red|c0 dog|c0 the|c0 dog|c0 big|c0 small|c0
small|c0 old|c0 has|c0 young|c0 has|c0
blue|c0 dog|c0 sees|c0 man|c0 the|c0
the|c0 fast|c0 fast|c0 old|c0
the|c0 fast|c0 dog|c0 sees|c0 tree|c0
fast|c0 old|c0 woman|c0 child|c0 house|c0 has|c0
red|c0 woman|c0 the|c0 tree|c0 has|c0
house|c0 has|c0 sees|c0 young|c0 man|c0 cat|c0 red|c0
dog|c0 big|c0 woman|c0 red|c0 man|c0
sees|c0 red|c0 young|c0 big|c0 woman|c0 red|c0 fast|c0
loves|c0 fast|c0 big|c0 sees|c0 sees|c0 has|c0
cat|c0 big|c0 loves|c0 small|c0 blue|c0 red|c0
dog|c0 the|c0 the|c0 dog|c0 tree|c0 the|c0
the|c0 tree|c0 big|c0 blue|c0 the|c0 the|c0 old|c0 house|c0
red|c0 cat|c0 dog|c0
small|c0 loves|c0 young|c0 child|c0 man|c0 child|c0
the|c0 has|c0 dog|c0 small|c0 dog|c0 the|c0 blue|c0
child|c0 tree|c0 small|c0 house|c0 fast|c0
loves|c0 big|c0 blue|c0 woman|c0 blue|c0 the|c0 the|c0 young|c0 blue|c0
