small red has tree child man house house man
blue cat big sees young
loves tree woman the dog
fast the blue red child blue
loves small man big young young old fast red
blue woman dog fast red the the the house
woman house child big old old
the has child fast has
woman young sees blue the old loves child the
old house the house red young
blue big the
old man young young red fast fast
woman red child blue sees man loves
house the blue
red woman house fast loves small has small child
sees the red
small small old old
small sees tree blue
blue big house house blue
child cat sees dog tree tree cat red man
fast man old dog the old man
tree cat child woman has
old sees red house big loves
small small sees the
blue the the loves the the
the the woman fast tree sees
man house child has
cat the man young blue child big
the young man tree old big
the the cat old woman man old loves child
cat loves big young red
the the red the big old dog woman cat
has the child the woman young old
child woman red sees
house woman red
cat young blue tree the child has
child cat dog
man the woman loves sees dog the young
tree young young cat big cat man man
dog blue fast the sees dog the big child
has blue woman fast young young
small fast tree
red woman child young man dog woman fast
dog house the young the man sees house fast
small cat man tree the cat the big fast
big cat old man red young small big cat
has sees fast sees loves small
old fast tree has
tree the dog woman
the tree woman young the
cat old house the sees the dog cat old
small old woman man
the tree tree the red dog tree
has has woman
house loves the old man
tree cat old young
red big has big small tree child
house woman old dog small has cat the
has small child sees loves the
loves fast child woman young the small
child woman child young
cat dog house
sees big small the child
big sees the
loves has the
the child the young
man house blue the old woman small
woman loves woman
tree dog the the
cat red house big cat old
fast big blue old cat young fast
the has the woman
big tree cat big tree the sees
sees the loves loves young
has the tree big
man the the fast the blue
blue blue big fast
has red red dog the dog big small
small old has young has
blue dog sees man the
the fast fast old
the fast dog sees tree
fast old woman child house has
red woman the tree has
house has sees young man cat red
dog big woman red man
sees red young big woman red fast
loves fast big sees sees has
cat big loves small blue red
dog the the dog tree the
the tree big blue the the old house
red cat dog
small loves young child man child
the has dog small dog the blue
child tree small house fast
loves big blue woman blue the the young blue
