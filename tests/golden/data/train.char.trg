s m a l l _ r e d _ h a s _ t r e e _ c h i l d _ m a n _ h o u s e _ h o u s e _ m a n
b l u e _ c a t _ b i g _ s e e s _ y o u n g
l o v e s _ t r e e _ w o m a n _ t h e _ d o g
f a s t _ t h e _ b l u e _ r e d _ c h i l d _ b l u e
l o v e s _ s m a l l _ m a n _ b i g _ y o u n g _ y o u n g _ o l d _ f a s t _ r e d
b l u e _ w o m a n _ d o g _ f a s t _ r e d _ t h e _ t h e _ t h e _ h o u s e
w o m a n _ h o u s e _ c h i l d _ b i g _ o l d _ o l d
t h e _ h a s _ c h i l d _ f a s t _ h a s
w o m a n _ y o u n g _ s e e s _ b l u e _ t h e _ o l d _ l o v e s _ c h i l d _ t h e
o l d _ h o u s e _ t h e _ h o u s e _ r e d _ y o u n g
b l u e _ b i g _ t h e
o l d _ m a n _ y o u n g _ y o u n g _ r e d _ f a s t _ f a s t
w o m a n _ r e d _ c h i l d _ b l u e _ s e e s _ m a n _ l o v e s
h o u s e _ t h e _ b l u e
r e d _ w o m a n _ h o u s e _ f a s t _ l o v e s _ s m a l l _ h a s _ s m a l l _ c h i l d
s e e s _ t h e _ r e d
s m a l l _ s m a l l _ o l d _ o l d
s m a l l _ s e e s _ t r e e _ b l u e
b l u e _ b i g _ h o u s e _ h o u s e _ b l u e
c h i l d _ c a t _ s e e s _ d o g _ t r e e _ t r e e _ c a t _ r e d _ m a n
f a s t _ m a n _ o l d _ d o g _ t h e _ o l d _ m a n
t r e e _ c a t _ c h i l d _ w o m a n _ h a s
o l d _ s e e s _ r e d _ h o u s e _ b i g _ l o v e s
s m a l l _ s m a l l _ s e e s _ t h e
b l u e _ t h e _ t h e _ l o v e s _ t h e _ t h e
t h e _ t h e _ w o m a n _ f a s t _ t r e e _ s e e s
m a n _ h o u s e _ c h i l d _ h a s
c a t _ t h e _ m a n _ y o u n g _ b l u e _ c h i l d _ b i g
t h e _ y o u n g _ m a n _ t r e e _ o l d _ b i g
t h e _ t h e _ c a t _ o l d _ w o m a n _ m a n _ o l d _ l o v e s _ c h i l d
c a t _ l o v e s _ b i g _ y o u n g _ r e d
t h e _ t h e _ r e d _ t h e _ b i g _ o l d _ d o g _ w o m a n _ c a t
h a s _ t h e _ c h i l d _ t h e _ w o m a n _ y o u n g _ o l d
c h i l d _ w o m a n _ r e d _ s e e s
h o u s e _ w o m a n _ r e d
c a t _ y o u n g _ b l u e _ t r e e _ t h e _ c h i l d _ h a s
c h i l d _ c a t _ d o g
m a n _ t h e _ w o m a n _ l o v e s _ s e e s _ d o g _ t h e _ y o u n g
t r e e _ y o u n g _ y o u n g _ c a t _ b i g _ c a t _ m a n _ m a n
d o g _ b l u e _ f a s t _ t h e _ s e e s _ d o g _ t h e _ b i g _ c h i l d
h a s _ b l u e _ w o m a n _ f a s t _ y o u n g _ y o u n g
s m a l l _ f a s t _ t r e e
r e d _ w o m a n _ c h i l d _ y o u n g _ m a n _ d o g _ w o m a n _ f a s t
d o g _ h o u s e _ t h e _ y o u n g _ t h e _ m a n _ s e e s _ h o u s e _ f a s t
s m a l l _ c a t _ m a n _ t r e e _ t h e _ c a t _ t h e _ b i g _ f a s t
b i g _ c a t _ o l d _ m a n _ r e d _ y o u n g _ s m a l l _ b i g _ c a t
h a s _ s e e s _ f a s t _ s e e s _ l o v e s _ s m a l l
o l d _ f a s t _ t r e e _ h a s
t r e e _ t h e _ d o g _ w o m a n
t h e _ t r e e _ w o m a n _ y o u n g _ t h e
c a t _ o l d _ h o u s e _ t h e _ s e e s _ t h e _ d o g _ c a t _ o l d
s m a l l _ o l d _ w o m a n _ m a n
t h e _ t r e e _ t r e e _ t h e _ r e d _ d o g _ t r e e
h a s _ h a s _ w o m a n
h o u s e _ l o v e s _ t h e _ o l d _ m a n
t r e e _ c a t _ o l d _ y o u n g
r e d _ b i g _ h a s _ b i g _ s m a l l _ t r e e _ c h i l d
h o u s e _ w o m a n _ o l d _ d o g _ s m a l l _ h a s _ c a t _ t h e
h a s _ s m a l l _ c h i l d _ s e e s _ l o v e s _ t h e
l o v e s _ f a s t _ c h i l d _ w o m a n _ y o u n g _ t h e _ s m a l l
c h i l d _ w o m a n _ c h i l d _ y o u n g
c a t _ d o g _ h o u s e
s e e s _ b i g _ s m a l l _ t h e _ c h i l d
b i g _ s e e s _ t h e
l o v e s _ h a s _ t h e
t h e _ c h i l d _ t h e _ y o u n g
m a n _ h o u s e _ b l u e _ t h e _ o l d _ w o m a n _ s m a l l
w o m a n _ l o v e s _ w o m a n
t r e e _ d o g _ t h e _ t h e
c a t _ r e d _ h o u s e _ b i g _ c a t _ o l d
f a s t _ b i g _ b l u e _ o l d _ c a t _ y o u n g _ f a s t
t h e _ h a s _ t h e _ w o m a n
b i g _ t r e e _ c a t _ b i g _ t r e e _ t h e _ s e e s
s e e s _ t h e _ l o v e s _ l o v e s _ y o u n g
h a s _ t h e _ t r e e _ b i g
m a n _ t h e _ t h e _ f a s t _ t h e _ b l u e
b l u e _ b l u e _ b i g _ f a s t
h a s _ r e d _ r e d _ d o g _ t h e _ d o g _ b i g _ s m a l l
s m a l l _ o l d _ h a s _ y o u n g _ h a s
b l u e _ d o g _ s e e s _ m a n _ t h e
t h e _ f a s t _ f a s t _ o l d
t h e _ f a s t _ d o g _ s e e s _ t r e e
f a s t _ o l d _ w o m a n _ c h i l d _ h o u s e _ h a s
r e d _ w o m a n _ t h e _ t r e e _ h a s
h o u s e _ h a s _ s e e s _ y o u n g _ m a n _ c a t _ r e d
d o g _ b i g _ w o m a n _ r e d _ m a n
s e e s _ r e d _ y o u n g _ b i g _ w o m a n _ r e d _ f a s t
l o v e s _ f a s t _ b i g _ s e e s _ s e e s _ h a s
c a t _ b i g _ l o v e s _ s m a l l _ b l u e _ r e d
d o g _ t h e _ t h e _ d o g _ t r e e _ t h e
t h e _ t r e e _ b i g _ b l u e _ t h e _ t h e _ o l d _ h o u s e
r e d _ c a t _ d o g
s m a l l _ l o v e s _ y o u n g _ c h i l d _ m a n _ c h i l d
t h e _ h a s _ d o g _ s m a l l _ d o g _ t h e _ b l u e
c h i l d _ t r e e _ s m a l l _ h o u s e _ f a s t
l o v e s _ b i g _ b l u e _ w o m a n _ b l u e _ t h e _ t h e _ y o u n g _ b l u e
