"""Golden regression suite (reference: the marian-regression-tests repo
pattern, SURVEY §4 — "the cheapest strong e2e signal"; VERDICT r1 #3).

Five fixed-seed tiny configs mirroring BASELINE.json's benchmark families
train for 20 updates on the committed corpus in tests/golden/data/; the
per-update mean-CE trajectories and a greedy/beam decode are compared
against committed expected files:

    losses  — relative tolerance 1e-3 (CPU f32 determinism leaves headroom;
              a forward-math change of ±ε > 1e-3 fails the suite)
    decodes — exact token match

Regenerate after an INTENDED numeric change with:

    GOLDEN_REGEN=1 python -m pytest tests/golden -q
"""

import json
import os
import pathlib

import jax
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.data import BatchGenerator, Corpus, create_vocab
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.models.encoder_decoder import batch_to_arrays, create_model
from marian_tpu.training.graph_group import GraphGroup

pytestmark = pytest.mark.slow     # ~2.5 min CPU; always in the full run

HERE = pathlib.Path(__file__).resolve().parent
DATA = HERE / "data"
EXPECTED = HERE / "expected"
REGEN = bool(os.environ.get("GOLDEN_REGEN"))

N_UPDATES = 20
SEED = 1234

COMMON = {
    "precision": ["float32", "float32"],
    "learn-rate": 0.05, "lr-warmup": "0", "optimizer": "adam",
    "optimizer-params": [0.9, 0.98, 1e-9], "clip-norm": 1.0,
    "cost-type": "ce-mean-words", "label-smoothing": 0.1,
    "mini-batch": 16, "maxi-batch": 4, "maxi-batch-sort": "src",
    "shuffle": "data", "seed": SEED, "max-length": 24,
    "exponential-smoothing": 0.0,
}

# the 5 baseline config families (BASELINE.json), scaled to CPU-tiny
CONFIGS = {
    "transformer-base": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "transformer-ffn-activation": "relu",
    },
    "transformer-big-prenorm": {
        "type": "transformer", "dim-emb": 48, "transformer-heads": 4,
        "transformer-dim-ffn": 96, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "transformer-preprocess": "n", "transformer-postprocess": "da",
        "transformer-postprocess-top": "n",
        "transformer-ffn-activation": "swish",
    },
    "s2s": {
        "type": "s2s", "dim-emb": 32, "dim-rnn": 48,
        "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
        "dec-cell": "gru", "layer-normalization": False,
        "tied-embeddings": True,
    },
    "multi-source": {
        "type": "multi-transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 1, "dec-depth": 2,
        "tied-embeddings": True,
    },
    "aan-decoder": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "transformer-decoder-autoreg": "average-attention",
        "transformer-dim-aan": 64,
    },
    "char-s2s": {
        "type": "char-s2s", "dim-emb": 24, "dim-rnn": 32,
        "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
        "dec-cell": "gru", "char-stride": 3, "char-highway": 2,
        "tied-embeddings": True, "max-length": 80,
    },
    "transformer-lm": {
        "type": "transformer-lm", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "dec-depth": 2,
        "tied-embeddings-all": True,
    },
    "multi-s2s": {
        "type": "multi-s2s", "dim-emb": 24, "dim-rnn": 32,
        "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
        "dec-cell": "gru", "tied-embeddings": True,
    },
    "moe-transformer": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "transformer-moe-experts": 4, "transformer-moe-top-k": 2,
    },
    # BASELINE config #4 family (factored vocab) — plain src, factored trg
    # (tests/golden/data/vocab.fsv: each lemma with a 2-way c factor);
    # exercises factored_embed + factored softmax end-to-end (VERDICT r2
    # next-step #5: factored trajectory/decode drift was invisible)
    "factored": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "factor-weight": 1.0,
    },
    # factored TARGET vocab on the RNN family (round-3 closure of the
    # s2s factored refusal) — same data/fsv as the transformer config
    "factored-s2s": {
        "type": "s2s", "dim-emb": 24, "dim-rnn": 32,
        "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
        "dec-cell": "gru", "tied-embeddings": True,
    },
    # composed-mesh goldens (VERDICT r3 #3). NOTE: every config in this
    # file already trains on the conftest's 8-virtual-device data:8 mesh
    # (GraphGroup's default mesh covers all visible devices), so each
    # pinned trajectory above regression-tests the manual-DP scatter-
    # reduce path too. These two pin the OTHER parallelism axes: a
    # dp×tp×sp step (Megatron-style TP shardings + ring sequence
    # parallelism) and a dp×pipe×expert step (depth-stacked layer params
    # + expert-sharded MoE tables), trajectories and decode both.
    # the reference's production fast-decode architecture (WNGT-2019
    # students): SSRU autoregression instead of decoder self-attention.
    # Equivalence tests exist (test_decoder_autoreg); this pins the
    # TRAJECTORY + beam decode of the config the decode_ssru bench stage
    # measures.
    "ssru-transformer": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "transformer-decoder-autoreg": "rnn", "dec-cell": "ssru",
    },
    "tp-sp-transformer": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "mesh": ["data:2", "model:2", "seq:2"],
        "sequence-parallel": "ring",
    },
    "pipe-expert-moe": {
        "type": "transformer", "dim-emb": 32, "transformer-heads": 4,
        "transformer-dim-ffn": 64, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True,
        "transformer-moe-experts": 4, "transformer-moe-top-k": 2,
        "mesh": ["data:2", "pipe:2", "expert:2"],
    },
}


def _streams(name):
    src = str(DATA / "train.src")
    trg = str(DATA / "train.trg")
    if name in ("multi-source", "multi-s2s"):
        return [src, src, trg]          # doc-context style: 2 source streams
    if name == "char-s2s":
        return [str(DATA / "train.char.src"), str(DATA / "train.char.trg")]
    if name == "transformer-lm":
        return [trg]                    # single-stream LM corpus
    if name in ("factored", "factored-s2s"):
        return [src, str(DATA / "train.fac.trg")]
    return [src, trg]


def _build(name):
    cfg = CONFIGS[name]
    opts = Options({**COMMON, **cfg})
    paths = _streams(name)
    if name in ("factored", "factored-s2s"):
        from marian_tpu.data.factored_vocab import FactoredVocab
        src_v = DefaultVocab.build(
            pathlib.Path(paths[0]).read_text().splitlines())
        vocabs = [src_v, FactoredVocab.load(str(DATA / "vocab.fsv"))]
        corpus = Corpus(paths, vocabs, opts)
        model = create_model(opts, vocabs[0], vocabs[-1])
        return opts, vocabs, corpus, model
    if cfg.get("tied-embeddings-all"):
        # tied-all requires one joint vocabulary (Marian convention)
        lines = []
        for p in dict.fromkeys(paths):
            lines += pathlib.Path(p).read_text().splitlines()
        joint = DefaultVocab.build(lines)
        vocabs = [joint] * len(paths)
    else:
        vocabs = [DefaultVocab.build(pathlib.Path(p).read_text().splitlines())
                  for p in paths]
    corpus = Corpus(paths, vocabs, opts)
    src_side = vocabs[:-1] if len(vocabs) > 2 else vocabs[0]
    model = create_model(opts, src_side, vocabs[-1])
    if name == "char-s2s":
        # CPU-tiny filter bank (the Lee et al. defaults are WMT-sized)
        import dataclasses
        model.cfg = dataclasses.replace(model.cfg, conv_widths=(1, 3, 5),
                                        conv_filters=(8, 8, 8))
    return opts, vocabs, corpus, model


_train_memo = {}


def _train(name):
    # transformer-base is trained by both its parametrized golden AND the
    # int8 decode golden; training is fixed-seed deterministic and decode
    # never mutates the GraphGroup, so share one run (the suite runs on
    # one CPU core — 20 updates twice is pure waste)
    if name == "transformer-base" and name in _train_memo:
        return _train_memo[name]
    opts, vocabs, corpus, model = _build(name)
    gg = GraphGroup(model, opts)
    key = prng.root_key(SEED)
    gg.initialize(prng.stream(key, prng.STREAM_INIT))
    train_key = prng.stream(key, prng.STREAM_DROPOUT)
    losses = []
    step = 0
    while step < N_UPDATES:
        bg = BatchGenerator(corpus, opts, prefetch=False)
        for batch in bg:
            arrays = batch_to_arrays(batch)
            out = gg.update(arrays, step + 1, train_key)
            losses.append(out.loss_sum / max(out.labels, 1.0))
            step += 1
            if step >= N_UPDATES:
                break
    result = (losses, gg, opts, vocabs, model)
    if name == "transformer-base":
        _train_memo[name] = result
    return result


def _decode(gg, opts, vocabs, model, name, params=None,
            return_scores=False):
    """Beam-6 decode of the first 8 training sentences through the real
    BeamSearch (shapes bucketed like the translator driver). Decoder-only
    LMs pin per-sentence teacher-forced scores instead. ``params``
    overrides the trained weights (the int8 golden passes quantized
    ones)."""
    from marian_tpu.translator.beam_search import BeamSearch
    import jax.numpy as jnp
    if name == "transformer-lm":
        from marian_tpu.models import transformer as Tm
        from marian_tpu.ops.ops import cross_entropy
        lines = pathlib.Path(_streams(name)[0]).read_text().splitlines()[:8]
        voc = vocabs[0]
        enc = [voc.encode(l) for l in lines]
        tt = max(len(e) for e in enc)
        ids = np.zeros((len(enc), tt), np.int32)
        mask = np.zeros((len(enc), tt), np.float32)
        for i, e in enumerate(enc):
            ids[i, :len(e)] = e
            mask[i, :len(e)] = 1.0
        cp = Tm.cast_params(gg.export_params(), model.cfg.compute_dtype)
        logits = Tm.decode_train(model.cfg, cp, None, None,
                                 jnp.asarray(ids), jnp.asarray(mask),
                                 train=False)
        ce = np.asarray(cross_entropy(logits, jnp.asarray(ids), 0.0)
                        * jnp.asarray(mask))
        return [f"{-s:.6f}" for s in ce.sum(axis=1)]
    paths = _streams(name)
    src_lines = pathlib.Path(paths[0]).read_text().splitlines()[:8]
    svoc = vocabs[0]
    enc = [svoc.encode(l) for l in src_lines]
    ts = max(len(e) for e in enc)
    ids = np.zeros((len(enc), ts), np.int32)
    mask = np.zeros((len(enc), ts), np.float32)
    for i, e in enumerate(enc):
        ids[i, :len(e)] = e
        mask[i, :len(e)] = 1.0
    bopts = Options({"beam-size": 6, "normalize": 0.6, "max-length": 32,
                     "seed": SEED})
    bs = BeamSearch(model, [params if params is not None
                            else gg.export_params()], None, bopts,
                    vocabs[-1])
    n_src = len(vocabs) - 1 if len(vocabs) > 2 else 1
    if n_src > 1:
        args = (tuple([jnp.asarray(ids)] * n_src),
                tuple([jnp.asarray(mask)] * n_src))
    else:
        args = (jnp.asarray(ids), jnp.asarray(mask))
    nbests = bs.search(*args)
    tvoc = vocabs[-1]
    if return_scores:
        return ([tvoc.decode(nb[0]["tokens"]) for nb in nbests],
                [float(nb[0]["norm_score"]) for nb in nbests])
    return [tvoc.decode(nb[0]["tokens"]) for nb in nbests]


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_golden(name):
    losses, gg, opts, vocabs, model = _train(name)
    decodes = _decode(gg, opts, vocabs, model, name)

    loss_file = EXPECTED / f"{name}_losses.json"
    decode_file = EXPECTED / f"{name}_decode.txt"
    if REGEN or not loss_file.exists():
        loss_file.write_text(json.dumps([round(float(x), 8) for x in losses],
                                        indent=0) + "\n")
        decode_file.write_text("\n".join(decodes) + "\n")
        if not REGEN:
            pytest.skip(f"expected files for {name} regenerated; rerun")
        return

    expected_losses = json.loads(loss_file.read_text())
    assert len(losses) == len(expected_losses)
    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(expected_losses), rtol=1e-3,
                               err_msg=f"{name}: loss trajectory drifted "
                                       f"(regenerate with GOLDEN_REGEN=1 if "
                                       f"the change is intended)")
    expected_decodes = decode_file.read_text().splitlines()
    if CONFIGS[name]["type"] in ("transformer-lm", "lm-transformer", "lm"):
        # LM "decodes" are teacher-forced scores: numeric compare (exact
        # string equality at 1e-6 print granularity would flag fusion-level
        # float drift that the loss tolerance deliberately allows)
        np.testing.assert_allclose(
            np.asarray([float(d) for d in decodes]),
            np.asarray([float(d) for d in expected_decodes]), rtol=1e-4,
            err_msg=f"{name}: scores drifted (GOLDEN_REGEN=1 if intended)")
    else:
        assert decodes == expected_decodes, (
            f"{name}: beam-6 decodes drifted (GOLDEN_REGEN=1 if intended)")

    # sanity: the model actually learned something in 20 updates
    assert losses[-1] < losses[0]


def test_golden_int8_decode():
    """BASELINE config #5 family: train the tiny transformer, quantize
    offline (marian-conv int8tpu equivalent), pin the beam-6 int8 decode
    EXACTLY. Catches drift anywhere in the QTensor dot path between
    rounds (VERDICT r2 next-step #5: int8 decode drift was invisible)."""
    import jax.numpy as jnp

    from marian_tpu.ops.quantization import quantize_params, wrap_quantized

    losses, gg, opts, vocabs, model = _train("transformer-base")
    # quantize → wrap into QTensor leaves: only QTensors route the int8
    # dot path (same sequence as the translator loading an int8
    # checkpoint, translator.py:42)
    qparams = wrap_quantized(
        {k: jnp.asarray(v)
         for k, v in quantize_params(gg.export_params()).items()})
    decodes, scores = _decode(gg, opts, vocabs, model, "transformer-base",
                              params=qparams, return_scores=True)

    # the short-trained tiny model decodes the empty hypothesis (so does
    # the float golden) — the SCORES are the teeth: any drift in the
    # int8 quantize→dot path moves the beam's normalized log-probs
    decode_file = EXPECTED / "int8-transformer_decode.json"
    if REGEN or not decode_file.exists():
        decode_file.write_text(json.dumps(
            {"decodes": decodes,
             "scores": [round(s, 6) for s in scores]}, indent=0) + "\n")
        if not REGEN:
            pytest.skip("int8 expected decode regenerated; rerun")
        return
    expected = json.loads(decode_file.read_text())
    assert decodes == expected["decodes"], (
        "int8 beam-6 decodes drifted (GOLDEN_REGEN=1 if intended)")
    np.testing.assert_allclose(
        np.asarray(scores), np.asarray(expected["scores"]), rtol=1e-4,
        err_msg="int8 beam scores drifted (GOLDEN_REGEN=1 if intended)")
