"""Trainer robustness (reference: training/graph_group.cpp + ISSUE 4).

Gradient-side flags (parallel/zero.py step_fn): --normalize-gradient,
--check-gradient-nan, --dynamic-gradient-scaling +
--gradient-norm-average-window.

Crash-resume (ISSUE 4 acceptance): a trainer SUBPROCESS killed by an
injected fault at every stage of the checkpoint save (MARIAN_FAULTS=
"<point>=kill@N" — a real os._exit, no cleanup) restarts and resumes
BIT-EXACTLY — params, optimizer state, and progress equal to an
uninterrupted run — from a validated bundle, never a torn one."""

import hashlib
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.training import bundle as bdl
from marian_tpu.training.graph_group import GraphGroup


def _gg(**over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16,
            "learn-rate": 0.05, "optimizer": "adam", "clip-norm": 0.0,
            "exponential-smoothing": 0.0, "cost-type": "ce-sum"}
    base.update(over)
    opts = Options(base)
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(21))
    return gg


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


def _params_delta(gg_kwargs, steps=1):
    gg = _gg(**gg_kwargs)
    before = {k: np.asarray(v) for k, v in gg.export_params().items()}
    key = prng.stream(prng.root_key(21), prng.STREAM_DROPOUT)
    out = None
    for i in range(steps):
        out = gg.update(_batch(i), i + 1, key)
    after = gg.export_params()
    delta = sum(float(np.abs(np.asarray(after[k]) - before[k]).sum())
                for k in before)
    return gg, delta, out


class TestNormalizeGradient:
    def test_smaller_effective_gradient(self):
        """ce-sum + --normalize-gradient divides grads by target words —
        the reported gnorm must shrink accordingly vs the plain run."""
        _, _, out_plain = _params_delta({})
        _, _, out_norm = _params_delta({"normalize-gradient": True})
        # 8 rows x 7 trg tokens = 56 labels
        assert float(out_norm.grad_norm) == pytest.approx(
            float(out_plain.grad_norm) / 56.0, rel=1e-4)


class TestCheckGradientNan:
    def _poisoned(self, **over):
        gg = _gg(**over)
        # poison one weight: forward becomes non-finite -> nan gradients
        k = "encoder_l1_ffn_W1"
        assert k in gg.params
        gg.params[k] = jnp.full_like(gg.params[k], jnp.inf)
        return gg

    def test_nan_update_is_skipped(self):
        gg = self._poisoned(**{"check-gradient-nan": True})
        w_before = np.asarray(gg.params["Wemb"])
        out = gg.update(_batch(), 1,
                        prng.stream(prng.root_key(21),
                                    prng.STREAM_DROPOUT))
        np.testing.assert_array_equal(np.asarray(gg.params["Wemb"]),
                                      w_before)
        assert float(np.asarray(gg.opt_state["t"])) == 0.0

    def test_skipped_batch_does_not_poison_metrics(self):
        """The skip must also zero the reported ce_sum/labels — a nan
        loss flowing into the scheduler would read as the divergence the
        skip just averted (interacts with --throw-on-divergence)."""
        gg = self._poisoned(**{"check-gradient-nan": True})
        out = gg.update(_batch(), 1,
                        prng.stream(prng.root_key(21),
                                    prng.STREAM_DROPOUT))
        assert float(np.asarray(out.loss_sum)) == 0.0
        assert float(np.asarray(out.labels)) == 0.0

    def test_without_flag_nan_propagates(self):
        gg = self._poisoned()
        gg.update(_batch(), 1,
                  prng.stream(prng.root_key(21), prng.STREAM_DROPOUT))
        assert not np.isfinite(np.asarray(gg.params["Wemb"])).all()


class TestDynamicGradientScaling:
    def test_statistics_track_norm(self):
        gg, _, out = _params_delta(
            {"dynamic-gradient-scaling": ["2", "log"],
             "gradient-norm-average-window": 4}, steps=3)
        gs = gg.opt_state["gstat"]
        assert float(np.asarray(gs["n"])) == 3.0
        # log-mode average sits near log(gnorm)
        assert float(np.asarray(gs["avg"])) == pytest.approx(
            float(np.log(out.grad_norm)), abs=2.0)

    def test_tiny_factor_scales_updates_down(self):
        """factor=1e-3: once statistics warm up, every step's gradient is
        scaled down hard — cumulative parameter movement must be much
        smaller than the unscaled run over the same steps. SGD, because
        Adam's m/sqrt(v) preconditioning is invariant to uniform
        gradient scaling (the very reason the flag targets the raw
        norm, not the update)."""
        sgd = {"optimizer": "sgd", "gradient-norm-average-window": 4}

        def post_warm_movement(kwargs):
            gg = _gg(**kwargs)
            key = prng.stream(prng.root_key(21), prng.STREAM_DROPOUT)
            for i in range(3):          # warmup: statistics fill, no scaling
                gg.update(_batch(i), i + 1, key)
            snap = {k: np.asarray(v) for k, v in gg.export_params().items()}
            for i in range(3, 10):
                gg.update(_batch(i), i + 1, key)
            after = gg.export_params()
            return sum(float(np.abs(np.asarray(after[k]) - snap[k]).sum())
                       for k in snap)

        d_plain = post_warm_movement(dict(sgd))
        d_scaled = post_warm_movement(
            dict(sgd, **{"dynamic-gradient-scaling": ["0.001"]}))
        # scaled run: every post-warm gradient shrunk to ~0.1% → params
        # essentially frozen
        assert d_scaled < 0.05 * d_plain

    def test_composes_with_clip_as_min_not_product(self):
        """--clip-norm + --dynamic-gradient-scaling must cap the norm at
        min(clip, threshold), never scale twice. With a huge clip-norm
        the clip is inert, so the trajectory equals the no-clip run."""
        sgd = {"optimizer": "sgd", "gradient-norm-average-window": 4,
               "dynamic-gradient-scaling": ["2"]}
        _, d_noclip, _ = _params_delta(dict(sgd), steps=6)
        _, d_bigclip, _ = _params_delta(
            dict(sgd, **{"clip-norm": 1e6}), steps=6)
        assert d_bigclip == pytest.approx(d_noclip, rel=1e-5)

    def test_checkpoint_roundtrip_keeps_gstat(self):
        gg, _, _ = _params_delta(
            {"dynamic-gradient-scaling": ["2", "log"]}, steps=2)
        flat = gg.optimizer_arrays()
        assert "gstat:avg" in flat and "gstat:n" in flat
        gg2 = _gg(**{"dynamic-gradient-scaling": ["2", "log"]})
        gg2.load_optimizer_arrays(flat)
        assert float(np.asarray(gg2.opt_state["gstat"]["n"])) == 2.0


# ---------------------------------------------------------------------------
# crash-resume under injected kills (ISSUE 4)
# ---------------------------------------------------------------------------

_TRAIN_SNIPPET = (
    "import json, sys\n"
    "from marian_tpu.common import Options\n"
    "from marian_tpu.training.train import train_main\n"
    "train_main(Options(json.load(open(sys.argv[1]))))\n")


def _crash_config(d, src, vocab):
    return {
        "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
        "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
        "tied-embeddings-all": True, "max-length": 16,
        "precision": ["float32", "float32"], "seed": 7,
        "train-sets": [src, src], "vocabs": [vocab, vocab],
        "model": os.path.join(d, "model.npz"),
        # maxi-batch 1 aligns every save-freq boundary with a maxi-window
        # boundary, where the corpus resume snapshot is exact
        "mini-batch": 4, "maxi-batch": 1, "after-batches": 4,
        "save-freq": "2u", "disp-freq": 10, "learn-rate": 0.01,
        "shuffle": "none", "overwrite": True, "quiet": True,
    }


def _run_inprocess(cfg):
    from marian_tpu.training.train import train_main
    train_main(Options(dict(cfg)))


def _run_killed(cfg, d, faults):
    cfg_path = os.path.join(d, "cfg.json")
    with open(cfg_path, "w") as fh:
        json.dump(cfg, fh)
    env = dict(os.environ, JAX_PLATFORMS="cpu", MARIAN_FAULTS=faults)
    return subprocess.run(
        [sys.executable, "-c", _TRAIN_SNIPPET, cfg_path], env=env,
        timeout=300, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _ckpt_digest(model_path):
    """Content digest of params + optimizer + progress. Tensor content,
    not npz bytes (zip entries carry mtimes); the embedded config text is
    skipped (it names per-run paths). Mirrors scripts/chaos.py::
    final_digest on purpose — change the rules in BOTH places."""
    out = {}
    for suffix in ("", ".optimizer.npz"):
        h = hashlib.sha256()
        with np.load(model_path + suffix) as z:
            for name in sorted(z.files):
                if name.startswith("special:"):
                    continue
                a = z[name]
                h.update(f"{name}|{a.dtype}|{a.shape}".encode())
                h.update(np.ascontiguousarray(a).tobytes())
        out[suffix or "model"] = h.hexdigest()
    with open(model_path + ".progress.yml", "rb") as fh:
        out["progress"] = hashlib.sha256(fh.read()).hexdigest()
    return out


def _assert_never_torn(model_path):
    root = bdl.bundle_root(model_path)
    names = bdl.list_bundles(root)
    for name in names:
        ok, why, _ = bdl.validate_bundle(os.path.join(root, name))
        assert ok, f"torn bundle survived the kill: {name}: {why}"
    return names


@pytest.fixture(scope="module")
def crash_env(tmp_path_factory):
    """Shared corpus + vocab + an uninterrupted reference run."""
    base = tmp_path_factory.mktemp("crash_resume")
    lines = ["a b c d", "b c d e", "c d e f", "d e f g",
             "e f g a", "f g a b", "g a b c", "a c e g"] * 2
    src = str(base / "t.src")
    with open(src, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    from marian_tpu.data.vocab import DefaultVocab
    vocab = str(base / "v.yml")
    DefaultVocab.build(lines).save(vocab)
    ref_dir = str(base / "ref")
    os.mkdir(ref_dir)
    _run_inprocess(_crash_config(ref_dir, src, vocab))
    ref = _ckpt_digest(os.path.join(ref_dir, "model.npz"))
    return {"base": base, "src": src, "vocab": vocab, "ref": ref}


def _kill_resume_roundtrip(crash_env, name, faults, extra_cfg=None):
    d = str(crash_env["base"] / name)
    os.mkdir(d)
    cfg = _crash_config(d, crash_env["src"], crash_env["vocab"])
    cfg.update(extra_cfg or {})
    mp = os.path.join(d, "model.npz")
    proc = _run_killed(cfg, d, faults)
    from marian_tpu.common.faultpoints import FAULT_EXIT_CODE
    assert proc.returncode == FAULT_EXIT_CODE, (
        f"expected injected kill, got exit {proc.returncode}:\n"
        + proc.stderr.decode("utf-8", "replace")[-2000:])
    _assert_never_torn(mp)          # the kill left no torn bundle behind
    _run_inprocess(cfg)             # restart: resume to completion
    assert _ckpt_digest(mp) == crash_env["ref"], (
        f"resume after {faults} is not bit-exact vs the uninterrupted run")
    _assert_never_torn(mp)


class TestCrashResume:
    """Tier-1: kill at the two highest-stakes stages — the optimizer
    member write (the original torn-bundle bug: model newer than its
    optimizer state) and the commit rename itself. The remaining fault
    points ride in the slow tier (same harness, full catalog)."""

    @pytest.mark.parametrize("faults", ["ckpt.write.optimizer=kill@2",
                                        "ckpt.commit=kill@2"])
    def test_kill_mid_save_resumes_bitexact(self, crash_env, faults):
        name = "t1_" + faults.split("=")[0].replace(".", "_")
        _kill_resume_roundtrip(crash_env, name, faults)

    @pytest.mark.parametrize("faults,extra", [
        ("ckpt.write.model=kill@2", None),
        ("ckpt.write.progress=kill@2", None),
        ("ckpt.write.manifest=kill@2", None),
        ("ckpt.publish=kill@2", None),
        ("ckpt.async.worker=kill@2", {"async-save": True}),
        ("data.batch.next=kill@3", None),
    ])
    def test_kill_at_remaining_fault_points_resumes_bitexact(
            self, crash_env, faults, extra):
        name = "slow_" + faults.split("=")[0].replace(".", "_")
        _kill_resume_roundtrip(crash_env, name, faults, extra_cfg=extra)