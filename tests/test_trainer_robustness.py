"""Trainer robustness flags (parallel/zero.py step_fn — reference:
training/graph_group.cpp): --normalize-gradient, --check-gradient-nan,
--dynamic-gradient-scaling + --gradient-norm-average-window."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.training.graph_group import GraphGroup


def _gg(**over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16,
            "learn-rate": 0.05, "optimizer": "adam", "clip-norm": 0.0,
            "exponential-smoothing": 0.0, "cost-type": "ce-sum"}
    base.update(over)
    opts = Options(base)
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(21))
    return gg


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


def _params_delta(gg_kwargs, steps=1):
    gg = _gg(**gg_kwargs)
    before = {k: np.asarray(v) for k, v in gg.export_params().items()}
    key = prng.stream(prng.root_key(21), prng.STREAM_DROPOUT)
    out = None
    for i in range(steps):
        out = gg.update(_batch(i), i + 1, key)
    after = gg.export_params()
    delta = sum(float(np.abs(np.asarray(after[k]) - before[k]).sum())
                for k in before)
    return gg, delta, out


class TestNormalizeGradient:
    def test_smaller_effective_gradient(self):
        """ce-sum + --normalize-gradient divides grads by target words —
        the reported gnorm must shrink accordingly vs the plain run."""
        _, _, out_plain = _params_delta({})
        _, _, out_norm = _params_delta({"normalize-gradient": True})
        # 8 rows x 7 trg tokens = 56 labels
        assert float(out_norm.grad_norm) == pytest.approx(
            float(out_plain.grad_norm) / 56.0, rel=1e-4)


class TestCheckGradientNan:
    def _poisoned(self, **over):
        gg = _gg(**over)
        # poison one weight: forward becomes non-finite -> nan gradients
        k = "encoder_l1_ffn_W1"
        assert k in gg.params
        gg.params[k] = jnp.full_like(gg.params[k], jnp.inf)
        return gg

    def test_nan_update_is_skipped(self):
        gg = self._poisoned(**{"check-gradient-nan": True})
        w_before = np.asarray(gg.params["Wemb"])
        out = gg.update(_batch(), 1,
                        prng.stream(prng.root_key(21),
                                    prng.STREAM_DROPOUT))
        np.testing.assert_array_equal(np.asarray(gg.params["Wemb"]),
                                      w_before)
        assert float(np.asarray(gg.opt_state["t"])) == 0.0

    def test_skipped_batch_does_not_poison_metrics(self):
        """The skip must also zero the reported ce_sum/labels — a nan
        loss flowing into the scheduler would read as the divergence the
        skip just averted (interacts with --throw-on-divergence)."""
        gg = self._poisoned(**{"check-gradient-nan": True})
        out = gg.update(_batch(), 1,
                        prng.stream(prng.root_key(21),
                                    prng.STREAM_DROPOUT))
        assert float(np.asarray(out.loss_sum)) == 0.0
        assert float(np.asarray(out.labels)) == 0.0

    def test_without_flag_nan_propagates(self):
        gg = self._poisoned()
        gg.update(_batch(), 1,
                  prng.stream(prng.root_key(21), prng.STREAM_DROPOUT))
        assert not np.isfinite(np.asarray(gg.params["Wemb"])).all()


class TestDynamicGradientScaling:
    def test_statistics_track_norm(self):
        gg, _, out = _params_delta(
            {"dynamic-gradient-scaling": ["2", "log"],
             "gradient-norm-average-window": 4}, steps=3)
        gs = gg.opt_state["gstat"]
        assert float(np.asarray(gs["n"])) == 3.0
        # log-mode average sits near log(gnorm)
        assert float(np.asarray(gs["avg"])) == pytest.approx(
            float(np.log(out.grad_norm)), abs=2.0)

    def test_tiny_factor_scales_updates_down(self):
        """factor=1e-3: once statistics warm up, every step's gradient is
        scaled down hard — cumulative parameter movement must be much
        smaller than the unscaled run over the same steps. SGD, because
        Adam's m/sqrt(v) preconditioning is invariant to uniform
        gradient scaling (the very reason the flag targets the raw
        norm, not the update)."""
        sgd = {"optimizer": "sgd", "gradient-norm-average-window": 4}

        def post_warm_movement(kwargs):
            gg = _gg(**kwargs)
            key = prng.stream(prng.root_key(21), prng.STREAM_DROPOUT)
            for i in range(3):          # warmup: statistics fill, no scaling
                gg.update(_batch(i), i + 1, key)
            snap = {k: np.asarray(v) for k, v in gg.export_params().items()}
            for i in range(3, 10):
                gg.update(_batch(i), i + 1, key)
            after = gg.export_params()
            return sum(float(np.abs(np.asarray(after[k]) - snap[k]).sum())
                       for k in snap)

        d_plain = post_warm_movement(dict(sgd))
        d_scaled = post_warm_movement(
            dict(sgd, **{"dynamic-gradient-scaling": ["0.001"]}))
        # scaled run: every post-warm gradient shrunk to ~0.1% → params
        # essentially frozen
        assert d_scaled < 0.05 * d_plain

    def test_composes_with_clip_as_min_not_product(self):
        """--clip-norm + --dynamic-gradient-scaling must cap the norm at
        min(clip, threshold), never scale twice. With a huge clip-norm
        the clip is inert, so the trajectory equals the no-clip run."""
        sgd = {"optimizer": "sgd", "gradient-norm-average-window": 4,
               "dynamic-gradient-scaling": ["2"]}
        _, d_noclip, _ = _params_delta(dict(sgd), steps=6)
        _, d_bigclip, _ = _params_delta(
            dict(sgd, **{"clip-norm": 1e6}), steps=6)
        assert d_bigclip == pytest.approx(d_noclip, rel=1e-5)

    def test_checkpoint_roundtrip_keeps_gstat(self):
        gg, _, _ = _params_delta(
            {"dynamic-gradient-scaling": ["2", "log"]}, steps=2)
        flat = gg.optimizer_arrays()
        assert "gstat:avg" in flat and "gstat:n" in flat
        gg2 = _gg(**{"dynamic-gradient-scaling": ["2", "log"]})
        gg2.load_optimizer_arrays(flat)
        assert float(np.asarray(gg2.opt_state["gstat"]["n"])) == 2.0