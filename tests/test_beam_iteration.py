"""COW paged beam search + cross-request prefix sharing (ISSUE 12):
refcounted page sharing in the KV pool, the beam iteration engine's
bitwise equivalence to full replication (and token parity vs the dense
beam search), worst-case-owned admission pricing, the prefix cache's
hit/miss/eviction/version-isolation semantics, the refcount-corruption
drill, and the metric census for every new series. Runs under
JAX_PLATFORMS=cpu with a tiny real transformer; MARIAN_POOL_AUDIT=1
(conftest) audits every round."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import faultpoints as fp
from marian_tpu.data.vocab import DefaultVocab, EOS_ID
from marian_tpu.ops.pallas.kv_pool import (KVPool, PoolCorruption,
                                           PoolExhausted)
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.scheduler import ContinuousScheduler, RowEvicted
from marian_tpu.translator.beam_iteration import PagedBeamEngine
from marian_tpu.translator.beam_search import BeamConfig, beam_search_jit
from marian_tpu.translator.iteration import PagedDecodeEngine
from marian_tpu.translator.prefix_cache import PrefixCache

from tests.test_beam_search import tiny_model


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """KVPool._lock / PrefixCache._lock / engine locks cross the device
    worker and the metrics scrape thread here; the shared witness pins
    the observed acquisition orders inside the static lattice."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _ownership_witness(ownership_witness):
    """The beam reorder's retable diff and the prefix cache's adoption
    path are exactly the handoffs the ownership witness audits; the
    shared fixture asserts observed pairings ⊆ the static graph."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _jitwit_witness(jitwit_witness):
    """Beam step / pool-fork jits compiled here must map to sites the
    static jit model predicts, with no instrumented-key retrace
    (ISSUE 17)."""
    yield


VOCAB_WORDS = [" ".join(f"w{i}" for i in range(35))]
TEXTS = ["w3 w4 w5", "w6 w7", "w8 w9 w10 w11", "w2 w3",
         "w4 w4 w4 w4 w4"]
K = 3


@pytest.fixture(scope="module")
def tiny():
    vocab = DefaultVocab.build(VOCAB_WORDS)
    model, params, _ = tiny_model(vocab=len(vocab), seed=7,
                                  **{"dec-depth": 2, "enc-depth": 2})
    return model, params, vocab


def make_beam_engine(tiny, registry=None, prefix=None, **kw):
    model, params, vocab = tiny
    args = dict(beam_size=K, normalize=0.6, max_rows=2 * K, page_len=4,
                src_len_cap=8, max_length_cap=12, registry=registry,
                prefix_cache=prefix)
    args.update(kw)
    return PagedBeamEngine(model, params, vocab, vocab, **args)


def make_greedy_engine(tiny, registry=None, prefix=None, **kw):
    model, params, vocab = tiny
    args = dict(max_rows=4, page_len=4, src_len_cap=8,
                max_length_cap=12, registry=registry,
                prefix_cache=prefix)
    args.update(kw)
    return PagedDecodeEngine(model, params, vocab, vocab, **args)


def drive(eng, texts):
    """Decode texts through the slot machinery, retrying deferred and
    pool-evicted sentences; returns (texts-by-key, info-by-key)."""
    outs, infos = {}, {}
    pending = list(enumerate(texts))
    guard = 0
    while pending or not eng.idle():
        joins = []
        while pending and len(joins) < max(1, eng.free_slots()):
            joins.append(pending.pop(0))
        res = eng.admit_and_step(joins)
        for key, why in res.rejected:
            assert why in ("no_slot", "no_pages"), (key, why)
            pending.insert(0, (key, texts[key]))
        for key in res.pool_evicted:
            pending.insert(0, (key, texts[key]))
        outs.update(dict(res.finished))
        infos.update(res.finished_info)
        guard += 1
        assert guard < 1000, "beam decode failed to converge"
    assert eng.audit(context="test") == []
    return outs, infos


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# refcounted pool (satellite: audit invariants + drill)
# ---------------------------------------------------------------------------

class TestRefcountedPool:
    def test_share_retable_release_refcounts(self):
        p = KVPool(9, page_len=4)
        a = p.claim("a", 3)
        p.share("b", a[:2])
        own = p.claim_extra("b", 1)
        assert p.refcount(a[0]) == 2 and p.refcount(a[2]) == 1
        assert p.audit() == []
        assert p.release("a") == 3          # references dropped, not
        assert p.refcount(a[0]) == 1        # pages: b still holds them
        assert p.free_pages() == 8 - 3      # only a's exclusive page
        freed = p.retable("b", [own[0]])    # drop the aliases: with
        assert freed == 2                   # a gone, their last refs
        assert p.refcount(a[0]) == 0        # drop and the pages free
        assert p.free_pages() == 8 - 1
        assert p.audit() == []
        p.release("b")
        assert p.free_pages() == 8 and p.audit() == []

    def test_transfer_moves_references(self):
        p = KVPool(9, page_len=4)
        a = p.claim("row", 2)
        assert p.transfer("row", ("prefix", "v", "k")) == a
        assert p.pages_of("row") == []
        assert p.pages_of(("prefix", "v", "k")) == a
        assert p.audit() == []

    def test_share_dead_page_refused(self):
        p = KVPool(9, page_len=4)
        a = p.claim("a", 1)
        p.release("a")
        with pytest.raises(ValueError, match="not live"):
            p.share("b", a)

    def test_audit_refcount_invariants(self):
        """The three satellite invariants: reference-sum == refcount,
        no freed page with refcount > 0, no refcount-0 page outside
        the free list."""
        p = KVPool(9, page_len=4)
        a = p.claim("a", 2)
        p.share("b", a[:1])
        # (1) refcount drift vs table references
        p._refs[a[0]] += 1
        bad = p.audit()
        assert any("refcount drift" in v or "refcount" in v
                   for v in bad), bad
        p._refs[a[0]] -= 1
        assert p.audit() == []
        # (2) freed page with live refcount
        p._free.append(a[1])
        bad = p.audit()
        assert any("free but still has refcount" in v
                   or "double-free" in v for v in bad), bad
        p._free.pop()
        # (3) phantom refcount: no table reference names it
        ghost = p._free[-1]
        p._refs[ghost] = 1
        p._free.pop()
        bad = p.audit()
        assert any("phantom" in v for v in bad), bad

    def test_refcount_corrupt_drill_detected(self, tiny):
        """The pool.refcount_corrupt catalog point bumps a REAL live
        refcount without a table reference; the continuous audit must
        catch it and fail the round with the retriable PoolCorruption."""
        reg = msm.Registry()
        eng = make_beam_engine(tiny, registry=reg)
        eng.admit_and_step([(0, TEXTS[0])])
        with fp.active("pool.refcount_corrupt=fail@1"):
            with pytest.raises(PoolCorruption, match="audit failed"):
                eng.admit_and_step([])
        assert reg.get(
            "marian_serving_pool_audit_failures_total").value >= 1


# ---------------------------------------------------------------------------
# COW beam: bitwise vs replication, token parity vs dense beam search
# ---------------------------------------------------------------------------

class TestBeamParity:
    def _dense_best(self, tiny, text):
        model, params, vocab = tiny
        ids = vocab.encode(text, add_eos=True, inference=True)
        L = int(min(12, max(8, round(3.0 * len(ids)))))
        cfg = BeamConfig(beam_size=K, normalize=0.6, max_length=L)
        src = jnp.asarray(np.array([ids], np.int32))
        mask = jnp.ones((1, len(ids)), jnp.float32)
        toks, scores, lengths, norm, _, _ = beam_search_jit(
            model, [params], [1.0], cfg, src, mask)
        toks, scores, lengths, norm = map(
            np.asarray, (toks, scores, lengths, norm))
        j = np.argsort(-norm[0], kind="stable")[0]
        ln = int(lengths[0, j])
        tl = toks[0, j, :ln].tolist()
        if tl and tl[-1] == EOS_ID:
            tl = tl[:-1]
        return tl, float(scores[0, j]), ln

    def test_cow_bitwise_equals_replication(self, tiny):
        """THE COW correctness property: aliasing full pages + forking
        only partials produces BITWISE the tokens and raw path scores
        of full per-child page replication (the dense reorder's data
        movement over the same pool) — mid-decode forks included, since
        every reorder with two live children of one parent is one.
        merge="host" pins BOTH arms to the per-step host merge so this
        stays a pure COW-vs-replication property (fused-vs-host merge
        parity is its own test, tests/test_translate_beam_fused.py)."""
        cow_o, cow_i = drive(make_beam_engine(tiny, cow=True,
                                              merge="host"), TEXTS)
        eng = make_beam_engine(tiny, cow=False)
        rep = make_beam_engine(tiny, cow=False,
                               pool_bytes=64 * eng.page_bytes)
        rep_o, rep_i = drive(rep, TEXTS)
        assert cow_o == rep_o
        for k in cow_i:
            assert cow_i[k]["tokens"] == rep_i[k]["tokens"]
            assert np.float32(cow_i[k]["score"]) \
                == np.float32(rep_i[k]["score"])

    def test_freed_then_reforked_rows_stay_bitwise(self, tiny):
        """Rows freed mid-decode and reforked onto RECYCLED pages stay
        bitwise: (a) a sentence evicted mid-decode (pages freed) and
        rejoined re-decodes onto the just-freed pages identically; (b)
        a long-lived engine whose every sentence reuses its
        predecessors' pages (LIFO free list) matches fresh engines.
        merge="host" everywhere: page recycling is merge-path-agnostic
        (same pool verbs either way) and this test builds 7 engines —
        the host path keeps it off the fused warm cost."""
        eng = make_beam_engine(tiny, max_rows=K, merge="host")
        eng.admit_and_step([(0, TEXTS[4])])
        for _ in range(4):
            eng.admit_and_step([])
        eng.admit_and_step([], evicts=[0])    # freed mid-decode
        assert eng.pool.free_pages() == eng.pool.usable_pages
        assert eng.audit(context="test") == []
        re_o, re_i = drive(eng, [TEXTS[4]])   # refork onto freed pages
        fresh_o, fresh_i = drive(make_beam_engine(tiny, max_rows=K,
                                                  merge="host"),
                                 [TEXTS[4]])
        assert re_o == fresh_o
        assert np.float32(re_i[0]["score"]) \
            == np.float32(fresh_i[0]["score"])
        # (b): sequential reuse of one engine's recycled pages
        for i, t in enumerate(TEXTS):
            o, inf = drive(eng, [t])
            f_o, f_i = drive(make_beam_engine(tiny, max_rows=K,
                                              merge="host"), [t])
            assert o == f_o, i
            assert np.float32(inf[0]["score"]) \
                == np.float32(f_i[0]["score"]), i

    def test_token_parity_vs_dense_beam_search(self, tiny):
        """End-to-end vs translator/beam_search.py: identical winning
        tokens and hypothesis lengths; raw scores agree to accumulated-
        ULP tolerance (the paged attention read and the dense cache
        path order a handful of f32 ops differently — the same
        tolerance class the greedy paged parity lives with; the
        BITWISE pin for the COW machinery itself is the replication
        test above)."""
        _, infos = drive(make_beam_engine(tiny), TEXTS)
        for i, t in enumerate(TEXTS):
            tl, score, ln = self._dense_best(tiny, t)
            mine = infos[i]
            crop = mine["tokens"][:mine["length"]]
            if crop and crop[-1] == EOS_ID:
                crop = crop[:-1]
            assert crop == tl, (i, crop, tl)
            assert mine["length"] == ln
            assert abs(mine["score"] - score) < 1e-4

    def test_mid_decode_join_beside_running_beam(self, tiny):
        eng = make_beam_engine(tiny)
        r0 = eng.admit_and_step([(0, TEXTS[0])])
        assert r0.accepted == [0] and r0.mid_decode_joins == 0
        for _ in range(3):
            eng.admit_and_step([])
        r1 = eng.admit_and_step([(1, TEXTS[1])])
        assert r1.accepted == [1] and r1.mid_decode_joins == 1
        outs = dict(r0.finished + r1.finished)
        guard = 0
        while not eng.idle():
            outs.update(dict(eng.admit_and_step([]).finished))
            guard += 1
            assert guard < 200
        solo0, _ = drive(make_beam_engine(tiny, max_rows=K), [TEXTS[0]])
        solo1, _ = drive(make_beam_engine(tiny, max_rows=K), [TEXTS[1]])
        assert outs[0] == solo0[0] and outs[1] == solo1[0]
        assert eng.pool.free_pages() == eng.pool.usable_pages


# ---------------------------------------------------------------------------
# admission pricing (satellite: worst-case OWNED pages, not kx)
# ---------------------------------------------------------------------------

class TestBeamPricing:
    def test_beam_priced_at_owned_pages_not_k_times(self, tiny):
        greedy = make_greedy_engine(tiny)
        beam6 = make_beam_engine(tiny, beam_size=6, max_rows=6)
        text = TEXTS[0]
        base = greedy.pages_for_text(text)
        priced = beam6.pages_for_text(text)
        assert priced == base + 5            # trunk + (k-1) partials
        assert priced < 6 * base             # never kx replication

    def test_beam6_request_not_shed_at_6x(self, tiny):
        """Regression: a beam-6 request against a page bound sized for
        trunk+partials admission must NOT shed as if it replicated its
        trunk 6x."""
        from marian_tpu.serving.admission import AdmissionController
        beam6 = make_beam_engine(tiny, beam_size=6, max_rows=6)
        reg = msm.Registry()
        sched = ContinuousScheduler(None, registry=reg,
                                    batching_mode="iteration",
                                    engine=beam6, window_s=0.0)
        priced = beam6.pages_for_text(TEXTS[0])
        adm = AdmissionController(0, sched.queued_units, registry=reg,
                                  max_queue_pages=priced,
                                  pages_fn=sched.queued_pages)
        adm.admit(1, n_pages=priced)         # fits exactly: admitted
        naive = 6 * make_greedy_engine(tiny).pages_for_text(TEXTS[0])
        assert naive > priced                # the old pricing would shed


# ---------------------------------------------------------------------------
# serving: beam engine through the iteration scheduler (+ quiesce)
# ---------------------------------------------------------------------------

def make_sched(tiny, registry=None, engine=None, **kw):
    reg = registry if registry is not None else msm.Registry()
    eng = engine if engine is not None else make_beam_engine(
        tiny, registry=reg)
    sched = ContinuousScheduler(None, registry=reg,
                                batching_mode="iteration", engine=eng,
                                window_s=0.0, **kw)
    return sched, eng, reg


class TestBeamServing:
    def test_end_to_end_beam_serving(self, tiny):
        sched, eng, reg = make_sched(tiny)

        async def main():
            sched.start()
            f1 = sched.submit(TEXTS[:2])
            await asyncio.sleep(0.05)
            f2 = sched.submit([TEXTS[2]])     # lands mid-decode
            r1, r2 = await f1, await f2
            await sched.stop()
            return r1, r2

        r1, r2 = run(main())
        solo = {}
        for i in range(3):
            o, _ = drive(make_beam_engine(tiny, max_rows=K),
                         [TEXTS[i]])
            solo[i] = o[0]
        assert r1 == [solo[0], solo[1]] and r2 == [solo[2]]
        assert sched.m_joins.value == 3
        assert eng.audit(context="test") == []
        assert eng.pool.free_pages() == eng.pool.usable_pages

    def test_cancel_mid_decode_frees_refcounted_rows(self, tiny):
        sched, eng, reg = make_sched(tiny)

        async def main():
            sched.start()
            f1 = sched.submit([TEXTS[4]])
            await asyncio.sleep(0.05)
            f1.cancel()
            f2 = sched.submit([TEXTS[1]])
            await f2
            for _ in range(50):
                if sched.m_evictions.value:
                    break
                await asyncio.sleep(0.01)
            await sched.stop()

        run(main())
        assert sched.m_evictions.value >= 1
        assert eng.idle()
        assert eng.audit(context="test") == []
        assert eng.pool.free_pages() == eng.pool.usable_pages

    def test_pool_evicted_rows_fail_retriably(self, tiny):
        """Mid-decode COW exhaustion resolves the victim with the
        retriable RowEvicted — never a hang, never silent corruption."""
        roomy = make_beam_engine(tiny, max_rows=K)
        tight = make_beam_engine(tiny, max_rows=2 * K,
                                 pool_bytes=8 * roomy.page_bytes)
        sched, eng, reg = make_sched(tiny, engine=tight)

        async def main():
            sched.start()
            futs = [sched.submit([t]) for t in TEXTS]
            evicted = 0
            for f in futs:
                try:
                    await asyncio.wait_for(f, timeout=120)
                except RowEvicted:
                    evicted += 1
            await sched.stop()
            return evicted

        evicted = run(main())
        # under this pool some sentence must have been pool-evicted OR
        # deferred-and-served; either way the pool ends clean
        assert evicted >= 0
        assert tight.audit(context="test") == []
        assert tight.pool.free_pages() == tight.pool.usable_pages

    def test_quiesce_with_refcounted_rows(self, tiny):
        """A quiesce mid-beam-decode drains/evicts refcounted rows,
        audits both engines clean, and re-points at the new beam
        engine (the ISSUE 12 acceptance's swap-mid-run leg)."""
        sched, eng, reg = make_sched(tiny)
        new_eng = make_beam_engine(tiny)

        async def main():
            sched.start()
            f1 = sched.submit([TEXTS[4]])
            await asyncio.sleep(0.05)         # decoding now
            loop = asyncio.get_event_loop()
            op = await loop.run_in_executor(
                None, lambda: sched.request_quiesce(
                    lambda: sched.install_engine(new_eng),
                    deadline_s=0.0, reason="test-swap", wait=True,
                    timeout=60))
            try:
                await f1
            except RowEvicted:
                pass                          # deadline 0: evicted
            f2 = sched.submit([TEXTS[1]])
            r2 = await f2
            await sched.stop()
            return op, r2

        op, r2 = run(main())
        assert op.ok and op.install_ok
        assert sched.engine is new_eng
        solo, _ = drive(make_beam_engine(tiny, max_rows=K), [TEXTS[1]])
        assert r2 == [solo[0]]
        assert eng.audit(context="test") == []
        assert new_eng.audit(context="test") == []
        assert eng.pool.free_pages() == eng.pool.usable_pages


# ---------------------------------------------------------------------------
# cross-request prefix sharing
# ---------------------------------------------------------------------------

class TestPrefixCache:
    def test_live_fork_and_done_replay_bitwise_vs_cold(self, tiny):
        """The acceptance identity: with >= 50% shared-prefix traffic,
        warm-cache outputs are bitwise the cold-cache outputs, hit
        metrics count pages reused > 0, and audits stay clean."""
        cold = make_greedy_engine(tiny).decode_texts(TEXTS)
        reg = msm.Registry()
        cache = PrefixCache(max_entries=8, version="v1", registry=reg)
        eng = make_greedy_engine(tiny, registry=reg, prefix=cache)
        # leader decodes a few rounds, then an exact repeat forks live
        r = eng.admit_and_step([(0, TEXTS[4])])
        assert r.accepted == [0]
        for _ in range(5):
            eng.admit_and_step([])
        r2 = eng.admit_and_step([(1, TEXTS[4])])
        assert r2.accepted == [1]
        assert cache.m_hits.value == 1
        assert cache.m_pages_reused.value >= 1
        assert cache.m_tokens_saved.value >= 1
        outs = {}
        guard = 0
        while not eng.idle():
            outs.update(dict(eng.admit_and_step([]).finished))
            guard += 1
            assert guard < 200
        assert outs[0] == outs[1] == cold[4]
        # completed-entry replay: instant, no decode, same text
        r3 = eng.admit_and_step([(2, TEXTS[4])])
        assert r3.accepted == [2]
        assert dict(r3.finished)[2] == cold[4]
        assert cache.m_hits.value == 2
        assert eng.audit(context="test") == []

    def test_fifty_percent_shared_traffic_identical_to_cold(self, tiny):
        traffic = [TEXTS[4], TEXTS[0], TEXTS[4], TEXTS[1], TEXTS[4],
                   TEXTS[0], TEXTS[4], TEXTS[0]]
        cold = make_greedy_engine(tiny).decode_texts(traffic)
        cache = PrefixCache(max_entries=8, version="v1")
        warm = make_greedy_engine(tiny, prefix=cache).decode_texts(
            traffic)
        assert warm == cold
        assert cache.entries() > 0

    def test_pool_pressure_evicts_lru_entries(self, tiny):
        """Cache-held pages yield to live claims: a join that would
        fail claims pages back from LRU entries instead of deferring
        forever."""
        reg = msm.Registry()
        cache = PrefixCache(max_entries=8, version="v1", registry=reg)
        # pool fits exactly one sentence's 3 pages
        eng = make_greedy_engine(tiny, registry=reg, prefix=cache,
                                 max_rows=2,
                                 pool_bytes=3 * 2 * 2 * 2 * 4 * 8 * 4)
        assert eng.pool.usable_pages == 3
        outs = eng.decode_texts([TEXTS[0]])
        assert cache.entries() == 1          # pages now cache-held
        assert eng.pool.free_pages() == 0
        assert eng.free_pages() == 3         # reclaimable counts
        outs2 = eng.decode_texts([TEXTS[1]])  # forces the eviction
        assert cache.m_evictions.value >= 1
        assert outs2 == [make_greedy_engine(tiny).decode_texts(
            [TEXTS[1]])[0]]
        assert eng.audit(context="test") == []

    def test_version_isolation_across_swap(self, tiny):
        """A swap must not serve stale-version pages/outputs: engines
        are cache-scoped, and even a (hypothetically) shared cache
        refuses entries stamped with another version."""
        cache_a = PrefixCache(max_entries=8, version="vA")
        eng_a = make_greedy_engine(tiny, prefix=cache_a)
        eng_a.decode_texts([TEXTS[0]])
        assert cache_a.entries() == 1
        key = next(iter(cache_a._done))
        # belt: version-stamped entries don't cross versions
        assert cache_a.get(key, "vB") is None
        assert cache_a.get(key, "vA") is not None
        # braces: the swapped-in engine owns a FRESH cache — no hits
        reg_b = msm.Registry()
        cache_b = PrefixCache(max_entries=8, version="vB",
                              registry=reg_b)
        eng_b = make_greedy_engine(tiny, registry=reg_b,
                                   prefix=cache_b)
        out_b = eng_b.decode_texts([TEXTS[0]])
        assert cache_b.m_hits.value == 0
        assert cache_b.m_misses.value >= 1
        assert out_b == make_greedy_engine(tiny).decode_texts(
            [TEXTS[0]])

    def test_beam_engine_replays_completed_decodes(self, tiny):
        reg = msm.Registry()
        cache = PrefixCache(max_entries=8, version="v1", registry=reg)
        eng = make_beam_engine(tiny, registry=reg, prefix=cache)
        first, _ = drive(eng, [TEXTS[3]])
        assert cache.entries() == 1
        r = eng.admit_and_step([(1, TEXTS[3])])
        assert r.accepted == [1]
        assert dict(r.finished)[1] == first[0]
        assert cache.m_hits.value == 1
        assert eng.audit(context="test") == []


# ---------------------------------------------------------------------------
# metric census (every new series is declared and scrapeable)
# ---------------------------------------------------------------------------

class TestMetricCensus:
    def test_prefix_and_beam_series_render(self, tiny):
        reg = msm.Registry()
        cache = PrefixCache(max_entries=4, version="v1", registry=reg)
        eng = make_greedy_engine(tiny, registry=reg, prefix=cache)
        eng.decode_texts([TEXTS[0], TEXTS[0]])
        text = reg.render()
        for name in ("marian_prefix_hits_total",
                     "marian_prefix_misses_total",
                     "marian_prefix_tokens_saved_total",
                     "marian_prefix_pages_reused_total",
                     "marian_prefix_evictions_total",
                     "marian_prefix_entries"):
            assert name in text, name
        from marian_tpu.serving.promlint import lint_metrics_text
        assert lint_metrics_text(text) == []
