"""Factored TARGET vocab for the s2s family (models/s2s.py — reference:
factored vocabs apply across model families; closes the round-2-era
refusal for the RNN lineage). Source-side factors remain a loud
transformer-only refusal."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.factored_vocab import FactoredVocab
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.models.encoder_decoder import create_model

FSV = """\
</s>
<unk>
hello|ci
hello|cn
world|cn
world|ci
cat|cn
dog|cn
"""


@pytest.fixture
def fvocab(tmp_path):
    p = tmp_path / "v.fsv"
    p.write_text(FSV)
    return FactoredVocab.load(str(p))


@pytest.fixture
def rng():
    return np.random.RandomState(7)


def _model(fvocab, **over):
    base = {"type": "s2s", "dim-emb": 16, "dim-rnn": 24,
            "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
            "dec-cell": "gru", "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16}
    base.update(over)
    src = DefaultVocab.build(["a b c d e f"])
    model = create_model(Options(base), src, fvocab)
    return model, model.init(jax.random.key(7)), len(src)


class TestS2SFactored:
    def test_tables_sized_in_units(self, fvocab):
        _, params, _ = _model(fvocab)
        assert params["Wemb_dec"].shape[0] == fvocab.n_units
        assert params["ff_logit_l2_b"].shape[1] == fvocab.n_units

    def test_trains_and_gradients_flow(self, fvocab, rng):
        model, params, nsrc = _model(fvocab)
        v = len(fvocab)
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, nsrc, (2, 5)), jnp.int32),
            "src_mask": jnp.ones((2, 5), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, v, (2, 6)), jnp.int32),
            "trg_mask": jnp.ones((2, 6), jnp.float32),
        }
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))
        assert float(jnp.abs(grads["Wemb_dec"]).sum()) > 0

    def test_beam_decodes_factored_forms(self, fvocab, rng):
        from marian_tpu.translator.beam_search import BeamSearch
        model, params, nsrc = _model(fvocab)
        bs = BeamSearch(model, [params], None,
                        Options({"beam-size": 2, "normalize": 0.6,
                                 "max-length": 8}), fvocab)
        ids = jnp.asarray(rng.randint(2, nsrc, (2, 4)), jnp.int32)
        nbests = bs.search(ids, jnp.ones((2, 4), jnp.float32))
        assert len(nbests) == 2
        for nb in nbests:
            assert np.isfinite(nb[0]["norm_score"])
            assert all(0 <= t < len(fvocab) for t in nb[0]["tokens"])

    def test_multi_s2s_factored_target(self, fvocab, rng):
        """The factored target composes with the rest of the RNN family
        (multi-encoder here)."""
        src = DefaultVocab.build(["a b c d e f"])
        model = create_model(
            Options({"type": "multi-s2s", "dim-emb": 16, "dim-rnn": 24,
                     "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
                     "dec-cell": "gru", "label-smoothing": 0.0,
                     "precision": ["float32", "float32"],
                     "max-length": 16}), [src, src], fvocab)
        params = model.init(jax.random.key(7))
        assert params["Wemb_dec"].shape[0] == fvocab.n_units
        batch = {
            "src_ids": jnp.asarray(rng.randint(2, 8, (2, 5)), jnp.int32),
            "src_mask": jnp.ones((2, 5), jnp.float32),
            "src2_ids": jnp.asarray(rng.randint(2, 8, (2, 4)), jnp.int32),
            "src2_mask": jnp.ones((2, 4), jnp.float32),
            "trg_ids": jnp.asarray(rng.randint(2, len(fvocab), (2, 6)),
                                   jnp.int32),
            "trg_mask": jnp.ones((2, 6), jnp.float32),
        }
        loss, _ = model.loss(params, batch, None, train=False)
        assert np.isfinite(float(loss))

    def test_tied_embeddings_trg_side_ok(self, fvocab, rng):
        model, params, _ = _model(fvocab, **{"tied-embeddings": True})
        assert "ff_logit_l2_W" not in params    # output tied to Wemb_dec

    def test_tied_all_refused(self, fvocab):
        with pytest.raises(ValueError, match="factored target"):
            _model(fvocab, **{"tied-embeddings-all": True})

    def test_src_factors_still_refused(self, fvocab):
        with pytest.raises(NotImplementedError, match="SOURCE"):
            create_model(Options({"type": "s2s", "dim-emb": 16,
                                  "dim-rnn": 24}), fvocab, fvocab)