"""Decoder-only transformer LM (--type transformer-lm; reference:
model_factory.cpp decoder-only assembly used by marian-scorer for LM
scoring / R2L reranking)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import transformer as T
from marian_tpu.models.encoder_decoder import create_model


@pytest.fixture
def rng():
    return np.random.RandomState(29)


def lm_model(vocab=23, **over):
    opts = Options({
        "type": "transformer-lm", "dim-emb": 16, "transformer-heads": 2,
        "transformer-dim-ffn": 32, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True, "precision": ["float32", "float32"],
        "max-length": 32, **over,
    })
    model = create_model(opts, vocab, vocab)
    return model, model.init(jax.random.key(0))


def lm_batch(rng, b=3, tt=8, vocab=23):
    ids = jnp.asarray(rng.randint(2, vocab, (b, tt)), jnp.int32)
    mask = jnp.ones((b, tt), jnp.float32)
    # single-stream corpus: src and trg are the same stream
    return {"src_ids": ids, "src_mask": mask,
            "trg_ids": ids, "trg_mask": mask}


class TestTransformerLM:
    def test_no_encoder_or_cross_params(self):
        model, params = lm_model()
        assert not any(n.startswith("encoder") for n in params)
        assert not any("_context" in n for n in params)
        assert any(n.startswith("decoder_l1_self") for n in params)

    def test_loss_trains(self, rng):
        model, params = lm_model()
        batch = lm_batch(rng)

        @jax.jit
        def step(p):
            def loss_fn(pp):
                total, aux = model.loss(pp, batch, key=None, train=False)
                return total / jnp.maximum(aux["labels"], 1.0)
            l, g = jax.value_and_grad(loss_fn)(p)
            return l, {k: v - 0.5 * g[k] for k, v in p.items()}

        losses = []
        for _ in range(5):
            l, params = step(params)
            losses.append(float(l))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_step_matches_teacher_forcing(self, rng):
        model, params = lm_model()
        batch = lm_batch(rng)
        full = T.decode_train(model.cfg, params, None, None,
                              batch["trg_ids"], batch["trg_mask"],
                              train=False)
        state = T.init_decode_state(model.cfg, params, None,
                                    batch["trg_mask"], max_len=10)
        prev = jnp.zeros((3, 1), jnp.int32)
        for t in range(batch["trg_ids"].shape[1]):
            logits, state = T.decode_step(model.cfg, params, state, prev,
                                          batch["trg_mask"])
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t, :]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_scorer_cli(self, rng, tmp_path):
        """marian-scorer over a single-stream corpus with an LM model."""
        from marian_tpu.cli import marian_train, marian_scorer
        lines = ["a b c d", "b c d a", "c d a b", "d a b c"] * 3
        (tmp_path / "t.txt").write_text("\n".join(lines) + "\n")
        model = str(tmp_path / "lm.npz")
        marian_train.main([
            "--type", "transformer-lm",
            "--train-sets", str(tmp_path / "t.txt"),
            "--vocabs", str(tmp_path / "v.yml"),
            "--model", model, "--dim-emb", "16",
            "--transformer-heads", "2", "--transformer-dim-ffn", "32",
            "--dec-depth", "1", "--precision", "float32", "float32",
            "--tied-embeddings-all",
            "--mini-batch", "8", "--learn-rate", "0.01",
            "--after-batches", "6", "--disp-freq", "3u",
            "--save-freq", "100u", "--seed", "1", "--max-length", "20",
            "--quiet", "--overwrite", "--cost-type", "ce-mean-words",
        ])
        import io
        import contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            marian_scorer.main([
                "--models", model,
                "--vocabs", str(tmp_path / "v.yml"),
                "--train-sets", str(tmp_path / "t.txt"),
                "--quiet",
            ])
        scores = [float(x) for x in buf.getvalue().split()]
        assert len(scores) == len(lines)
        assert all(np.isfinite(scores))

    def test_translate_refused(self, rng):
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = lm_model()
        batch = lm_batch(rng)
        bs = BeamSearch(model, [params], None,
                        Options({"beam-size": 2, "max-length": 8}), None)
        with pytest.raises(ValueError, match="marian-scorer"):
            bs.search(batch["src_ids"], batch["src_mask"])
