"""Scaling-efficiency telemetry on the virtual mesh (VERDICT r3 #6).

Correctness tests can't see an accidental host sync or a re-replication
regression in the sharded step — the numbers stay right while every
update quietly pays N× compute or an extra device round-trip. This
measures what those regressions inflate: per-step wall time at 1 vs 8
virtual devices at FIXED per-device batch, plus the compiled collective
footprint. On one CPU core the 8 virtual devices serialize, so the ideal
wall-clock ratio is ~8×; a replicated-optimizer regression pushes it
well past that (8× compute + 8× optimizer math + resharding traffic),
and a host sync shows up as a constant floor per step.

Measured numbers are recorded in docs/PERFORMANCE.md (round 4).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.optimizers.optimizers import OptimizerConfig, init_state
from marian_tpu.optimizers.schedule import LRSchedule
from marian_tpu.parallel import mesh as M
from marian_tpu.parallel.zero import build_train_step, place

DIM = 64
PER_DEV_B = 8
T = 16


def _opts():
    return Options({
        "type": "transformer", "dim-emb": DIM, "transformer-heads": 4,
        "transformer-dim-ffn": 2 * DIM, "enc-depth": 2, "dec-depth": 2,
        "tied-embeddings-all": True, "precision": ["float32", "float32"],
        "max-length": T, "label-smoothing": 0.1,
        "cost-type": "ce-mean-words", "learn-rate": 1e-3,
        "optimizer": "adam", "clip-norm": 1.0,
    })


def _timed_step(n_dev, vocab=64, n_steps=6):
    o = _opts()
    mesh = M.make_mesh(None, jax.devices()[:n_dev])
    model = create_model(o, vocab, vocab)
    params = model.init(jax.random.key(0))
    cfg = OptimizerConfig.from_options(o)
    st = init_state(cfg, params)
    params, st = place(params, st, mesh)
    step = build_train_step(model, cfg, LRSchedule.from_options(o),
                            "ce-mean-words", mesh, params, st,
                            delay=1, donate=False)
    rs = np.random.RandomState(0)
    b = M.shard_batch({
        "src_ids": jnp.asarray(rs.randint(2, vocab, (PER_DEV_B * n_dev, T)),
                               jnp.int32),
        "src_mask": jnp.ones((PER_DEV_B * n_dev, T), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, vocab, (PER_DEV_B * n_dev, T)),
                               jnp.int32),
        "trg_mask": jnp.ones((PER_DEV_B * n_dev, T), jnp.float32)}, mesh)
    args = (b, jnp.asarray(1.0, jnp.float32), jax.random.key(1))
    p, s = params, st
    for _ in range(2):                      # compile + settle
        p, s, m = step(p, s, *args)
    jax.block_until_ready((p, s))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        p, s, m = step(p, s, *args)
    jax.block_until_ready((p, s))
    per_step = (time.perf_counter() - t0) / n_steps
    lowered = step.lower(params, st, *args).compile().as_text()
    return per_step, lowered, len(params)


@pytest.mark.slow
def test_scaling_overhead_bound_and_collective_budget():
    assert len(jax.devices()) >= 8
    t1, _, _ = _timed_step(1)
    t8, hlo8, n_leaves = _timed_step(8)
    ratio = t8 / t1
    # the N cores share the 8 virtual devices' serialized compute →
    # ideal wall ratio is 8 / min(8, cores) at fixed per-device batch
    # (8.0 on the usual 1-core box). Bounds leave headroom for timer
    # noise and in-process collective scheduling; a replicated-Adam or
    # re-replication regression lands well above, a vanished shard
    # (under-provisioned mesh) well below.
    import os
    ideal = 8.0 / min(8, os.cpu_count() or 1)
    assert ideal * 0.45 < ratio < ideal * 2.0 + 2.0, \
        f"8-dev/1-dev wall ratio {ratio:.2f} (ideal {ideal:.1f})"

    from marian_tpu.parallel.collectives import (collective_stats,
                                                 format_stats)
    stats = collective_stats(hlo8)
    # collective BUDGET at fixed model: one reduce-scatter and one
    # all-gather per param leaf per step, nothing param-sized in
    # all-reduce (the pattern test pins presence; this pins absence of
    # growth — e.g. a second all-gather per leaf from an EMA reshard)
    assert stats["reduce-scatter"]["count"] == n_leaves
    assert stats["all-gather"]["count"] == n_leaves
    assert stats.get("all-reduce", {"count": 0})["count"] <= 4
    print(f"\nscaling telemetry: t1={t1 * 1e3:.1f}ms "
          f"t8={t8 * 1e3:.1f}ms ratio={ratio:.2f} (ideal 8.0)\n"
          + format_stats(stats))
