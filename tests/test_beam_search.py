"""Beam-search tests: the jitted static-shape beam must match an independent
host-loop reference beam exactly on tiny models (the reference pins decode
outputs in its regression suite — SURVEY.md §4/§7 stage-4 gate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.vocab import EOS_ID, UNK_ID
from marian_tpu.models.encoder_decoder import create_model
from marian_tpu.translator.beam_search import BeamSearch, BeamConfig, beam_search_jit
from marian_tpu.translator.greedy import greedy_decode


def tiny_model(vocab=19, seed=0, **over):
    base = {
        "type": "transformer",
        "dim-emb": 16, "transformer-heads": 2, "transformer-dim-ffn": 32,
        "enc-depth": 1, "dec-depth": 1, "tied-embeddings-all": True,
        "precision": ["float32", "float32"], "max-length": 64,
    }
    base.update(over)
    opts = Options(base)
    model = create_model(opts, vocab, vocab, inference=True)
    params = model.init(jax.random.key(seed))
    return model, params, opts


def reference_beam(model, params, src_ids, src_mask, k, L, normalize=0.0,
                   allow_unk=False):
    """Plain-python beam search over model.step — deliberately different
    control flow from the jitted version (dynamic beam lists, no masking)."""
    b = src_ids.shape[0]
    results = []
    for i in range(b):
        sid = jnp.asarray(src_ids[i:i + 1])
        smask = jnp.asarray(src_mask[i:i + 1])
        enc = model.encode_for_decode(params, sid, smask)
        enc_k = jnp.repeat(enc, 1, axis=0)
        # beams: list of (tokens, score, state, finished)
        state0 = model.start_state(params, enc, smask, L)
        beams = [([], 0.0, state0, False)]
        finished = []
        for t in range(L):
            cands = []
            for toks, score, st, fin in beams:
                if fin:
                    continue
                prev = jnp.asarray([[toks[-1] if toks else 0]], jnp.int32)
                logits, st2 = model.step(params, st, prev, smask)
                lp = np.array(jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1))[0]
                if not allow_unk:
                    lp[UNK_ID] = -1e9
                for v in np.argsort(-lp)[: k + 1]:
                    cands.append((toks + [int(v)], score + float(lp[v]), st2,
                                  int(v) == EOS_ID))
            if not cands:
                break
            cands.sort(key=lambda c: -c[1])
            beams = []
            for c in cands[:k]:
                if c[3]:
                    finished.append(c)
                else:
                    beams.append(c)
            if len(finished) >= k:
                break
        for toks, score, st, fin in beams:
            finished.append((toks, score, st, False))

        def norm_score(c):
            ln = len(c[0])
            return c[1] / (ln ** normalize if normalize > 0 else 1.0)
        finished.sort(key=lambda c: -norm_score(c))
        best = finished[0]
        toks = best[0]
        if toks and toks[-1] == EOS_ID:
            toks = toks[:-1]
        results.append((toks, norm_score(best)))
    return results


def random_batch(vocab, b, ts, seed):
    rs = np.random.RandomState(seed)
    src = rs.randint(2, vocab, (b, ts)).astype(np.int32)
    src[:, -1] = EOS_ID
    mask = np.ones((b, ts), np.float32)
    return src, mask


class TestBeamVsReference:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_reference_beam(self, seed):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=seed)
        src, mask = random_batch(vocab, b=3, ts=6, seed=seed)
        L = 12
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 4, "normalize": 0.0,
                                      "max-length": L,
                                      "max-length-factor": L / 6}),
                        trg_vocab=None)
        got = bs.search(src, mask)
        ref = reference_beam(model, params, src, mask, k=4, L=L)
        for i in range(3):
            assert got[i][0]["tokens"] == ref[i][0], \
                f"sent {i}: {got[i][0]['tokens']} vs {ref[i][0]}"

    def test_normalized_matches_reference(self):
        vocab = 17
        model, params, opts = tiny_model(vocab, seed=5)
        src, mask = random_batch(vocab, b=2, ts=5, seed=9)
        L = 10
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 4, "normalize": 0.6,
                                      "max-length": L,
                                      "max-length-factor": 2.0}),
                        trg_vocab=None)
        got = bs.search(src, mask)
        ref = reference_beam(model, params, src, mask, k=4, L=L, normalize=0.6)
        for i in range(2):
            assert got[i][0]["tokens"] == ref[i][0]
            assert got[i][0]["norm_score"] == pytest.approx(ref[i][1], rel=1e-3)


class TestBeamProperties:
    def test_beam1_equals_greedy(self):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=3)
        src, mask = random_batch(vocab, b=4, ts=6, seed=3)
        bs = BeamSearch(model, [params], None,
                        opts.with_(**{"beam-size": 1, "normalize": 0.0,
                                      "max-length": 12,
                                      "max-length-factor": 2.0}),
                        trg_vocab=None)
        got = bs.search(src, mask)
        greedy = greedy_decode(model, params, jnp.asarray(src),
                               jnp.asarray(mask), max_len=12)
        for i in range(4):
            g = [int(x) for x in greedy[i]]
            g = g[: g.index(EOS_ID)] if EOS_ID in g else g
            assert got[i][0]["tokens"] == g

    def test_ensemble_of_identical_models_is_identity(self):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=4)
        src, mask = random_batch(vocab, b=2, ts=5, seed=4)
        o = opts.with_(**{"beam-size": 3, "normalize": 0.0, "max-length": 10,
                          "max-length-factor": 2.0})
        single = BeamSearch(model, [params], None, o, None).search(src, mask)
        double = BeamSearch(model, [params, params], None, o, None).search(src, mask)
        for i in range(2):
            assert single[i][0]["tokens"] == double[i][0]["tokens"]

    def test_nbest_sorted_and_distinct(self):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=6)
        src, mask = random_batch(vocab, b=2, ts=5, seed=6)
        o = opts.with_(**{"beam-size": 4, "normalize": 0.6, "n-best": True,
                          "max-length": 10, "max-length-factor": 2.0})
        res = BeamSearch(model, [params], None, o, None).search(src, mask)
        for nbest in res:
            assert len(nbest) == 4
            scores = [h["norm_score"] for h in nbest]
            assert scores == sorted(scores, reverse=True)

    def test_shortlist_restricts_vocab(self):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=7)
        src, mask = random_batch(vocab, b=2, ts=5, seed=7)
        o = opts.with_(**{"beam-size": 2, "normalize": 0.0, "max-length": 8,
                          "max-length-factor": 2.0})

        class FakeShortlist:
            # allowed ids only (padded to 8 with EOS); includes EOS + UNK
            indices = np.array([0, 1, 3, 5, 7, 0, 0, 0], dtype=np.int32)

        res = BeamSearch(model, [params], None, o, None).search(
            src, mask, shortlist=FakeShortlist())
        allowed = {0, 1, 3, 5, 7}
        for nbest in res:
            for h in nbest:
                assert set(h["tokens"]) <= allowed

    def test_unk_suppressed_by_default(self):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=8)
        src, mask = random_batch(vocab, b=4, ts=6, seed=8)
        o = opts.with_(**{"beam-size": 4, "normalize": 0.0, "max-length": 12,
                          "max-length-factor": 2.0, "n-best": True})
        res = BeamSearch(model, [params], None, o, None).search(src, mask)
        for nbest in res:
            for h in nbest:
                assert UNK_ID not in h["tokens"]

    def test_alignment_output_shape(self):
        vocab = 19
        model, params, opts = tiny_model(vocab, seed=9)
        src, mask = random_batch(vocab, b=2, ts=5, seed=9)
        o = opts.with_(**{"beam-size": 2, "normalize": 0.0, "max-length": 8,
                          "max-length-factor": 2.0, "alignment": "soft"})
        res = BeamSearch(model, [params], None, o, None).search(src, mask)
        h = res[0][0]
        assert "alignment" in h
        assert h["alignment"].shape[1] == 5  # src length
        # rows are attention distributions
        np.testing.assert_allclose(h["alignment"].sum(-1),
                                   np.ones(h["alignment"].shape[0]), atol=1e-3)
