"""Smoke tests for the driver-facing bench entry points (bench.py /
bench_decode.py). These are the round's headline deliverable — a
regression here would otherwise surface only when the driver runs the
bench on scarce TPU time."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, env_extra, tmp_path, timeout=420):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "MARIAN_BENCH_PARTIAL": str(tmp_path / "partial.json")})
    env.update(env_extra)
    r = subprocess.run([sys.executable, os.path.join(ROOT, script)],
                      capture_output=True, text=True, env=env,
                      timeout=timeout, cwd=ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def test_train_bench_tiny_contract(tmp_path):
    out = _run("bench.py", {"MARIAN_BENCH_PRESET": "tiny"}, tmp_path)
    # the driver's contract: metric/value/unit/vs_baseline on ONE line
    assert out["metric"] == "train_src_tokens_per_sec_per_chip"
    assert out["value"] > 0 and out["unit"] == "src-tokens/sec/chip"
    assert 0 < out["vs_baseline"] < 10
    # round-3 additions
    assert out["chip"] == "cpu" and out["mfu"] is None
    assert out["flops_per_src_token"] > 0
    # progress checkpoints landed and finished
    partial = json.loads((tmp_path / "partial.json").read_text())
    assert partial["phase"] == "done"
    assert partial["shape_warm_s"]


def test_decode_bench_tiny_contract(tmp_path):
    out = _run("bench_decode.py", {"MARIAN_DECBENCH_PRESET": "tiny"},
               tmp_path)
    assert out["metric"] == "beam6_sentences_per_sec"
    assert out["value"] > 0 and out["unit"] == "sent/sec"