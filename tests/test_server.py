"""marian-server: end-to-end protocol tests against the REAL _serve wiring
(server/server.py — reference src/command/marian_server.cpp; the serving
subsystem behind it is unit-tested in tests/test_serving.py).

Two transports, one ServingApp: the Marian WebSocket protocol (gated on the
``websockets`` package) and the dependency-free length-prefixed TCP framing
the server falls back to without it — so a real-model round trip is
exercised in every environment."""

import asyncio

import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.vocab import DefaultVocab


def _tiny_server_options(tmp_path, seed=2):
    """Build + save a tiny real model; returns server-mode Options."""
    import jax
    from marian_tpu.common import io as mio
    from marian_tpu.models.encoder_decoder import create_model

    words = [f"w{i}" for i in range(20)]
    vocab = DefaultVocab.build([" ".join(words)])
    vpath = tmp_path / "v.yml"
    vocab.save(str(vpath))
    opts = Options({"type": "transformer", "dim-emb": 16,
                    "transformer-heads": 2, "transformer-dim-ffn": 32,
                    "enc-depth": 1, "dec-depth": 1,
                    "tied-embeddings-all": True, "max-length": 16,
                    "precision": ["float32", "float32"], "seed": seed})
    model = create_model(opts, len(vocab), len(vocab), inference=True)
    params = model.init(jax.random.key(seed))
    mpath = tmp_path / "m.npz"
    mio.save_model(str(mpath), {k: np.asarray(v) for k, v in params.items()},
                   opts.as_yaml())
    return Options({"models": [str(mpath)], "vocabs": [str(vpath),
                                                       str(vpath)],
                    "beam-size": 2, "max-length": 16, "port": 0,
                    "mini-batch": 8, "max-queue": 64,
                    "batch-token-budget": 128})


async def _drive_serve(sopts, client_fn):
    """Start the REAL _serve (scheduler, admission, transport) on an
    ephemeral port, run client_fn(port), tear down."""
    from marian_tpu.server import server as srv
    loop = asyncio.get_event_loop()
    ready = loop.create_future()
    server_task = asyncio.ensure_future(srv._serve(sopts, ready=ready))
    port = await asyncio.wait_for(ready, 60)
    try:
        return await client_fn(port)
    finally:
        server_task.cancel()
        try:
            await server_task
        except (asyncio.CancelledError, Exception):
            pass


async def _tcp_request(port: int, text: str) -> str:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    payload = text.encode("utf-8")
    writer.write(b"MTPU %d\n" % len(payload) + payload)
    await writer.drain()
    header = await reader.readline()
    assert header.startswith(b"MTPU ")
    reply = await reader.readexactly(int(header.split()[1]))
    writer.close()
    return reply.decode("utf-8")


def test_server_e2e_websocket(tmp_path):
    """Real model, real websocket round trip, two concurrent clients."""
    websockets = pytest.importorskip("websockets")
    from marian_tpu.server import server as srv
    if not srv.HAVE_WS:  # pragma: no cover — importorskip above covers it
        pytest.skip("server module loaded without websockets")

    sopts = _tiny_server_options(tmp_path)

    async def clients(port):
        async def client(text):
            async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
                await ws.send(text)
                return await ws.recv()

        return await asyncio.gather(client("w3 w4 w5"),
                                    client("w6 w7\nw8 w9"))

    r1, r2 = asyncio.run(_drive_serve(sopts, clients))
    assert isinstance(r1, str)
    assert r2.count("\n") == 1          # two sentences → two reply lines


def test_server_e2e_tcp_fallback(tmp_path, monkeypatch):
    """Real model over the dependency-free TCP framing — the transport
    _serve falls back to without websockets (forced here so the test is
    deterministic in every environment)."""
    from marian_tpu.server import server as srv
    monkeypatch.setattr(srv, "HAVE_WS", False)

    sopts = _tiny_server_options(tmp_path)

    async def clients(port):
        return await asyncio.gather(
            _tcp_request(port, "w3 w4 w5"),
            _tcp_request(port, "w6 w7\nw8 w9"))

    r1, r2 = asyncio.run(_drive_serve(sopts, clients))
    assert isinstance(r1, str)
    assert r2.count("\n") == 1


def test_server_e2e_iteration_beam_with_prefix_cache(tmp_path, monkeypatch):
    """ISSUE 12 acceptance leg: the server no longer refuses beam>1 in
    iteration mode — COW-paged beam serving works end-to-end on the
    real CPU server (TCP framing), with --prefix-cache turning an
    exact repeat into a hit whose reply is identical to the cold one
    (deterministic decode)."""
    from marian_tpu.server import server as srv
    monkeypatch.setattr(srv, "HAVE_WS", False)

    # seed 3 decodes short nonempty outputs WITH a mid-decode EOS (one
    # hypothesis freezes while its sibling continues — the COW path's
    # page-free-at-freeze leg runs on the real server)
    base = _tiny_server_options(tmp_path, seed=3)
    dense = srv.TranslationService(base).translate_lines(["w3 w4 w5"])
    sopts = base.with_(**{
        "batching-mode": "iteration", "beam-size": 2,
        "iteration-rows": 8, "kv-page-len": 4,
        "prefix-cache": True})

    async def clients(port):
        cold = await _tcp_request(port, "w3 w4 w5")
        warm = await _tcp_request(port, "w3 w4 w5")   # exact repeat
        multi = await _tcp_request(port, "w6 w7\nw8 w9")
        return cold, warm, multi

    cold, warm, multi = asyncio.run(_drive_serve(sopts, clients))
    assert cold and not cold.startswith("!!SERVER-")
    assert cold == dense[0]              # paged beam == dense beam
    assert warm == cold                  # prefix replay == cold decode
    assert multi.count("\n") == 1
