"""marian-server: WebSocket protocol + dynamic request batching
(server/server.py — reference src/command/marian_server.cpp; the
batching across concurrent requests is beyond-reference)."""

import asyncio

import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.vocab import DefaultVocab

websockets = pytest.importorskip("websockets")


class TestBatchingWorker:
    def _run(self, coro):
        return asyncio.run(coro)

    def test_coalesces_concurrent_requests_one_device_batch(self):
        from marian_tpu.server.server import _batching_worker

        calls = []

        def fake_translate(lines):
            calls.append(list(lines))
            return [f"T({l})" for l in lines]

        async def scenario():
            q = asyncio.Queue()
            worker = asyncio.ensure_future(_batching_worker(q, fake_translate))
            loop = asyncio.get_event_loop()
            futs = []
            # three requests land inside one batching window
            for text in ("a\nb", "c", "d\ne\nf"):
                f = loop.create_future()
                await q.put((text, f))
                futs.append(f)
            replies = await asyncio.gather(*futs)
            worker.cancel()
            return replies

        replies = self._run(scenario())
        assert replies == ["T(a)\nT(b)", "T(c)", "T(d)\nT(e)\nT(f)"]
        # one translate call served all three requests
        assert calls == [["a", "b", "c", "d", "e", "f"]]

    def test_error_propagates_without_killing_worker(self):
        from marian_tpu.server.server import _batching_worker

        state = {"fail": True}

        def flaky(lines):
            if state["fail"]:
                state["fail"] = False
                raise ValueError("boom")
            return [l.upper() for l in lines]

        async def scenario():
            q = asyncio.Queue()
            worker = asyncio.ensure_future(_batching_worker(q, flaky))
            loop = asyncio.get_event_loop()
            f1 = loop.create_future()
            await q.put(("x", f1))
            with pytest.raises(RuntimeError, match="boom"):
                await f1
            # the worker survives and serves the next request
            f2 = loop.create_future()
            await q.put(("ok", f2))
            out = await f2
            worker.cancel()
            return out

        assert self._run(scenario()) == "OK"


def test_server_e2e_websocket(tmp_path):
    """Real model, real websocket round trip, two concurrent clients."""
    import jax
    from marian_tpu.common import io as mio
    from marian_tpu.models.encoder_decoder import create_model
    from marian_tpu.server import server as srv

    words = [f"w{i}" for i in range(20)]
    vocab = DefaultVocab.build([" ".join(words)])
    vpath = tmp_path / "v.yml"
    vocab.save(str(vpath))
    opts = Options({"type": "transformer", "dim-emb": 16,
                    "transformer-heads": 2, "transformer-dim-ffn": 32,
                    "enc-depth": 1, "dec-depth": 1,
                    "tied-embeddings-all": True, "max-length": 16,
                    "precision": ["float32", "float32"], "seed": 2})
    model = create_model(opts, len(vocab), len(vocab), inference=True)
    params = model.init(jax.random.key(2))
    mpath = tmp_path / "m.npz"
    mio.save_model(str(mpath), {k: np.asarray(v) for k, v in params.items()},
                   opts.as_yaml())

    sopts = Options({"models": [str(mpath)], "vocabs": [str(vpath),
                                                        str(vpath)],
                     "beam-size": 2, "max-length": 16, "port": 0,
                     "mini-batch": 8})

    async def scenario():
        # drive the REAL _serve wiring (worker startup, handler, queue)
        # on an ephemeral port announced via the ready future
        loop = asyncio.get_event_loop()
        ready = loop.create_future()
        server_task = asyncio.ensure_future(srv._serve(sopts, ready=ready))
        port = await asyncio.wait_for(ready, 60)

        async def client(text):
            async with websockets.connect(f"ws://127.0.0.1:{port}") as ws:
                await ws.send(text)
                return await ws.recv()

        try:
            r1, r2 = await asyncio.gather(client("w3 w4 w5"),
                                          client("w6 w7\nw8 w9"))
        finally:
            server_task.cancel()
            try:
                await server_task
            except (asyncio.CancelledError, Exception):
                pass
        return r1, r2

    r1, r2 = asyncio.run(scenario())
    assert isinstance(r1, str)
    assert r2.count("\n") == 1          # two sentences → two reply lines