"""Round-3 flag-parity closures (the ~16 reference flags the parser
lacked): --tsv/--tsv-fields, --word-scores, --output-omit-bias,
--transformer-aan-{depth,activation,nogate}. Trainer flags are covered
in test_trainer_robustness.py; warn/refuse classes in the flag audit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.data.corpus import Corpus
from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.models.encoder_decoder import create_model


class TestTsvCorpus:
    def _tsv(self, tmp_path, rows):
        p = tmp_path / "train.tsv"
        p.write_text("\n".join("\t".join(r) for r in rows) + "\n")
        return str(p)

    def test_columns_become_streams(self, tmp_path):
        path = self._tsv(tmp_path, [["a b", "c d"], ["e", "f g h"]])
        v = DefaultVocab.build(["a b c d e f g h"])
        corpus = Corpus([path], [v, v],
                        Options({"tsv": True, "max-length": 10,
                                 "shuffle": "none"}))
        tuples = list(corpus)
        assert len(tuples) == 2
        # stream 0 = column 0, stream 1 = column 1
        assert v.decode(tuples[0].streams[0]) == "a b"
        assert v.decode(tuples[0].streams[1]) == "c d"
        assert v.decode(tuples[1].streams[1]) == "f g h"

    def test_field_count_mismatch_is_loud(self, tmp_path):
        path = self._tsv(tmp_path, [["a", "b"], ["only-one-column"]])
        v = DefaultVocab.build(["a b"])
        corpus = Corpus([path], [v, v],
                        Options({"tsv": True, "shuffle": "none"}))
        with pytest.raises(ValueError, match="line 2"):
            list(corpus)

    def test_tsv_fields_must_match_vocabs(self, tmp_path):
        path = self._tsv(tmp_path, [["a", "b"]])
        v = DefaultVocab.build(["a b"])
        with pytest.raises(ValueError, match="tsv-fields"):
            Corpus([path], [v, v], Options({"tsv": True, "tsv-fields": 3}))

    def test_tsv_needs_one_file(self, tmp_path):
        v = DefaultVocab.build(["a"])
        with pytest.raises(ValueError, match="ONE"):
            Corpus(["a.tsv", "b.tsv"], [v, v], Options({"tsv": True}))


class TestInputReorder:
    def test_permutes_tsv_columns(self, tmp_path):
        p = tmp_path / "t.tsv"
        p.write_text("src line\ttrg line\n")
        v = DefaultVocab.build(["src trg line"])
        corpus = Corpus([str(p)], [v, v],
                        Options({"tsv": True, "input-reorder": [1, 0],
                                 "shuffle": "none"}))
        t = list(corpus)[0]
        assert v.decode(t.streams[0]) == "trg line"
        assert v.decode(t.streams[1]) == "src line"

    def test_rejects_non_permutation(self, tmp_path):
        p = tmp_path / "t.tsv"
        p.write_text("a\tb\n")
        v = DefaultVocab.build(["a b"])
        with pytest.raises(ValueError, match="permutation"):
            list(Corpus([str(p)], [v, v],
                        Options({"tsv": True, "input-reorder": [0, 2]})))


class TestFp16AndDivergence:
    def test_fp16_maps_to_bf16(self, tmp_path):
        from marian_tpu.common.config_parser import parse_options
        opts = parse_options(
            ["--type", "transformer", "--fp16",
             "--train-sets", "a.src", "a.trg",
             "--vocabs", "v.src", "v.trg", "--model", "m.npz"],
            mode="training", validate=False)
        assert list(opts.get("precision"))[0] == "bfloat16"
        # explicit --precision wins over the shortcut
        opts2 = parse_options(
            ["--type", "transformer", "--fp16",
             "--precision", "float32", "float32",
             "--train-sets", "a.src", "a.trg",
             "--vocabs", "v.src", "v.trg", "--model", "m.npz"],
            mode="training", validate=False)
        assert list(opts2.get("precision"))[0] == "float32"

    def test_throw_on_divergence(self):
        from marian_tpu.training.scheduler import (DivergenceError,
                                                   Scheduler)
        from marian_tpu.training.training_state import TrainingState
        sch = Scheduler(Options({"disp-freq": 1,
                                 "throw-on-divergence": True}),
                        TrainingState())
        with pytest.raises(DivergenceError, match="non-finite"):
            sch.update(float("nan"), 10, 2)
        # without the flag: logged, not raised
        sch2 = Scheduler(Options({"disp-freq": 1}), TrainingState())
        sch2.update(float("nan"), 10, 2)


def _model_and_batch(rng, **over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16}
    base.update(over)
    model = create_model(Options(base), 64, 64)
    params = model.init(jax.random.key(9))
    batch = {
        "src_ids": jnp.asarray(rng.randint(2, 64, (2, 5)), jnp.int32),
        "src_mask": jnp.ones((2, 5), jnp.float32),
        "trg_ids": jnp.asarray(rng.randint(2, 64, (2, 6)), jnp.int32),
        "trg_mask": jnp.ones((2, 6), jnp.float32),
    }
    return model, params, batch


@pytest.fixture
def rng():
    return np.random.RandomState(9)


class TestOutputOmitBias:
    def test_no_bias_param_and_trains(self, rng):
        model, params, batch = _model_and_batch(
            rng, **{"output-omit-bias": True})
        assert "decoder_ff_logit_out_b" not in params
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, batch, None, train=False)[0])(params)
        assert np.isfinite(float(loss))

    def test_default_keeps_bias(self, rng):
        _, params, _ = _model_and_batch(rng)
        assert "decoder_ff_logit_out_b" in params


class TestAanVariants:
    AAN = {"transformer-decoder-autoreg": "average-attention",
           "transformer-dim-aan": 32}

    def test_depth_3_params_and_loss(self, rng):
        model, params, batch = _model_and_batch(
            rng, **{**self.AAN, "transformer-aan-depth": 3})
        assert "decoder_l1_aan_W3" in params
        assert params["decoder_l1_aan_W2"].shape == (32, 32)
        loss, _ = model.loss(params, batch, None, train=False)
        assert np.isfinite(float(loss))

    def test_nogate_drops_gate_params(self, rng):
        model, params, batch = _model_and_batch(
            rng, **{**self.AAN, "transformer-aan-nogate": True})
        assert "decoder_l1_aan_Wi" not in params
        assert "decoder_l1_aan_Wg" not in params
        loss, _ = model.loss(params, batch, None, train=False)
        assert np.isfinite(float(loss))

    def test_activation_changes_numbers(self, rng):
        losses = {}
        for act in ("relu", "swish"):
            model, params, batch = _model_and_batch(
                rng, **{**self.AAN, "transformer-aan-depth": 3,
                        "transformer-aan-activation": act})
            losses[act] = float(model.loss(params, batch, None,
                                           train=False)[0])
        assert losses["relu"] != losses["swish"]


class TestWordScores:
    def test_word_scores_sum_to_raw_score(self, rng):
        """Internal consistency: per-word logPs must sum to the beam's
        cumulative raw score, and the n-best line carries WordScores."""
        from marian_tpu.translator.beam_search import BeamSearch
        model, params, batch = _model_and_batch(rng)
        vocab = DefaultVocab.build(
            [" ".join(f"w{i}" for i in range(62))])
        bs = BeamSearch(model, [params], None,
                        Options({"beam-size": 3, "normalize": 0.6,
                                 "max-length": 16, "n-best": True,
                                 "word-scores": True}), vocab)
        nbests = bs.search(batch["src_ids"], batch["src_mask"])
        for nbest in nbests:
            for h in nbest:
                assert "word_scores" in h
                # word scores cover the emitted tokens, + EOS when the
                # hypothesis terminated (a random model may hit the cap)
                assert len(h["word_scores"]) in (len(h["tokens"]),
                                                 len(h["tokens"]) + 1)
                assert sum(h["word_scores"]) == pytest.approx(
                    h["score"], abs=1e-3)

        from marian_tpu.translator.output_collector import OutputPrinter
        printer = OutputPrinter(Options({"n-best": True,
                                         "word-scores": True}), vocab)
        line = printer.line(0, nbests[0])
        assert "WordScores= " in line

        # --word-scores + --alignment together: segment order is
        # id ||| translation ||| alignment ||| WordScores ||| Score |||
        # norm, matching Marian's OutputPrinter (ADVICE r3 — index-based
        # n-best consumers rely on alignment preceding WordScores)
        h = dict(nbests[0][0])
        h["alignment"] = np.full((len(h["tokens"]) + 1, 4), 0.25)
        both = OutputPrinter(Options({"n-best": True, "word-scores": True,
                                      "alignment": "hard"}), vocab)
        segs = both.line(0, [h]).split(" ||| ")
        assert segs[0] == "0"
        assert segs[3].startswith("WordScores= ")
        assert segs[4].startswith("Score= ")
        # segs[2] is the alignment (src-trg pairs), between them
        assert all("-" in p for p in segs[2].split())
        # single-best: translation ||| alignment ||| WordScores
        single = OutputPrinter(Options({"word-scores": True,
                                        "alignment": "hard"}), vocab)
        s = single.line(0, [h]).split(" ||| ")
        assert s[2].startswith("WordScores= ") and "-" in s[1]