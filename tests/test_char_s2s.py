"""char-s2s: convolutional character encoder (reference: src/models/
char_s2s.h :: CharS2SEncoder + the cuDNN conv/pool wrappers → lax.conv /
masked max-pool; Lee et al. 2017)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.models import s2s as S
from marian_tpu.models.encoder_decoder import create_model


@pytest.fixture
def rng():
    return np.random.RandomState(23)


def char_model(vocab=30, **over):
    opts = Options({
        "type": "char-s2s", "dim-emb": 16, "dim-rnn": 24,
        "enc-depth": 1, "dec-depth": 1, "enc-cell": "gru",
        "dec-cell": "gru", "char-stride": 3, "char-highway": 2,
        "precision": ["float32", "float32"], "max-length": 64, **over,
    })
    model = create_model(opts, vocab, vocab)
    # shrink the Lee-et-al filter bank for CPU-tiny tests
    import dataclasses
    model.cfg = dataclasses.replace(model.cfg,
                                    conv_widths=(1, 3, 5),
                                    conv_filters=(8, 8, 8))
    return model, model.init(jax.random.key(0))


def char_batch(rng, b=2, ts=13, tt=6, vocab=30):
    return {
        "src_ids": jnp.asarray(rng.randint(2, vocab, (b, ts)), jnp.int32),
        "src_mask": jnp.ones((b, ts), jnp.float32),
        "trg_ids": jnp.asarray(rng.randint(2, vocab, (b, tt)), jnp.int32),
        "trg_mask": jnp.ones((b, tt), jnp.float32),
    }


class TestCharEncoder:
    def test_pooled_length_and_mask(self, rng):
        model, params = char_model()
        batch = char_batch(rng, ts=13)          # ceil(13/3) = 5 windows
        enc = model.encode_for_decode(params, batch["src_ids"],
                                      batch["src_mask"])
        assert enc.shape[1] == 5
        pm = S.enc_mask(model.cfg, batch["src_mask"])
        assert pm.shape == (2, 5)
        np.testing.assert_array_equal(np.asarray(pm), 1.0)

    def test_ragged_mask_pools(self, rng):
        model, params = char_model()
        mask = np.ones((2, 13), np.float32)
        mask[0, 4:] = 0.0                       # 4 real chars → 2 windows
        pm = np.asarray(S.enc_mask(model.cfg, jnp.asarray(mask)))
        np.testing.assert_array_equal(pm[0], [1, 1, 0, 0, 0])
        np.testing.assert_array_equal(pm[1], 1.0)

    def test_loss_and_grads_finite(self, rng):
        model, params = char_model()
        batch = char_batch(rng)

        def loss_fn(p):
            total, aux = model.loss(p, batch, key=None, train=False)
            return total / jnp.maximum(aux["labels"], 1.0)

        l, g = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(l))
        assert any("char_conv" in k for k in g)
        assert all(np.isfinite(np.asarray(v)).all() for v in g.values())

    def test_step_matches_teacher_forcing(self, rng):
        model, params = char_model()
        batch = char_batch(rng)
        enc = model.encode_for_decode(params, batch["src_ids"],
                                      batch["src_mask"])
        full = S.decode_train(model.cfg, params, enc, batch["src_mask"],
                              batch["trg_ids"], batch["trg_mask"],
                              train=False)
        state = model.start_state(params, enc, batch["src_mask"], max_len=8)
        prev = jnp.zeros((2, 1), jnp.int32)
        for t in range(batch["trg_ids"].shape[1]):
            logits, state = model.step(params, state, prev,
                                       batch["src_mask"])
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, t, :]),
                                       rtol=2e-4, atol=2e-4)
            prev = batch["trg_ids"][:, t:t + 1]

    def test_beam_decode_runs(self, rng):
        from marian_tpu.translator.beam_search import BeamSearch
        model, params = char_model()
        batch = char_batch(rng)
        out = BeamSearch(model, [params], None,
                         Options({"beam-size": 3, "max-length": 10}),
                         None).search(batch["src_ids"], batch["src_mask"])
        assert len(out) == 2
