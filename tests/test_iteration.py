"""Iteration-level continuous batching (ISSUE 10): the paged slot
engine (translator/iteration.py), the paged greedy restructuring
(translator/greedy.py), and the serving scheduler's
--batching-mode iteration worker — mid-decode joins, page-priced
admission, pool-exhaustion behavior (defer or shed, never a deadlocked
step), join-time queue accounting, and deterministic replay. Runs
under JAX_PLATFORMS=cpu with a tiny real transformer."""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from marian_tpu.data.vocab import DefaultVocab
from marian_tpu.serving import metrics as msm
from marian_tpu.serving.admission import AdmissionController, Overloaded
from marian_tpu.serving.scheduler import ContinuousScheduler
from marian_tpu.translator.greedy import greedy_decode, greedy_decode_paged
from marian_tpu.translator.iteration import (FATAL_REASONS,
                                             PagedDecodeEngine)

from tests.test_beam_search import tiny_model


@pytest.fixture(scope="module", autouse=True)
def _lockdep_witness(lockdep_witness):
    """KVPool._lock / PagedDecodeEngine._lock cross the device-worker
    and metrics-scrape threads here; the shared witness asserts the
    observed acquisition orders stay inside the static lattice."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _ownership_witness(ownership_witness):
    """Every page this suite's engines claim/release/adopt records its
    acting call site; the shared witness asserts observed ownership
    pairings stay inside the static ownership graph (ISSUE 15)."""
    yield


@pytest.fixture(scope="module", autouse=True)
def _jitwit_witness(jitwit_witness):
    """Every backend compile this suite's engines trigger is attributed
    to its jit site; the shared witness asserts compiles stay inside the
    static jit model and no instrumented key retraced (ISSUE 17)."""
    yield


VOCAB_WORDS = [" ".join(f"w{i}" for i in range(35))]


@pytest.fixture(scope="module")
def tiny():
    vocab = DefaultVocab.build(VOCAB_WORDS)
    model, params, _ = tiny_model(vocab=len(vocab), seed=7,
                                  **{"dec-depth": 2, "enc-depth": 2})
    return model, params, vocab


def make_engine(tiny, registry=None, **kw):
    model, params, vocab = tiny
    args = dict(max_rows=4, page_len=4, src_len_cap=8,
                max_length_cap=12, registry=registry)
    args.update(kw)
    return PagedDecodeEngine(model, params, vocab, vocab, **args)


TEXTS = ["w3 w4 w5", "w6 w7", "w8 w9 w10 w11", "w2 w3",
         "w4 w4 w4 w4 w4"]


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# paged greedy restructuring: rows as slots
# ---------------------------------------------------------------------------

class TestGreedyPaged:
    def test_matches_dense_greedy(self, rng, tiny):
        model, params, _ = tiny
        b, ts = 5, 7
        ids = np.zeros((b, ts), np.int32)
        mask = np.zeros((b, ts), np.float32)
        for i, n in enumerate(rng.randint(3, ts + 1, size=b)):
            ids[i, :n] = rng.randint(3, 35, n)
            mask[i, :n] = 1.0
        dense = greedy_decode(model, params, jnp.asarray(ids),
                              jnp.asarray(mask), 12)
        paged = greedy_decode_paged(model, params, jnp.asarray(ids),
                                    jnp.asarray(mask), 12, page_len=4)
        n = min(dense.shape[1], paged.shape[1])
        assert (np.asarray(dense)[:, :n] == paged[:, :n]).all()


# ---------------------------------------------------------------------------
# the slot engine
# ---------------------------------------------------------------------------

class TestEngine:
    def test_outputs_independent_of_join_schedule(self, tiny):
        """THE iteration-batching correctness property: a sentence's
        tokens cannot depend on who shares its steps or when it
        joined."""
        batch = make_engine(tiny, max_rows=4).decode_texts(TEXTS)
        solo = [make_engine(tiny, max_rows=1).decode_texts([t])[0]
                for t in TEXTS]
        assert batch == solo

    def test_mid_decode_join_and_early_leave(self, tiny):
        eng = make_engine(tiny, max_rows=3)
        r0 = eng.admit_and_step([(0, TEXTS[0]), (2, TEXTS[2])])
        assert sorted(r0.accepted) == [0, 2]
        assert r0.mid_decode_joins == 0         # nothing was running yet
        for _ in range(3):
            eng.admit_and_step([])
        r = eng.admit_and_step([(1, TEXTS[1])])
        assert r.accepted == [1]
        assert r.mid_decode_joins == 1          # joined a RUNNING decode
        outs = dict(r0.finished + r.finished)
        guard = 0
        free_seen = []
        while not eng.idle():
            free_seen.append(eng.free_pages())
            rr = eng.admit_and_step([])
            outs.update(dict(rr.finished))
            guard += 1
            assert guard < 100
        # early leave: pages were released as sentences finished, not
        # all at once at the end
        assert eng.free_pages() == eng.pool.usable_pages
        assert len(set(free_seen)) > 1
        solo = [make_engine(tiny, max_rows=1).decode_texts([t])[0]
                for t in TEXTS[:3]]
        assert [outs[i] for i in (0, 1, 2)] == solo

    def test_multi_step_rounds_same_outputs(self, tiny):
        """steps_per_round > 1 (one jitted scan per round) must yield
        EXACTLY the per-step engine's outputs — the greedy chain is the
        same; only the admission granularity changes. A row finishing
        mid-scan self-feeds until the host cuts at its EOS; the
        overshoot must never leak into any sentence's text."""
        one = make_engine(tiny, max_rows=4).decode_texts(TEXTS)
        four = make_engine(tiny, max_rows=4,
                           steps_per_round=4).decode_texts(TEXTS)
        assert one == four

    def test_deterministic_replay(self, tiny):
        """An identical join/evict schedule replayed on a fresh engine
        yields identical outputs (the acceptance criterion's replay
        pin: trash-page writes and page reuse are deterministic)."""
        def one_run():
            eng = make_engine(tiny, max_rows=2)
            outs = {}
            sched = [[(0, TEXTS[0]), (1, TEXTS[1])], [], [(2, TEXTS[2])],
                     [], [(3, TEXTS[3])], [(4, TEXTS[4])]]
            pending = []
            i = 0
            guard = 0
            while i < len(sched) or pending or not eng.idle():
                joins = (sched[i] if i < len(sched) else []) + pending
                pending = []
                res = eng.admit_and_step(joins)
                for key, why in res.rejected:
                    assert why not in FATAL_REASONS
                    pending.append((key, dict(enumerate(TEXTS))[key]))
                outs.update(dict(res.finished))
                i += 1
                guard += 1
                assert guard < 200
            return [outs[k] for k in sorted(outs)]
        assert one_run() == one_run()

    def test_eviction_mid_decode_frees_pages(self, tiny):
        eng = make_engine(tiny, max_rows=2)
        eng.admit_and_step([(0, TEXTS[0]), (1, TEXTS[2])])
        used_before = eng.pool.used_pages()
        assert used_before > 0
        res = eng.admit_and_step([], evicts=[0])
        assert eng.pool.used_pages() < used_before
        assert eng.active_rows() == 1
        # the evicted key never appears in finished afterwards
        guard = 0
        while not eng.idle():
            res = eng.admit_and_step([])
            assert all(k != 0 for k, _ in res.finished)
            guard += 1
            assert guard < 100

    def test_pool_exhaustion_defers_join_never_stalls_step(self, tiny):
        """A pool too small for two sentences: the second DEFERS
        (reason no_pages) while the first keeps decoding — the step
        loop never deadlocks — and joins once pages free up."""
        # one sentence needs ceil(12/4)=3 pages; pool holds exactly 3
        eng = make_engine(tiny, max_rows=2,
                          pool_bytes=3 * 2 * 2 * 2 * 4 * 8 * 4)
        assert eng.pool.usable_pages == 3
        r = eng.admit_and_step([(0, TEXTS[0]), (1, TEXTS[1])])
        assert r.accepted == [0]
        assert r.rejected == [(1, "no_pages")]
        guard = 0
        joined_late = False
        outs = {}
        while not eng.idle() or not joined_late:
            res = eng.admit_and_step(
                [] if joined_late else [(1, TEXTS[1])])
            if 1 in res.accepted:
                joined_late = True
            for key, why in res.rejected:
                assert why == "no_pages"
            outs.update(dict(res.finished))
            guard += 1
            assert guard < 200
        while not eng.idle():
            outs.update(dict(eng.admit_and_step([]).finished))
        assert set(outs) == {0, 1}

    def test_oversized_sentence_is_a_fatal_reject(self, tiny):
        """A sentence that could NEVER fit (needs more pages than the
        whole pool) must be rejected permanently — deferring it would
        deadlock the queue head forever."""
        eng = make_engine(tiny, max_rows=2,
                          pool_bytes=1 * 2 * 2 * 2 * 4 * 8 * 4)
        assert eng.pool.usable_pages == 1
        r = eng.admit_and_step([(0, TEXTS[0])])   # cap 12 -> 3 pages
        assert r.rejected and r.rejected[0][1] in FATAL_REASONS

    def test_src_too_long_is_fatal(self, tiny):
        eng = make_engine(tiny)
        long_text = " ".join("w3" for _ in range(50))
        r = eng.admit_and_step([(0, long_text)])
        assert r.rejected == [(0, "src_too_long")]

    def test_fragmentation_and_gauges(self, tiny):
        reg = msm.Registry()
        eng = make_engine(tiny, registry=reg)
        eng.admit_and_step([(0, TEXTS[0])])
        text = reg.render()
        assert "marian_serving_kv_pool_pages" in text
        assert "marian_serving_kv_pool_pages_free" in text
        assert "marian_serving_kv_pool_fragmentation_ratio" in text
        assert "marian_serving_active_rows 1" in text
        # one token written into 3 claimed pages of 4 slots each
        assert 0.0 < eng.fragmentation() < 1.0
        guard = 0
        while not eng.idle():
            eng.admit_and_step([])
            guard += 1
            assert guard < 100
        assert eng.fragmentation() == 0.0


# ---------------------------------------------------------------------------
# scheduler: --batching-mode iteration
# ---------------------------------------------------------------------------

def make_sched(tiny, registry=None, engine=None, **kw):
    reg = registry if registry is not None else msm.Registry()
    eng = engine if engine is not None else make_engine(tiny,
                                                        registry=reg)
    sched = ContinuousScheduler(None, registry=reg,
                                batching_mode="iteration", engine=eng,
                                window_s=0.0, **kw)
    return sched, eng, reg


class TestIterationScheduler:
    def test_requires_engine(self):
        with pytest.raises(ValueError):
            ContinuousScheduler(lambda ls: ls,
                                registry=msm.Registry(),
                                batching_mode="iteration")
        with pytest.raises(ValueError):
            ContinuousScheduler(lambda ls: ls,
                                registry=msm.Registry(),
                                batching_mode="bogus")

    def test_end_to_end_resolves_and_counts_joins(self, tiny):
        sched, eng, reg = make_sched(tiny)

        async def main():
            sched.start()
            f1 = sched.submit(TEXTS[:2])
            await asyncio.sleep(0.05)
            f2 = sched.submit([TEXTS[2]])     # lands mid-decode
            r1, r2 = await f1, await f2
            await sched.stop()
            return r1, r2

        r1, r2 = run(main())
        solo = [make_engine(tiny, max_rows=1).decode_texts([t])[0]
                for t in TEXTS[:3]]
        assert r1 == solo[:2] and r2 == [solo[2]]
        assert sched.m_joins.value == 3
        assert sched.m_mid_joins.value >= 1
        assert sched.m_steps.value > 0
        text = reg.render()
        assert "marian_serving_joins_total 3" in text
        assert "marian_serving_mid_decode_joins_total" in text
        assert "marian_serving_decode_steps_total" in text
        assert "marian_serving_step_active_rows" in text
        assert "marian_serving_queue_depth_pages 0" in text
        assert "marian_serving_evictions_total 0" in text

    def test_queue_ms_stops_at_join_time(self, tiny):
        """ISSUE 10 small fix: a sentence that QUEUED behind a full
        pool must report that wait as queue_ms and only its own decode
        as service_ms — it must not inherit the running decode's
        dispatch-time accounting. (#trace breakdown regression)"""
        # pool fits ONE sentence: the second must queue until the
        # first finishes
        eng = make_engine(tiny, max_rows=2,
                          pool_bytes=3 * 2 * 2 * 2 * 4 * 8 * 4)
        sched, eng, reg = make_sched(tiny, engine=eng)
        meta_a, meta_b = {}, {}

        async def main():
            sched.start()
            fa = sched.submit([TEXTS[0]], meta=meta_a, trace_id="ta")
            await asyncio.sleep(0.02)
            fb = sched.submit([TEXTS[3]], meta=meta_b, trace_id="tb")
            await fa
            await fb
            await sched.stop()

        run(main())
        assert meta_a["outcome"] == "ok" and meta_b["outcome"] == "ok"
        # b queued behind a's pool claim: it must have WAITED in queue
        # and then decoded quickly — the wait lands in queue_s, not in
        # service_s (inheriting a's dispatch time would zero it)
        assert meta_b["queue_s"] > 0.0
        assert meta_b["service_s"] > 0.0
        # a joined immediately; essentially no queueing
        assert meta_a["queue_s"] <= meta_b["queue_s"]
        # b's queue wait covers most of a's decode: service began only
        # at b's OWN join
        assert meta_b["queue_s"] >= 0.5 * meta_a["service_s"]

    def test_cancellation_mid_decode_evicts(self, tiny):
        sched, eng, reg = make_sched(tiny)

        async def main():
            sched.start()
            f1 = sched.submit([TEXTS[4]])
            await asyncio.sleep(0.05)         # decoding now
            f1.cancel()
            f2 = sched.submit([TEXTS[1]])     # keeps the loop turning
            await f2
            for _ in range(50):
                if sched.m_evictions.value:
                    break
                await asyncio.sleep(0.01)
            await sched.stop()

        run(main())
        assert sched.m_evictions.value >= 1
        assert eng.idle()
        assert eng.free_pages() == eng.pool.usable_pages

    def test_oversized_request_fails_explicitly(self, tiny):
        """Pool exhaustion of the permanent kind sheds EXPLICITLY: a
        sentence larger than the whole pool resolves with an error —
        never a hung future, never a stalled step loop."""
        eng = make_engine(tiny, max_rows=2,
                          pool_bytes=1 * 2 * 2 * 2 * 4 * 8 * 4)
        sched, eng, reg = make_sched(tiny, engine=eng)

        async def main():
            sched.start()
            f = sched.submit([TEXTS[0]])
            with pytest.raises(RuntimeError, match="cannot be admitted"):
                await asyncio.wait_for(f, timeout=10)
            await sched.stop()

        run(main())

    def test_admission_prices_pages(self, tiny):
        """Page-debt admission: queued page estimates gate new requests
        (the iteration-mode analog of the sentence bound)."""
        sched, eng, reg = make_sched(tiny)
        adm = AdmissionController(0, sched.queued_units, registry=reg,
                                  max_queue_pages=5,
                                  pages_fn=sched.queued_pages)
        # nothing queued: a 2-page request passes
        adm.admit(1, n_pages=2)
        with pytest.raises(Overloaded, match="page debt"):
            adm.admit(1, n_pages=6)
        assert "pages_full" in reg.render()

    def test_queued_pages_counts_backlog(self, tiny):
        """With the worker NOT running, queued sentences owe pages."""
        sched, eng, reg = make_sched(tiny)

        async def main():
            fut = sched.submit(TEXTS[:3])     # worker never started
            pages = sched.queued_pages()
            assert pages == sum(eng.pages_for_text(t)
                                for t in TEXTS[:3])
            fut.cancel()
            # cancellation discounts the dead units immediately
            await asyncio.sleep(0)
            assert sched.queued_pages() == 0

        run(main())


# ---------------------------------------------------------------------------
# compile-cache hygiene (ISSUE 17): the closed shape set + round-key
# warmup telemetry
# ---------------------------------------------------------------------------

class TestClosedShapeSet:
    def test_grid_warmed_engine_pays_zero_postwarm_compiles(self, tiny):
        """THE closed-shape-set regression: warm a real engine across
        its full bucket grid (warm_grid), then drive mixed-length
        mixed-batch traffic through it — the jit retrace witness must
        observe ZERO backend compiles in the window. This is the
        executable form of 'compile once, serve forever': every shape
        steady-state traffic can reach was already compiled off the
        serving path."""
        from marian_tpu.common import jitwit
        eng = make_engine(tiny)
        driven = eng.warm_grid()
        assert driven, "warm_grid drove nothing"
        with jitwit.strict() as w:
            out = eng.decode_texts(TEXTS)          # mixed lengths, 5 rows
            out2 = eng.decode_texts(TEXTS[1:3])    # different mix
        assert len(out) == len(TEXTS) and len(out2) == 2
        assert w.compiles == [], (
            "post-warm traffic recompiled — the warm grid does not "
            f"close the engine's shape set: {w.compiles}")

    def test_unwarmed_engine_does_compile_in_window(self, tiny):
        """Sanity for the regression above: the SAME traffic on a cold
        engine does compile — proving the strict window actually
        observes this engine's compiles (no vacuous pass)."""
        from marian_tpu.common import jitwit
        eng = make_engine(tiny)
        with jitwit.strict() as w:
            eng.decode_texts(TEXTS[:2])
        assert any("translator/iteration.py" in site
                   for site, _ in w.compiles)


class TestRoundKeyWarmup:
    def test_round_key_vocabulary(self):
        from marian_tpu.obs.perf import round_bucket_key
        assert round_bucket_key(4, 16, 2) == "r4.w16.s2"

    def test_engine_grid_smoke_closes_steady_state_rounds(self, tiny):
        """Satellite 1: lifecycle warmup smokes the engine's bucket
        grid and registers every (row bucket, encode width, steps)
        round key as warm — a steady-state round landing on any grid
        key is NOT a recompile incident, while an off-grid key still
        fires one (same discipline as request-mode width buckets)."""
        from marian_tpu import obs
        from marian_tpu.obs.perf import TRIGGER_SWAP, round_bucket_key
        from marian_tpu.serving.lifecycle.warmup import smoke_engine_grid
        from marian_tpu.translator.iteration import EngineExecutor

        reg = msm.Registry()
        obs.PERF.reset()
        obs.PERF.enable(registry=reg, hook_jax=False)
        eng = make_engine(tiny)
        smoke_engine_grid(EngineExecutor(eng), "vG", TRIGGER_SWAP, "test")
        # every grid pairing is warm: a round on any (rb, enc_w, steps)
        # from the engine's own tables is not an incident
        steps = eng.steps_per_round
        for rb in eng.row_buckets:
            for enc_w in eng.encode_widths():
                obs.PERF.record_batch(
                    "vG", rows=rb, width=rb, src_tokens=4, trg_tokens=4,
                    device_s=0.01,
                    bucket_key=round_bucket_key(rb, enc_w, steps))
        assert obs.PERF.steady_recompiles() == 0
        # an off-grid round key is still a steady-state incident
        obs.PERF.record_batch(
            "vG", rows=1, width=1, src_tokens=4, trg_tokens=4,
            device_s=0.01, bucket_key=round_bucket_key(99, 512, 7))
        assert obs.PERF.steady_recompiles() == 1

    def test_warm_executor_drives_engine_grid(self, tiny):
        """warm_executor on an iteration-mode executor reaches the
        engine grid smoke (the lifecycle wiring, not just the helper)."""
        from marian_tpu import obs
        from marian_tpu.obs.perf import round_bucket_key
        from marian_tpu.serving.lifecycle import warmup
        from marian_tpu.translator.iteration import EngineExecutor

        reg = msm.Registry()
        obs.PERF.reset()
        obs.PERF.enable(registry=reg, hook_jax=False)
        eng = make_engine(tiny)
        ex = warmup.warm_executor(
            "bundle-x", None, lambda d, m: EngineExecutor(eng),
            ["w3 w4"], version="vW")
        assert ex.engine is eng
        # a grid round key was registered warm by the smoke
        obs.PERF.record_batch(
            "vW", rows=1, width=1, src_tokens=2, trg_tokens=2,
            device_s=0.01,
            bucket_key=round_bucket_key(eng.row_buckets[0],
                                        eng.encode_widths()[0],
                                        eng.steps_per_round))
        assert obs.PERF.steady_recompiles() == 0
