"""Fault-injection registry (common/faultpoints.py — ISSUE 4): spec
parsing, deterministic triggering by seed + hit-count, every mode, env
activation across a process boundary. Stdlib-only layer — no jax, no
model; the fault points' *placement* is exercised by the checkpoint /
serving / trainer tests and audited by mtlint's fault-hygiene rule."""

import os
import subprocess
import sys
import time

import pytest

from marian_tpu.common import faultpoints as fp


@pytest.fixture(autouse=True)
def _clean_registry():
    fp.reset_for_tests()
    os.environ.pop(fp.ENV_SPEC, None)
    yield
    fp.reset_for_tests()
    os.environ.pop(fp.ENV_SPEC, None)


class TestSpecParsing:
    def test_modes_and_hits(self):
        specs = fp.parse_spec(
            "ckpt.commit=kill@2, ckpt.write.model=fail,"
            "serving.translate=hang:0.5@*, data.batch.next=prob:0.25@3+")
        assert specs["ckpt.commit"].mode == "kill"
        assert specs["ckpt.commit"].matches(2)
        assert not specs["ckpt.commit"].matches(1)
        assert specs["ckpt.write.model"].matches(1)       # default @1
        assert not specs["ckpt.write.model"].matches(2)
        assert specs["serving.translate"].arg == 0.5
        assert all(specs["serving.translate"].matches(n)
                   for n in (1, 5, 100))                   # @*
        assert specs["data.batch.next"].matches(3)
        assert specs["data.batch.next"].matches(9)         # @3+
        assert not specs["data.batch.next"].matches(2)

    def test_unknown_point_rejected(self):
        with pytest.raises(fp.FaultSpecError, match="unknown fault point"):
            fp.parse_spec("no.such.point=fail")

    def test_unknown_mode_rejected(self):
        with pytest.raises(fp.FaultSpecError, match="unknown mode"):
            fp.parse_spec("ckpt.commit=explode")

    def test_prob_needs_probability(self):
        with pytest.raises(fp.FaultSpecError, match="prob needs"):
            fp.parse_spec("ckpt.commit=prob")

    def test_bare_prob_applies_per_hit(self):
        """prob without a hit selector means per-hit probability (@*) —
        an implicit @1 would roll the dice once and report a clean
        drill."""
        spec = fp.parse_spec("data.batch.next=prob:0.5")["data.batch.next"]
        assert all(spec.matches(n) for n in (1, 2, 50))
        fired = 0
        with fp.active("data.batch.next=prob:0.5", seed=3):
            for _ in range(32):
                try:
                    fp.fault_point("data.batch.next")
                except fp.InjectedFault:
                    fired += 1
        assert fired > 1                      # not a one-shot

    def test_bad_hit_selectors_rejected(self):
        """@x and @0 must be spec errors: a selector that can never
        match would silently disarm the drill."""
        with pytest.raises(fp.FaultSpecError, match="bad hit selector"):
            fp.parse_spec("ckpt.commit=kill@x")
        with pytest.raises(fp.FaultSpecError, match="must be >= 1"):
            fp.parse_spec("ckpt.commit=kill@0")
        with pytest.raises(fp.FaultSpecError, match="must be >= 1"):
            fp.parse_spec("ckpt.commit=fail@0+")

    def test_catalog_described(self):
        rows = dict(fp.describe())
        assert set(rows) == set(fp.CATALOG)
        assert all(desc for desc in rows.values())


class TestTriggering:
    def test_unarmed_is_noop_but_counts(self):
        fp.activate("")                       # armed with nothing
        fp.fault_point("ckpt.commit")
        fp.fault_point("ckpt.commit")
        assert fp.hits("ckpt.commit") == 2

    def test_fail_on_exact_hit(self):
        with fp.active("ckpt.commit=fail@2"):
            fp.fault_point("ckpt.commit")     # hit 1: passes
            with pytest.raises(fp.InjectedFault, match="ckpt.commit"):
                fp.fault_point("ckpt.commit")  # hit 2: fires
            fp.fault_point("ckpt.commit")     # hit 3: passes again

    def test_context_manager_disarms(self):
        with fp.active("ckpt.commit=fail"):
            pass
        fp.fault_point("ckpt.commit")         # disarmed: no raise

    def test_undeclared_call_site_is_loud(self):
        with pytest.raises(fp.FaultSpecError, match="CATALOG"):
            fp.fault_point("not.in.catalog")

    def test_hang_sleeps(self):
        with fp.active("serving.translate=hang:0.1"):
            t0 = time.monotonic()
            fp.fault_point("serving.translate")
            assert time.monotonic() - t0 >= 0.1

    def test_prob_deterministic_by_seed(self):
        def fire_pattern(seed, n=32):
            out = []
            with fp.active("data.batch.next=prob:0.5@*", seed=seed):
                for _ in range(n):
                    try:
                        fp.fault_point("data.batch.next")
                        out.append(0)
                    except fp.InjectedFault:
                        out.append(1)
            return out

        a, b = fire_pattern(7), fire_pattern(7)
        assert a == b                         # same seed: same schedule
        assert 0 < sum(a) < 32                # actually probabilistic
        assert fire_pattern(8) != a           # another seed: another one

    def test_activate_resets_hits(self):
        fp.activate("ckpt.commit=fail@5")
        fp.fault_point("ckpt.commit")
        assert fp.hits("ckpt.commit") == 1
        fp.activate("ckpt.commit=fail@5")
        assert fp.hits("ckpt.commit") == 0


class TestProcessBoundary:
    def test_env_arms_and_kill_exits_with_fault_code(self):
        """MARIAN_FAULTS crosses the process boundary and kill is a real
        no-cleanup death — the mechanism the crash-resume trainer tests
        and scripts/chaos.py are built on."""
        code = ("from marian_tpu.common import faultpoints as fp\n"
                "fp.fault_point('ckpt.commit')\n"
                "fp.fault_point('ckpt.commit')\n"
                "print('SURVIVED')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   MARIAN_FAULTS="ckpt.commit=kill@2")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, timeout=120)
        assert proc.returncode == fp.FAULT_EXIT_CODE
        assert b"SURVIVED" not in proc.stdout
        assert b"FAULTPOINT ckpt.commit hit 2" in proc.stderr

    def test_env_spec_ignored_after_programmatic_arming(self):
        os.environ[fp.ENV_SPEC] = "ckpt.commit=fail"
        fp.reset_for_tests()
        fp.activate("")                       # programmatic wins
        fp.fault_point("ckpt.commit")         # env spec must NOT fire

    def test_env_spec_loads_on_first_hit(self):
        os.environ[fp.ENV_SPEC] = "ckpt.commit=fail"
        fp.reset_for_tests()
        with pytest.raises(fp.InjectedFault):
            fp.fault_point("ckpt.commit")

    def test_malformed_env_spec_raises_every_crossing(self):
        """A typo'd MARIAN_FAULTS must keep failing loudly — raising once
        and then silently disarming would let a chaos drill inject
        nothing and report success."""
        os.environ[fp.ENV_SPEC] = "ckpt.comit=kill"      # typo'd name
        fp.reset_for_tests()
        for _ in range(3):
            with pytest.raises(fp.FaultSpecError,
                               match="unknown fault point"):
                fp.fault_point("data.batch.next")
