"""--async-save: overlapped checkpoint writes (training/checkpoint.py ::
AsyncSaver — beyond the reference, whose Train::save blocks the update
loop while serializing; reference resume layout per SURVEY §5)."""

import os

import jax
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import prng
from marian_tpu.training.checkpoint import (AsyncSaver, load_checkpoint,
                                            save_checkpoint)
from marian_tpu.training.graph_group import GraphGroup
from marian_tpu.training.training_state import TrainingState
from marian_tpu.models.encoder_decoder import create_model


def _tiny_gg(**over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16,
            "learn-rate": 0.05, "optimizer": "adam", "clip-norm": 0.0,
            "exponential-smoothing": 1e-3}
    base.update(over)
    opts = Options(base)
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(7))
    return opts, gg


def _batch(seed=0):
    import jax.numpy as jnp
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


class TestAsyncSave:
    def test_bitwise_equal_to_sync_save(self, tmp_path):
        """Async and sync saves of the same training moment produce
        bitwise-identical model/optimizer/progress files."""
        opts, gg = _tiny_gg()
        key = prng.stream(prng.root_key(7), prng.STREAM_DROPOUT)
        for i in range(3):
            gg.update(_batch(i), i + 1, key)
        state = TrainingState()
        state.batches = 3
        saver = AsyncSaver()
        sp = str(tmp_path / "sync.npz")
        ap = str(tmp_path / "async.npz")
        save_checkpoint(sp, gg.export_params(), "x: 1", gg, state,
                        smooth_params=gg.smoothed())
        save_checkpoint(ap, gg.export_params(), "x: 1", gg, state,
                        smooth_params=gg.smoothed(), async_saver=saver)
        saver.wait()
        for suffix in ("", ".optimizer.npz"):
            a = np.load(ap + suffix) if suffix else np.load(ap)
            s = np.load(sp + suffix) if suffix else np.load(sp)
            assert sorted(a.files) == sorted(s.files)
            for k in s.files:
                np.testing.assert_array_equal(a[k], s[k], err_msg=k)
        assert (tmp_path / "async.npz.progress.yml").read_text() == \
               (tmp_path / "sync.npz.progress.yml").read_text()
        assert os.path.exists(str(tmp_path / "async.ema.npz"))

    def test_snapshot_survives_donation(self, tmp_path):
        """The save captures the EXACT training moment it was issued at,
        even though later updates donate (invalidate) the very buffers
        that were live at save time — the device-copy snapshot is the
        mechanism. The written file must equal a reference sync save
        taken at the same moment, not the post-update weights."""
        opts, gg = _tiny_gg()
        key = prng.stream(prng.root_key(7), prng.STREAM_DROPOUT)
        gg.update(_batch(0), 1, key)
        ref = {k: np.asarray(v) for k, v in gg.export_params().items()}

        saver = AsyncSaver()
        ap = str(tmp_path / "m.npz")
        save_checkpoint(ap, gg.export_params(), "x: 1", gg, None,
                        async_saver=saver)
        # keep training BEFORE waiting: donation reuses the old buffers
        for i in range(1, 4):
            gg.update(_batch(i), i + 1, key)
        saver.wait()

        with np.load(ap) as z:
            for k, v in ref.items():
                np.testing.assert_array_equal(z[k], v, err_msg=k)
        # and the post-save training really moved the weights
        moved = any(
            not np.array_equal(np.asarray(v), ref[k])
            for k, v in gg.export_params().items())
        assert moved

    def test_failed_save_raises_on_wait(self, tmp_path):
        opts, gg = _tiny_gg()
        saver = AsyncSaver()
        bad = str(tmp_path / "no_such_dir" / "m.npz")
        save_checkpoint(bad, gg.export_params(), "x: 1", None, None,
                        async_saver=saver)
        with pytest.raises(Exception):
            saver.wait()
        # saver is reusable after a failed save
        ok = str(tmp_path / "ok.npz")
        save_checkpoint(ok, gg.export_params(), "x: 1", None, None,
                        async_saver=saver)
        saver.wait()
        params, cfg, _ = load_checkpoint(ok)
        assert cfg is not None and len(params) > 0

    def test_train_loop_end_to_end(self, tmp_path):
        """--async-save through the real marian-train driver: checkpoint
        + resume files land and a fresh load round-trips."""
        src = tmp_path / "t.src"
        trg = tmp_path / "t.trg"
        lines = ["a b c d", "b c d e", "c d e f", "d e f g"] * 4
        src.write_text("\n".join(lines) + "\n")
        trg.write_text("\n".join(lines) + "\n")
        from marian_tpu.data.vocab import DefaultVocab
        v = tmp_path / "v.yml"
        DefaultVocab.build(lines).save(str(v))
        model_path = str(tmp_path / "model.npz")
        from marian_tpu.training.train import train_main
        train_main(Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "max-length": 16,
            "precision": ["float32", "float32"], "seed": 5,
            "train-sets": [str(src), str(trg)],
            "vocabs": [str(v), str(v)], "model": model_path,
            "mini-batch": 4, "after-batches": 6, "save-freq": "3u",
            "disp-freq": 3, "learn-rate": 0.01, "async-save": True,
            "overwrite": True,
        }))
        params, cfg, state = load_checkpoint(model_path)
        assert len(params) > 0
        assert state is not None and state.batches == 6