"""--async-save: overlapped checkpoint writes (training/checkpoint.py ::
AsyncSaver — beyond the reference, whose Train::save blocks the update
loop while serializing; reference resume layout per SURVEY §5) + the
crash-safe bundle protocol behind every save (training/bundle.py —
ISSUE 4: atomic commit, checksummed manifest, keep-last-N rotation,
restore-time validation with fallback to the last good bundle)."""

import json
import os

import jax
import numpy as np
import pytest

from marian_tpu.common import Options
from marian_tpu.common import faultpoints as fp
from marian_tpu.common import prng
from marian_tpu.training import bundle as bdl
from marian_tpu.training.checkpoint import (AsyncSaver, load_checkpoint,
                                            save_checkpoint)
from marian_tpu.training.graph_group import GraphGroup
from marian_tpu.training.training_state import TrainingState
from marian_tpu.models.encoder_decoder import create_model


def _tiny_gg(**over):
    base = {"type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "label-smoothing": 0.0,
            "precision": ["float32", "float32"], "max-length": 16,
            "learn-rate": 0.05, "optimizer": "adam", "clip-norm": 0.0,
            "exponential-smoothing": 1e-3}
    base.update(over)
    opts = Options(base)
    model = create_model(opts, 64, 64)
    gg = GraphGroup(model, opts)
    gg.initialize(prng.root_key(7))
    return opts, gg


def _batch(seed=0):
    import jax.numpy as jnp
    rs = np.random.RandomState(seed)
    return {
        "src_ids": jnp.asarray(rs.randint(2, 64, (8, 6)), jnp.int32),
        "src_mask": jnp.ones((8, 6), jnp.float32),
        "trg_ids": jnp.asarray(rs.randint(2, 64, (8, 7)), jnp.int32),
        "trg_mask": jnp.ones((8, 7), jnp.float32),
    }


class TestAsyncSave:
    def test_bitwise_equal_to_sync_save(self, tmp_path):
        """Async and sync saves of the same training moment produce
        bitwise-identical model/optimizer/progress files."""
        opts, gg = _tiny_gg()
        key = prng.stream(prng.root_key(7), prng.STREAM_DROPOUT)
        for i in range(3):
            gg.update(_batch(i), i + 1, key)
        state = TrainingState()
        state.batches = 3
        saver = AsyncSaver()
        sp = str(tmp_path / "sync.npz")
        ap = str(tmp_path / "async.npz")
        save_checkpoint(sp, gg.export_params(), "x: 1", gg, state,
                        smooth_params=gg.smoothed())
        save_checkpoint(ap, gg.export_params(), "x: 1", gg, state,
                        smooth_params=gg.smoothed(), async_saver=saver)
        saver.wait()
        for suffix in ("", ".optimizer.npz"):
            a = np.load(ap + suffix) if suffix else np.load(ap)
            s = np.load(sp + suffix) if suffix else np.load(sp)
            assert sorted(a.files) == sorted(s.files)
            for k in s.files:
                np.testing.assert_array_equal(a[k], s[k], err_msg=k)
        assert (tmp_path / "async.npz.progress.yml").read_text() == \
               (tmp_path / "sync.npz.progress.yml").read_text()
        assert os.path.exists(str(tmp_path / "async.ema.npz"))

    def test_snapshot_survives_donation(self, tmp_path):
        """The save captures the EXACT training moment it was issued at,
        even though later updates donate (invalidate) the very buffers
        that were live at save time — the device-copy snapshot is the
        mechanism. The written file must equal a reference sync save
        taken at the same moment, not the post-update weights."""
        opts, gg = _tiny_gg()
        key = prng.stream(prng.root_key(7), prng.STREAM_DROPOUT)
        gg.update(_batch(0), 1, key)
        ref = {k: np.asarray(v) for k, v in gg.export_params().items()}

        saver = AsyncSaver()
        ap = str(tmp_path / "m.npz")
        save_checkpoint(ap, gg.export_params(), "x: 1", gg, None,
                        async_saver=saver)
        # keep training BEFORE waiting: donation reuses the old buffers
        for i in range(1, 4):
            gg.update(_batch(i), i + 1, key)
        saver.wait()

        with np.load(ap) as z:
            for k, v in ref.items():
                np.testing.assert_array_equal(z[k], v, err_msg=k)
        # and the post-save training really moved the weights
        moved = any(
            not np.array_equal(np.asarray(v), ref[k])
            for k, v in gg.export_params().items())
        assert moved

    def test_failed_save_raises_on_wait(self, tmp_path):
        opts, gg = _tiny_gg()
        saver = AsyncSaver()
        bad = str(tmp_path / "no_such_dir" / "m.npz")
        save_checkpoint(bad, gg.export_params(), "x: 1", None, None,
                        async_saver=saver)
        with pytest.raises(Exception):
            saver.wait()
        # saver is reusable after a failed save
        ok = str(tmp_path / "ok.npz")
        save_checkpoint(ok, gg.export_params(), "x: 1", None, None,
                        async_saver=saver)
        saver.wait()
        params, cfg, _ = load_checkpoint(ok)
        assert cfg is not None and len(params) > 0

    def test_train_loop_end_to_end(self, tmp_path):
        """--async-save through the real marian-train driver: checkpoint
        + resume files land and a fresh load round-trips."""
        src = tmp_path / "t.src"
        trg = tmp_path / "t.trg"
        lines = ["a b c d", "b c d e", "c d e f", "d e f g"] * 4
        src.write_text("\n".join(lines) + "\n")
        trg.write_text("\n".join(lines) + "\n")
        from marian_tpu.data.vocab import DefaultVocab
        v = tmp_path / "v.yml"
        DefaultVocab.build(lines).save(str(v))
        model_path = str(tmp_path / "model.npz")
        from marian_tpu.training.train import train_main
        train_main(Options({
            "type": "transformer", "dim-emb": 16, "transformer-heads": 2,
            "transformer-dim-ffn": 32, "enc-depth": 1, "dec-depth": 1,
            "tied-embeddings-all": True, "max-length": 16,
            "precision": ["float32", "float32"], "seed": 5,
            "train-sets": [str(src), str(trg)],
            "vocabs": [str(v), str(v)], "model": model_path,
            "mini-batch": 4, "after-batches": 6, "save-freq": "3u",
            "disp-freq": 3, "learn-rate": 0.01, "async-save": True,
            "overwrite": True,
        }))
        params, cfg, state = load_checkpoint(model_path)
        assert len(params) > 0
        assert state is not None and state.batches == 6


# ---------------------------------------------------------------------------
# crash-safe bundle protocol (training/bundle.py — ISSUE 4)
# ---------------------------------------------------------------------------

class _FakeGG:
    """Minimal graph-group stand-in: just enough optimizer state for the
    bundle's .optimizer.npz member, without building a model."""

    def __init__(self):
        self.arrays = {"t": np.float32(3.0),
                       "m:w": np.arange(4, dtype=np.float32)}
        self.loaded = None

    def optimizer_device_arrays(self):
        return dict(self.arrays)

    def load_optimizer_arrays(self, flat):
        self.loaded = {k: np.asarray(v) for k, v in flat.items()}


def _params(shift=0.0):
    return {"w": np.arange(6, dtype=np.float32).reshape(2, 3) + shift}


def _save(mp, shift=0.0, batches=1, gg=None, **kw):
    st = TrainingState()
    st.batches = batches
    save_checkpoint(mp, _params(shift), "x: 1",
                    gg if gg is not None else _FakeGG(), st, **kw)
    return st


@pytest.fixture(autouse=True)
def _disarm_faults():
    fp.reset_for_tests()
    yield
    fp.reset_for_tests()


class TestBundleProtocol:
    def test_bundle_layout_manifest_and_published_view(self, tmp_path):
        mp = str(tmp_path / "model.npz")
        _save(mp, batches=2)
        root = bdl.bundle_root(mp)
        names = bdl.list_bundles(root)
        assert names == ["bundle-00000001"]
        bdir = os.path.join(root, names[0])
        manifest = json.load(open(os.path.join(bdir, bdl.MANIFEST_NAME)))
        assert set(manifest["members"]) == {
            "model.npz", "model.npz.optimizer.npz",
            "model.npz.progress.yml"}
        assert manifest["meta"]["batches"] == 2
        for rel, info in manifest["members"].items():
            assert info["sha256"] and info["bytes"] > 0
            # the published top-level view is byte-identical to the
            # committed bundle member
            with open(os.path.join(bdir, rel), "rb") as a, \
                    open(str(tmp_path / rel), "rb") as b:
                assert a.read() == b.read(), rel
        ok, why, _ = bdl.validate_bundle(bdir)
        assert ok, why

    def test_rotation_keeps_last_n(self, tmp_path):
        mp = str(tmp_path / "model.npz")
        for i in range(5):
            _save(mp, shift=float(i), batches=i + 1, keep_bundles=2)
        names = bdl.list_bundles(bdl.bundle_root(mp))
        assert names == ["bundle-00000004", "bundle-00000005"]
        params, _, st = load_checkpoint(mp)
        np.testing.assert_array_equal(params["w"], _params(4.0)["w"])
        assert st.batches == 5

    def test_corrupt_newest_falls_back_to_last_good(self, tmp_path):
        mp = str(tmp_path / "model.npz")
        _save(mp, shift=0.0, batches=1)
        _save(mp, shift=9.0, batches=2)
        root = bdl.bundle_root(mp)
        newest = os.path.join(root, bdl.list_bundles(root)[-1])
        target = os.path.join(newest, "model.npz")
        os.chmod(target, 0o644)   # members are read-only once committed;
        # bit rot / a misbehaving root process doesn't ask permission
        with open(target, "r+b") as fh:
            fh.seek(12)
            fh.write(b"\xde\xad\xbe\xef")
        gg = _FakeGG()
        params, _, st = load_checkpoint(mp, gg)
        np.testing.assert_array_equal(params["w"], _params(0.0)["w"])
        assert st.batches == 1
        # the optimizer member restored from the SAME bundle as params —
        # the consistency the flat layout could not guarantee
        np.testing.assert_array_equal(gg.loaded["m:w"],
                                      np.arange(4, dtype=np.float32))

    def test_truncated_member_detected(self, tmp_path):
        mp = str(tmp_path / "model.npz")
        _save(mp, batches=1)
        _save(mp, shift=1.0, batches=2)
        root = bdl.bundle_root(mp)
        newest = os.path.join(root, bdl.list_bundles(root)[-1])
        target = os.path.join(newest, "model.npz.optimizer.npz")
        os.chmod(target, 0o644)
        with open(target, "r+b") as fh:
            fh.truncate(os.path.getsize(target) // 2)
        ok, why, _ = bdl.validate_bundle(newest)
        assert not ok and "truncated" in why
        _, _, st = load_checkpoint(mp)
        assert st.batches == 1

    def test_all_bundles_bad_and_no_flat_layout_is_loud(self, tmp_path):
        mp = str(tmp_path / "model.npz")
        _save(mp, batches=1)
        root = bdl.bundle_root(mp)
        for name in bdl.list_bundles(root):
            os.remove(os.path.join(root, name, bdl.MANIFEST_NAME))
        for rel in ("model.npz", "model.npz.optimizer.npz",
                    "model.npz.progress.yml"):
            os.remove(str(tmp_path / rel))
        with pytest.raises(bdl.BundleError, match="failed validation"):
            load_checkpoint(mp)

    def test_all_bundles_bad_never_falls_back_to_flat_view(self, tmp_path):
        """The flat layout is the published HARDLINK of a bundle's
        members — when every bundle fails validation, 'falling back' to
        it would resume from exactly the corrupt bytes the checksums
        refused. Must be a loud BundleError even though model.npz
        exists."""
        mp = str(tmp_path / "model.npz")
        _save(mp, batches=1)
        root = bdl.bundle_root(mp)
        bdir = os.path.join(root, bdl.list_bundles(root)[0])
        target = os.path.join(bdir, "model.npz")
        os.chmod(target, 0o644)
        with open(target, "r+b") as fh:     # bit rot on the shared inode
            fh.seek(12)
            fh.write(b"\xde\xad")
        assert os.path.exists(mp)           # flat view is present...
        with pytest.raises(bdl.BundleError,
                           match="published view of a rejected bundle"):
            load_checkpoint(mp)             # ...and still refused

    def test_committed_members_are_readonly(self, tmp_path):
        """The published top-level view hardlinks the committed bundle
        member (one inode). Read-only mode is what turns an external
        tool's in-place edit of the 'convenience' copy — which would
        silently break the recorded checksum — into a loud EACCES."""
        mp = str(tmp_path / "model.npz")
        _save(mp, batches=1)
        root = bdl.bundle_root(mp)
        bdir = os.path.join(root, bdl.list_bundles(root)[0])
        for rel in ("model.npz", "model.npz.optimizer.npz",
                    "model.npz.progress.yml"):
            member = os.path.join(bdir, rel)
            assert os.stat(member).st_mode & 0o777 == 0o444, rel
            top = str(tmp_path / rel)
            # same inode: the published view shares the protection
            assert os.path.samefile(member, top), rel
        # a REPLACING rewrite of the top-level file (temp+rename, what
        # numpy/save_items do) still works and leaves the bundle intact
        from marian_tpu.common import io as mio
        mio.save_model(mp, _params(9.0), "x: 2")
        ok, why, _ = bdl.validate_bundle(bdir)
        assert ok, why

    def test_legacy_flat_layout_still_loads(self, tmp_path):
        """Pre-bundle checkpoints (hand-copied models, upstream Marian
        exports) keep loading without a .bundles/ dir."""
        from marian_tpu.common import io as mio
        mp = str(tmp_path / "legacy.npz")
        mio.save_model(mp, _params(), "x: 1")
        st = TrainingState()
        st.batches = 7
        st.save(mp + ".progress.yml")
        params, cfg, state = load_checkpoint(mp)
        np.testing.assert_array_equal(params["w"], _params()["w"])
        assert state.batches == 7 and cfg == "x: 1"


FAIL_POINTS = ("ckpt.write.model", "ckpt.write.optimizer",
               "ckpt.write.progress", "ckpt.write.manifest", "ckpt.commit")


class TestInjectedSaveFailures:
    @pytest.mark.parametrize("point", FAIL_POINTS)
    def test_fail_mid_save_never_tears_previous_bundle(self, tmp_path,
                                                       point):
        """An injected IO failure at EVERY stage of the bundle write
        leaves the previous committed bundle fully valid, no staging
        litter behind, and restore returns the previous moment."""
        mp = str(tmp_path / "model.npz")
        _save(mp, shift=0.0, batches=1)
        with fp.active(f"{point}=fail"):
            with pytest.raises(fp.InjectedFault):
                _save(mp, shift=5.0, batches=2)
        root = bdl.bundle_root(mp)
        assert bdl.list_bundles(root) == ["bundle-00000001"]
        assert not [d for d in os.listdir(root)
                    if d.startswith(".staging")]
        params, _, st = load_checkpoint(mp)
        np.testing.assert_array_equal(params["w"], _params(0.0)["w"])
        assert st.batches == 1

    def test_publish_failure_does_not_lose_the_commit(self, tmp_path):
        """ckpt.publish fires AFTER the atomic rename: the save errors,
        the top-level view is stale, but the committed bundle is the new
        moment and restore sees it."""
        mp = str(tmp_path / "model.npz")
        _save(mp, shift=0.0, batches=1)
        with fp.active("ckpt.publish=fail"):
            with pytest.raises(fp.InjectedFault):
                _save(mp, shift=5.0, batches=2)
        assert len(bdl.list_bundles(bdl.bundle_root(mp))) == 2
        params, _, st = load_checkpoint(mp)
        np.testing.assert_array_equal(params["w"], _params(5.0)["w"])
        assert st.batches == 2
        # the stale top-level file was NOT half-replaced
        flat, _ = __import__("marian_tpu.common.io",
                             fromlist=["io"]).load_model(mp)
        np.testing.assert_array_equal(flat["w"], _params(0.0)["w"])

    def test_async_worker_failure_raises_on_wait(self, tmp_path):
        """ckpt.async.worker fires on the AsyncSaver thread; wait() must
        re-raise it on the training thread and leave no bundle behind."""
        mp = str(tmp_path / "model.npz")
        saver = AsyncSaver()
        with fp.active("ckpt.async.worker=fail"):
            _save(mp, batches=1, async_saver=saver)
            with pytest.raises(fp.InjectedFault):
                saver.wait()
        assert bdl.list_bundles(bdl.bundle_root(mp)) == []
        # saver reusable after the injected failure
        _save(mp, batches=1, async_saver=saver)
        saver.wait()
        assert len(bdl.list_bundles(bdl.bundle_root(mp))) == 1