"""Native (C++) data loader vs the Python BatchGenerator — the two pipelines
must agree batch-for-batch with shuffle off (marian_tpu/native/data_loader.cpp
mirrors data/batch_generator.py; reference: src/data/batch_generator.h)."""

import os

import numpy as np
import pytest

from marian_tpu.common.options import Options
from marian_tpu.data.batch_generator import BatchGenerator
from marian_tpu.data.corpus import Corpus
from marian_tpu.data.vocab import DefaultVocab

native = pytest.importorskip("marian_tpu.native")

if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


@pytest.fixture(scope="module")
def corpus_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("native")
    src_lines = ["the cat sat on the mat", "a dog barks", "hello world",
                 "the quick brown fox jumps over the lazy dog",
                 "a cat and a dog", "hello again world", "the dog runs",
                 "a fox jumps", "the lazy dog sleeps", "hello cat dog fox"]
    tgt_lines = ["die katze sass auf der matte", "ein hund bellt",
                 "hallo welt",
                 "der schnelle braune fuchs springt ueber den faulen hund",
                 "eine katze und ein hund", "hallo nochmal welt",
                 "der hund rennt", "ein fuchs springt",
                 "der faule hund schlaeft", "hallo katze hund fuchs"]
    src = tmp / "c.src"; src.write_text("\n".join(src_lines) + "\n")
    tgt = tmp / "c.tgt"; tgt.write_text("\n".join(tgt_lines) + "\n")
    vs = DefaultVocab.build(src_lines)
    vt = DefaultVocab.build(tgt_lines)
    return str(src), str(tgt), vs, vt


def _python_batches(src, tgt, vs, vt, **kw):
    opts = Options({"max-length": 50, "shuffle": "none", "seed": 7, **{
        k.replace("_", "-"): v for k, v in kw.items()}})
    corpus = Corpus([src, tgt], [vs, vt], opts)
    bg = BatchGenerator(corpus, opts, shuffle_batches=False, prefetch=False)
    return list(bg)


def _native_batches(src, tgt, vs, vt, **kw):
    opts = Options({"max-length": 50, "shuffle": "none", "seed": 7, **{
        k.replace("_", "-"): v for k, v in kw.items()}})
    bg = native.NativeBatchGenerator([src, tgt], [vs, vt], opts)
    return list(bg)


class TestNativeMatchesPython:
    @pytest.mark.parametrize("kw", [
        dict(mini_batch=4),
        dict(mini_batch=3, maxi_batch=2),
        dict(mini_batch_words=40, mini_batch=64),
        dict(mini_batch=4, maxi_batch_sort="src"),
    ])
    def test_batch_for_batch(self, corpus_files, kw):
        src, tgt, vs, vt = corpus_files
        pb = _python_batches(src, tgt, vs, vt, **kw)
        nb = _native_batches(src, tgt, vs, vt, **kw)
        assert len(pb) == len(nb)
        for p, n in zip(pb, nb):
            assert p.src.ids.shape == n.src.ids.shape
            np.testing.assert_array_equal(p.src.ids, n.src.ids)
            np.testing.assert_array_equal(p.trg.ids, n.trg.ids)
            np.testing.assert_array_equal(p.src.mask, n.src.mask)
            np.testing.assert_array_equal(p.trg.mask, n.trg.mask)
            np.testing.assert_array_equal(p.sentence_ids, n.sentence_ids)

    def test_max_length_skip(self, corpus_files):
        src, tgt, vs, vt = corpus_files
        nb = native.NativeBatchGenerator(
            [src, tgt], [vs, vt], None, mini_batch=64, shuffle=False,
            max_length=5)
        # only sentences with <=5 tokens incl. EOS survive on BOTH sides
        total = sum(b.size for b in nb)
        pb = _python_batches(src, tgt, vs, vt, mini_batch=64)
        opts = Options({"max-length": 5, "shuffle": "none"})
        corpus = Corpus([src, tgt], [vs, vt], opts)
        expect = sum(1 for _ in corpus)
        assert total == expect

    def test_shuffle_covers_corpus(self, corpus_files):
        src, tgt, vs, vt = corpus_files
        bg = native.NativeBatchGenerator([src, tgt], [vs, vt], None,
                                         mini_batch=3, shuffle=True, seed=3)
        seen = []
        for b in bg:
            seen.extend(int(i) for i in b.sentence_ids if i >= 0)
        assert sorted(seen) == list(range(10))
        first_epoch = list(seen)
        seen2 = []
        for b in bg:          # second epoch: different permutation
            seen2.extend(int(i) for i in b.sentence_ids if i >= 0)
        assert sorted(seen2) == list(range(10))
        assert seen2 != first_epoch

    def test_resume_seek(self, corpus_files):
        """Window-granular exact resume (maxi_batch=1 → one batch per
        window, so positions step per batch; mirrors the Python
        BatchGenerator's corpus-state snapshot semantics)."""
        src, tgt, vs, vt = corpus_files
        kw = dict(mini_batch=2, maxi_batch=1, shuffle=False)
        bg = native.NativeBatchGenerator([src, tgt], [vs, vt], None, **kw)
        all_ids = []
        states = []
        for b in bg:
            states.append(dict(b.corpus_state))
            all_ids.append([int(i) for i in b.sentence_ids if i >= 0])
        # with one batch per window, the state after batch i resumes at i+1
        assert states[1]["position"] == 4
        bg2 = native.NativeBatchGenerator([src, tgt], [vs, vt], None, **kw)
        bg2.seek(states[1]["epoch"], states[1]["position"])
        replay = [[int(i) for i in b.sentence_ids if i >= 0] for b in bg2]
        assert replay == all_ids[2:]


class TestMisalignedStreams:
    @pytest.mark.parametrize("extra_on", ["src", "tgt"])
    def test_native_raises_like_python(self, tmp_path, extra_on):
        """Parallel files of unequal length must raise, not silently
        truncate (ADVICE r1 medium: native loader stopped at first EOF)."""
        src_lines = ["a b", "b c", "c d"]
        tgt_lines = ["x y", "y z", "z w"]
        (src_lines if extra_on == "src" else tgt_lines).append("extra line")
        src = tmp_path / "m.src"; src.write_text("\n".join(src_lines) + "\n")
        tgt = tmp_path / "m.tgt"; tgt.write_text("\n".join(tgt_lines) + "\n")
        vs = DefaultVocab.build(src_lines)
        vt = DefaultVocab.build(tgt_lines)
        with pytest.raises(Exception, match="differ in length"):
            native.NativeBatchGenerator([str(src), str(tgt)], [vs, vt], None,
                                        mini_batch=2, shuffle=False)


class TestBackendTag:
    def test_state_dicts_tagged(self, corpus_files):
        src, tgt, vs, vt = corpus_files
        bg = native.NativeBatchGenerator([src, tgt], [vs, vt], None,
                                         mini_batch=4, shuffle=False)
        assert bg.state_dict()["backend"] == "native"
        opts = Options({"max-length": 50, "shuffle": "none"})
        corpus = Corpus([src, tgt], [vs, vt], opts)
        assert corpus.state.as_dict()["backend"] == "python"
        # round trip: python restore tolerates the tag (and native's)
        corpus.restore(corpus.state.as_dict())
        corpus.restore(bg.state_dict())


class TestNativeTrainCLI:
    def test_train_with_native_backend(self, tmp_path):
        from marian_tpu.cli import marian_train
        src_lines = ["a b c", "b c d", "c d a", "d a b"] * 3
        tgt_lines = ["x y z", "y z w", "z w x", "w x y"] * 3
        (tmp_path / "t.src").write_text("\n".join(src_lines) + "\n")
        (tmp_path / "t.tgt").write_text("\n".join(tgt_lines) + "\n")
        model = str(tmp_path / "m.npz")
        marian_train.main([
            "--type", "transformer",
            "--train-sets", str(tmp_path / "t.src"), str(tmp_path / "t.tgt"),
            "--vocabs", str(tmp_path / "v.s.yml"), str(tmp_path / "v.t.yml"),
            "--model", model, "--data-backend", "native",
            "--dim-emb", "32", "--transformer-heads", "4",
            "--transformer-dim-ffn", "64", "--enc-depth", "1",
            "--dec-depth", "1", "--precision", "float32", "float32",
            "--mini-batch", "8", "--learn-rate", "0.01",
            "--after-batches", "10", "--disp-freq", "5u",
            "--save-freq", "100u", "--seed", "1", "--max-length", "20",
            "--quiet", "--cost-type", "ce-mean-words",
        ])
        assert os.path.exists(model)
