"""Benchmark: training throughput (src-tokens/sec/chip) of transformer-big
En-De-shaped training — the driver's headline metric (BASELINE.json: north
star 180k src-tok/s/chip on v4-32; vs_baseline is measured/180k).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Runs on whatever jax.devices() provides (the real TPU chip under the axon
tunnel; CPU fallback for smoke-testing with MARIAN_BENCH_PRESET=tiny).
Method: jitted fused train step (grads + Adam + EMA, bf16 compute, donated
buffers), warmup until compile settles, then timed steps with a single
block_until_ready at the end — no host sync inside the loop.
"""

import json
import os
import time


def main():
    preset = os.environ.get("MARIAN_BENCH_PRESET", "big")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from marian_tpu.common.options import Options
    from marian_tpu.models.encoder_decoder import create_model
    from marian_tpu.optimizers.optimizers import OptimizerConfig, init_state
    from marian_tpu.optimizers.schedule import LRSchedule
    from marian_tpu.parallel import mesh as M
    from marian_tpu.parallel.zero import build_train_step, place

    if preset == "big":
        # transformer-big En-De (BASELINE.json config #2); 32k joint vocab
        dims = dict(emb=1024, ffn=4096, heads=16, depth=6, vocab=32000)
        batch, src_len, trg_len = 64, 64, 64
        steps, warmup = 20, 3
    elif preset == "base":
        dims = dict(emb=512, ffn=2048, heads=8, depth=6, vocab=32000)
        batch, src_len, trg_len = 128, 64, 64
        steps, warmup = 20, 3
    else:  # tiny smoke preset
        dims = dict(emb=64, ffn=128, heads=4, depth=2, vocab=512)
        batch, src_len, trg_len = 16, 16, 16
        steps, warmup = 5, 2

    opts = Options({
        "type": "transformer",
        "dim-emb": dims["emb"], "transformer-dim-ffn": dims["ffn"],
        "transformer-heads": dims["heads"],
        "enc-depth": dims["depth"], "dec-depth": dims["depth"],
        "tied-embeddings-all": True,
        "transformer-ffn-activation": "relu",
        "precision": ["bfloat16", "float32"],
        "label-smoothing": 0.1, "cost-type": "ce-mean-words",
        "learn-rate": 2e-4, "lr-warmup": "8000", "lr-decay-inv-sqrt": ["8000"],
        "optimizer": "adam", "optimizer-params": [0.9, 0.98, 1e-9],
        "clip-norm": 0.0, "exponential-smoothing": 1e-4,
        "max-length": max(src_len, trg_len),
    })

    devices = jax.devices()
    mesh = M.make_mesh(None, devices)
    n_chips = len(devices)

    model = create_model(opts, dims["vocab"], dims["vocab"])
    params = model.init(jax.random.key(0))
    opt_cfg = OptimizerConfig.from_options(opts)
    opt_state = init_state(opt_cfg, params)
    params, opt_state = place(params, opt_state, mesh)
    schedule = LRSchedule.from_options(opts)
    step_fn = build_train_step(model, opt_cfg, schedule, "ce-mean-words",
                               mesh, params, opt_state, delay=1, donate=True)

    global_batch = batch * max(1, mesh.shape["data"])

    def make_batch(seed):
        r = np.random.RandomState(seed)
        return M.shard_batch({
            "src_ids": jnp.asarray(r.randint(2, dims["vocab"],
                                             (global_batch, src_len)), jnp.int32),
            "src_mask": jnp.ones((global_batch, src_len), jnp.float32),
            "trg_ids": jnp.asarray(r.randint(2, dims["vocab"],
                                             (global_batch, trg_len)), jnp.int32),
            "trg_mask": jnp.ones((global_batch, trg_len), jnp.float32),
        }, mesh)

    batches = [make_batch(i) for i in range(4)]
    rng = jax.random.key(1)

    for i in range(warmup):
        params, opt_state, metrics = step_fn(
            params, opt_state, batches[i % 4],
            jnp.asarray(i + 1, jnp.float32), rng)
    jax.block_until_ready(params)

    t0 = time.perf_counter()
    for i in range(steps):
        params, opt_state, metrics = step_fn(
            params, opt_state, batches[i % 4],
            jnp.asarray(warmup + i + 1, jnp.float32), rng)
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0

    src_tokens = steps * global_batch * src_len
    tok_per_sec_chip = src_tokens / dt / n_chips
    baseline = 180_000.0  # north-star src-tok/s/chip (BASELINE.json)
    print(json.dumps({
        "metric": "train_src_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "src-tokens/sec/chip",
        "vs_baseline": round(tok_per_sec_chip / baseline, 4),
    }))


if __name__ == "__main__":
    main()
